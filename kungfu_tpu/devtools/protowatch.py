"""Runtime collective-order sentinel (``KF_DEBUG_PROTOCOL=1``).

kfcheck's KF7xx rules see the protocol a call site *spells*; this layer
sees the collective sequence each peer actually *runs*. The engine's
worst failure mode is a cross-peer protocol divergence — peers whose
collective sequences, wire names or payload shapes differ hang in a
rendezvous nobody else will enter, and the postmortem shows only "walk
timed out". When attached (from ``HostSession.__init__`` under the
knob), protowatch wraps the session's public collective entry points
and the async scheduler's ``submit``/``flush`` to keep, per peer, a
rolling **round window** of entries::

    (kind, name, dtype, nbytes, strategy)  +  call site file.py:lineno

At every scheduler ``flush()`` boundary (and on demand via
:func:`check`) the window is cross-checked on the **knob-independent
star walk** (the ``check_knob_consensus`` machinery — fixed graphs,
fixed names, so the check itself cannot deadlock on the very divergence
it hunts):

1. a 2-round byte consensus over the window digest — agreement clears
   the window and the round is done;
2. on mismatch, a fixed-shape entry exchange (MAX of lengths, then a
   SUM-allreduce where each rank fills its own row) hands every peer
   every peer's entries, and each peer reports the **first divergent
   entry per peer** — its own call site, the other peer's entry, the
   round index — as ``protocol_divergence`` audit events (journaled by
   the flight recorder, so postmortems carry the protocol tail), a
   ``log.warn`` line and ``kungfu_debug_protocol_divergences_total``.

This reports *before the hang*: a divergent round is named at the
boundary that follows it, while the cluster can still exchange bytes on
the star walk — not after the next mismatched rendezvous has eaten the
full walk timeout. The async scheduler's registration consensus already
*detects* a divergent first round; protowatch names the exact tensor
and the submitting call site on every peer.

Recording is order-insensitive inside a window (entries are sorted
before digesting): the scheduler's overlap means submit-side and
walk-side entries interleave differently per peer even when the
protocol is identical. Divergence therefore means a *set* difference —
an extra, missing or differently-shaped collective — which is exactly
the class that deadlocks.

Known blind spots, stated:

- collectives driven below the public surface (raw ``_run_graphs``
  calls) are invisible — every engine path in the tree enters through a
  wrapped method;
- windows past ``KF_DEBUG_PROTOCOL_WINDOW`` entries fold their prefix
  into the rolling digest: divergence is still *detected*, but the
  per-entry diff covers only the tail;
- the boundary check requires every peer to reach a boundary; a peer
  already hung inside a divergent walk is named by the surviving peers'
  next postmortem, not by a live check (the check itself would have to
  rendezvous with the hung peer).

``KF_DEBUG_PROTOCOL`` unset means this module is never imported and the
session is never wrapped — zero overhead, subprocess-asserted by
tests/test_protowatch.py exactly like lockwatch.
"""

from __future__ import annotations

import functools
import hashlib
import json
import os
import sys
import threading
from typing import List, Optional, Tuple

_DIVERGENCES = "kungfu_debug_protocol_divergences_total"
_CHECKS = "kungfu_debug_protocol_checks_total"

# one protowatch consensus lane per check, stamped by the state's own
# counter (KF700 discipline: the sentinel must not violate the rule it
# polices)
_CHECK_TAG = ":protowatch:{n}"


def _caller_site() -> str:
    """file.py:lineno of the nearest frame outside this module and the
    wrapped session/scheduler modules — the project call site that
    issued the collective."""
    skip = (__name__, "kungfu_tpu.collective.host_session")
    f = sys._getframe(2)
    while f is not None and f.f_globals.get("__name__") in skip:
        f = f.f_back
    if f is None:
        return "?"
    return f"{os.path.basename(f.f_code.co_filename)}:{f.f_lineno}"


class _Watch:
    """Per-session sentinel state: the round window, its rolling digest,
    and the check counter. All mutation under one lock — entries arrive
    from the caller's thread AND (on the sync sharded path) scheduler
    hand-off threads."""

    def __init__(self, sess, window_cap: int):
        self.sess = sess
        self.window_cap = window_cap
        self.lock = threading.Lock()
        # (entry tuple, call site) in arrival order; compared as a
        # sorted multiset (arrival order is timing-dependent under the
        # scheduler's overlap even when the protocol agrees)
        self.window: List[Tuple[tuple, str]] = []
        self.folded = hashlib.sha256()  # overflow prefix, digest-only
        self.folded_n = 0
        self.round = 0
        self.checks = 0
        self.divergences = 0

    # -- recording ----------------------------------------------------

    def record(self, kind: str, name: str, dtype: str, nbytes: int) -> None:
        # walk-side collectives issued FROM the scheduler's registered
        # stage threads are excluded: their timing relative to the flush
        # boundary is peer-local (a slow gather stage records round r's
        # zag entry after the boundary on one peer, before it on
        # another), while the submit-side entries already carry the
        # async protocol deterministically. The KF303 thread-naming
        # discipline is what makes this exclusion reliable.
        if threading.current_thread().name.startswith("kf-sched-"):
            return
        try:
            strategy = self.sess.active_candidate_name()
        # kfcheck: disable=KF400 — observe-only layer: a session mid-
        # teardown may lack adaptive state; '?' in the entry IS the
        # record of that, and raising would kill the caller's collective
        except Exception:
            strategy = "?"
        entry = (kind, str(name), str(dtype), int(nbytes), strategy)
        site = _caller_site()
        with self.lock:
            self.window.append((entry, site))
            if len(self.window) > self.window_cap:
                spill = self.window.pop(0)
                self.folded.update(repr(spill[0]).encode())
                self.folded_n += 1

    def record_workspace(self, kind: str, w) -> None:
        self.record(kind, w.name, w.send.dtype.str, int(w.recv.nbytes))

    # -- the boundary check -------------------------------------------

    @staticmethod
    def _digest(entries: List[Tuple[tuple, str]], folded,
                folded_n: int) -> bytes:
        # entries only — call SITES legitimately differ across peers
        # (different frontends can drive the identical protocol)
        h = folded.copy()
        for entry, _ in sorted(entries):
            h.update(repr(entry).encode())
        return f"{folded_n + len(entries)}:".encode() + h.digest()

    def check(self) -> bool:
        """Cross-check this round's window against every peer on the
        knob-independent star walk; True when the cluster agrees. On
        divergence, report per-peer first-divergent entries (audit +
        log + metric) and return False. The window is snapshotted and
        reset up front, so entries recorded concurrently (overlapped
        next-round work) land in the next round's window. An EMPTY
        window still joins the walk — "this peer ran zero collectives
        while the others ran some" is precisely a divergence (the KF702
        class), and a peer that skipped the exchange would report clean
        while the rest stall in it; the flip side is the documented
        boundary contract: every peer must reach every boundary."""
        sess = self.sess
        with self.lock:
            entries = self.window
            folded, folded_n = self.folded, self.folded_n
            rnd = self.round
            self.window = []
            self.folded = hashlib.sha256()
            self.folded_n = 0
            self.round += 1
            n = self.checks
            self.checks += 1
        if sess.size < 2:
            return True
        digest = self._digest(entries, folded, folded_n)
        agreed = sess._bytes_agree(
            digest, _CHECK_TAG.format(n=n), sess._fixed_allreduce
        )
        self._count(_CHECKS, "Boundary digest cross-checks run by the "
                    "KF_DEBUG_PROTOCOL collective-order sentinel")
        if agreed:
            return True
        with self.lock:
            self.divergences += 1
        mine = json.dumps(
            [[list(e), site] for e, site in sorted(entries)]
        ).encode()
        theirs = self._exchange(mine, n)
        self._report(rnd, entries, theirs)
        return False

    def _exchange(self, mine: bytes, n: int) -> List[Optional[list]]:
        """Every peer's serialized window, via two fixed-shape star
        walks: MAX of lengths, then a SUM-allreduce of a (k, maxlen)
        byte matrix where each rank fills only its own row."""
        import numpy as np

        from kungfu_tpu.base.ops import ReduceOp
        from kungfu_tpu.base.workspace import Workspace

        sess = self.sess
        k = sess.size
        lens = np.zeros(k, np.int64)
        lens[sess.rank] = len(mine)
        lens_out = np.zeros(k, np.int64)
        sess._fixed_allreduce(Workspace(
            lens, lens_out, ReduceOp.MAX,
            _CHECK_TAG.format(n=n) + ":len",
        ))
        maxlen = int(lens_out.max())
        rows = np.zeros(k * maxlen, np.uint8)
        if maxlen:
            rows[sess.rank * maxlen:sess.rank * maxlen + len(mine)] = (
                np.frombuffer(mine, np.uint8)
            )
        rows_out = np.zeros(k * maxlen, np.uint8)
        sess._fixed_allreduce(Workspace(
            rows, rows_out, ReduceOp.SUM,
            _CHECK_TAG.format(n=n) + ":entries",
        ))
        out: List[Optional[list]] = []
        for r in range(k):
            blob = bytes(rows_out[r * maxlen:r * maxlen + int(lens_out[r])])
            try:
                out.append(json.loads(blob.decode()) if blob else [])
            except ValueError:
                out.append(None)  # peer overflowed / garbled: shape-only
        return out

    def _report(self, rnd: int, entries, all_peers: List[Optional[list]]) -> None:
        from kungfu_tpu.telemetry import audit, log

        sess = self.sess
        mine_sorted = sorted(entries)
        for r, theirs in enumerate(all_peers):
            if r == sess.rank:
                continue
            if theirs is None:
                detail = {"peer_entries": "unavailable"}
            else:
                their_sorted = [(tuple(e), site) for e, site in theirs]
                idx, mine_at, theirs_at = _first_divergence(
                    mine_sorted, their_sorted
                )
                if idx is None:
                    continue  # this pair agrees; a third peer diverged
                detail = {
                    "divergent_index": idx,
                    "mine": _fmt(mine_at),
                    "theirs": _fmt(theirs_at),
                }
            detail.update({
                "round": rnd,
                "other_peer": f"rank{r}",
                "window": len(mine_sorted),
            })
            log.warn(
                "protowatch protocol_divergence round=%s vs rank%s: "
                "mine=%s theirs=%s",
                rnd, r, detail.get("mine"), detail.get("theirs"),
            )
            audit.record_event(
                "protocol_divergence", peer=str(sess.self_id), **detail
            )
            self._count(
                _DIVERGENCES,
                "Cross-peer collective-sequence divergences found by the "
                "KF_DEBUG_PROTOCOL sentinel (each pairs with a "
                "protocol_divergence audit event naming both call sites)",
            )

    def _count(self, name: str, help_: str) -> None:
        try:
            from kungfu_tpu.telemetry import metrics

            metrics.counter(name, help_).inc()
        except Exception as e:  # noqa: BLE001 - the sentinel must never kill training
            sys.stderr.write(f"protowatch: metric update failed: {e}\n")


def _fmt(item: Optional[tuple]) -> str:
    if item is None:
        return "(no entry — this side ran fewer collectives)"
    entry, site = item
    kind, name, dtype, nbytes, strategy = entry
    return f"{kind}({name!r}, {dtype}, {nbytes}B, {strategy}) at {site}"


def _first_divergence(mine: list, theirs: list):
    """Index + both sides' items at the first position where the sorted
    windows' ENTRIES differ ((None, None, None) when identical — sites
    are reporting payload, not identity)."""
    for i in range(max(len(mine), len(theirs))):
        a = mine[i] if i < len(mine) else None
        b = theirs[i] if i < len(theirs) else None
        if (a[0] if a else None) != (b[0] if b else None):
            return i, a, b
    return None, None, None


# ---------------------------------------------------------------------
# attachment (instance-level wrapping: the hot path of unwatched
# sessions is untouched, and uninstalling is just "don't attach")
# ---------------------------------------------------------------------

# (method name, kind label, workspace-arg position) for entry points
# whose first argument is a Workspace
_WS_METHODS = (
    ("all_reduce", "all_reduce"),
    ("monitored_all_reduce", "monitored_all_reduce"),
    ("all_gather", "all_gather"),
)


def attach(sess) -> "_Watch":
    """Wrap one HostSession's public collective entry points (and, via
    :func:`attach_scheduler`, its scheduler) with recording shims.
    Called from HostSession.__init__ under the knob; idempotent."""
    existing = getattr(sess, "_protowatch", None)
    if existing is not None:
        return existing
    from kungfu_tpu import knobs

    watch = _Watch(sess, max(8, int(knobs.get("KF_DEBUG_PROTOCOL_WINDOW"))))
    sess._protowatch = watch

    def wrap_ws(name: str, kind: str) -> None:
        orig = getattr(sess, name)

        @functools.wraps(orig)
        def shim(w, *a, **kw):
            watch.record_workspace(kind, w)
            return orig(w, *a, **kw)

        setattr(sess, name, shim)

    for name, kind in _WS_METHODS:
        wrap_ws(name, kind)

    orig_rs = sess.reduce_scatter

    @functools.wraps(orig_rs)
    def shim_rs(w, *a, **kw):
        watch.record_workspace("reduce_scatter", w)
        return orig_rs(w, *a, **kw)

    sess.reduce_scatter = shim_rs

    orig_ag = sess.all_gather_shards

    @functools.wraps(orig_ag)
    def shim_ag(full, name, *a, **kw):
        watch.record("all_gather_shards", name, full.dtype.str,
                     int(full.nbytes))
        return orig_ag(full, name, *a, **kw)

    sess.all_gather_shards = shim_ag

    orig_group = sess.group_all_reduce

    @functools.wraps(orig_group)
    def shim_group(ws, *a, **kw):
        for w in ws:
            watch.record_workspace("group_all_reduce", w)
        return orig_group(ws, *a, **kw)

    sess.group_all_reduce = shim_group

    # the bytes-taking entry points record a LENGTH-FREE identity: their
    # payload legitimately differs per rank (a non-root passes b"" to
    # broadcast_bytes; bytes_consensus exists to compare bytes that may
    # disagree) — the rendezvous name is the protocol, the bytes are data
    orig_bc = sess.bytes_consensus

    @functools.wraps(orig_bc)
    def shim_bc(bs, name, *a, **kw):
        watch.record("bytes_consensus", name, "bytes", 0)
        return orig_bc(bs, name, *a, **kw)

    sess.bytes_consensus = shim_bc

    orig_bb = sess.broadcast_bytes

    @functools.wraps(orig_bb)
    def shim_bb(bs, name, *a, **kw):
        watch.record("broadcast_bytes", name, "bytes", 0)
        return orig_bb(bs, name, *a, **kw)

    sess.broadcast_bytes = shim_bb

    return watch


def attach_scheduler(sched) -> None:
    """Wrap a session's CollectiveScheduler: submissions record their
    registered identity + call site, every successful flush runs the
    boundary check. Called from HostSession.scheduler() when the session
    is watched."""
    watch = getattr(sched.sess, "_protowatch", None)
    if watch is None or getattr(sched, "_protowatch_attached", False):
        return
    sched._protowatch_attached = True
    orig_submit = sched.submit

    @functools.wraps(orig_submit)
    def shim_submit(w, *a, **kw):
        if not w.is_empty:
            kind = "submit" if kw.get("handler") is None else "submit:zero"
            watch.record(kind, w.name, w.send.dtype.str, int(w.recv.nbytes))
        return orig_submit(w, *a, **kw)

    sched.submit = shim_submit
    orig_flush = sched.flush

    def _guarded_check() -> None:
        # the sentinel must never change error semantics: a check that
        # cannot complete (a peer is gone or already hung) times out on
        # the star walk and is logged, not raised
        try:
            watch.check()
        except Exception as e:  # noqa: BLE001 - observe-only layer
            from kungfu_tpu.telemetry import log

            log.warn("protowatch boundary check failed: %s", e)

    @functools.wraps(orig_flush)
    def shim_flush(*a, **kw):
        from kungfu_tpu.collective.scheduler import SchedulerClosed

        try:
            orig_flush(*a, **kw)
        except SchedulerClosed:
            raise  # epoch over: peers are swapping sessions, no walk
        except (RuntimeError, ValueError):
            # registration divergence / missing-or-duplicate submission:
            # every live peer raises or checks symmetrically, and this
            # is exactly the moment the window names WHO diverged —
            # check first, then let the engine's error propagate
            _guarded_check()
            raise
        _guarded_check()

    sched.flush = shim_flush


def check(sess) -> bool:
    """Explicit boundary check for the synchronous path (benches, the
    protowatch e2e): call at a step/round boundary on EVERY peer. True
    when the cluster's windows agree."""
    watch = getattr(sess, "_protowatch", None)
    if watch is None:
        return True
    return watch.check()


def stats(sess) -> dict:
    watch = getattr(sess, "_protowatch", None)
    if watch is None:
        return {}
    with watch.lock:
        return {
            "window": len(watch.window),
            "round": watch.round,
            "checks": watch.checks,
            "divergences": watch.divergences,
        }
