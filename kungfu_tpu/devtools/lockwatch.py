"""Runtime lock-order detector (``KF_DEBUG_LOCKS=1``).

kfcheck's KF2xx rules see locks a ``with`` statement *names*; this layer
sees every lock the process actually takes. When installed (from
``kungfu_tpu/__init__`` under the knob, so it precedes every other
kungfu import) it replaces ``threading.Lock``/``RLock`` with
instrumented proxies that maintain:

- a per-thread stack of held locks;
- a process-wide acquisition graph keyed by lock *instance* (a real
  ABBA deadlock is between two specific lock objects; instances carry
  their creation site ``file.py:lineno`` for reporting, and findings
  dedupe at site level so a pool of per-peer locks reports once);
- per-acquisition hold timers.

Before an acquire blocks, the would-be edges ``held -> wanted`` are
added and the graph is searched for a cycle — an ABBA deadlock is
reported at the moment the second thread *tries* the reversed order,
not after the hang. On release, holds longer than
``KF_DEBUG_LOCKS_HELD_MS`` are reported. Reports flow through the
existing telemetry plane: ``lock_order_violation`` / ``lock_long_held``
audit events (journaled by the flight recorder, surfaced by
``info postmortem``) and ``kungfu_debug_lock_*`` metrics.

Known blind spots, stated:

- locks created BEFORE install (only module-level locks of modules
  imported before ``kungfu_tpu``) are not wrapped;
- the edge graph grows with distinct nested lock *pairs* and is never
  pruned (debug mode; nodes only exist for locks that ever nest);
- long-held reporting covers locks CREATED in project code only —
  stdlib-internal locks (subprocess's waitpid lock, Condition
  internals) are order-tracked but not hold-timed, because their hold
  semantics are not ours to fix;
- ``threading.Condition``'s internal waiter locks come from the raw
  allocator and are deliberately invisible.

``KF_DEBUG_LOCKS`` unset means :func:`install` is never called and this
module is never imported — zero overhead, asserted by tests.
"""

from __future__ import annotations

import itertools
import os
import sys
import threading
import time
from typing import Dict, List, Optional, Tuple

_REAL_LOCK = threading.Lock
_REAL_RLOCK = threading.RLock

# graph mutex uses the REAL lock type: the detector must not watch
# itself
_graph_lock = _REAL_LOCK()
# lock seq -> {lock seq: (thread label, acquire site)} first-seen edges
_edges: Dict[int, Dict[int, Tuple[str, str]]] = {}
_sites: Dict[int, str] = {}  # lock seq -> creation site (reporting)
_reported_cycles: set = set()
_reported_held: set = set()
_tls = threading.local()
_seq_counter = itertools.count(1)

_installed = False
_VIOLATIONS = "kungfu_debug_lock_order_violations_total"
_LONG_HELD = "kungfu_debug_lock_long_held_total"
_SITES = "kungfu_debug_lock_sites"


_held_ms_cache: Optional[float] = None


def _held_ms() -> float:
    global _held_ms_cache
    if _held_ms_cache is None:
        from kungfu_tpu import knobs

        _held_ms_cache = float(knobs.get("KF_DEBUG_LOCKS_HELD_MS"))
    return _held_ms_cache


def _caller_frame(depth: int):
    """First frame outside this module, or None."""
    f = sys._getframe(depth)
    while f is not None and f.f_globals.get("__name__") == __name__:
        f = f.f_back
    return f


def _caller_site(depth: int) -> str:
    """file.py:lineno of the first frame outside this module."""
    f = _caller_frame(depth + 1)
    if f is None:
        return "?"
    return f"{os.path.basename(f.f_code.co_filename)}:{f.f_lineno}"


def _ours(path: str) -> bool:
    """Project code (kungfu_tpu/, tests/, interactive snippets) vs
    stdlib/third-party. Long-held reporting is scoped to project-created
    locks: a Popen.wait() legitimately holds subprocess's waitpid lock
    for the child's whole lifetime, and flagging stdlib semantics we
    cannot change is noise. Ordering detection stays global — an ABBA
    cycle through a stdlib lock is still a deadlock."""
    return (
        "kungfu_tpu" in path
        or f"{os.sep}tests{os.sep}" in path
        or path.startswith("<")  # <stdin>, <string>: REPL/driver scripts
    )


# tid -> that thread's held stack. threading.Lock legally supports
# acquire-on-A / release-on-B (handoff patterns in wrapped user code);
# the registry lets a cross-thread release find and clear the holder's
# entry instead of stranding it (a stale entry would emit false
# `held -> wanted` edges from A forever after). All stack MUTATIONS
# happen under _graph_lock so the cross-thread path cannot race the
# owner; reads of a thread's own stack stay lock-free (GIL-safe).
_stacks: Dict[int, List[Tuple[int, str, float]]] = {}


def _stack() -> List[Tuple[int, str, float]]:
    s = getattr(_tls, "stack", None)
    if s is None:
        s = _tls.stack = []
        tid = threading.get_ident()
        with _graph_lock:
            alive = {t.ident for t in threading.enumerate()}
            for dead in [t for t in _stacks if t not in alive and t != tid]:
                del _stacks[dead]
            _stacks[tid] = s
    return s


def _reporting() -> bool:
    return getattr(_tls, "reporting", False)


# Reports are NEVER emitted from the detecting thread: that thread may
# hold arbitrary instrumented locks (a long-held report fires while the
# outer locks of a nest are still held), and log/audit/metrics take
# locks of their own — emitting inline would let the detector introduce
# the very deadlocks it hunts. Findings go through a raw-primitive queue
# (deque + real-lock Condition; a queue.Queue would allocate instrumented
# locks) to a daemon reporter thread that holds nothing.
_report_q: "list" = []
_report_cond = threading.Condition(_REAL_LOCK())
_reporter_started = False
_report_busy = False  # a batch is mid-emission (flush correctness)


def _report(kind: str, counter: str, **detail) -> None:
    detail.setdefault("thread", f"tid:{threading.get_ident()}")
    with _report_cond:
        _report_q.append((kind, counter, detail))
        _report_cond.notify()


def _emit(kind: str, counter: str, detail: dict) -> None:
    _tls.reporting = True
    try:
        from kungfu_tpu.telemetry import audit, log, metrics

        log.warn("lockwatch %s: %s", kind,
                 " ".join(f"{k}={v}" for k, v in detail.items()))
        audit.record_event(kind, **detail)
        metrics.counter(
            counter,
            "Findings of the KF_DEBUG_LOCKS runtime lock detector",
        ).inc()
    except Exception as e:  # noqa: BLE001 - the detector must never kill training
        sys.stderr.write(f"lockwatch: report failed: {e}\n")
    finally:
        _tls.reporting = False


def _reporter_loop() -> None:
    global _report_busy
    while True:
        with _report_cond:
            # kfcheck: disable=KF301 — daemon reporter parks on its work
            # queue; timeout would only add wakeups, process exit reaps it
            _report_cond.wait_for(lambda: _report_q)
            batch, _report_q[:] = list(_report_q), []
            _report_busy = True
        for kind, counter, detail in batch:
            _emit(kind, counter, detail)
        with _report_cond:
            _report_busy = False
            _report_cond.notify_all()


def _ensure_reporter() -> None:
    global _reporter_started
    if not _reporter_started:
        threading.Thread(
            target=_reporter_loop, name="kf-lockwatch-report", daemon=True,
        ).start()
        _reporter_started = True


def flush(timeout: float = 5.0) -> bool:
    """Block until queued findings have been emitted (tests, atexit).
    True when the queue drained in time."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        with _report_cond:
            if not _report_q and not _report_busy:
                return True
        time.sleep(0.01)
    return False


def _find_cycle(start: int, target: int) -> Optional[List[int]]:
    """Path target -> ... -> start in the edge graph (call with the
    would-be edge start->target already conceptually added); a hit means
    start->target closes a cycle."""
    seen = set()
    path: List[int] = []

    def dfs(node: int) -> bool:
        if node == start:
            path.append(node)
            return True
        if node in seen:
            return False
        seen.add(node)
        for nxt in _edges.get(node, ()):
            if dfs(nxt):
                path.append(node)
                return True
        return False

    return list(reversed(path)) if dfs(target) else None


class _DebugLockBase:
    """Proxy around a real lock; subclasses pick the inner type."""

    _reentrant = False

    def __init__(self):
        self._inner = self._make_inner()
        f = _caller_frame(2)
        path = f.f_code.co_filename if f is not None else "?"
        self.site = (
            f"{os.path.basename(path)}:{f.f_lineno}" if f is not None else "?"
        )
        self._held_watch = _ours(path)
        self._seq = next(_seq_counter)

    def _make_inner(self):
        raise NotImplementedError

    # -- instrumentation

    def _before_acquire(self) -> None:
        stack = _stack()
        if any(seq == self._seq for seq, _, _ in stack):
            return  # reentrant re-acquire: no new ordering information
        acquire_site = _caller_site(3)
        # NOT current_thread(): during thread bootstrap that mints a
        # _DummyThread whose Event would recurse into this very path
        me = f"tid:{threading.get_ident()}"
        cycle_msg = None
        with _graph_lock:
            _sites.setdefault(self._seq, self.site)
            for held_seq, held_site, _ in stack:
                _sites.setdefault(held_seq, held_site)
                first = _edges.setdefault(held_seq, {})
                if self._seq not in first:
                    first[self._seq] = (me, acquire_site)
                cycle = _find_cycle(held_seq, self._seq)
                if cycle is not None:
                    names = [
                        f"{_sites.get(s, '?')}#{s}" for s in cycle
                    ]
                    # dedupe at SITE level so a pool of per-peer locks
                    # reports its ordering bug once, not once per pair
                    sig = "->".join(sorted({_sites.get(s, "?")
                                            for s in cycle}))
                    if sig not in _reported_cycles:
                        _reported_cycles.add(sig)
                        other = _edges.get(self._seq, {}).get(held_seq)
                        cycle_msg = {
                            "cycle": "->".join(names + [names[0]]),
                            "acquirer": me,
                            "at": acquire_site,
                            "holding": held_site,
                            "wants": self.site,
                            "reverse_seen": (
                                f"{other[0]} at {other[1]}" if other else "?"
                            ),
                        }
        if cycle_msg is not None:
            _report("lock_order_violation", _VIOLATIONS, **cycle_msg)

    def _on_acquired(self) -> None:
        stack = _stack()
        with _graph_lock:
            stack.append((self._seq, self.site, time.monotonic()))

    def _on_release(self) -> None:
        stack = _stack()
        popped = None
        with _graph_lock:
            for i in range(len(stack) - 1, -1, -1):
                if stack[i][0] == self._seq:
                    popped = stack.pop(i)
                    break
            else:
                # released on a different thread than acquired it:
                # clear the holder's entry or it emits false ordering
                # edges forever after (hold timing still meaningful —
                # the entry carries its acquire timestamp). Match the
                # OLDEST entry for this lock: the real release ran
                # before this bookkeeping, so a racing re-acquire may
                # already have pushed a fresh entry on the new holder's
                # stack — the handoff's stale entry is strictly older
                oldest = None  # (t0, stack, index)
                for other in _stacks.values():
                    for i in range(len(other) - 1, -1, -1):
                        if other[i][0] == self._seq and (
                            oldest is None or other[i][2] < oldest[0]
                        ):
                            oldest = (other[i][2], other, i)
                if oldest is not None:
                    popped = oldest[1].pop(oldest[2])
        if popped is None:
            return
        _, site, t0 = popped
        held = (time.monotonic() - t0) * 1e3
        if self._held_watch and held >= _held_ms():
            # counterless dedup by site: one audit event per
            # site per process, or a pathological lock floods
            # the (bounded) audit ring every release
            if site not in _reported_held:
                _reported_held.add(site)
                _report(
                    "lock_long_held", _LONG_HELD,
                    lock=site, held_ms=round(held, 1),
                    released_at=_caller_site(2),
                )

    # -- lock API

    def acquire(self, blocking: bool = True, timeout: float = -1):
        if _reporting():
            return self._inner.acquire(blocking, timeout)
        if blocking:
            self._before_acquire()
        got = self._inner.acquire(blocking, timeout)
        if got and not _reporting():
            self._on_acquired()
        return got

    def release(self) -> None:
        # real release FIRST: bookkeeping only queues onto the reporter,
        # but keeping zero work between caller and unlock is free safety
        self._inner.release()
        if not _reporting():
            self._on_release()

    def locked(self) -> bool:
        return self._inner.locked()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def __getattr__(self, name):
        # Condition needs _is_owned/_release_save/_acquire_restore on
        # RLocks; forward anything we don't instrument
        return getattr(self._inner, name)

    def __repr__(self) -> str:
        return f"<lockwatch {type(self).__name__} {self.site} {self._inner!r}>"


class _DebugLock(_DebugLockBase):
    def _make_inner(self):
        return _REAL_LOCK()


class _DebugRLock(_DebugLockBase):
    _reentrant = True

    def _make_inner(self):
        return _REAL_RLOCK()

    # Condition prefers these over release()/acquire() on RLocks; without
    # explicit wrappers __getattr__ would hand back the INNER methods and
    # a cond.wait() would leave a stale held-entry ticking toward a false
    # long-held report
    def _release_save(self):
        state = self._inner._release_save()
        if not _reporting():
            self._on_release()
        return state

    def _acquire_restore(self, state) -> None:
        self._inner._acquire_restore(state)
        if not _reporting():
            self._on_acquired()

    def _is_owned(self) -> bool:
        return self._inner._is_owned()


def install() -> bool:
    """Swap threading.Lock/RLock for the instrumented proxies.
    Idempotent; returns True when (already) installed."""
    global _installed
    if _installed:
        return True
    _ensure_reporter()
    threading.Lock = _DebugLock
    threading.RLock = _DebugRLock
    import atexit

    atexit.register(flush, 2.0)  # don't lose findings queued at exit
    _installed = True
    return True


def uninstall() -> None:
    """Restore the real factories and drop detector state (tests).
    Locks created while installed keep working — they proxy real
    primitives."""
    global _installed, _held_ms_cache
    threading.Lock = _REAL_LOCK
    threading.RLock = _REAL_RLOCK
    _held_ms_cache = None
    with _graph_lock:
        _edges.clear()
        _sites.clear()
        _reported_cycles.clear()
        _reported_held.clear()
        for s in _stacks.values():
            del s[:]  # live threads keep their registered list object
    _installed = False


def installed() -> bool:
    return _installed


def edge_count() -> int:
    with _graph_lock:
        return sum(len(v) for v in _edges.values())


def publish_gauges() -> None:
    """Export detector state gauges (called from tests/benches; cheap)."""
    from kungfu_tpu.telemetry import metrics

    with _graph_lock:
        sites = len({_sites.get(s, s) for s in _edges})
    metrics.gauge(
        _SITES, "Lock creation sites in the lockwatch acquisition graph"
    ).set(sites)
