"""CLI driver: ``python -m kungfu_tpu.devtools.kfcheck``.

Exit status is the contract — 0 means the tree is clean (every
suppression justified), 1 means findings, 2 means usage error. CI and
tests/test_kfcheck.py key off it.
"""

from __future__ import annotations

import argparse
import sys

from kungfu_tpu.devtools.kfcheck import core


def _write_knobs_doc(repo_root: str) -> str:
    import os

    from kungfu_tpu import knobs

    path = os.path.join(repo_root, "docs", "knobs.md")
    with open(path, "w", encoding="utf-8") as f:
        f.write(knobs.render_doc())
    return path


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m kungfu_tpu.devtools.kfcheck",
        description="project-specific static analysis for kungfu_tpu "
        "(config registry, lock discipline, thread lifecycle, exception "
        "hygiene, CLI/doc lint)",
    )
    p.add_argument("--json", action="store_true",
                   help="machine-readable findings on stdout")
    p.add_argument("--select", default="",
                   help="comma-separated rule ids to run (default: all; "
                   "stale-suppression findings are skipped for subsets)")
    p.add_argument("--list-rules", action="store_true",
                   help="print every rule id + description and exit")
    p.add_argument("--no-cache", action="store_true",
                   help="bypass the per-file result cache "
                   "(.kfcheck-cache.json): re-parse and re-analyze "
                   "every file")
    p.add_argument("--write-knobs-doc", action="store_true",
                   help="regenerate docs/knobs.md from the knob registry "
                   "and exit")
    args = p.parse_args(argv)

    if args.write_knobs_doc:
        path = _write_knobs_doc(core.REPO_ROOT)
        sys.stdout.write(f"wrote {path}\n")
        return 0

    core._ensure_rules_loaded()
    if args.list_rules:
        for rid in core.known_rule_ids():
            r = core.RULES.get(rid)
            desc = r.help if r is not None else core._META_RULES[rid]
            name = r.name if r is not None else "meta"
            sys.stdout.write(f"{rid}  {name}\n    {desc}\n")
        return 0

    select = None
    if args.select:
        select = [s.strip().upper() for s in args.select.split(",")
                  if s.strip()]
        unknown = [s for s in select if s not in core.known_rule_ids()]
        if unknown:
            sys.stderr.write(
                f"unknown rule id(s): {', '.join(unknown)} "
                f"(see --list-rules)\n"
            )
            return 2

    findings = core.run_project(select=select, use_cache=not args.no_cache)
    if args.json:
        sys.stdout.write(core.to_json(findings))
    else:
        for f in findings:
            sys.stdout.write(f.render() + "\n")
        n = len(findings)
        sys.stdout.write(
            "kfcheck: clean\n" if n == 0
            else f"kfcheck: {n} finding{'s' if n != 1 else ''}\n"
        )
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
