"""kfcheck driver: file walking, AST contexts, rule registry, inline
suppressions, the findings model and the per-file result cache.

Design: rules are plain functions registered with :func:`rule`. File
rules get a :class:`FileContext` (path, source, AST, module constants,
comment map); project rules get the :class:`Project` (every file context
plus repo paths) and run once — they own cross-file invariants like
"docs/knobs.md matches the registry" or the KF7xx distributed-protocol
family.

Caching (ISSUE 12 satellite): the tier-1 full-tree gate used to re-parse
every file on every run. Now each file's *raw* file-rule findings plus
the per-file **facts** the project rules consume (module string
constants, imports, knob literals, environment reads, wire-name call
sites, suppressions) are cached in ``<repo>/.kfcheck-cache.json`` keyed
on (content sha256, rule-set version = hash of core.py + rules.py).
A cache hit skips ``ast.parse`` and the tokenizer entirely; the AST
stays available lazily (the :attr:`FileContext.tree` property parses on
first access) for the few project rules that need real trees (KF701
reads exactly two files). Suppressions are re-applied per run from the
cached facts, so a cached file behaves identically to a fresh one.
``--no-cache`` (or ``run_project(use_cache=False)``) bypasses it.

Suppressions are line-anchored comments::

    x = risky()  # kfcheck: disable=KF200 — send timeout bounds the hold

    # kfcheck: disable=KF301 — waiting ON the abort signal is abort-aware
    flag.wait()

A suppression must carry a justification after an em-dash/`--`/`-`
separator; bare ``disable=KF200`` is a KF001 finding. Suppressions that
match no finding are KF003 findings — a stale suppression hides nothing
but still rots trust in the ones that matter. ``disable-file=`` scopes a
rule off for a whole file (same justification contract).
"""

from __future__ import annotations

import ast
import dataclasses
import hashlib
import io
import json
import os
import re
import tokenize
from typing import Callable, Dict, Iterable, List, Optional, Tuple

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))))

# rule ids for the driver's own meta findings
PARSE_ERROR = "KF000"
SUPPRESSION_NO_REASON = "KF001"
SUPPRESSION_UNKNOWN_RULE = "KF002"
SUPPRESSION_UNUSED = "KF003"

_META_RULES = {
    PARSE_ERROR: "file does not parse",
    SUPPRESSION_NO_REASON: "suppression missing a written justification",
    SUPPRESSION_UNKNOWN_RULE: "suppression names an unknown rule",
    SUPPRESSION_UNUSED: "suppression matches no finding (stale)",
}

# a whole-string knob name: KF_WIRE, KF_CONFIG_ALGO ... but not the bare
# "KF_"/"KF_CONFIG_" prefixes used for startswith() filters (shared by
# the fact extractor here and rules KF100/KF101)
KNOB_RE = re.compile(r"^KF_[A-Z0-9_]*[A-Z0-9]$")


def _attr_chain(node: ast.AST) -> Optional[str]:
    """Dotted name for Name/Attribute chains ("os.environ.get"), else
    None."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


@dataclasses.dataclass(frozen=True)
class Finding:
    rule: str
    path: str  # repo-relative, posix separators
    line: int
    message: str

    def render(self) -> str:
        return f"{self.path}:{self.line}: {self.rule} {self.message}"

    def to_json(self) -> dict:
        return dataclasses.asdict(self)


@dataclasses.dataclass
class Suppression:
    line: int  # line the comment sits on
    rules: Tuple[str, ...]
    reason: str
    file_scope: bool
    target: int  # code line covered (== line for trailing comments; the
    # next non-comment/non-blank line for comment-only lines, so a
    # justification may span several comment lines above the code)
    used: bool = False

    def covers(self, rule: str, line: int) -> bool:
        if rule not in self.rules:
            return False
        if self.file_scope:
            return True
        return line == self.target


_SUPPRESS_RE = re.compile(
    r"#\s*kfcheck:\s*(disable(?:-file)?)\s*=\s*"
    r"([A-Za-z0-9_,\s]*?)\s*(?:(?:—|–|--|-)\s*(.*))?$"
)

# environment-read call chains (fact extraction for KF101)
_ENV_READ_CHAINS = ("os.environ.get", "environ.get", "os.getenv", "getenv")

# wire-name call sites (fact extraction for KF700): method/ctor name ->
# (positional index of the name argument, keyword name). Workspace's
# `name` is the rendezvous identity every walk message derives from;
# the others take an explicit wire/consensus name.
_NAME_SITES = {
    "Workspace": (3, "name"),
    "all_gather_shards": (1, "name"),
    "broadcast_bytes": (1, "name"),
    "bytes_consensus": (1, "name"),
    "consensus": (1, "name"),
    "barrier": (0, "tag"),
}

_UNPARSED = object()


def _name_desc(expr: Optional[ast.expr]) -> Optional[dict]:
    """Compact, JSON-able descriptor of a wire-name expression (cached as
    a fact). `const` descriptors are the KF700 findings-to-be; `name` and
    `attr` resolve against module constants at rule time; `dyn` means the
    name carries runtime content (round stamps, identities) and passes."""
    if expr is None:
        return None
    if isinstance(expr, ast.Constant) and isinstance(expr.value, str):
        return {"t": "const", "v": expr.value}
    if isinstance(expr, ast.JoinedStr):
        if any(isinstance(v, ast.FormattedValue) for v in expr.values):
            return {"t": "dyn"}
        parts = [v.value for v in expr.values
                 if isinstance(v, ast.Constant) and isinstance(v.value, str)]
        return {"t": "const", "v": "".join(parts)}
    if isinstance(expr, ast.BinOp) and isinstance(expr.op, ast.Add):
        left = _name_desc(expr.left)
        right = _name_desc(expr.right)
        if (left and right and left["t"] == "const"
                and right["t"] == "const"):
            return {"t": "const", "v": left["v"] + right["v"]}
        return {"t": "dyn"}
    if isinstance(expr, ast.Name):
        return {"t": "name", "v": expr.id}
    if isinstance(expr, ast.Attribute) and isinstance(expr.value, ast.Name):
        return {"t": "attr", "base": expr.value.id, "attr": expr.attr}
    return {"t": "dyn"}


class FileContext:
    """One analyzed file. Constructed either by parsing (fresh) or from
    cached facts (no parse); :attr:`tree` parses lazily in the cached
    case so project rules that need a real AST still get one."""

    def __init__(self, path: str, relpath: str, source: str,
                 cached: Optional[dict] = None, sha: Optional[str] = None):
        self.path = path
        self.relpath = relpath
        self.source = source
        # load_files passes the digest it already computed for the cache
        # lookup; direct constructions (fixture tests) compute their own
        self.sha = sha or hashlib.sha256(source.encode("utf-8")).hexdigest()
        self.lines = source.splitlines()
        self._tree = _UNPARSED
        self.parse_error: Optional[str] = None
        self.suppressions: List[Suppression] = []
        self.malformed: List[Finding] = []  # KF001 raised during parse
        # facts (project-rule inputs; all JSON-able)
        self.str_constants: Dict[str, str] = {}
        # local name -> (source module basename, original name) for
        # `from pkg.mod import NAME [as alias]` — lets rules resolve
        # constants imported from other analyzed modules
        self.imported_names: Dict[str, Tuple[str, str]] = {}
        self.knob_literals: List[Tuple[int, str]] = []
        self.env_reads: List[Tuple[int, dict]] = []
        self.name_sites: List[Tuple[int, str, dict]] = []
        self.from_cache = cached is not None
        # raw file-rule findings restored from the cache (None = compute)
        self.cached_findings: Optional[List[Finding]] = None
        if cached is not None:
            self._load_cached(cached)
        else:
            self._parse()
            self._scan_comments()
            if self._tree is not None and self._tree is not _UNPARSED:
                self._extract_facts()

    # -- parsing ------------------------------------------------------

    @property
    def tree(self) -> Optional[ast.AST]:
        if self._tree is _UNPARSED:
            self._parse()
        return self._tree

    def _parse(self) -> None:
        try:
            self._tree = ast.parse(self.source, filename=self.path)
        except SyntaxError as e:
            self._tree = None
            self.parse_error = f"{e.msg} (line {e.lineno})"

    def walk(self) -> Iterable[ast.AST]:
        if self.tree is None:
            return ()
        return ast.walk(self.tree)

    # -- fact extraction (one walk, everything project rules consume) --

    def _extract_facts(self) -> None:
        for node in self._tree.body:
            if (
                isinstance(node, ast.Assign)
                and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and isinstance(node.value, ast.Constant)
                and isinstance(node.value.value, str)
            ):
                self.str_constants[node.targets[0].id] = node.value.value
            elif isinstance(node, ast.ImportFrom) and node.module:
                mod = node.module.rsplit(".", 1)[-1]
                for alias in node.names:
                    self.imported_names[alias.asname or alias.name] = (
                        mod, alias.name,
                    )
        for node in ast.walk(self._tree):
            if (isinstance(node, ast.Constant)
                    and isinstance(node.value, str)
                    and KNOB_RE.match(node.value)):
                self.knob_literals.append((node.lineno, node.value))
            elif isinstance(node, ast.Call):
                self._extract_env_read(node)
                self._extract_name_site(node)
            elif (
                isinstance(node, ast.Subscript)
                and isinstance(node.ctx, ast.Load)
                and _attr_chain(node.value) in ("os.environ", "environ")
            ):
                desc = _name_desc(node.slice)
                if desc is not None:
                    self.env_reads.append((node.lineno, desc))

    def _extract_env_read(self, node: ast.Call) -> None:
        chain = _attr_chain(node.func)
        if chain in _ENV_READ_CHAINS and node.args:
            desc = _name_desc(node.args[0])
            if desc is not None:
                self.env_reads.append((node.lineno, desc))

    def _extract_name_site(self, node: ast.Call) -> None:
        seg = None
        if isinstance(node.func, ast.Attribute):
            seg = node.func.attr
        elif isinstance(node.func, ast.Name):
            seg = node.func.id
        if seg not in _NAME_SITES:
            return
        if seg != "Workspace" and not isinstance(node.func, ast.Attribute):
            # the collective entry points are methods (sess.barrier(...));
            # bare-name calls of e.g. `consensus` are unrelated helpers
            return
        pos, kw = _NAME_SITES[seg]
        expr = None
        for k in node.keywords:
            if k.arg == kw:
                expr = k.value
                break
        if expr is None and len(node.args) > pos:
            expr = node.args[pos]
        if expr is None:
            return
        desc = _name_desc(expr)
        if desc is not None:
            self.name_sites.append((node.lineno, seg, desc))

    # -- suppression comments -----------------------------------------

    def _scan_comments(self) -> None:
        try:
            tokens = tokenize.generate_tokens(io.StringIO(self.source).readline)
            comments = [
                (t.start[0], t.start[1], t.string)
                for t in tokens
                if t.type == tokenize.COMMENT
            ]
        except (tokenize.TokenError, IndentationError, SyntaxError):
            return
        for lineno, col, text in comments:
            m = _SUPPRESS_RE.search(text)
            if m is None:
                if "kfcheck:" in text:
                    self.malformed.append(Finding(
                        SUPPRESSION_NO_REASON, self.relpath, lineno,
                        f"unparseable kfcheck comment: {text.strip()!r}",
                    ))
                continue
            kind, rules_raw, reason = m.group(1), m.group(2), m.group(3)
            rules = tuple(
                r.strip().upper() for r in rules_raw.split(",") if r.strip()
            )
            reason = (reason or "").strip()
            if not rules or not reason:
                self.malformed.append(Finding(
                    SUPPRESSION_NO_REASON, self.relpath, lineno,
                    "suppression must name rule(s) and carry a written "
                    "justification: `# kfcheck: disable=KFxxx — <why>`",
                ))
                continue
            target = lineno
            if self.lines[lineno - 1].strip().startswith("#"):
                # comment-only line: cover the next code line, skipping
                # the rest of the justification block
                target = lineno + 1
                while target <= len(self.lines):
                    stripped = self.lines[target - 1].strip()
                    if stripped and not stripped.startswith("#"):
                        break
                    target += 1
            self.suppressions.append(Suppression(
                line=lineno,
                rules=rules,
                reason=reason,
                file_scope=(kind == "disable-file"),
                target=target,
            ))

    # -- cache (de)serialization --------------------------------------

    def facts_to_cache(self) -> dict:
        return {
            "parse_error": self.parse_error,
            "str_constants": self.str_constants,
            "imported_names": {
                k: list(v) for k, v in self.imported_names.items()
            },
            "knob_literals": [list(t) for t in self.knob_literals],
            "env_reads": [list(t) for t in self.env_reads],
            "name_sites": [list(t) for t in self.name_sites],
            "suppressions": [
                {
                    "line": s.line, "rules": list(s.rules),
                    "reason": s.reason, "file_scope": s.file_scope,
                    "target": s.target,
                }
                for s in self.suppressions
            ],
            "malformed": [f.to_json() for f in self.malformed],
        }

    def _load_cached(self, cached: dict) -> None:
        facts = cached["facts"]
        self.parse_error = facts["parse_error"]
        self.str_constants = dict(facts["str_constants"])
        self.imported_names = {
            k: tuple(v) for k, v in facts["imported_names"].items()
        }
        self.knob_literals = [tuple(t) for t in facts["knob_literals"]]
        self.env_reads = [(t[0], t[1]) for t in facts["env_reads"]]
        self.name_sites = [(t[0], t[1], t[2]) for t in facts["name_sites"]]
        self.suppressions = [
            Suppression(
                line=s["line"], rules=tuple(s["rules"]), reason=s["reason"],
                file_scope=s["file_scope"], target=s["target"],
            )
            for s in facts["suppressions"]
        ]
        self.malformed = [Finding(**f) for f in facts["malformed"]]
        self.cached_findings = [Finding(**f) for f in cached["findings"]]


class Project:
    """Everything the project-level rules need: the analyzed package,
    the repo root (docs live there) and every parsed file."""

    def __init__(self, pkg_root: str, repo_root: str,
                 files: List[FileContext]):
        self.pkg_root = pkg_root
        self.repo_root = repo_root
        self.files = files


@dataclasses.dataclass(frozen=True)
class Rule:
    id: str
    name: str
    help: str
    fn: Callable
    scope: str  # "file" | "project"


RULES: Dict[str, Rule] = {}


def rule(id: str, name: str, help: str, *, scope: str = "file"):
    """Register a rule. File rules: fn(ctx: FileContext) -> [Finding].
    Project rules: fn(project: Project) -> [Finding]."""

    def deco(fn):
        if id in RULES:
            raise ValueError(f"rule {id} registered twice")
        RULES[id] = Rule(id=id, name=name, help=help, fn=fn, scope=scope)
        return fn

    return deco


def known_rule_ids() -> List[str]:
    return sorted(set(RULES) | set(_META_RULES))


def _iter_py_files(root: str) -> Iterable[str]:
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = sorted(
            d for d in dirnames if d != "__pycache__" and not d.startswith(".")
        )
        for fn in sorted(filenames):
            if fn.endswith(".py"):
                yield os.path.join(dirpath, fn)


# ---------------------------------------------------------------------
# the per-file result cache
# ---------------------------------------------------------------------

CACHE_NAME = ".kfcheck-cache.json"

_ruleset_version_memo: Optional[str] = None


def ruleset_version() -> str:
    """Hash of the analyzer's own source (core.py + rules.py): any rule
    edit — new rule, changed pattern, changed fact extraction —
    invalidates every cache entry. Self-maintaining, no manual bump."""
    global _ruleset_version_memo
    if _ruleset_version_memo is None:
        h = hashlib.sha256()
        here = os.path.dirname(os.path.abspath(__file__))
        for name in ("core.py", "rules.py"):
            with open(os.path.join(here, name), "rb") as f:
                h.update(f.read())
        _ruleset_version_memo = h.hexdigest()
    return _ruleset_version_memo


class ResultCache:
    """Per-file raw findings + facts keyed on (content sha, rule-set
    version). Unreadable/corrupt/mismatched caches are silently treated
    as empty — the cache can only skip work, never change results."""

    def __init__(self, repo_root: str):
        self.path = os.path.join(repo_root, CACHE_NAME)
        self.files: Dict[str, dict] = {}
        self.dirty = False
        try:
            with open(self.path, encoding="utf-8") as f:
                data = json.load(f)
            if data.get("version") == ruleset_version():
                self.files = data.get("files", {})
        except (OSError, ValueError, KeyError, TypeError):
            pass

    def lookup(self, relpath: str, sha: str) -> Optional[dict]:
        entry = self.files.get(relpath)
        if entry is not None and entry.get("sha") == sha:
            return entry
        return None

    def store(self, ctx: FileContext, findings: List[Finding]) -> None:
        self.files[ctx.relpath] = {
            "sha": ctx.sha,
            "facts": ctx.facts_to_cache(),
            "findings": [f.to_json() for f in findings],
        }
        self.dirty = True

    def prune(self, live_relpaths: Iterable[str]) -> None:
        live = set(live_relpaths)
        for gone in [p for p in self.files if p not in live]:
            del self.files[gone]
            self.dirty = True

    def save(self) -> None:
        if not self.dirty:
            return
        tmp = self.path + ".tmp"
        try:
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump(
                    {"version": ruleset_version(), "files": self.files}, f
                )
            os.replace(tmp, self.path)
        except OSError:
            # a read-only checkout just runs uncached
            try:
                os.unlink(tmp)
            except OSError:
                pass


def load_files(pkg_root: str, repo_root: str,
               cache: Optional[ResultCache] = None) -> List[FileContext]:
    out = []
    for path in _iter_py_files(pkg_root):
        rel = os.path.relpath(path, repo_root).replace(os.sep, "/")
        with open(path, encoding="utf-8") as f:
            source = f.read()
        sha = hashlib.sha256(source.encode("utf-8")).hexdigest()
        cached = cache.lookup(rel, sha) if cache is not None else None
        out.append(FileContext(path, rel, source, cached=cached, sha=sha))
    return out


def _ensure_rules_loaded() -> None:
    # import for side effect: each module registers its rules
    from kungfu_tpu.devtools.kfcheck import rules as _rules  # noqa: F401


def run_project(
    pkg_root: Optional[str] = None,
    repo_root: Optional[str] = None,
    select: Optional[Iterable[str]] = None,
    use_cache: bool = True,
) -> List[Finding]:
    """Run every (selected) rule over the package; returns unsuppressed
    findings plus suppression-hygiene findings, sorted by location.

    With `use_cache` (the default) unchanged files skip parsing and the
    file-scope rules, reusing cached raw findings; the cache is only
    WRITTEN by full runs (`select=None` — a subset run computes a subset
    of findings, which must never masquerade as a file's complete
    result)."""
    _ensure_rules_loaded()
    repo_root = repo_root or REPO_ROOT
    pkg_root = pkg_root or os.path.join(repo_root, "kungfu_tpu")
    selected = set(select) if select else None

    cache = ResultCache(repo_root) if use_cache else None
    files = load_files(pkg_root, repo_root, cache)
    project = Project(pkg_root, repo_root, files)

    findings: List[Finding] = []
    raw: List[Finding] = []

    file_rules = [r for r in RULES.values() if r.scope == "file"]
    for ctx in files:
        findings.extend(ctx.malformed)
        for sup in ctx.suppressions:
            for rid in sup.rules:
                if rid not in RULES and rid not in _META_RULES:
                    findings.append(Finding(
                        SUPPRESSION_UNKNOWN_RULE, ctx.relpath, sup.line,
                        f"suppression names unknown rule {rid!r} "
                        f"(known: {', '.join(known_rule_ids())})",
                    ))
        if ctx.parse_error is not None:
            findings.append(Finding(
                PARSE_ERROR, ctx.relpath, 1, ctx.parse_error))
            continue
        if ctx.cached_findings is not None:
            raw.extend(
                f for f in ctx.cached_findings
                if selected is None or f.rule in selected
            )
            continue
        computed: List[Finding] = []
        for r in file_rules:
            if selected is not None and r.id not in selected:
                continue
            computed.extend(r.fn(ctx))
        raw.extend(computed)
        if cache is not None and selected is None:
            cache.store(ctx, computed)

    for r in RULES.values():
        if r.scope != "project":
            continue
        if selected is not None and r.id not in selected:
            continue
        raw.extend(r.fn(project))

    # apply suppressions
    by_rel: Dict[str, FileContext] = {f.relpath: f for f in files}
    for f in raw:
        ctx = by_rel.get(f.path)
        sup = None
        if ctx is not None:
            for s in ctx.suppressions:
                if s.covers(f.rule, f.line):
                    sup = s
                    break
        if sup is not None:
            sup.used = True
        else:
            findings.append(f)

    # stale suppressions (skip when a rule subset is selected: the rules
    # that would have used them did not run)
    if selected is None:
        for ctx in files:
            for s in ctx.suppressions:
                if not s.used:
                    findings.append(Finding(
                        SUPPRESSION_UNUSED, ctx.relpath, s.line,
                        f"suppression for {','.join(s.rules)} matches no "
                        "finding — remove it (stale suppressions rot trust "
                        "in the live ones)",
                    ))

    if cache is not None and selected is None:
        cache.prune(f.relpath for f in files)
        cache.save()

    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return findings


def to_json(findings: List[Finding]) -> str:
    return json.dumps([f.to_json() for f in findings], indent=2) + "\n"
