"""kfcheck driver: file walking, AST contexts, rule registry, inline
suppressions and the findings model.

Design: rules are plain functions registered with :func:`rule`. File
rules get a :class:`FileContext` (path, source, AST, module constants,
comment map); project rules get the :class:`Project` (every file context
plus repo paths) and run once — they own cross-file invariants like
"docs/knobs.md matches the registry".

Suppressions are line-anchored comments::

    x = risky()  # kfcheck: disable=KF200 — send timeout bounds the hold

    # kfcheck: disable=KF301 — waiting ON the abort signal is abort-aware
    flag.wait()

A suppression must carry a justification after an em-dash/`--`/`-`
separator; bare ``disable=KF200`` is a KF001 finding. Suppressions that
match no finding are KF003 findings — a stale suppression hides nothing
but still rots trust in the ones that matter. ``disable-file=`` scopes a
rule off for a whole file (same justification contract).
"""

from __future__ import annotations

import ast
import dataclasses
import io
import json
import os
import re
import tokenize
from typing import Callable, Dict, Iterable, List, Optional, Tuple

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))))

# rule ids for the driver's own meta findings
PARSE_ERROR = "KF000"
SUPPRESSION_NO_REASON = "KF001"
SUPPRESSION_UNKNOWN_RULE = "KF002"
SUPPRESSION_UNUSED = "KF003"

_META_RULES = {
    PARSE_ERROR: "file does not parse",
    SUPPRESSION_NO_REASON: "suppression missing a written justification",
    SUPPRESSION_UNKNOWN_RULE: "suppression names an unknown rule",
    SUPPRESSION_UNUSED: "suppression matches no finding (stale)",
}


@dataclasses.dataclass(frozen=True)
class Finding:
    rule: str
    path: str  # repo-relative, posix separators
    line: int
    message: str

    def render(self) -> str:
        return f"{self.path}:{self.line}: {self.rule} {self.message}"

    def to_json(self) -> dict:
        return dataclasses.asdict(self)


@dataclasses.dataclass
class Suppression:
    line: int  # line the comment sits on
    rules: Tuple[str, ...]
    reason: str
    file_scope: bool
    target: int  # code line covered (== line for trailing comments; the
    # next non-comment/non-blank line for comment-only lines, so a
    # justification may span several comment lines above the code)
    used: bool = False

    def covers(self, rule: str, line: int) -> bool:
        if rule not in self.rules:
            return False
        if self.file_scope:
            return True
        return line == self.target


_SUPPRESS_RE = re.compile(
    r"#\s*kfcheck:\s*(disable(?:-file)?)\s*=\s*"
    r"([A-Za-z0-9_,\s]*?)\s*(?:(?:—|–|--|-)\s*(.*))?$"
)


class FileContext:
    def __init__(self, path: str, relpath: str, source: str):
        self.path = path
        self.relpath = relpath
        self.source = source
        self.tree: Optional[ast.AST] = None
        self.parse_error: Optional[str] = None
        try:
            self.tree = ast.parse(source, filename=path)
        except SyntaxError as e:
            self.parse_error = f"{e.msg} (line {e.lineno})"
        self.lines = source.splitlines()
        self.suppressions: List[Suppression] = []
        self.malformed: List[Finding] = []  # KF001 raised during parse
        self._scan_comments()
        # module-level NAME = "literal" constants (knob-name resolution)
        self.str_constants: Dict[str, str] = {}
        # local name -> (source module basename, original name) for
        # `from pkg.mod import NAME [as alias]` — lets rules resolve
        # constants imported from other analyzed modules
        self.imported_names: Dict[str, Tuple[str, str]] = {}
        if self.tree is not None:
            for node in self.tree.body:
                if (
                    isinstance(node, ast.Assign)
                    and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)
                    and isinstance(node.value, ast.Constant)
                    and isinstance(node.value.value, str)
                ):
                    self.str_constants[node.targets[0].id] = node.value.value
                elif isinstance(node, ast.ImportFrom) and node.module:
                    mod = node.module.rsplit(".", 1)[-1]
                    for alias in node.names:
                        self.imported_names[alias.asname or alias.name] = (
                            mod, alias.name,
                        )

    def _scan_comments(self) -> None:
        try:
            tokens = tokenize.generate_tokens(io.StringIO(self.source).readline)
            comments = [
                (t.start[0], t.start[1], t.string)
                for t in tokens
                if t.type == tokenize.COMMENT
            ]
        except (tokenize.TokenError, IndentationError, SyntaxError):
            return
        for lineno, col, text in comments:
            m = _SUPPRESS_RE.search(text)
            if m is None:
                if "kfcheck:" in text:
                    self.malformed.append(Finding(
                        SUPPRESSION_NO_REASON, self.relpath, lineno,
                        f"unparseable kfcheck comment: {text.strip()!r}",
                    ))
                continue
            kind, rules_raw, reason = m.group(1), m.group(2), m.group(3)
            rules = tuple(
                r.strip().upper() for r in rules_raw.split(",") if r.strip()
            )
            reason = (reason or "").strip()
            if not rules or not reason:
                self.malformed.append(Finding(
                    SUPPRESSION_NO_REASON, self.relpath, lineno,
                    "suppression must name rule(s) and carry a written "
                    "justification: `# kfcheck: disable=KFxxx — <why>`",
                ))
                continue
            target = lineno
            if self.lines[lineno - 1].strip().startswith("#"):
                # comment-only line: cover the next code line, skipping
                # the rest of the justification block
                target = lineno + 1
                while target <= len(self.lines):
                    stripped = self.lines[target - 1].strip()
                    if stripped and not stripped.startswith("#"):
                        break
                    target += 1
            self.suppressions.append(Suppression(
                line=lineno,
                rules=rules,
                reason=reason,
                file_scope=(kind == "disable-file"),
                target=target,
            ))

    def walk(self) -> Iterable[ast.AST]:
        if self.tree is None:
            return ()
        return ast.walk(self.tree)


class Project:
    """Everything the project-level rules need: the analyzed package,
    the repo root (docs live there) and every parsed file."""

    def __init__(self, pkg_root: str, repo_root: str,
                 files: List[FileContext]):
        self.pkg_root = pkg_root
        self.repo_root = repo_root
        self.files = files


@dataclasses.dataclass(frozen=True)
class Rule:
    id: str
    name: str
    help: str
    fn: Callable
    scope: str  # "file" | "project"


RULES: Dict[str, Rule] = {}


def rule(id: str, name: str, help: str, *, scope: str = "file"):
    """Register a rule. File rules: fn(ctx: FileContext) -> [Finding].
    Project rules: fn(project: Project) -> [Finding]."""

    def deco(fn):
        if id in RULES:
            raise ValueError(f"rule {id} registered twice")
        RULES[id] = Rule(id=id, name=name, help=help, fn=fn, scope=scope)
        return fn

    return deco


def known_rule_ids() -> List[str]:
    return sorted(set(RULES) | set(_META_RULES))


def _iter_py_files(root: str) -> Iterable[str]:
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = sorted(
            d for d in dirnames if d != "__pycache__" and not d.startswith(".")
        )
        for fn in sorted(filenames):
            if fn.endswith(".py"):
                yield os.path.join(dirpath, fn)


def load_files(pkg_root: str, repo_root: str) -> List[FileContext]:
    out = []
    for path in _iter_py_files(pkg_root):
        rel = os.path.relpath(path, repo_root).replace(os.sep, "/")
        with open(path, encoding="utf-8") as f:
            out.append(FileContext(path, rel, f.read()))
    return out


def _ensure_rules_loaded() -> None:
    # import for side effect: each module registers its rules
    from kungfu_tpu.devtools.kfcheck import rules as _rules  # noqa: F401


def run_project(
    pkg_root: Optional[str] = None,
    repo_root: Optional[str] = None,
    select: Optional[Iterable[str]] = None,
) -> List[Finding]:
    """Run every (selected) rule over the package; returns unsuppressed
    findings plus suppression-hygiene findings, sorted by location."""
    _ensure_rules_loaded()
    repo_root = repo_root or REPO_ROOT
    pkg_root = pkg_root or os.path.join(repo_root, "kungfu_tpu")
    selected = set(select) if select else None

    files = load_files(pkg_root, repo_root)
    project = Project(pkg_root, repo_root, files)

    findings: List[Finding] = []
    raw: List[Finding] = []

    for ctx in files:
        findings.extend(ctx.malformed)
        for sup in ctx.suppressions:
            for rid in sup.rules:
                if rid not in RULES and rid not in _META_RULES:
                    findings.append(Finding(
                        SUPPRESSION_UNKNOWN_RULE, ctx.relpath, sup.line,
                        f"suppression names unknown rule {rid!r} "
                        f"(known: {', '.join(known_rule_ids())})",
                    ))
        if ctx.parse_error is not None:
            findings.append(Finding(
                PARSE_ERROR, ctx.relpath, 1, ctx.parse_error))
            continue
        for r in RULES.values():
            if r.scope != "file":
                continue
            if selected is not None and r.id not in selected:
                continue
            raw.extend(r.fn(ctx))

    for r in RULES.values():
        if r.scope != "project":
            continue
        if selected is not None and r.id not in selected:
            continue
        raw.extend(r.fn(project))

    # apply suppressions
    by_rel: Dict[str, FileContext] = {f.relpath: f for f in files}
    for f in raw:
        ctx = by_rel.get(f.path)
        sup = None
        if ctx is not None:
            for s in ctx.suppressions:
                if s.covers(f.rule, f.line):
                    sup = s
                    break
        if sup is not None:
            sup.used = True
        else:
            findings.append(f)

    # stale suppressions (skip when a rule subset is selected: the rules
    # that would have used them did not run)
    if selected is None:
        for ctx in files:
            for s in ctx.suppressions:
                if not s.used:
                    findings.append(Finding(
                        SUPPRESSION_UNUSED, ctx.relpath, s.line,
                        f"suppression for {','.join(s.rules)} matches no "
                        "finding — remove it (stale suppressions rot trust "
                        "in the live ones)",
                    ))

    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return findings


def to_json(findings: List[Finding]) -> str:
    return json.dumps([f.to_json() for f in findings], indent=2) + "\n"
