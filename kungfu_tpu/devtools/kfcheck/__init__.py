"""kfcheck: project-wide static analysis for kungfu_tpu (ISSUE 7).

The engine is a deeply multithreaded system whose failure modes (PRs
4-6) were all hand-found concurrency bugs; generic linters know nothing
about our lock hierarchy, knob registry or telemetry discipline. kfcheck
is the project-specific layer: an AST-based driver with pluggable rules,
a machine-readable findings format and inline suppressions that REQUIRE
a written justification.

Run: ``python -m kungfu_tpu.devtools.kfcheck [--json] [paths...]``

Rule families (see docs/devtools.md):

- KF0xx  driver/suppression hygiene (parse errors, bad suppressions)
- KF1xx  config registry (KF_* knobs declared + read via kungfu_tpu.knobs)
- KF2xx  lock discipline (no blocking under a lock, declared lock order)
- KF3xx  thread lifecycle (daemon or bounded join, bounded waits)
- KF4xx  exception hygiene (no silent broad excepts)
- KF5xx  CLI surface (no bare print outside cli/info)
- KF6xx  telemetry docs (metric families documented, no ghost rows)
- KF7xx  distributed protocol (ISSUE 12, the first cross-module rules:
         wire-name discipline, knob-consensus coverage, collective
         symmetry, caller-buffer ownership) — paired with the runtime
         collective-order sentinel, devtools/protowatch.py

Suppression format, enforced::

    # kfcheck: disable=KF201 — <why this is safe, in words>

A suppression without a justification is itself a finding (KF001), and
an unused suppression is a finding (KF003), so the suppression surface
cannot rot.
"""

from kungfu_tpu.devtools.kfcheck.core import (  # noqa: F401
    Finding,
    run_project,
)
