"""kfcheck rules: the project-specific invariants, one family per
section (see docs/devtools.md for the operator-facing descriptions).

Everything here is AST-shaped, not grep-shaped: docstrings and comments
can mention ``print()`` or ``KF_FOO`` freely, only real call/literal
nodes count. Rules err toward reporting — a false positive costs one
justified suppression line, a false negative costs a 3am deadlock.

Static limits, stated rather than hidden:

- KF101 resolves environ keys that are string literals, module-level
  constants, or ``module.CONST`` attributes of analyzed modules; a key
  computed at runtime is invisible to it (KF100 still catches the
  knob-name literal wherever it is spelled).
- KF200/KF201 reason about ``with <lock>:`` blocks where the context
  expression *names* a lock (its last segment contains ``lock``/
  ``mutex``/``cond``); a lock hidden behind an arbitrary name is
  invisible. The runtime detector (devtools/lockwatch.py) has no such
  blind spot — the two layers are complementary.
- KF300 accepts a thread as "provably joined" when the same module
  joins a receiver of the same name with a bounded timeout; it does not
  do interprocedural dataflow.
"""

from __future__ import annotations

import ast
import os
import re
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from kungfu_tpu.devtools.kfcheck.core import (
    FileContext,
    Finding,
    Project,
    rule,
)

# ---------------------------------------------------------------------
# shared AST helpers
# ---------------------------------------------------------------------


def _attr_chain(node: ast.AST) -> Optional[str]:
    """Dotted name for Name/Attribute chains ("os.environ.get"), else
    None."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _last_segment(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


def _kw(call: ast.Call, name: str) -> Optional[ast.expr]:
    for k in call.keywords:
        if k.arg == name:
            return k.value
    return None


def _is_true(node: Optional[ast.expr]) -> bool:
    return isinstance(node, ast.Constant) and node.value is True


def _is_false(node: Optional[ast.expr]) -> bool:
    return isinstance(node, ast.Constant) and node.value is False


def _has_timeout(call: ast.Call, *, positional_at: Optional[int] = None) -> bool:
    if _kw(call, "timeout") is not None:
        return True
    if positional_at is not None and len(call.args) > positional_at:
        return True
    return False


def _module_basename(relpath: str) -> str:
    """"kungfu_tpu/telemetry/flight.py" -> "flight"; packages resolve to
    their directory name so `from x import pkg` attribute reads work."""
    base = os.path.basename(relpath)
    if base == "__init__.py":
        return os.path.basename(os.path.dirname(relpath))
    return base[:-3] if base.endswith(".py") else base


# ---------------------------------------------------------------------
# KF1xx — config registry
# ---------------------------------------------------------------------

# a whole-string knob name: KF_WIRE, KF_CONFIG_ALGO ... but not the bare
# "KF_"/"KF_CONFIG_" prefixes used for startswith() filters
KNOB_RE = re.compile(r"^KF_[A-Z0-9_]*[A-Z0-9]$")

# the registry itself is the only place allowed to spell environ
# plumbing for knobs
_REGISTRY_FILE = "kungfu_tpu/knobs.py"


def _declared_knobs() -> Set[str]:
    from kungfu_tpu import knobs

    return set(knobs.names())


@rule(
    "KF100",
    "undeclared-knob",
    "every KF_* env literal must be declared in kungfu_tpu/knobs.py "
    "(name, default, parser, doc) — scattered ad-hoc knobs are how 48 "
    "of them went undocumented",
    scope="project",
)
def check_knob_declared(project: Project) -> List[Finding]:
    declared = _declared_knobs()
    out = []
    for ctx in project.files:
        if ctx.relpath == _REGISTRY_FILE:
            continue
        for node in ctx.walk():
            if not (isinstance(node, ast.Constant)
                    and isinstance(node.value, str)):
                continue
            if KNOB_RE.match(node.value) and node.value not in declared:
                out.append(Finding(
                    "KF100", ctx.relpath, node.lineno,
                    f"KF_* literal {node.value!r} is not declared in the "
                    "knob registry (kungfu_tpu/knobs.py) — declare it "
                    "with a default, parser and doc string",
                ))
    return out


def _environ_read_key(node: ast.Call) -> Optional[ast.expr]:
    """The key expression when `node` reads the environment
    (os.environ.get / os.getenv), else None."""
    chain = _attr_chain(node.func)
    if chain in ("os.environ.get", "environ.get", "os.getenv", "getenv"):
        return node.args[0] if node.args else None
    return None


def _resolve_key(
    expr: Optional[ast.expr],
    ctx: FileContext,
    cross: Dict[str, Dict[str, str]],
) -> Optional[str]:
    if expr is None:
        return None
    if isinstance(expr, ast.Constant) and isinstance(expr.value, str):
        return expr.value
    if isinstance(expr, ast.Name):
        if expr.id in ctx.str_constants:
            return ctx.str_constants[expr.id]
        imp = ctx.imported_names.get(expr.id)
        if imp is not None:
            return cross.get(imp[0], {}).get(imp[1])
        return None
    if isinstance(expr, ast.Attribute) and isinstance(expr.value, ast.Name):
        return cross.get(expr.value.id, {}).get(expr.attr)
    return None


@rule(
    "KF101",
    "env-read-bypasses-registry",
    "KF_* environment variables are read only through kungfu_tpu.knobs "
    "(get/raw/is_set) — direct os.environ reads re-invent parsing and "
    "default semantics per call site",
    scope="project",
)
def check_env_reads(project: Project) -> List[Finding]:
    # module-basename -> {CONST: value} for `flight.DIR_ENV`-style keys
    cross: Dict[str, Dict[str, str]] = {}
    for ctx in project.files:
        cross.setdefault(_module_basename(ctx.relpath), {}).update(
            ctx.str_constants
        )
    out = []
    for ctx in project.files:
        if ctx.relpath == _REGISTRY_FILE:
            continue
        for node in ctx.walk():
            key = None
            if isinstance(node, ast.Call):
                key = _environ_read_key(node)
            elif (
                isinstance(node, ast.Subscript)
                and isinstance(node.ctx, ast.Load)
                and _attr_chain(node.value) in ("os.environ", "environ")
            ):
                key = node.slice
            if key is None:
                continue
            resolved = _resolve_key(key, ctx, cross)
            if resolved is not None and resolved.startswith("KF_"):
                out.append(Finding(
                    "KF101", ctx.relpath, node.lineno,
                    f"direct environment read of {resolved!r} — go "
                    "through kungfu_tpu.knobs (get/raw/is_set) so "
                    "parsing, defaults and docs stay single-sourced",
                ))
    return out


@rule(
    "KF102",
    "knobs-doc-stale",
    "docs/knobs.md is generated from the registry and must match it "
    "byte-for-byte (regenerate: python -m kungfu_tpu.devtools.kfcheck "
    "--write-knobs-doc)",
    scope="project",
)
def check_knobs_doc(project: Project) -> List[Finding]:
    from kungfu_tpu import knobs

    doc_path = os.path.join(project.repo_root, "docs", "knobs.md")
    rel = "docs/knobs.md"
    if not os.path.exists(doc_path):
        return [Finding(
            "KF102", rel, 1,
            "docs/knobs.md does not exist — generate it with "
            "`python -m kungfu_tpu.devtools.kfcheck --write-knobs-doc`",
        )]
    with open(doc_path, encoding="utf-8") as f:
        on_disk = f.read()
    want = knobs.render_doc()
    if on_disk != want:
        # first differing line makes the finding actionable
        lineno = 1
        for i, (a, b) in enumerate(
            zip(on_disk.splitlines(), want.splitlines()), start=1
        ):
            if a != b:
                lineno = i
                break
        else:
            lineno = min(len(on_disk.splitlines()),
                         len(want.splitlines())) + 1
        return [Finding(
            "KF102", rel, lineno,
            "docs/knobs.md is stale vs the registry — regenerate with "
            "`python -m kungfu_tpu.devtools.kfcheck --write-knobs-doc`",
        )]
    return []


# ---------------------------------------------------------------------
# KF2xx — lock discipline
# ---------------------------------------------------------------------

_LOCKISH = re.compile(r"lock|mutex|(^|_)cond(ition)?$", re.IGNORECASE)


def _lock_name(expr: ast.expr) -> Optional[str]:
    """Last segment of a with-context expression when it names a lock
    ("self._lock" -> "_lock"), else None."""
    seg = _last_segment(expr)
    if seg is not None and _LOCKISH.search(seg):
        return seg
    return None


def _blocking_reason(call: ast.Call) -> Optional[str]:
    """A short human label when `call` can block indefinitely (or for a
    humanly-long time), else None."""
    chain = _attr_chain(call.func)
    if chain in ("time.sleep", "sleep"):
        return "time.sleep"
    if chain and chain.startswith("subprocess."):
        return chain
    if chain in ("urllib.request.urlopen", "request.urlopen", "urlopen"):
        return "urlopen"
    if not isinstance(call.func, ast.Attribute):
        return None
    attr = call.func.attr
    if attr == "wait" and not call.args and not _has_timeout(call):
        return ".wait() without timeout"
    if attr == "wait_for" and not _has_timeout(call, positional_at=1):
        return ".wait_for() without timeout"
    if attr == "join" and not call.args and not _has_timeout(call):
        return ".join() without timeout"
    if attr == "get" and not call.args and not call.keywords:
        # zero-arg .get() is a blocking queue get (dict.get needs a key)
        return ".get() without timeout"
    if attr in ("recv", "recv_into", "accept", "connect", "sendall"):
        return f"socket .{attr}()"
    return None


class _LockWalker(ast.NodeVisitor):
    """Tracks the stack of with-held locks while walking one file;
    collects KF200 (blocking under a lock) and KF201 (hierarchy)
    findings. Nested function bodies are walked with a FRESH stack:
    a closure defined under a lock does not run under it."""

    def __init__(self, ctx: FileContext, order: Sequence[str]):
        self.ctx = ctx
        self.order = list(order)
        self.stack: List[Tuple[str, int]] = []  # (lock name, lineno)
        self.findings: List[Finding] = []

    # -- helpers

    def _rank(self, name: str) -> Optional[int]:
        try:
            return self.order.index(name)
        except ValueError:
            return None

    def _enter_lock(self, name: str, lineno: int) -> None:
        if self.stack:
            outer, outer_line = self.stack[-1]
            if not self.order:
                self.findings.append(Finding(
                    "KF201", self.ctx.relpath, lineno,
                    f"nested lock acquisition {outer!r} (line "
                    f"{outer_line}) -> {name!r} but the module declares "
                    "no lock hierarchy — add `_KF_LOCK_ORDER = "
                    f"({outer!r}, {name!r})` at module level",
                ))
            else:
                ro, ri = self._rank(outer), self._rank(name)
                if ri is None:
                    self.findings.append(Finding(
                        "KF201", self.ctx.relpath, lineno,
                        f"lock {name!r} acquired under {outer!r} but is "
                        "not in the module's _KF_LOCK_ORDER declaration",
                    ))
                elif ro is None:
                    self.findings.append(Finding(
                        "KF201", self.ctx.relpath, lineno,
                        f"lock {outer!r} (held at line {outer_line}) is "
                        "not in the module's _KF_LOCK_ORDER declaration",
                    ))
                elif ri <= ro:
                    self.findings.append(Finding(
                        "KF201", self.ctx.relpath, lineno,
                        f"lock order violation: {name!r} acquired while "
                        f"holding {outer!r} (line {outer_line}), but "
                        "_KF_LOCK_ORDER declares "
                        f"{name!r} <= {outer!r}",
                    ))
        self.stack.append((name, lineno))

    # -- visitors

    def _fresh(self, node: ast.AST) -> None:
        saved, self.stack = self.stack, []
        self.generic_visit(node)
        self.stack = saved

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        self._fresh(node)

    def visit_AsyncFunctionDef(self, node) -> None:
        self._fresh(node)

    def visit_Lambda(self, node: ast.Lambda) -> None:
        self._fresh(node)

    def visit_With(self, node: ast.With) -> None:
        entered = 0
        for item in node.items:
            name = _lock_name(item.context_expr)
            if name is not None:
                self._enter_lock(name, node.lineno)
                entered += 1
        for stmt in node.body:
            self.visit(stmt)
        for _ in range(entered):
            self.stack.pop()

    visit_AsyncWith = visit_With

    def visit_Call(self, node: ast.Call) -> None:
        if self.stack:
            reason = _blocking_reason(node)
            if reason is not None and not self._is_cond_wait_idiom(node):
                held = self.stack[-1][0]
                self.findings.append(Finding(
                    "KF200", self.ctx.relpath, node.lineno,
                    f"blocking call ({reason}) while holding lock "
                    f"{held!r} — move the blocking work outside the "
                    "critical section or bound it",
                ))
        self.generic_visit(node)

    def _is_cond_wait_idiom(self, node: ast.Call) -> bool:
        """`with cond: cond.wait[_for](...)` — Condition.wait RELEASES
        the held lock for the duration, so it is not blocking-under-lock
        (KF301 still judges its unboundedness)."""
        if not (isinstance(node.func, ast.Attribute)
                and node.func.attr in ("wait", "wait_for")):
            return False
        receiver = _last_segment(node.func.value)
        return receiver is not None and receiver == self.stack[-1][0]


def _declared_lock_order(ctx: FileContext) -> List[str]:
    if ctx.tree is None:
        return []
    for node in ctx.tree.body:
        if (
            isinstance(node, ast.Assign)
            and len(node.targets) == 1
            and isinstance(node.targets[0], ast.Name)
            and node.targets[0].id == "_KF_LOCK_ORDER"
            and isinstance(node.value, (ast.Tuple, ast.List))
        ):
            return [
                e.value for e in node.value.elts
                if isinstance(e, ast.Constant) and isinstance(e.value, str)
            ]
    return []


@rule(
    "KF200",
    "blocking-under-lock",
    "no call that can block indefinitely (sleep, subprocess, socket "
    "recv/send, unbounded wait/join/get) while holding a lock — a "
    "stalled peer must never extend a critical section",
)
def check_blocking_under_lock(ctx: FileContext) -> List[Finding]:
    if ctx.tree is None:
        return []
    w = _LockWalker(ctx, _declared_lock_order(ctx))
    w.visit(ctx.tree)
    return [f for f in w.findings if f.rule == "KF200"]


@rule(
    "KF201",
    "lock-hierarchy",
    "modules that nest lock acquisitions must declare the order as "
    "`_KF_LOCK_ORDER = (outer, ..., inner)` and every nesting must "
    "respect it — ABBA deadlocks are ordering bugs, caught here at "
    "review time and by lockwatch at runtime",
)
def check_lock_hierarchy(ctx: FileContext) -> List[Finding]:
    if ctx.tree is None:
        return []
    w = _LockWalker(ctx, _declared_lock_order(ctx))
    w.visit(ctx.tree)
    return [f for f in w.findings if f.rule == "KF201"]


# ---------------------------------------------------------------------
# KF3xx — thread lifecycle
# ---------------------------------------------------------------------


def _is_thread_ctor(call: ast.Call) -> bool:
    chain = _attr_chain(call.func)
    return chain in ("threading.Thread", "Thread")


@rule(
    "KF300",
    "thread-lifecycle",
    "every threading.Thread is daemon=True or joined with a bounded "
    "timeout — a forgotten non-daemon thread turns every crash into a "
    "hang at interpreter exit",
)
def check_thread_lifecycle(ctx: FileContext) -> List[Finding]:
    if ctx.tree is None:
        return []
    # receivers that get `X.daemon = True` or a bounded `X.join(...)`
    # anywhere in the module (same-name matching, not dataflow)
    daemoned: Set[str] = set()
    bounded_join: Set[str] = set()
    assigned_to: Dict[int, str] = {}  # id(call node) -> receiver segment
    for node in ctx.walk():
        if isinstance(node, ast.Assign):
            for tgt in node.targets:
                if (
                    isinstance(tgt, ast.Attribute)
                    and tgt.attr == "daemon"
                    and _is_true(node.value)
                ):
                    seg = _last_segment(tgt.value)
                    if seg:
                        daemoned.add(seg)
                seg = _last_segment(tgt)
                if seg and isinstance(node.value, ast.Call):
                    assigned_to[id(node.value)] = seg
        elif isinstance(node, ast.Call):
            if (
                isinstance(node.func, ast.Attribute)
                and node.func.attr == "join"
                and (node.args or _kw(node, "timeout") is not None)
            ):
                seg = _last_segment(node.func.value)
                if seg:
                    bounded_join.add(seg)
    out = []
    for node in ctx.walk():
        if not (isinstance(node, ast.Call) and _is_thread_ctor(node)):
            continue
        if _is_true(_kw(node, "daemon")):
            continue
        seg = assigned_to.get(id(node))
        if seg is not None and (seg in daemoned or seg in bounded_join):
            continue
        out.append(Finding(
            "KF300", ctx.relpath, node.lineno,
            "Thread created without daemon=True and without a bounded "
            "join in this module — pass daemon=True or join it with a "
            "timeout",
        ))
    return out


@rule(
    "KF301",
    "unbounded-wait",
    "every Event.wait/Condition.wait(_for)/Popen.wait is bounded — an "
    "unbounded wait on a signal that never comes is a silent hang; "
    "abort-aware waits get a justified suppression",
)
def check_unbounded_wait(ctx: FileContext) -> List[Finding]:
    if ctx.tree is None:
        return []
    out = []
    for node in ctx.walk():
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)):
            continue
        attr = node.func.attr
        if attr == "wait" and not node.args and not _has_timeout(node):
            out.append(Finding(
                "KF301", ctx.relpath, node.lineno,
                "unbounded .wait() — pass a timeout (retry in a loop if "
                "the wait is legitimate) so a lost signal cannot hang "
                "this thread forever",
            ))
        elif attr == "wait_for" and not _has_timeout(node, positional_at=1):
            out.append(Finding(
                "KF301", ctx.relpath, node.lineno,
                "unbounded .wait_for() — pass a timeout so a lost "
                "notify cannot hang this thread forever",
            ))
    return out


@rule(
    "KF302",
    "unbounded-join",
    "every .join() is bounded — joining a thread/process that never "
    "exits hangs shutdown paths; join with a timeout and handle the "
    "still-alive case",
)
def check_unbounded_join(ctx: FileContext) -> List[Finding]:
    if ctx.tree is None:
        return []
    out = []
    for node in ctx.walk():
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr == "join"
            and not node.args
            and not node.keywords
        ):
            out.append(Finding(
                "KF302", ctx.relpath, node.lineno,
                "unbounded .join() — pass a timeout and handle the "
                "still-running case (log, escalate, or abandon as "
                "daemon)",
            ))
    return out


# the modules that run background stages against a session epoch: their
# threads MUST register with the abort protocol (a declared joinable
# set that close() joins), or a forgotten stage outlives the epoch and
# keeps walking against a dead transport token
_KF303_MODULES = (
    "kungfu_tpu/collective/scheduler.py",
    "kungfu_tpu/collective/pipeline.py",
)

_KF303_FACTORY = "_spawn_registered"


def _declared_joinable_threads(ctx: FileContext) -> Optional[List[str]]:
    """The module-level `_KF_JOINABLE_THREADS` tuple of thread names, or
    None when the module declares none."""
    if ctx.tree is None:
        return None
    for node in ctx.tree.body:
        if (
            isinstance(node, ast.Assign)
            and len(node.targets) == 1
            and isinstance(node.targets[0], ast.Name)
            and node.targets[0].id == "_KF_JOINABLE_THREADS"
            and isinstance(node.value, (ast.Tuple, ast.List))
        ):
            return [
                e.value for e in node.value.elts
                if isinstance(e, ast.Constant) and isinstance(e.value, str)
            ]
    return None


class _ThreadSiteWalker(ast.NodeVisitor):
    """Collects (enclosing function name, Thread-ctor node) pairs and
    every `*._spawn_registered(...)` call in one file."""

    def __init__(self):
        self.func_stack: List[str] = []
        self.ctors: List[Tuple[Optional[str], ast.Call]] = []
        self.spawns: List[ast.Call] = []

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        self.func_stack.append(node.name)
        self.generic_visit(node)
        self.func_stack.pop()

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Call(self, node: ast.Call) -> None:
        if _is_thread_ctor(node):
            enclosing = self.func_stack[-1] if self.func_stack else None
            self.ctors.append((enclosing, node))
        if _last_segment(node.func) == _KF303_FACTORY:
            self.spawns.append(node)
        self.generic_visit(node)


@rule(
    "KF303",
    "unregistered-scheduler-thread",
    "threads started by the collective scheduler/pipeline modules must "
    "register with the abort protocol: constructed only inside the "
    "_spawn_registered factory, spawned with a literal name declared in "
    "the module-level _KF_JOINABLE_THREADS joinable-set (close() joins "
    "exactly that set), so a future stage cannot silently outlive a "
    "session epoch",
)
def check_scheduler_threads(ctx: FileContext) -> List[Finding]:
    if ctx.relpath not in _KF303_MODULES or ctx.tree is None:
        return []
    w = _ThreadSiteWalker()
    w.visit(ctx.tree)
    declared = _declared_joinable_threads(ctx)
    out: List[Finding] = []
    if (w.ctors or w.spawns) and declared is None:
        first = w.ctors[0][1] if w.ctors else w.spawns[0]
        out.append(Finding(
            "KF303", ctx.relpath, first.lineno,
            "this module starts threads but declares no "
            "_KF_JOINABLE_THREADS joinable-set — declare the thread "
            "names at module level so close() provably joins them all",
        ))
        declared = []
    for enclosing, node in w.ctors:
        if enclosing != _KF303_FACTORY:
            out.append(Finding(
                "KF303", ctx.relpath, node.lineno,
                f"threading.Thread constructed outside {_KF303_FACTORY} "
                "— scheduler/pipeline threads must go through the "
                "registering factory (named, declared, tracked for "
                "close() to join)",
            ))
    used: Set[str] = set()
    for node in w.spawns:
        arg0 = node.args[0] if node.args else None
        if not (isinstance(arg0, ast.Constant) and isinstance(arg0.value, str)):
            out.append(Finding(
                "KF303", ctx.relpath, node.lineno,
                f"{_KF303_FACTORY} must be called with a literal thread "
                "name (the declared joinable-set is matched statically)",
            ))
            continue
        used.add(arg0.value)
        if declared is not None and arg0.value not in declared:
            out.append(Finding(
                "KF303", ctx.relpath, node.lineno,
                f"thread name {arg0.value!r} is not declared in "
                "_KF_JOINABLE_THREADS — add it so the joinable-set "
                "stays the complete inventory",
            ))
    for name in declared or []:
        if name not in used:
            out.append(Finding(
                "KF303", ctx.relpath, 1,
                f"_KF_JOINABLE_THREADS declares {name!r} but no "
                f"{_KF303_FACTORY} call spawns it — drop the stale "
                "entry (a rotting inventory hides real leaks)",
            ))
    return out


# ---------------------------------------------------------------------
# KF4xx — exception hygiene
# ---------------------------------------------------------------------

_LOG_FNS = frozenset({
    "debug", "info", "warn", "warning", "error", "exception", "critical",
    "fatal", "echo",
})


def _is_broad(handler: ast.ExceptHandler) -> Optional[str]:
    t = handler.type
    if t is None:
        return "bare except:"
    names = []
    if isinstance(t, ast.Tuple):
        names = [_last_segment(e) for e in t.elts]
    else:
        names = [_last_segment(t)]
    for n in names:
        if n in ("Exception", "BaseException"):
            return f"except {n}"
    return None


def _handler_accounts(handler: ast.ExceptHandler) -> bool:
    """True when the handler re-raises, logs, audits, exits, prints
    (CLI surfaces), or *uses the bound exception* — capturing the error
    into a list that a waiter re-raises is channeling, not swallowing."""
    for node in ast.walk(handler):
        if isinstance(node, ast.Raise):
            return True
        if isinstance(node, ast.Call):
            chain = _attr_chain(node.func)
            if chain in ("sys.exit", "os._exit"):
                return True
            if isinstance(node.func, ast.Attribute):
                if node.func.attr in _LOG_FNS:
                    return True
                if node.func.attr == "record_event":
                    return True
            elif isinstance(node.func, ast.Name):
                if node.func.id in _LOG_FNS | {"record_event", "print"}:
                    return True
        if (
            handler.name is not None
            and isinstance(node, ast.Name)
            and isinstance(node.ctx, ast.Load)
            and node.id == handler.name
        ):
            return True
    return False


@rule(
    "KF400",
    "silent-broad-except",
    "a bare/broad except must log through telemetry.log, record an "
    "audit event, or re-raise — errors that vanish here are the ones "
    "postmortems cannot explain",
)
def check_silent_broad_except(ctx: FileContext) -> List[Finding]:
    if ctx.tree is None:
        return []
    out = []
    for node in ctx.walk():
        if not isinstance(node, ast.ExceptHandler):
            continue
        broad = _is_broad(node)
        if broad is None:
            continue
        if _handler_accounts(node):
            continue
        out.append(Finding(
            "KF400", ctx.relpath, node.lineno,
            f"{broad} swallows without logging or re-raising — log via "
            "telemetry.log, record an audit event, narrow the type, or "
            "re-raise",
        ))
    return out


# ---------------------------------------------------------------------
# KF5xx — CLI surface
# ---------------------------------------------------------------------

_PRINT_EXEMPT = ("kungfu_tpu/runner/cli.py",)
_PRINT_EXEMPT_PREFIX = ("kungfu_tpu/info/",)


@rule(
    "KF500",
    "bare-print",
    "no bare print() outside the CLI surfaces (runner/cli.py, info/) — "
    "everything else routes through kungfu_tpu.telemetry.log so output "
    "is leveled, rank-prefixed and capturable",
)
def check_bare_print(ctx: FileContext) -> List[Finding]:
    if ctx.tree is None:
        return []
    if ctx.relpath in _PRINT_EXEMPT or ctx.relpath.startswith(
        _PRINT_EXEMPT_PREFIX
    ):
        return []
    out = []
    for node in ctx.walk():
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Name)
            and node.func.id == "print"
        ):
            out.append(Finding(
                "KF500", ctx.relpath, node.lineno,
                "bare print() — use kungfu_tpu.telemetry.log (or "
                "log.echo() for CLI result lines)",
            ))
    return out


# ---------------------------------------------------------------------
# KF6xx — telemetry docs
# ---------------------------------------------------------------------

_METRIC_RE = re.compile(r'"(kungfu_[a-z0-9_]+[a-z0-9])"')

# rendered by bespoke renderers (monitor/net.py rate gauges), not
# registered via a string literal at one call site
_RENDERED_ONLY = frozenset({"kungfu_egress_rate", "kungfu_ingress_rate"})


def _source_metric_names(project: Project) -> Set[str]:
    names: Set[str] = set()
    for ctx in project.files:
        names.update(_METRIC_RE.findall(ctx.source))
    return names


def _telemetry_doc(project: Project) -> Optional[Tuple[str, List[str]]]:
    path = os.path.join(project.repo_root, "docs", "telemetry.md")
    try:
        with open(path, encoding="utf-8") as f:
            text = f.read()
    except OSError:
        return None
    return text, text.splitlines()


@rule(
    "KF600",
    "metric-undocumented",
    "every kungfu_* metric family registered anywhere in the package "
    "appears in docs/telemetry.md — an undocumented family is invisible "
    "to the operator staring at a dashboard at 3am",
    scope="project",
)
def check_metrics_documented(project: Project) -> List[Finding]:
    names = _source_metric_names(project)
    out = []
    if len(names) <= 30:
        # the scan must keep finding the registry — a rename must not
        # silently turn this rule into a no-op
        out.append(Finding(
            "KF600", "docs/telemetry.md", 1,
            f"metric-name scan found only {len(names)} families — the "
            "lexical scan looks broken (rename?), fix the rule before "
            "trusting it",
        ))
        return out
    got = _telemetry_doc(project)
    if got is None:
        return [Finding("KF600", "docs/telemetry.md", 1,
                        "docs/telemetry.md is missing")]
    doc, _ = got
    for name in sorted(names):
        if name not in doc:
            out.append(Finding(
                "KF600", "docs/telemetry.md", 1,
                f"metric family {name!r} is registered in the package "
                "but absent from docs/telemetry.md — add it to the "
                "metrics table",
            ))
    return out


@rule(
    "KF601",
    "metric-ghost-row",
    "metric families named in docs/telemetry.md's table must still "
    "exist in code — stale rows mislead operators as much as missing "
    "ones",
    scope="project",
)
def check_metric_ghosts(project: Project) -> List[Finding]:
    names = _source_metric_names(project) | _RENDERED_ONLY
    got = _telemetry_doc(project)
    if got is None:
        return []  # KF600 already reports the missing doc
    _, lines = got
    rows = [
        (i, l) for i, l in enumerate(lines, start=1)
        if l.startswith("| `kungfu_")
    ]
    out = []
    if len(rows) <= 20:
        out.append(Finding(
            "KF601", "docs/telemetry.md", 1,
            "metrics table not found where expected (fewer than 20 "
            "`| \\`kungfu_...\\`` rows) — the doc layout moved, fix the "
            "rule",
        ))
        return out
    for lineno, row in rows:
        for doc_name in re.findall(r"`(kungfu_[a-z0-9_]+)`",
                                   row.split("|")[1]):
            if doc_name not in names:
                out.append(Finding(
                    "KF601", "docs/telemetry.md", lineno,
                    f"docs/telemetry.md documents {doc_name!r} but no "
                    "code registers it — drop the stale row",
                ))
    return out
