"""kfcheck rules: the project-specific invariants, one family per
section (see docs/devtools.md for the operator-facing descriptions).

Everything here is AST-shaped, not grep-shaped: docstrings and comments
can mention ``print()`` or ``KF_FOO`` freely, only real call/literal
nodes count. Rules err toward reporting — a false positive costs one
justified suppression line, a false negative costs a 3am deadlock.

Static limits, stated rather than hidden:

- KF101 resolves environ keys that are string literals, module-level
  constants, or ``module.CONST`` attributes of analyzed modules; a key
  computed at runtime is invisible to it (KF100 still catches the
  knob-name literal wherever it is spelled).
- KF200/KF201 reason about ``with <lock>:`` blocks where the context
  expression *names* a lock (its last segment contains ``lock``/
  ``mutex``/``cond``); a lock hidden behind an arbitrary name is
  invisible. The runtime detector (devtools/lockwatch.py) has no such
  blind spot — the two layers are complementary.
- KF300 accepts a thread as "provably joined" when the same module
  joins a receiver of the same name with a bounded timeout; it does not
  do interprocedural dataflow.
- KF700 sees names the call site *spells*: literals, module constants,
  constant-folded concatenations and f-strings without interpolation
  are findings; any interpolated f-string passes, even one whose
  interpolated parts are round-invariant. The runtime sentinel
  (devtools/protowatch.py) covers that blind spot — like KF2xx and
  lockwatch, the two layers are complementary.
- KF702 is the *lexical shadow* of the registration-divergence runtime
  error: it sees rank conditionals whose test names rank/identity
  attributes and collective calls spelled as method calls in either
  branch. Point-to-point traffic (client.send / endpoint.recv) is
  deliberately out of scope — send/recv asymmetry under a rank guard is
  how rooted walks are built.
- KF703 recognizes caller-owned buffers by the module's own naming
  conventions (`.recv` workspace fields, the segmented walk's `acc`
  alias, loop variables iterating `.params`) and abort scopes by name
  (`cancel`/`abort`/`_abort`); a buffer aliased to an arbitrary name is
  invisible.
"""

from __future__ import annotations

import ast
import os
import re
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from kungfu_tpu.devtools.kfcheck.core import (
    KNOB_RE,
    FileContext,
    Finding,
    Project,
    _attr_chain,
    rule,
)

# ---------------------------------------------------------------------
# shared AST helpers (chain resolution lives in core — the fact
# extractor and the rules must agree on what an expression names)
# ---------------------------------------------------------------------


def _last_segment(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


def _kw(call: ast.Call, name: str) -> Optional[ast.expr]:
    for k in call.keywords:
        if k.arg == name:
            return k.value
    return None


def _is_true(node: Optional[ast.expr]) -> bool:
    return isinstance(node, ast.Constant) and node.value is True


def _is_false(node: Optional[ast.expr]) -> bool:
    return isinstance(node, ast.Constant) and node.value is False


def _has_timeout(call: ast.Call, *, positional_at: Optional[int] = None) -> bool:
    if _kw(call, "timeout") is not None:
        return True
    if positional_at is not None and len(call.args) > positional_at:
        return True
    return False


def _module_basename(relpath: str) -> str:
    """"kungfu_tpu/telemetry/flight.py" -> "flight"; packages resolve to
    their directory name so `from x import pkg` attribute reads work."""
    base = os.path.basename(relpath)
    if base == "__init__.py":
        return os.path.basename(os.path.dirname(relpath))
    return base[:-3] if base.endswith(".py") else base


# ---------------------------------------------------------------------
# KF1xx — config registry
# ---------------------------------------------------------------------

# the registry itself is the only place allowed to spell environ
# plumbing for knobs
_REGISTRY_FILE = "kungfu_tpu/knobs.py"


def _declared_knobs() -> Set[str]:
    from kungfu_tpu import knobs

    return set(knobs.names())


def _cross_constants(project: Project) -> Dict[str, Dict[str, str]]:
    """module-basename -> {CONST: value} for `flight.DIR_ENV`-style
    cross-module constant resolution (from the per-file facts)."""
    cross: Dict[str, Dict[str, str]] = {}
    for ctx in project.files:
        cross.setdefault(_module_basename(ctx.relpath), {}).update(
            ctx.str_constants
        )
    return cross


def _resolve_desc(
    desc: dict,
    ctx: FileContext,
    cross: Dict[str, Dict[str, str]],
) -> Optional[str]:
    """Constant value of a cached name/key descriptor (see
    core._name_desc), or None when it carries runtime content."""
    t = desc.get("t")
    if t == "const":
        return desc["v"]
    if t == "name":
        if desc["v"] in ctx.str_constants:
            return ctx.str_constants[desc["v"]]
        imp = ctx.imported_names.get(desc["v"])
        if imp is not None:
            return cross.get(imp[0], {}).get(imp[1])
        return None
    if t == "attr":
        return cross.get(desc["base"], {}).get(desc["attr"])
    return None


@rule(
    "KF100",
    "undeclared-knob",
    "every KF_* env literal must be declared in kungfu_tpu/knobs.py "
    "(name, default, parser, doc) — scattered ad-hoc knobs are how 48 "
    "of them went undocumented",
    scope="project",
)
def check_knob_declared(project: Project) -> List[Finding]:
    declared = _declared_knobs()
    out = []
    for ctx in project.files:
        if ctx.relpath == _REGISTRY_FILE:
            continue
        for lineno, literal in ctx.knob_literals:
            if literal not in declared:
                out.append(Finding(
                    "KF100", ctx.relpath, lineno,
                    f"KF_* literal {literal!r} is not declared in the "
                    "knob registry (kungfu_tpu/knobs.py) — declare it "
                    "with a default, parser and doc string",
                ))
    return out


@rule(
    "KF101",
    "env-read-bypasses-registry",
    "KF_* environment variables are read only through kungfu_tpu.knobs "
    "(get/raw/is_set) — direct os.environ reads re-invent parsing and "
    "default semantics per call site",
    scope="project",
)
def check_env_reads(project: Project) -> List[Finding]:
    cross = _cross_constants(project)
    out = []
    for ctx in project.files:
        if ctx.relpath == _REGISTRY_FILE:
            continue
        for lineno, desc in ctx.env_reads:
            resolved = _resolve_desc(desc, ctx, cross)
            if resolved is not None and resolved.startswith("KF_"):
                out.append(Finding(
                    "KF101", ctx.relpath, lineno,
                    f"direct environment read of {resolved!r} — go "
                    "through kungfu_tpu.knobs (get/raw/is_set) so "
                    "parsing, defaults and docs stay single-sourced",
                ))
    return out


@rule(
    "KF102",
    "knobs-doc-stale",
    "docs/knobs.md is generated from the registry and must match it "
    "byte-for-byte (regenerate: python -m kungfu_tpu.devtools.kfcheck "
    "--write-knobs-doc)",
    scope="project",
)
def check_knobs_doc(project: Project) -> List[Finding]:
    from kungfu_tpu import knobs

    doc_path = os.path.join(project.repo_root, "docs", "knobs.md")
    rel = "docs/knobs.md"
    if not os.path.exists(doc_path):
        return [Finding(
            "KF102", rel, 1,
            "docs/knobs.md does not exist — generate it with "
            "`python -m kungfu_tpu.devtools.kfcheck --write-knobs-doc`",
        )]
    with open(doc_path, encoding="utf-8") as f:
        on_disk = f.read()
    want = knobs.render_doc()
    if on_disk != want:
        # first differing line makes the finding actionable
        lineno = 1
        for i, (a, b) in enumerate(
            zip(on_disk.splitlines(), want.splitlines()), start=1
        ):
            if a != b:
                lineno = i
                break
        else:
            lineno = min(len(on_disk.splitlines()),
                         len(want.splitlines())) + 1
        return [Finding(
            "KF102", rel, lineno,
            "docs/knobs.md is stale vs the registry — regenerate with "
            "`python -m kungfu_tpu.devtools.kfcheck --write-knobs-doc`",
        )]
    return []


# ---------------------------------------------------------------------
# KF2xx — lock discipline
# ---------------------------------------------------------------------

_LOCKISH = re.compile(r"lock|mutex|(^|_)cond(ition)?$", re.IGNORECASE)


def _lock_name(expr: ast.expr) -> Optional[str]:
    """Last segment of a with-context expression when it names a lock
    ("self._lock" -> "_lock"), else None."""
    seg = _last_segment(expr)
    if seg is not None and _LOCKISH.search(seg):
        return seg
    return None


def _blocking_reason(call: ast.Call) -> Optional[str]:
    """A short human label when `call` can block indefinitely (or for a
    humanly-long time), else None."""
    chain = _attr_chain(call.func)
    if chain in ("time.sleep", "sleep"):
        return "time.sleep"
    if chain and chain.startswith("subprocess."):
        return chain
    if chain in ("urllib.request.urlopen", "request.urlopen", "urlopen"):
        return "urlopen"
    if not isinstance(call.func, ast.Attribute):
        return None
    attr = call.func.attr
    if attr == "wait" and not call.args and not _has_timeout(call):
        return ".wait() without timeout"
    if attr == "wait_for" and not _has_timeout(call, positional_at=1):
        return ".wait_for() without timeout"
    if attr == "join" and not call.args and not _has_timeout(call):
        return ".join() without timeout"
    if attr == "get" and not call.args and not call.keywords:
        # zero-arg .get() is a blocking queue get (dict.get needs a key)
        return ".get() without timeout"
    if attr in ("recv", "recv_into", "accept", "connect", "sendall"):
        return f"socket .{attr}()"
    return None


class _LockWalker(ast.NodeVisitor):
    """Tracks the stack of with-held locks while walking one file;
    collects KF200 (blocking under a lock) and KF201 (hierarchy)
    findings. Nested function bodies are walked with a FRESH stack:
    a closure defined under a lock does not run under it."""

    def __init__(self, ctx: FileContext, order: Sequence[str]):
        self.ctx = ctx
        self.order = list(order)
        self.stack: List[Tuple[str, int]] = []  # (lock name, lineno)
        self.findings: List[Finding] = []

    # -- helpers

    def _rank(self, name: str) -> Optional[int]:
        try:
            return self.order.index(name)
        except ValueError:
            return None

    def _enter_lock(self, name: str, lineno: int) -> None:
        if self.stack:
            outer, outer_line = self.stack[-1]
            if not self.order:
                self.findings.append(Finding(
                    "KF201", self.ctx.relpath, lineno,
                    f"nested lock acquisition {outer!r} (line "
                    f"{outer_line}) -> {name!r} but the module declares "
                    "no lock hierarchy — add `_KF_LOCK_ORDER = "
                    f"({outer!r}, {name!r})` at module level",
                ))
            else:
                ro, ri = self._rank(outer), self._rank(name)
                if ri is None:
                    self.findings.append(Finding(
                        "KF201", self.ctx.relpath, lineno,
                        f"lock {name!r} acquired under {outer!r} but is "
                        "not in the module's _KF_LOCK_ORDER declaration",
                    ))
                elif ro is None:
                    self.findings.append(Finding(
                        "KF201", self.ctx.relpath, lineno,
                        f"lock {outer!r} (held at line {outer_line}) is "
                        "not in the module's _KF_LOCK_ORDER declaration",
                    ))
                elif ri <= ro:
                    self.findings.append(Finding(
                        "KF201", self.ctx.relpath, lineno,
                        f"lock order violation: {name!r} acquired while "
                        f"holding {outer!r} (line {outer_line}), but "
                        "_KF_LOCK_ORDER declares "
                        f"{name!r} <= {outer!r}",
                    ))
        self.stack.append((name, lineno))

    # -- visitors

    def _fresh(self, node: ast.AST) -> None:
        saved, self.stack = self.stack, []
        self.generic_visit(node)
        self.stack = saved

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        self._fresh(node)

    def visit_AsyncFunctionDef(self, node) -> None:
        self._fresh(node)

    def visit_Lambda(self, node: ast.Lambda) -> None:
        self._fresh(node)

    def visit_With(self, node: ast.With) -> None:
        entered = 0
        for item in node.items:
            name = _lock_name(item.context_expr)
            if name is not None:
                self._enter_lock(name, node.lineno)
                entered += 1
        for stmt in node.body:
            self.visit(stmt)
        for _ in range(entered):
            self.stack.pop()

    visit_AsyncWith = visit_With

    def visit_Call(self, node: ast.Call) -> None:
        if self.stack:
            reason = _blocking_reason(node)
            if reason is not None and not self._is_cond_wait_idiom(node):
                held = self.stack[-1][0]
                self.findings.append(Finding(
                    "KF200", self.ctx.relpath, node.lineno,
                    f"blocking call ({reason}) while holding lock "
                    f"{held!r} — move the blocking work outside the "
                    "critical section or bound it",
                ))
        self.generic_visit(node)

    def _is_cond_wait_idiom(self, node: ast.Call) -> bool:
        """`with cond: cond.wait[_for](...)` — Condition.wait RELEASES
        the held lock for the duration, so it is not blocking-under-lock
        (KF301 still judges its unboundedness)."""
        if not (isinstance(node.func, ast.Attribute)
                and node.func.attr in ("wait", "wait_for")):
            return False
        receiver = _last_segment(node.func.value)
        return receiver is not None and receiver == self.stack[-1][0]


def _declared_lock_order(ctx: FileContext) -> List[str]:
    if ctx.tree is None:
        return []
    for node in ctx.tree.body:
        if (
            isinstance(node, ast.Assign)
            and len(node.targets) == 1
            and isinstance(node.targets[0], ast.Name)
            and node.targets[0].id == "_KF_LOCK_ORDER"
            and isinstance(node.value, (ast.Tuple, ast.List))
        ):
            return [
                e.value for e in node.value.elts
                if isinstance(e, ast.Constant) and isinstance(e.value, str)
            ]
    return []


@rule(
    "KF200",
    "blocking-under-lock",
    "no call that can block indefinitely (sleep, subprocess, socket "
    "recv/send, unbounded wait/join/get) while holding a lock — a "
    "stalled peer must never extend a critical section",
)
def check_blocking_under_lock(ctx: FileContext) -> List[Finding]:
    if ctx.tree is None:
        return []
    w = _LockWalker(ctx, _declared_lock_order(ctx))
    w.visit(ctx.tree)
    return [f for f in w.findings if f.rule == "KF200"]


@rule(
    "KF201",
    "lock-hierarchy",
    "modules that nest lock acquisitions must declare the order as "
    "`_KF_LOCK_ORDER = (outer, ..., inner)` and every nesting must "
    "respect it — ABBA deadlocks are ordering bugs, caught here at "
    "review time and by lockwatch at runtime",
)
def check_lock_hierarchy(ctx: FileContext) -> List[Finding]:
    if ctx.tree is None:
        return []
    w = _LockWalker(ctx, _declared_lock_order(ctx))
    w.visit(ctx.tree)
    return [f for f in w.findings if f.rule == "KF201"]


# ---------------------------------------------------------------------
# KF3xx — thread lifecycle
# ---------------------------------------------------------------------


def _is_thread_ctor(call: ast.Call) -> bool:
    chain = _attr_chain(call.func)
    return chain in ("threading.Thread", "Thread")


@rule(
    "KF300",
    "thread-lifecycle",
    "every threading.Thread is daemon=True or joined with a bounded "
    "timeout — a forgotten non-daemon thread turns every crash into a "
    "hang at interpreter exit",
)
def check_thread_lifecycle(ctx: FileContext) -> List[Finding]:
    if ctx.tree is None:
        return []
    # receivers that get `X.daemon = True` or a bounded `X.join(...)`
    # anywhere in the module (same-name matching, not dataflow)
    daemoned: Set[str] = set()
    bounded_join: Set[str] = set()
    assigned_to: Dict[int, str] = {}  # id(call node) -> receiver segment
    for node in ctx.walk():
        if isinstance(node, ast.Assign):
            for tgt in node.targets:
                if (
                    isinstance(tgt, ast.Attribute)
                    and tgt.attr == "daemon"
                    and _is_true(node.value)
                ):
                    seg = _last_segment(tgt.value)
                    if seg:
                        daemoned.add(seg)
                seg = _last_segment(tgt)
                if seg and isinstance(node.value, ast.Call):
                    assigned_to[id(node.value)] = seg
        elif isinstance(node, ast.Call):
            if (
                isinstance(node.func, ast.Attribute)
                and node.func.attr == "join"
                and (node.args or _kw(node, "timeout") is not None)
            ):
                seg = _last_segment(node.func.value)
                if seg:
                    bounded_join.add(seg)
    out = []
    for node in ctx.walk():
        if not (isinstance(node, ast.Call) and _is_thread_ctor(node)):
            continue
        if _is_true(_kw(node, "daemon")):
            continue
        seg = assigned_to.get(id(node))
        if seg is not None and (seg in daemoned or seg in bounded_join):
            continue
        out.append(Finding(
            "KF300", ctx.relpath, node.lineno,
            "Thread created without daemon=True and without a bounded "
            "join in this module — pass daemon=True or join it with a "
            "timeout",
        ))
    return out


@rule(
    "KF301",
    "unbounded-wait",
    "every Event.wait/Condition.wait(_for)/Popen.wait is bounded — an "
    "unbounded wait on a signal that never comes is a silent hang; "
    "abort-aware waits get a justified suppression",
)
def check_unbounded_wait(ctx: FileContext) -> List[Finding]:
    if ctx.tree is None:
        return []
    out = []
    for node in ctx.walk():
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)):
            continue
        attr = node.func.attr
        if attr == "wait" and not node.args and not _has_timeout(node):
            out.append(Finding(
                "KF301", ctx.relpath, node.lineno,
                "unbounded .wait() — pass a timeout (retry in a loop if "
                "the wait is legitimate) so a lost signal cannot hang "
                "this thread forever",
            ))
        elif attr == "wait_for" and not _has_timeout(node, positional_at=1):
            out.append(Finding(
                "KF301", ctx.relpath, node.lineno,
                "unbounded .wait_for() — pass a timeout so a lost "
                "notify cannot hang this thread forever",
            ))
    return out


@rule(
    "KF302",
    "unbounded-join",
    "every .join() is bounded — joining a thread/process that never "
    "exits hangs shutdown paths; join with a timeout and handle the "
    "still-alive case",
)
def check_unbounded_join(ctx: FileContext) -> List[Finding]:
    if ctx.tree is None:
        return []
    out = []
    for node in ctx.walk():
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr == "join"
            and not node.args
            and not node.keywords
        ):
            out.append(Finding(
                "KF302", ctx.relpath, node.lineno,
                "unbounded .join() — pass a timeout and handle the "
                "still-running case (log, escalate, or abandon as "
                "daemon)",
            ))
    return out


# the modules that run background stages against a session epoch: their
# threads MUST register with the abort protocol (a declared joinable
# set that close() joins), or a forgotten stage outlives the epoch and
# keeps walking against a dead transport token. zero.py joined the set
# in ISSUE 12: today its settled-gate polling and gather-stage work run
# ON the scheduler's registered threads, and a future helper thread
# must not slip in unregistered.
_KF303_MODULES = (
    "kungfu_tpu/collective/scheduler.py",
    "kungfu_tpu/collective/pipeline.py",
    "kungfu_tpu/collective/zero.py",
)

_KF303_FACTORY = "_spawn_registered"


def _declared_joinable_threads(ctx: FileContext) -> Optional[List[str]]:
    """The module-level `_KF_JOINABLE_THREADS` tuple of thread names, or
    None when the module declares none."""
    if ctx.tree is None:
        return None
    for node in ctx.tree.body:
        if (
            isinstance(node, ast.Assign)
            and len(node.targets) == 1
            and isinstance(node.targets[0], ast.Name)
            and node.targets[0].id == "_KF_JOINABLE_THREADS"
            and isinstance(node.value, (ast.Tuple, ast.List))
        ):
            return [
                e.value for e in node.value.elts
                if isinstance(e, ast.Constant) and isinstance(e.value, str)
            ]
    return None


class _ThreadSiteWalker(ast.NodeVisitor):
    """Collects (enclosing function name, Thread-ctor node) pairs and
    every `*._spawn_registered(...)` call in one file."""

    def __init__(self):
        self.func_stack: List[str] = []
        self.ctors: List[Tuple[Optional[str], ast.Call]] = []
        self.spawns: List[ast.Call] = []

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        self.func_stack.append(node.name)
        self.generic_visit(node)
        self.func_stack.pop()

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Call(self, node: ast.Call) -> None:
        if _is_thread_ctor(node):
            enclosing = self.func_stack[-1] if self.func_stack else None
            self.ctors.append((enclosing, node))
        if _last_segment(node.func) == _KF303_FACTORY:
            self.spawns.append(node)
        self.generic_visit(node)


@rule(
    "KF303",
    "unregistered-scheduler-thread",
    "threads started by the collective scheduler/pipeline modules must "
    "register with the abort protocol: constructed only inside the "
    "_spawn_registered factory, spawned with a literal name declared in "
    "the module-level _KF_JOINABLE_THREADS joinable-set (close() joins "
    "exactly that set), so a future stage cannot silently outlive a "
    "session epoch",
)
def check_scheduler_threads(ctx: FileContext) -> List[Finding]:
    if ctx.relpath not in _KF303_MODULES or ctx.tree is None:
        return []
    w = _ThreadSiteWalker()
    w.visit(ctx.tree)
    declared = _declared_joinable_threads(ctx)
    out: List[Finding] = []
    if (w.ctors or w.spawns) and declared is None:
        first = w.ctors[0][1] if w.ctors else w.spawns[0]
        out.append(Finding(
            "KF303", ctx.relpath, first.lineno,
            "this module starts threads but declares no "
            "_KF_JOINABLE_THREADS joinable-set — declare the thread "
            "names at module level so close() provably joins them all",
        ))
        declared = []
    for enclosing, node in w.ctors:
        if enclosing != _KF303_FACTORY:
            out.append(Finding(
                "KF303", ctx.relpath, node.lineno,
                f"threading.Thread constructed outside {_KF303_FACTORY} "
                "— scheduler/pipeline threads must go through the "
                "registering factory (named, declared, tracked for "
                "close() to join)",
            ))
    used: Set[str] = set()
    for node in w.spawns:
        arg0 = node.args[0] if node.args else None
        if not (isinstance(arg0, ast.Constant) and isinstance(arg0.value, str)):
            out.append(Finding(
                "KF303", ctx.relpath, node.lineno,
                f"{_KF303_FACTORY} must be called with a literal thread "
                "name (the declared joinable-set is matched statically)",
            ))
            continue
        used.add(arg0.value)
        if declared is not None and arg0.value not in declared:
            out.append(Finding(
                "KF303", ctx.relpath, node.lineno,
                f"thread name {arg0.value!r} is not declared in "
                "_KF_JOINABLE_THREADS — add it so the joinable-set "
                "stays the complete inventory",
            ))
    for name in declared or []:
        if name not in used:
            out.append(Finding(
                "KF303", ctx.relpath, 1,
                f"_KF_JOINABLE_THREADS declares {name!r} but no "
                f"{_KF303_FACTORY} call spawns it — drop the stale "
                "entry (a rotting inventory hides real leaks)",
            ))
    return out


# ---------------------------------------------------------------------
# KF4xx — exception hygiene
# ---------------------------------------------------------------------

_LOG_FNS = frozenset({
    "debug", "info", "warn", "warning", "error", "exception", "critical",
    "fatal", "echo",
})


def _is_broad(handler: ast.ExceptHandler) -> Optional[str]:
    t = handler.type
    if t is None:
        return "bare except:"
    names = []
    if isinstance(t, ast.Tuple):
        names = [_last_segment(e) for e in t.elts]
    else:
        names = [_last_segment(t)]
    for n in names:
        if n in ("Exception", "BaseException"):
            return f"except {n}"
    return None


def _handler_accounts(handler: ast.ExceptHandler) -> bool:
    """True when the handler re-raises, logs, audits, exits, prints
    (CLI surfaces), or *uses the bound exception* — capturing the error
    into a list that a waiter re-raises is channeling, not swallowing."""
    for node in ast.walk(handler):
        if isinstance(node, ast.Raise):
            return True
        if isinstance(node, ast.Call):
            chain = _attr_chain(node.func)
            if chain in ("sys.exit", "os._exit"):
                return True
            if isinstance(node.func, ast.Attribute):
                if node.func.attr in _LOG_FNS:
                    return True
                if node.func.attr == "record_event":
                    return True
            elif isinstance(node.func, ast.Name):
                if node.func.id in _LOG_FNS | {"record_event", "print"}:
                    return True
        if (
            handler.name is not None
            and isinstance(node, ast.Name)
            and isinstance(node.ctx, ast.Load)
            and node.id == handler.name
        ):
            return True
    return False


@rule(
    "KF400",
    "silent-broad-except",
    "a bare/broad except must log through telemetry.log, record an "
    "audit event, or re-raise — errors that vanish here are the ones "
    "postmortems cannot explain",
)
def check_silent_broad_except(ctx: FileContext) -> List[Finding]:
    if ctx.tree is None:
        return []
    out = []
    for node in ctx.walk():
        if not isinstance(node, ast.ExceptHandler):
            continue
        broad = _is_broad(node)
        if broad is None:
            continue
        if _handler_accounts(node):
            continue
        out.append(Finding(
            "KF400", ctx.relpath, node.lineno,
            f"{broad} swallows without logging or re-raising — log via "
            "telemetry.log, record an audit event, narrow the type, or "
            "re-raise",
        ))
    return out


# ---------------------------------------------------------------------
# KF5xx — CLI surface
# ---------------------------------------------------------------------

_PRINT_EXEMPT = ("kungfu_tpu/runner/cli.py",)
_PRINT_EXEMPT_PREFIX = ("kungfu_tpu/info/",)


@rule(
    "KF500",
    "bare-print",
    "no bare print() outside the CLI surfaces (runner/cli.py, info/) — "
    "everything else routes through kungfu_tpu.telemetry.log so output "
    "is leveled, rank-prefixed and capturable",
)
def check_bare_print(ctx: FileContext) -> List[Finding]:
    if ctx.tree is None:
        return []
    if ctx.relpath in _PRINT_EXEMPT or ctx.relpath.startswith(
        _PRINT_EXEMPT_PREFIX
    ):
        return []
    out = []
    for node in ctx.walk():
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Name)
            and node.func.id == "print"
        ):
            out.append(Finding(
                "KF500", ctx.relpath, node.lineno,
                "bare print() — use kungfu_tpu.telemetry.log (or "
                "log.echo() for CLI result lines)",
            ))
    return out


# ---------------------------------------------------------------------
# KF6xx — telemetry docs
# ---------------------------------------------------------------------

_METRIC_RE = re.compile(r'"(kungfu_[a-z0-9_]+[a-z0-9])"')

# rendered by bespoke renderers (monitor/net.py rate gauges), not
# registered via a string literal at one call site
_RENDERED_ONLY = frozenset({"kungfu_egress_rate", "kungfu_ingress_rate"})


def _source_metric_names(project: Project) -> Set[str]:
    names: Set[str] = set()
    for ctx in project.files:
        names.update(_METRIC_RE.findall(ctx.source))
    return names


def _telemetry_doc(project: Project) -> Optional[Tuple[str, List[str]]]:
    path = os.path.join(project.repo_root, "docs", "telemetry.md")
    try:
        with open(path, encoding="utf-8") as f:
            text = f.read()
    except OSError:
        return None
    return text, text.splitlines()


@rule(
    "KF600",
    "metric-undocumented",
    "every kungfu_* metric family registered anywhere in the package "
    "appears in docs/telemetry.md — an undocumented family is invisible "
    "to the operator staring at a dashboard at 3am",
    scope="project",
)
def check_metrics_documented(project: Project) -> List[Finding]:
    names = _source_metric_names(project)
    out = []
    if len(names) <= 30:
        # the scan must keep finding the registry — a rename must not
        # silently turn this rule into a no-op
        out.append(Finding(
            "KF600", "docs/telemetry.md", 1,
            f"metric-name scan found only {len(names)} families — the "
            "lexical scan looks broken (rename?), fix the rule before "
            "trusting it",
        ))
        return out
    got = _telemetry_doc(project)
    if got is None:
        return [Finding("KF600", "docs/telemetry.md", 1,
                        "docs/telemetry.md is missing")]
    doc, _ = got
    for name in sorted(names):
        if name not in doc:
            out.append(Finding(
                "KF600", "docs/telemetry.md", 1,
                f"metric family {name!r} is registered in the package "
                "but absent from docs/telemetry.md — add it to the "
                "metrics table",
            ))
    return out


@rule(
    "KF601",
    "metric-ghost-row",
    "metric families named in docs/telemetry.md's table must still "
    "exist in code — stale rows mislead operators as much as missing "
    "ones",
    scope="project",
)
def check_metric_ghosts(project: Project) -> List[Finding]:
    names = _source_metric_names(project) | _RENDERED_ONLY
    got = _telemetry_doc(project)
    if got is None:
        return []  # KF600 already reports the missing doc
    _, lines = got
    rows = [
        (i, l) for i, l in enumerate(lines, start=1)
        if l.startswith("| `kungfu_")
    ]
    out = []
    if len(rows) <= 20:
        out.append(Finding(
            "KF601", "docs/telemetry.md", 1,
            "metrics table not found where expected (fewer than 20 "
            "`| \\`kungfu_...\\`` rows) — the doc layout moved, fix the "
            "rule",
        ))
        return out
    for lineno, row in rows:
        for doc_name in re.findall(r"`(kungfu_[a-z0-9_]+)`",
                                   row.split("|")[1]):
            if doc_name not in names:
                out.append(Finding(
                    "KF601", "docs/telemetry.md", lineno,
                    f"docs/telemetry.md documents {doc_name!r} but no "
                    "code registers it — drop the stale row",
                ))
    return out


# KF602 — span-doc lint (ISSUE 13 satellite): the span-kind shape of
# KF600/601 in one bidirectional rule. Every span-kind LITERAL emitted
# through the tracer (trace.span / trace.record / tracing.instant /
# trace.step spans) must appear in docs/telemetry.md's span table, and
# every table row must still exist in code. Dynamic names (f-strings —
# `collective.{kind}`, `host.walk[NMiB]`) are out of the table's scope
# and stay documented in the prose "Span naming scheme" section; kinds
# passed through a parameter indirection are declared in
# _SPAN_INDIRECT so the scan stays honest about its blind spot.

_SPAN_FNS = frozenset({"span", "record", "instant"})
_SPAN_MODULES = frozenset({"trace", "tracing"})
_SPAN_INDIRECT = frozenset({
    # walks.timed_step forwards its span_name parameter to trace.span
    "host.rs.step",
    "host.ag.step",
})

_SPAN_TABLE_HEADING = "## Span table"


def _source_span_names(project: Project) -> Set[str]:
    names: Set[str] = set()
    for ctx in project.files:
        if ctx.tree is None:
            continue
        for node in ctx.walk():
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            if not (
                isinstance(fn, ast.Attribute)
                and fn.attr in _SPAN_FNS
                and _last_segment(fn.value) in _SPAN_MODULES
            ):
                continue
            if (
                node.args
                and isinstance(node.args[0], ast.Constant)
                and isinstance(node.args[0].value, str)
            ):
                names.add(node.args[0].value)
    return names


def _span_table_rows(project: Project) -> Optional[List[Tuple[int, str]]]:
    """(lineno, span name) per row of the docs/telemetry.md span table,
    or None when the doc/heading is missing."""
    got = _telemetry_doc(project)
    if got is None:
        return None
    _, lines = got
    rows: List[Tuple[int, str]] = []
    in_table = False
    for i, line in enumerate(lines, start=1):
        if line.strip() == _SPAN_TABLE_HEADING:
            in_table = True
            continue
        if in_table and line.startswith("## "):
            break
        if in_table and line.startswith("| `"):
            for name in re.findall(r"`([a-z0-9_.]+)`", line.split("|")[1]):
                rows.append((i, name))
    return rows if in_table else None


@rule(
    "KF602",
    "span-doc-lint",
    "every span-kind literal emitted through the tracer must appear in "
    "docs/telemetry.md's span table AND every table row must still "
    "exist in code — the span table is the operator's legend for every "
    "/trace and /cluster/trace view (the KF600/601 contract, for spans)",
    scope="project",
)
def check_spans_documented(project: Project) -> List[Finding]:
    names = _source_span_names(project) | _SPAN_INDIRECT
    out: List[Finding] = []
    if len(names) <= 15:
        # the scan must keep finding the tracer call sites — a rename
        # must not silently turn this rule into a no-op
        out.append(Finding(
            "KF602", "docs/telemetry.md", 1,
            f"span-kind scan found only {len(names)} literals — the AST "
            "scan looks broken (tracer rename?), fix the rule before "
            "trusting it",
        ))
        return out
    rows = _span_table_rows(project)
    if rows is None:
        return [Finding(
            "KF602", "docs/telemetry.md", 1,
            f"docs/telemetry.md has no `{_SPAN_TABLE_HEADING}` section — "
            "add the span table (one row per span kind)",
        )]
    documented = {name for _, name in rows}
    for name in sorted(names - documented):
        out.append(Finding(
            "KF602", "docs/telemetry.md", 1,
            f"span kind {name!r} is emitted in the package but absent "
            "from docs/telemetry.md's span table — add a row",
        ))
    for lineno, name in rows:
        if name not in names:
            out.append(Finding(
                "KF602", "docs/telemetry.md", lineno,
                f"docs/telemetry.md's span table documents {name!r} but "
                "no code emits it — drop the stale row (dynamic-name "
                "spans belong in the prose section, not the table)",
            ))
    return out


# KF604 — audit-kind doc lint (ISSUE 15 satellite): the audit-event
# shape of KF600/602 in one bidirectional rule. Every event-kind
# LITERAL passed to telemetry.audit.record_event(...) must appear in
# docs/telemetry.md's audit event table, and every table row must still
# exist in code. record_resize() emits kind="resize" without a literal
# at its call sites, so "resize" is seeded whenever a call exists;
# kinds passed through a parameter indirection (lockwatch's reporter
# queue) are declared in _AUDIT_INDIRECT so the scan stays honest about
# its blind spot.

_AUDIT_MODULES = frozenset({"audit", "_audit"})
_AUDIT_INDIRECT = frozenset({
    # lockwatch._report enqueues (kind, counter, detail); _emit forwards
    # the kind parameter to audit.record_event
    "lock_order_violation",
    "lock_long_held",
})

_AUDIT_TABLE_HEADING = "## Audit event table"


def _source_audit_kinds(project: Project) -> Set[str]:
    kinds: Set[str] = set()
    for ctx in project.files:
        if ctx.tree is None:
            continue
        for node in ctx.walk():
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            if _last_segment(fn) == "record_resize":
                kinds.add("resize")
                continue
            if not (
                isinstance(fn, ast.Attribute)
                and fn.attr == "record_event"
                and _last_segment(fn.value) in _AUDIT_MODULES
            ):
                continue
            if (
                node.args
                and isinstance(node.args[0], ast.Constant)
                and isinstance(node.args[0].value, str)
            ):
                kinds.add(node.args[0].value)
    return kinds


def _audit_table_rows(project: Project) -> Optional[List[Tuple[int, str]]]:
    """(lineno, event kind) per row of docs/telemetry.md's audit event
    table, or None when the doc/heading is missing."""
    got = _telemetry_doc(project)
    if got is None:
        return None
    _, lines = got
    rows: List[Tuple[int, str]] = []
    in_table = False
    for i, line in enumerate(lines, start=1):
        if line.strip() == _AUDIT_TABLE_HEADING:
            in_table = True
            continue
        if in_table and line.startswith("## "):
            break
        if in_table and line.startswith("| `"):
            for name in re.findall(r"`([a-z0-9_]+)`", line.split("|")[1]):
                rows.append((i, name))
    return rows if in_table else None


@rule(
    "KF604",
    "audit-doc-lint",
    "every audit-event kind recorded through telemetry.audit must "
    "appear in docs/telemetry.md's audit event table AND every table "
    "row must still exist in code — the audit log is the operator's "
    "'what changed and when' surface, and an undocumented kind (or a "
    "stale row) misleads exactly the 3am reader it exists for (the "
    "KF600/602 contract, for audit events)",
    scope="project",
)
def check_audit_kinds_documented(project: Project) -> List[Finding]:
    kinds = _source_audit_kinds(project) | _AUDIT_INDIRECT
    out: List[Finding] = []
    if len(kinds) <= 8:
        # the scan must keep finding the recorder call sites — a rename
        # must not silently turn this rule into a no-op
        out.append(Finding(
            "KF604", "docs/telemetry.md", 1,
            f"audit-kind scan found only {len(kinds)} kinds — the AST "
            "scan looks broken (record_event rename?), fix the rule "
            "before trusting it",
        ))
        return out
    rows = _audit_table_rows(project)
    if rows is None:
        return [Finding(
            "KF604", "docs/telemetry.md", 1,
            f"docs/telemetry.md has no `{_AUDIT_TABLE_HEADING}` section "
            "— add the audit event table (one row per event kind)",
        )]
    documented = {name for _, name in rows}
    for name in sorted(kinds - documented):
        out.append(Finding(
            "KF604", "docs/telemetry.md", 1,
            f"audit event kind {name!r} is recorded in the package but "
            "absent from docs/telemetry.md's audit event table — add a "
            "row",
        ))
    for lineno, name in rows:
        if name not in kinds:
            out.append(Finding(
                "KF604", "docs/telemetry.md", lineno,
                f"docs/telemetry.md's audit event table documents "
                f"{name!r} but no code records it — drop the stale row "
                "(parameter-indirected kinds belong in _AUDIT_INDIRECT)",
            ))
    return out


# KF605 — policy-signal doc lint (ISSUE 16 satellite): the adaptation-
# signal shape of KF602/604 in one bidirectional rule. Every namespaced
# signal key LITERAL that reaches ``PolicyContext.metrics`` — written
# directly (``ctx.metrics["replan/last_order"] = ...``) or returned by
# a plane's ``signals()``/``local_signals()``/``health_signals()``
# function that policy.py merges in — must appear in docs/telemetry.md's
# policy signal table, and every table row must still exist in code.
# Signals are the contract between the telemetry planes and the
# adaptation policies; an undocumented key is a steering input nobody
# can audit, and a stale row describes a lever that no longer exists.
# Keys assembled at runtime (none today) would be declared in
# _SIGNAL_INDIRECT so the scan stays honest about its blind spot.

_SIGNAL_FNS = frozenset({"signals", "local_signals", "health_signals"})
_SIGNAL_INDIRECT: frozenset = frozenset()
_SIGNAL_KEY_RE = re.compile(r"^[a-z_]+/[a-z_]+$")

_SIGNAL_TABLE_HEADING = "## Policy signal table"


def _source_signal_keys(project: Project) -> Set[str]:
    keys: Set[str] = set()

    def _maybe(value: object) -> None:
        if isinstance(value, str) and _SIGNAL_KEY_RE.match(value):
            keys.add(value)

    for ctx in project.files:
        if ctx.tree is None:
            continue
        for node in ctx.walk():
            # ctx.metrics["x/y"] = ... anywhere in the package
            if isinstance(node, ast.Assign):
                for tgt in node.targets:
                    if (
                        isinstance(tgt, ast.Subscript)
                        and _last_segment(tgt.value) == "metrics"
                        and isinstance(tgt.slice, ast.Constant)
                    ):
                        _maybe(tgt.slice.value)
            # dict keys and subscript writes inside the signal builders
            if not (isinstance(node, ast.FunctionDef)
                    and node.name in _SIGNAL_FNS):
                continue
            for sub in ast.walk(node):
                if isinstance(sub, ast.Dict):
                    for k in sub.keys:
                        if isinstance(k, ast.Constant):
                            _maybe(k.value)
                elif isinstance(sub, ast.Assign):
                    for tgt in sub.targets:
                        if (isinstance(tgt, ast.Subscript)
                                and isinstance(tgt.slice, ast.Constant)):
                            _maybe(tgt.slice.value)
    return keys


def _signal_table_rows(project: Project) -> Optional[List[Tuple[int, str]]]:
    """(lineno, signal key) per row of docs/telemetry.md's policy signal
    table, or None when the doc/heading is missing."""
    got = _telemetry_doc(project)
    if got is None:
        return None
    rows: List[Tuple[int, str]] = []
    in_table = False
    for i, line in enumerate(got[1], start=1):
        if line.strip() == _SIGNAL_TABLE_HEADING:
            in_table = True
            continue
        if in_table and line.startswith("## "):
            break
        if in_table and line.startswith("| `"):
            for name in re.findall(r"`([a-z_]+/[a-z_]+)`",
                                   line.split("|")[1]):
                rows.append((i, name))
    return rows if in_table else None


@rule(
    "KF605",
    "signal-doc-lint",
    "every namespaced policy-signal key reaching PolicyContext.metrics "
    "(direct metrics[...] writes and the planes' signals()/"
    "local_signals()/health_signals() builders) must appear in "
    "docs/telemetry.md's policy signal table AND every table row must "
    "still exist in code — signals are the steering contract between "
    "telemetry and adaptation, and an undocumented key (or stale row) "
    "hides a lever from exactly the operator tuning it (the KF602/604 "
    "contract, for adaptation signals)",
    scope="project",
)
def check_signals_documented(project: Project) -> List[Finding]:
    keys = _source_signal_keys(project) | _SIGNAL_INDIRECT
    out: List[Finding] = []
    if len(keys) <= 10:
        # the scan must keep finding the signal builders — a rename
        # must not silently turn this rule into a no-op
        out.append(Finding(
            "KF605", "docs/telemetry.md", 1,
            f"signal-key scan found only {len(keys)} keys — the AST "
            "scan looks broken (signals() rename?), fix the rule "
            "before trusting it",
        ))
        return out
    rows = _signal_table_rows(project)
    if rows is None:
        return [Finding(
            "KF605", "docs/telemetry.md", 1,
            f"docs/telemetry.md has no `{_SIGNAL_TABLE_HEADING}` section "
            "— add the policy signal table (one row per signal key)",
        )]
    documented = {name for _, name in rows}
    for name in sorted(keys - documented):
        out.append(Finding(
            "KF605", "docs/telemetry.md", 1,
            f"policy signal {name!r} is written in the package but "
            "absent from docs/telemetry.md's policy signal table — add "
            "a row",
        ))
    for lineno, name in rows:
        if name not in keys:
            out.append(Finding(
                "KF605", "docs/telemetry.md", lineno,
                f"docs/telemetry.md's policy signal table documents "
                f"{name!r} but no code writes it — drop the stale row "
                "(runtime-assembled keys belong in _SIGNAL_INDIRECT)",
            ))
    return out


# KF606 — endpoint doc lint (ISSUE 18 satellite): the KF602/604/605
# shape for the HTTP surface itself. Every route literal served by the
# worker telemetry server (telemetry/http.py's route dict) or the
# cluster aggregator (telemetry/cluster.py's CLUSTER_ROUTES /
# HOST_DIGEST_PATH) must appear in docs/telemetry.md's endpoint table,
# and every table row must still be served. The endpoints are the
# operator's front door; an undocumented route is invisible tooling and
# a stale row is a 404 in the runbook. Routes assembled at runtime
# (embedder extra_routes) are out of scope by construction — the scan
# only reads these two files' literals.

_ENDPOINT_FILES = frozenset({
    "kungfu_tpu/telemetry/http.py",
    "kungfu_tpu/telemetry/cluster.py",
})
_ENDPOINT_INDIRECT: frozenset = frozenset()
_ENDPOINT_RE = re.compile(r"^/[a-z0-9_]+(?:/[a-z0-9_]+)*$")

_ENDPOINT_TABLE_HEADING = "## Endpoint table"


def _source_endpoints(project: Project) -> Set[str]:
    """Every route-path string literal in the two files that define the
    telemetry HTTP surface. Both files use the literals as dict/tuple
    route keys, so any slash-leading path literal IS a route (or a
    cursor key naming one — same string either way)."""
    paths: Set[str] = set()
    for ctx in project.files:
        if ctx.relpath not in _ENDPOINT_FILES or ctx.tree is None:
            continue
        for node in ctx.walk():
            if (
                isinstance(node, ast.Constant)
                and isinstance(node.value, str)
                and _ENDPOINT_RE.match(node.value)
            ):
                paths.add(node.value)
    return paths


def _endpoint_table_rows(project: Project) -> Optional[List[Tuple[int, str]]]:
    """(lineno, route path) per row of docs/telemetry.md's endpoint
    table, or None when the doc/heading is missing."""
    got = _telemetry_doc(project)
    if got is None:
        return None
    rows: List[Tuple[int, str]] = []
    in_table = False
    for i, line in enumerate(got[1], start=1):
        if line.strip() == _ENDPOINT_TABLE_HEADING:
            in_table = True
            continue
        if in_table and line.startswith("## "):
            break
        if in_table and line.startswith("| `"):
            for name in re.findall(r"`(/[a-z0-9_/]+)`", line.split("|")[1]):
                rows.append((i, name))
    return rows if in_table else None


@rule(
    "KF606",
    "endpoint-doc-lint",
    "every HTTP route literal served by the worker telemetry server "
    "(telemetry/http.py) or the cluster aggregator (telemetry/"
    "cluster.py) must appear in docs/telemetry.md's endpoint table AND "
    "every table row must still be served — the endpoints are the "
    "operator's front door, and an undocumented route (or stale row) "
    "breaks exactly the curl the runbook prescribes (the KF602/604/605 "
    "contract, for the HTTP surface)",
    scope="project",
)
def check_endpoints_documented(project: Project) -> List[Finding]:
    paths = _source_endpoints(project) | _ENDPOINT_INDIRECT
    out: List[Finding] = []
    if len(paths) <= 12:
        # the scan must keep finding the route literals — moving the
        # route tables must not silently turn this rule into a no-op
        out.append(Finding(
            "KF606", "docs/telemetry.md", 1,
            f"endpoint scan found only {len(paths)} routes — the "
            "literal scan looks broken (route dict moved?), fix the "
            "rule before trusting it",
        ))
        return out
    rows = _endpoint_table_rows(project)
    if rows is None:
        return [Finding(
            "KF606", "docs/telemetry.md", 1,
            f"docs/telemetry.md has no `{_ENDPOINT_TABLE_HEADING}` "
            "section — add the endpoint table (one row per route)",
        )]
    documented = {name for _, name in rows}
    for name in sorted(paths - documented):
        out.append(Finding(
            "KF606", "docs/telemetry.md", 1,
            f"endpoint {name!r} is served by the package but absent "
            "from docs/telemetry.md's endpoint table — add a row",
        ))
    for lineno, name in rows:
        if name not in paths:
            out.append(Finding(
                "KF606", "docs/telemetry.md", lineno,
                f"docs/telemetry.md's endpoint table documents {name!r} "
                "but no code serves it — drop the stale row "
                "(runtime-registered routes belong in _ENDPOINT_INDIRECT)",
            ))
    return out


# ---------------------------------------------------------------------
# KF7xx — distributed protocol (ISSUE 12: the first cross-module rules)
# ---------------------------------------------------------------------

# where the registry-declared consensus knobs must surface as the
# engine's consensus tuple (HostSession.engine_knobs)
_CONSENSUS_FILE = "kungfu_tpu/collective/host_session.py"
_CONSENSUS_FN = "engine_knobs"


@rule(
    "KF700",
    "wire-name-discipline",
    "every name reaching a collective/submit call site (Workspace name, "
    "all_gather_shards/broadcast_bytes/bytes_consensus names, barrier "
    "tags) must carry runtime content — a round/sequence stamp, a "
    "cluster version, the registered identity. A bare string literal "
    "rendezvous name collides across back-to-back rounds: a fast peer's "
    "round r+1 message is consumed by a slow peer still in round r "
    "(the PR 8 ':{i}@{seq}' fix, enforced instead of remembered)",
    scope="project",
)
def check_wire_names(project: Project) -> List[Finding]:
    cross = _cross_constants(project)
    out = []
    for ctx in project.files:
        for lineno, site, desc in ctx.name_sites:
            resolved = _resolve_desc(desc, ctx, cross)
            if resolved is None:
                continue  # interpolated / runtime-derived: passes
            out.append(Finding(
                "KF700", ctx.relpath, lineno,
                f"constant wire name {resolved!r} at a {site} call site "
                "— a name without a round/sequence stamp can collide "
                "across back-to-back rounds (a fast peer's next round is "
                "consumed by a slow peer's current one); stamp it with a "
                "round counter, cluster version or registered identity",
            ))
    return out


def _knob_registry_decls(ctx: FileContext) -> Dict[str, Tuple[int, bool]]:
    """name -> (lineno, consensus flag) for every `_knob("NAME", ...)`
    declaration in the registry file (AST, not import: fixtures supply
    their own registry source)."""
    decls: Dict[str, Tuple[int, bool]] = {}
    for node in ctx.walk():
        if not isinstance(node, ast.Call):
            continue
        if _last_segment(node.func) != "_knob":
            continue
        if not (node.args and isinstance(node.args[0], ast.Constant)
                and isinstance(node.args[0].value, str)):
            continue
        consensus = _is_true(_kw(node, "consensus"))
        decls[node.args[0].value] = (node.lineno, consensus)
    return decls


def _consensus_tuple_entries(ctx: FileContext) -> List[Tuple[str, int]]:
    """(knob name, lineno) for every literal-named entry of the list
    `engine_knobs()` returns."""
    entries: List[Tuple[str, int]] = []
    for node in ctx.walk():
        if not (isinstance(node, ast.FunctionDef)
                and node.name == _CONSENSUS_FN):
            continue
        for sub in ast.walk(node):
            if not isinstance(sub, ast.Return) or sub.value is None:
                continue
            for elt in ast.walk(sub.value):
                if (
                    isinstance(elt, ast.Tuple)
                    and elt.elts
                    and isinstance(elt.elts[0], ast.Constant)
                    and isinstance(elt.elts[0].value, str)
                ):
                    entries.append((elt.elts[0].value, elt.lineno))
    return entries


@rule(
    "KF701",
    "consensus-coverage",
    "the knob registry's consensus flags and the engine's consensus "
    "tuple (HostSession.engine_knobs) must agree exactly: a knob "
    "declared consensus=True but absent from the tuple would let peers "
    "resolve divergent walk-layout/codec values and deadlock on "
    "rendezvous names the consensus check never compared; a tuple entry "
    "not flagged in the registry leaves the single source of truth "
    "lying. The registry is authoritative — flag the knob there, cover "
    "it in engine_knobs(), or do neither",
    scope="project",
)
def check_consensus_coverage(project: Project) -> List[Finding]:
    reg_ctx = sess_ctx = None
    for ctx in project.files:
        if ctx.relpath == _REGISTRY_FILE:
            reg_ctx = ctx
        elif ctx.relpath == _CONSENSUS_FILE:
            sess_ctx = ctx
    if reg_ctx is None:
        return []  # not a tree with a knob registry (fixture subsets)
    decls = _knob_registry_decls(reg_ctx)
    consensus_decls = {
        name: line for name, (line, flag) in decls.items() if flag
    }
    if sess_ctx is None:
        if not consensus_decls:
            return []
        return [Finding(
            "KF701", _REGISTRY_FILE, 1,
            f"registry declares {len(consensus_decls)} consensus knobs "
            f"but {_CONSENSUS_FILE} (the engine_knobs() consensus tuple) "
            "is missing from the analyzed tree — the coverage "
            "cross-check cannot run",
        )]
    entries = _consensus_tuple_entries(sess_ctx)
    if not entries:
        # the scan must keep finding the tuple — a rename must not
        # silently turn this rule into a no-op
        return [Finding(
            "KF701", _CONSENSUS_FILE, 1,
            f"no literal-named entries found in {_CONSENSUS_FN}() — the "
            "consensus-tuple scan looks broken (rename?), fix the rule "
            "before trusting it",
        )]
    covered = {name for name, _ in entries}
    out = []
    for name, line in sorted(consensus_decls.items()):
        if name not in covered:
            out.append(Finding(
                "KF701", _REGISTRY_FILE, line,
                f"knob {name} is declared consensus=True (cluster-"
                "agreed) but does not appear in the engine_knobs() "
                f"consensus tuple ({_CONSENSUS_FILE}) — peers could "
                "resolve divergent values and deadlock on mismatched "
                "rendezvous names with no fail-fast; add it to the "
                "tuple",
            ))
    for name, line in entries:
        if name in decls and not decls[name][1]:
            out.append(Finding(
                "KF701", _CONSENSUS_FILE, line,
                f"engine_knobs() covers {name} but the registry does "
                "not declare it consensus=True — the registry is the "
                "single source of truth for the cluster-agreed set; "
                "flag it there (or drop it from the tuple)",
            ))
        elif name not in decls:
            out.append(Finding(
                "KF701", _CONSENSUS_FILE, line,
                f"engine_knobs() covers {name!r}, which the knob "
                "registry does not declare at all",
            ))
    return out


# the collective rendezvous entry points KF702 treats as "every peer
# must reach this together": method-call spellings only (module
# functions like functools.reduce stay out of scope)
_KF702_COLLECTIVES = frozenset({
    "all_reduce", "monitored_all_reduce", "group_all_reduce",
    "cross_all_reduce", "all_gather", "all_gather_shards",
    "reduce_scatter", "barrier", "bytes_consensus", "broadcast_bytes",
    "subset_all_reduce", "all_reduce_with", "group_all_reduce_async",
    "all_reduce_array", "run_barrier", "consensus",
})

# rank/identity attributes whose comparison marks a branch as
# peer-asymmetric
_KF702_IDENTITY = frozenset({
    "rank", "local_rank", "self_rank", "self_id", "local_size",
})


def _is_rank_test(test: ast.expr) -> bool:
    for node in ast.walk(test):
        if isinstance(node, ast.Compare):
            sides = [node.left] + list(node.comparators)
            for side in sides:
                seg = _last_segment(side)
                if seg in _KF702_IDENTITY:
                    return True
    return False


def _collective_calls(nodes: Sequence[ast.stmt]) -> List[ast.Call]:
    out = []
    for stmt in nodes:
        for node in ast.walk(stmt):
            if (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in _KF702_COLLECTIVES
            ):
                out.append(node)
    return out


@rule(
    "KF702",
    "collective-symmetry",
    "a collective call lexically guarded by a rank/peer-identity "
    "conditional with no collective in the counterpart branch means one "
    "subset of peers enters a rendezvous the rest never will — the "
    "static shadow of the scheduler's registration-divergence error, "
    "caught at review time instead of as a hang. Rooted data movement "
    "belongs in the engine's graph walks (reduce/broadcast/gather take "
    "a root argument and are called by every peer)",
)
def check_collective_symmetry(ctx: FileContext) -> List[Finding]:
    if ctx.tree is None:
        return []
    out = []
    for node in ctx.walk():
        if not isinstance(node, ast.If) or not _is_rank_test(node.test):
            continue
        body_calls = _collective_calls(node.body)
        else_calls = _collective_calls(node.orelse)
        lopsided = None
        if body_calls and not else_calls:
            lopsided = body_calls[0]
        elif else_calls and not body_calls:
            lopsided = else_calls[0]
        if lopsided is None:
            continue
        out.append(Finding(
            "KF702", ctx.relpath, lopsided.lineno,
            f".{lopsided.func.attr}() runs under a rank/identity "
            f"conditional (line {node.lineno}) whose other branch "
            "reaches no collective — peers taking the other branch "
            "never enter this rendezvous and the cluster hangs; make "
            "both branches collectively symmetric or lift the call out "
            "of the conditional",
        ))
    return out


# KF703: caller-owned-buffer mutation discipline for the walk engines.
# These modules write buffers the CALLER still owns (workspace recv
# views, torch param views) from background stages; PR 4 established —
# and PR 9 re-learned — that every such write must be dominated by an
# abort/cancel check, or a late-arriving stage writes into a buffer the
# caller already reused after a timeout.
_KF703_MODULES = (
    "kungfu_tpu/collective/walks.py",
    "kungfu_tpu/collective/pipeline.py",
    "kungfu_tpu/collective/zero.py",
)

_KF703_ABORT_NAMES = frozenset({"cancel", "abort", "_abort"})

# mutation helpers whose FIRST argument is the destination buffer
_KF703_WRITE_FNS = frozenset({
    "copyto", "decode_wire", "decode_accumulate", "reduce_inplace",
    "reduce_segment", "copy_segment", "transform2", "transform_n",
    "decode_into",
})


def _own_scope_stmts(fn: ast.AST) -> Iterable[ast.AST]:
    """Nodes of a function body EXCLUDING nested function/lambda bodies
    (a nested closure runs under its own abort discipline)."""
    stack = list(getattr(fn, "body", []))
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


def _caller_buffer_write(node: ast.AST, param_iters: Set[str]) -> Optional[str]:
    """A short label when `node` writes a caller-owned buffer, else
    None. Caller-owned: `<x>.recv` workspace views, the segmented
    walk's `acc` accumulator alias, and loop variables iterating a
    `.params` sequence (torch/optimizer views scatter writes back)."""
    def owned(expr: ast.expr) -> Optional[str]:
        seg = _last_segment(expr)
        if seg == "recv":
            return _attr_chain(expr) or "recv"
        if isinstance(expr, ast.Name) and (
            expr.id == "acc" or expr.id in param_iters
        ):
            return expr.id
        if isinstance(expr, ast.Subscript):
            return owned(expr.value)
        return None

    if isinstance(node, ast.Call):
        if _last_segment(node.func) in _KF703_WRITE_FNS and node.args:
            dst = owned(node.args[0])
            if dst is not None:
                return f"{_last_segment(node.func)}({dst}, ...)"
        return None
    if isinstance(node, ast.Assign):
        for tgt in node.targets:
            if isinstance(tgt, ast.Subscript):
                dst = owned(tgt.value)
                if dst is not None:
                    return f"{dst}[...] = ..."
    return None


@rule(
    "KF703",
    "caller-buffer-ownership",
    "in the walk-engine modules (collective/walks.py, pipeline.py, "
    "zero.py) every write to a caller-owned buffer (workspace .recv "
    "views, the segmented accumulator, param views) must be dominated "
    "by an abort/cancel is_set() check in the same function scope — a "
    "stage that skips the check can write a buffer the caller already "
    "reused after a timeout (the PR 4/PR 9 pre-mutation discipline, "
    "generalized)",
)
def check_caller_buffer_ownership(ctx: FileContext) -> List[Finding]:
    if ctx.relpath not in _KF703_MODULES or ctx.tree is None:
        return []
    out: List[Finding] = []
    for fn in ctx.walk():
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        abort_refs = {
            a.arg for a in fn.args.args + fn.args.kwonlyargs
            if a.arg in _KF703_ABORT_NAMES
        }
        param_iters: Set[str] = set()
        checks: List[int] = []
        writes: List[Tuple[int, str]] = []
        for node in _own_scope_stmts(fn):
            if isinstance(node, ast.Name) and node.id in _KF703_ABORT_NAMES:
                abort_refs.add(node.id)
            if isinstance(node, ast.For):
                iter_names = {
                    n.attr for n in ast.walk(node.iter)
                    if isinstance(n, ast.Attribute)
                }
                if "params" in iter_names:
                    for t in ast.walk(node.target):
                        if isinstance(t, ast.Name):
                            param_iters.add(t.id)
            if (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "is_set"
                and _last_segment(node.func.value) in _KF703_ABORT_NAMES
            ):
                checks.append(node.lineno)
        for node in _own_scope_stmts(fn):
            label = _caller_buffer_write(node, param_iters)
            if label is not None:
                writes.append((node.lineno, label))
        first_check = min(checks) if checks else None
        for lineno, label in sorted(writes):
            # a detected is_set() call IS proof of an abort scope even
            # when the event is held as an attribute (self._abort) the
            # Name-based abort_refs scan cannot see
            if not abort_refs and not checks:
                out.append(Finding(
                    "KF703", ctx.relpath, lineno,
                    f"caller-owned buffer write {label} in a function "
                    "with no abort/cancel in scope — thread the cancel "
                    "event through and check it before mutating, or "
                    "document the caller's guard with a suppression",
                ))
            elif first_check is None or lineno < first_check:
                out.append(Finding(
                    "KF703", ctx.relpath, lineno,
                    f"caller-owned buffer write {label} precedes every "
                    "abort/cancel is_set() check in this function — a "
                    "cancelled walk must observe the abort BEFORE "
                    "mutating buffers the caller may have reused",
                ))
    return out
