"""Single devtools gate: ``python -m kungfu_tpu.devtools.check``.

One command, one exit code, every project invariant (ISSUE 12
satellite). CI and the tier-1 gate used to make three separate
invocations — kfcheck over the tree, the docs/knobs.md byte-compare,
the metric-doc lint — each with its own entry point and failure shape.
All three are kfcheck rules today, so this driver runs the full rule
set ONCE (per-file cache and all) and sections the report by concern:

- ``[kfcheck]``      the code rules (KF0xx–KF5xx, KF7xx)
- ``[knobs-doc]``    docs/knobs.md vs the knob registry (KF102)
- ``[metric-docs]``  docs/telemetry.md vs registered families (KF600/601)
- ``[span-docs]``    docs/telemetry.md's span table vs emitted span
  kinds (KF602, ISSUE 13 satellite)
- ``[audit-docs]``   docs/telemetry.md's audit event table vs recorded
  audit kinds (KF604, ISSUE 15 satellite)
- ``[signal-docs]``  docs/telemetry.md's policy signal table vs the
  keys written into PolicyContext.metrics (KF605, ISSUE 16 satellite)
- ``[endpoint-docs]`` docs/telemetry.md's endpoint table vs the HTTP
  routes the worker server and cluster aggregator actually serve
  (KF606, ISSUE 18 satellite)

Exit status is the contract — 0 clean, 1 findings — matching the
kfcheck CLI. ``tests/test_kfcheck.py`` invokes it as the tier-1 gate;
the historical shims (tests/test_metrics_doc_lint.py,
tests/test_no_bare_print.py) keep their names but all ride this one
driver.
"""

from __future__ import annotations

import argparse
import sys
from typing import List

from kungfu_tpu.devtools.kfcheck import core

_DOC_RULES_KNOBS = ("KF102",)
_DOC_RULES_METRICS = ("KF600", "KF601")
_DOC_RULES_SPANS = ("KF602",)
_DOC_RULES_AUDIT = ("KF604",)
_DOC_RULES_SIGNALS = ("KF605",)
_DOC_RULES_ENDPOINTS = ("KF606",)


def _section(findings: List["core.Finding"], title: str, rules) -> List[str]:
    hits = [f for f in findings if f.rule in rules] if rules else findings
    lines = [f"[{title}] {'clean' if not hits else f'{len(hits)} finding(s)'}"]
    lines.extend("  " + f.render() for f in hits)
    return lines


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m kungfu_tpu.devtools.check",
        description="the whole devtools gate in one invocation: kfcheck "
        "rules, knobs-doc staleness, metric-doc lint (exit 0 = clean)",
    )
    p.add_argument("--no-cache", action="store_true",
                   help="bypass the kfcheck per-file result cache")
    args = p.parse_args(argv)

    core._ensure_rules_loaded()
    findings = core.run_project(use_cache=not args.no_cache)
    doc_rules = (
        set(_DOC_RULES_KNOBS) | set(_DOC_RULES_METRICS)
        | set(_DOC_RULES_SPANS) | set(_DOC_RULES_AUDIT)
        | set(_DOC_RULES_SIGNALS) | set(_DOC_RULES_ENDPOINTS)
    )
    code = [f for f in findings if f.rule not in doc_rules]
    out: List[str] = []
    out.extend(_section(code, "kfcheck", None))
    out.extend(_section(findings, "knobs-doc", _DOC_RULES_KNOBS))
    out.extend(_section(findings, "metric-docs", _DOC_RULES_METRICS))
    out.extend(_section(findings, "span-docs", _DOC_RULES_SPANS))
    out.extend(_section(findings, "audit-docs", _DOC_RULES_AUDIT))
    out.extend(_section(findings, "signal-docs", _DOC_RULES_SIGNALS))
    out.extend(_section(findings, "endpoint-docs", _DOC_RULES_ENDPOINTS))
    n = len(findings)
    out.append(
        "check: clean" if n == 0
        else f"check: {n} finding{'s' if n != 1 else ''}"
    )
    sys.stdout.write("\n".join(out) + "\n")
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
