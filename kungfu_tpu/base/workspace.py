"""Workspace: a named (send, recv, op) triple for one host collective.

Capability parity: srcs/go/kungfu/base/workspace.go:10-50 (Workspace with
``Split`` by partition function) and vector.go (zero-copy typed views).
Numpy arrays already give us zero-copy typed slicing, so there is no
separate Vector class.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, List, Sequence, Tuple

import numpy as np

from kungfu_tpu.base.ops import ReduceOp

# (begin, end) element intervals; mirrors plan.EvenPartition over Interval
# (srcs/go/plan/interval.go).
PartitionFunc = Callable[[int, int], Sequence[Tuple[int, int]]]


def even_partition(count: int, k: int) -> List[Tuple[int, int]]:
    """Split [0, count) into k contiguous intervals of near-equal size."""
    q, r = divmod(count, k)
    out = []
    begin = 0
    for i in range(k):
        end = begin + q + (1 if i < r else 0)
        out.append((begin, end))
        begin = end
    return out


@dataclasses.dataclass
class Workspace:
    send: np.ndarray  # 1-D
    recv: np.ndarray  # 1-D, same dtype/length as send
    op: ReduceOp
    name: str

    @property
    def is_empty(self) -> bool:
        return self.send.size == 0

    @property
    def is_inplace(self) -> bool:
        return self.send is self.recv or (
            self.send.__array_interface__["data"][0]
            == self.recv.__array_interface__["data"][0]
            and self.send.size == self.recv.size
        )

    def forward(self) -> None:
        """Copy send into recv (used when this rank only forwards data)."""
        if not self.is_inplace:
            np.copyto(self.recv, self.send)

    def split(self, partition: PartitionFunc, k: int) -> List["Workspace"]:
        """Split into k sub-workspaces named ``<name>[i/k]``."""
        out = []
        for i, (begin, end) in enumerate(partition(self.send.size, k)):
            out.append(
                Workspace(
                    send=self.send[begin:end],
                    recv=self.recv[begin:end],
                    op=self.op,
                    name=f"{self.name}[{i}/{k}]",
                )
            )
        return out
