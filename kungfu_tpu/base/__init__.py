from kungfu_tpu.base.dtype import DType
from kungfu_tpu.base.ops import ReduceOp, transform2
from kungfu_tpu.base.strategy import Strategy
from kungfu_tpu.base.workspace import Workspace

__all__ = ["DType", "ReduceOp", "Strategy", "Workspace", "transform2"]
