"""Element dtypes shared by host-side buffers and device arrays.

Capability parity: the reference's dtype enum mirrored between Go and C
(srcs/go/kungfu/base/dtype.go:8-22, srcs/cpp/include/kungfu/dtype.h).
TPU-first addition: BF16 is a first-class dtype (the MXU's native input
format); the reference only knows IEEE F16 (reduced via AVX F16C).
"""

from __future__ import annotations

import enum

import numpy as np


class DType(enum.IntEnum):
    U8 = 1
    I8 = 2
    I16 = 3
    I32 = 4
    I64 = 5
    U16 = 6
    U32 = 7
    U64 = 8
    F16 = 9
    BF16 = 10
    F32 = 11
    F64 = 12

    @property
    def size(self) -> int:
        """Size in bytes of one element."""
        return _SIZES[self]

    def to_numpy(self) -> np.dtype:
        try:
            return np.dtype(_NUMPY[self])
        except KeyError:
            raise ValueError(f"{self.name} requires ml_dtypes") from None

    @classmethod
    def from_numpy(cls, dt) -> "DType":
        dt = np.dtype(dt)
        try:
            return _FROM_NUMPY[dt.name]
        except KeyError:
            raise ValueError(f"unsupported dtype: {dt}") from None


_SIZES = {
    DType.U8: 1,
    DType.I8: 1,
    DType.I16: 2,
    DType.I32: 4,
    DType.I64: 8,
    DType.U16: 2,
    DType.U32: 4,
    DType.U64: 8,
    DType.F16: 2,
    DType.BF16: 2,
    DType.F32: 4,
    DType.F64: 8,
}

# bfloat16 comes from ml_dtypes (always present with jax).
try:
    import ml_dtypes

    _BF16 = np.dtype(ml_dtypes.bfloat16)
except ImportError:  # pragma: no cover - ml_dtypes ships with jax
    _BF16 = None

_NUMPY = {
    DType.U8: np.uint8,
    DType.I8: np.int8,
    DType.I16: np.int16,
    DType.I32: np.int32,
    DType.I64: np.int64,
    DType.U16: np.uint16,
    DType.U32: np.uint32,
    DType.U64: np.uint64,
    DType.F16: np.float16,
    DType.BF16: _BF16,
    DType.F32: np.float32,
    DType.F64: np.float64,
}

if _BF16 is None:  # pragma: no cover
    del _NUMPY[DType.BF16]

_FROM_NUMPY = {np.dtype(v).name: k for k, v in _NUMPY.items()}
