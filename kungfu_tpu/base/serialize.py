"""Dtype-preserving pytree-leaf serialization for host-plane exchange.

Used by elastic state re-sync (broadcast of live training state to
joiners) and the PairAveraging p2p model blobs. The wire format is a JSON
header of (dtype, shape) per leaf followed by each leaf's raw bytes —
np.savez cannot round-trip ml_dtypes leaves (bfloat16 / float8), which are
the PRIMARY TPU training dtypes.
"""

from __future__ import annotations

import json
import struct

import numpy as np


def resolve_dtype(name: str) -> np.dtype:
    """Resolve a dtype name, including ml_dtypes extension types (bfloat16,
    float8_*) that plain np.dtype() does not know by string."""
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes

        return np.dtype(getattr(ml_dtypes, name))


def pack_leaves(leaves) -> bytes:
    """Serialize a list of arrays as raw bytes + explicit dtype/shape."""
    arrs = [np.asarray(l) for l in leaves]
    meta = json.dumps(
        [{"dtype": a.dtype.name, "shape": list(a.shape)} for a in arrs]
    ).encode()
    parts = [struct.pack("<Q", len(meta)), meta]
    for a in arrs:
        parts.append(np.ascontiguousarray(a).tobytes())
    return b"".join(parts)


def unpack_leaves(blob: bytes, n: int):
    """Inverse of pack_leaves; validates the leaf count."""
    (meta_len,) = struct.unpack_from("<Q", blob, 0)
    meta = json.loads(blob[8 : 8 + meta_len].decode())
    if len(meta) != n:
        raise ValueError(f"leaf unpack: expected {n} leaves, got {len(meta)}")
    out, off = [], 8 + meta_len
    for m in meta:
        dt = resolve_dtype(m["dtype"])
        count = int(np.prod(m["shape"])) if m["shape"] else 1
        nbytes = count * dt.itemsize
        a = np.frombuffer(blob, dt, count=count, offset=off).reshape(m["shape"])
        out.append(a)
        off += nbytes
    return out
