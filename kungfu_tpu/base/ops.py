"""Host-side reduction kernels.

Capability parity: the reference's ``Transform2`` SIMD reduce
(srcs/go/kungfu/base/op.go:25-36 -> op.cpp ``std_transform_2``, with AVX
F16C for f16 in base/f16.c). Here the hot path is delegated to a small C++
kernel (native/reduce.cpp, loaded via ctypes) when built, with a numpy
fallback that is itself vectorized.

These run on the host only — control-plane collectives and the DCN-level
engine. Device reductions are XLA ``psum`` etc. (kungfu_tpu.ops).
"""

from __future__ import annotations

import enum

import numpy as np


class ReduceOp(enum.IntEnum):
    SUM = 0
    MIN = 1
    MAX = 2
    PROD = 3


_NUMPY_OPS = {
    ReduceOp.SUM: np.add,
    ReduceOp.MIN: np.minimum,
    ReduceOp.MAX: np.maximum,
    ReduceOp.PROD: np.multiply,
}

_native = None


def _load_native():
    """Load the optional C++ reduce kernel (built by native/build.sh)."""
    global _native
    if _native is None:
        try:
            from kungfu_tpu.base import _native_reduce

            _native = _native_reduce
        except (ImportError, OSError):  # missing/stale .so: numpy path
            _native = False
    return _native


def transform2(dst: np.ndarray, x: np.ndarray, y: np.ndarray, op: ReduceOp) -> None:
    """dst = x `op` y, elementwise; dst may alias x or y.

    All three must be 1-D views of equal length and dtype.
    """
    native = _load_native()
    if native and native.supported(x.dtype):
        native.transform2(dst, x, y, int(op))
        return
    _NUMPY_OPS[op](x, y, out=dst)


def reduce_inplace(acc: np.ndarray, incoming: np.ndarray, op: ReduceOp) -> None:
    """acc = acc `op` incoming."""
    transform2(acc, acc, incoming, op)


def _check_segment(buf: np.ndarray, begin: int, end: int,
                   incoming: np.ndarray) -> None:
    """Segment-bounds contract of the ring walks AND the sharded update
    (ISSUE 11): [begin, end) must lie inside the buffer and `incoming`
    must carry exactly end-begin elements. The native transform kernels
    take raw pointers and do NOT shape-check, so a shard-layout drift
    between sender and receiver (e.g. tensors that don't divide by k,
    partitioned differently on each side) must fail HERE, loudly — not
    corrupt adjacent segments silently. The layout itself is
    single-sourced in plan.topology.owned_segment_bounds/even_partition."""
    if not 0 <= begin <= end <= buf.size:
        raise ValueError(
            f"segment [{begin}:{end}) outside buffer of {buf.size} elements"
        )
    if incoming.size != end - begin:
        raise ValueError(
            f"segment payload mismatch: got {incoming.size} elements for "
            f"segment [{begin}:{end}) of {end - begin} — sender and "
            "receiver partitioned the payload differently"
        )


def reduce_segment(
    acc: np.ndarray, begin: int, end: int, incoming: np.ndarray, op: ReduceOp
) -> None:
    """acc[begin:end] = acc[begin:end] `op` incoming, in place.

    Offset segment reduction for the segmented ring walk: the accumulator
    is a zero-copy view into the full recv buffer, so per-step reduction
    touches only the 1/k segment on the wire — no staging copies, no
    full-payload passes."""
    _check_segment(acc, begin, end, incoming)
    seg = acc[begin:end]
    transform2(seg, seg, incoming, op)


def copy_segment(
    dst: np.ndarray, begin: int, end: int, incoming: np.ndarray
) -> None:
    """dst[begin:end] = incoming (all-gather phase: overwrite, no reduce)."""
    _check_segment(dst, begin, end, incoming)
    np.copyto(dst[begin:end], incoming)


# ---------------------------------------------------------------------------
# wire codec: f32 payloads travel the host plane as bf16/f16
# ---------------------------------------------------------------------------
#
# The collective engine encodes f32 workspaces to a 2-byte wire dtype
# before the transport and accumulates every incoming segment into the
# f32 buffer (fused decode+reduce), so each transmitted value is
# quantized exactly once and no rounding ever happens in 16-bit storage.
# Native kernels when built (kf_encode_wire / kf_decode_wire /
# kf_decode_accumulate, guarded like kf_transform_n); the numpy fallback
# is pure bit manipulation for bf16 (no ml_dtypes dependency) and astype
# for f16 — both round to nearest-even, bit-matching the native path.

from kungfu_tpu.base.dtype import DType

WIRE_DTYPES = (DType.BF16, DType.F16)


def _wire_native():
    native = _load_native()
    if native and getattr(native, "has_wire_codec", False):
        return native
    return None


def _check_wire(wire: DType) -> None:
    if wire not in WIRE_DTYPES:
        raise ValueError(f"unsupported wire dtype: {wire!r}")


def encode_wire(dst: np.ndarray, src: np.ndarray, wire: DType) -> None:
    """dst_u16 = encode(src_f32): round-to-nearest-even narrowing to the
    wire dtype. dst is a uint16 array of the same length as src."""
    _check_wire(wire)
    native = _wire_native()
    if native is not None:
        native.encode_wire(dst, src, int(wire))
        return
    if wire == DType.F16:
        # overflow-to-inf is the codec contract (matches the native
        # kernel); numpy warns on the cast, so silence just that
        with np.errstate(over="ignore"):
            dst[:] = src.astype(np.float16).view(np.uint16)
        return
    bits = src.view(np.uint32)
    # bf16 fold with RNE: (bits + 0x7fff + lsb-of-result) >> 16
    dst[:] = ((bits + np.uint32(0x7FFF) + ((bits >> np.uint32(16)) & np.uint32(1)))
              >> np.uint32(16)).astype(np.uint16)


def decode_wire(dst: np.ndarray, src: np.ndarray, wire: DType) -> None:
    """dst_f32 = decode(src_u16): exact widening from the wire dtype."""
    _check_wire(wire)
    native = _wire_native()
    if native is not None:
        native.decode_wire(dst, src, int(wire))
        return
    if wire == DType.F16:
        dst[:] = src.view(np.float16)
        return
    dst.view(np.uint32)[:] = src.astype(np.uint32) << np.uint32(16)


def decode_accumulate(
    acc: np.ndarray, begin: int, end: int, src: np.ndarray,
    wire: DType, op: ReduceOp,
) -> None:
    """acc[begin:end] = acc[begin:end] `op` decode(src), in f32.

    The per-step hot path of the compressed ring walk: the native kernel
    fuses decode and reduce into one pass over the segment so the wire
    payload is read once; the fallback decodes into a temporary then
    reduces (two passes, still f32 accumulation)."""
    _check_wire(wire)
    _check_segment(acc, begin, end, src)
    seg = acc[begin:end]
    native = _wire_native()
    if native is not None:
        native.decode_accumulate(seg, src, int(wire), int(op))
        return
    tmp = np.empty(seg.size, np.float32)
    decode_wire(tmp, src, wire)
    _NUMPY_OPS[op](seg, tmp, out=seg)


# ---------------------------------------------------------------------------
# block-scaled int8/int4 wire codec (ISSUE 20)
# ---------------------------------------------------------------------------
#
# Each `block`-element run of the f32 payload is scaled by one f32
# power-of-two s = 2^ceil(log2(absmax / Qmax)) and quantized to
# q = clamp(rne(x / s), -Qmax, Qmax) with Qmax = 127 (int8) / 7 (int4) —
# the encoded segment is [ceil(n/block) f32 scales][packed payload].
# The pow2 scale makes decode (s * q) EXACT in f32 and re-encoding a
# decoded block reproduce the identical bytes (idempotent re-encode),
# which is what lets graph-walk relays and the bcast-root roundtrip keep
# cross-peer bit-identity, matching the 2-byte codec's contract.
# Accumulation stays f32 (fused decode+reduce), and the collective layer
# adds error-feedback residuals so per-step rounding telescopes instead
# of compounding. Native kernels behind `has_wire_codec_q`; the numpy
# fallback below bit-matches them (np.frexp/np.ldexp/np.rint mirror
# frexpf/ldexpf/rintf — both sides round to nearest-even).


class QWire:
    """Wire spec for the block-scaled low-bit codec.

    Stands in for a ``DType`` in the walk layer's ``wire`` parameter:
    ``.name`` lowercases to the ``codec`` metric label ("int8"/"int4")
    exactly like ``DType.BF16.name``; payload sizes come from
    :func:`wire_nbytes`, not ``2 * count``.
    """

    __slots__ = ("bits", "block", "name")

    def __init__(self, bits: int, block: int = 16):
        if bits not in (8, 4):
            raise ValueError(f"unsupported wire bits: {bits!r}")
        if block < 1:
            raise ValueError(f"wire block must be >= 1: {block!r}")
        self.bits = int(bits)
        self.block = int(block)
        self.name = f"INT{bits}"

    def __repr__(self) -> str:
        return f"QWire(bits={self.bits}, block={self.block})"

    def __eq__(self, other) -> bool:
        return (
            isinstance(other, QWire)
            and self.bits == other.bits
            and self.block == other.block
        )

    def __hash__(self) -> int:
        return hash((QWire, self.bits, self.block))


def wire_nbytes_q(count: int, bits: int, block: int) -> int:
    """Encoded byte length of `count` f32 elements under the block-scaled
    layout: 4 bytes of scale per block + 1 byte (int8) or a nibble
    (int4, odd counts round up) per element."""
    nb = (count + block - 1) // block
    return 4 * nb + (count if bits == 8 else (count + 1) // 2)


def wire_nbytes(count: int, wire) -> int:
    """Encoded byte length of `count` f32 elements under any wire spec —
    2 bytes/element for the 16-bit dtypes, the block-scaled layout for
    :class:`QWire`."""
    if isinstance(wire, QWire):
        return wire_nbytes_q(count, wire.bits, wire.block)
    return 2 * count


def _wire_native_q():
    native = _load_native()
    if native and getattr(native, "has_wire_codec_q", False):
        return native
    return None


def _q_scales(src: np.ndarray, bits: int, block: int) -> np.ndarray:
    """Per-block pow2 scales, bit-matching the native q_block_scale."""
    n = src.size
    nb = (n + block - 1) // block
    padded = np.zeros(nb * block, np.float32)
    padded[:n] = src
    amax = np.max(np.abs(padded.reshape(nb, block)), axis=1)
    qmax = np.float32(127.0 if bits == 8 else 7.0)
    with np.errstate(divide="ignore", invalid="ignore"):
        t = amax / qmax
        m, e = np.frexp(t)
        s = np.ldexp(np.float32(1.0), np.where(m == np.float32(0.5), e - 1, e))
    return np.where(amax == 0.0, np.float32(0.0), s.astype(np.float32))


def encode_wire_q(dst: np.ndarray, src: np.ndarray, wire: QWire) -> None:
    """dst_u8 = [block scales][packed payload] of src_f32. dst must hold
    exactly ``wire_nbytes(src.size, wire)`` bytes."""
    n = src.size
    nb = (n + wire.block - 1) // wire.block
    if dst.size != wire_nbytes(n, wire):
        raise ValueError(
            f"encoded buffer mismatch: {dst.size} bytes for {n} elements "
            f"of {wire!r} (want {wire_nbytes(n, wire)})"
        )
    native = _wire_native_q()
    if native is not None:
        native.encode_wire_q(dst, src, wire.bits, wire.block)
        return
    s = _q_scales(src, wire.bits, wire.block)
    dst[: 4 * nb] = np.frombuffer(s.astype("<f4").tobytes(), np.uint8)
    qmax = np.float32(127.0 if wire.bits == 8 else 7.0)
    with np.errstate(divide="ignore"):
        inv = np.where(s == 0.0, np.float32(0.0), np.float32(1.0) / s)
    padded = np.zeros(nb * wire.block, np.float32)
    padded[:n] = src
    q = np.clip(
        np.rint(padded.reshape(nb, wire.block) * inv[:, None]), -qmax, qmax
    ).astype(np.int8).reshape(-1)[:n]
    if wire.bits == 8:
        dst[4 * nb:] = q.view(np.uint8)
        return
    if n & 1:
        q = np.concatenate([q, np.zeros(1, np.int8)])
    nibs = q.view(np.uint8) & np.uint8(0xF)
    dst[4 * nb:] = nibs[0::2] | (nibs[1::2] << np.uint8(4))


def decode_wire_q(dst: np.ndarray, src: np.ndarray, wire: QWire) -> None:
    """dst_f32 = decode(src_u8); element count comes from dst. Exact:
    every decoded value is a pow2 scale times a small integer."""
    n = dst.size
    nb = (n + wire.block - 1) // wire.block
    if src.size != wire_nbytes(n, wire):
        raise ValueError(
            f"encoded payload mismatch: {src.size} bytes for {n} elements "
            f"of {wire!r} (want {wire_nbytes(n, wire)})"
        )
    native = _wire_native_q()
    if native is not None:
        native.decode_wire_q(dst, src, wire.bits, wire.block)
        return
    s = np.frombuffer(src[: 4 * nb].tobytes(), "<f4").astype(np.float32)
    if wire.bits == 8:
        q = src[4 * nb:].view(np.int8).astype(np.float32)
    else:
        packed = src[4 * nb:]
        nibs = np.empty(2 * packed.size, np.uint8)
        nibs[0::2] = packed & np.uint8(0xF)
        nibs[1::2] = packed >> np.uint8(4)
        q = nibs[:n].astype(np.int16)
        q = np.where(q >= 8, q - 16, q).astype(np.float32)
    dst[:] = np.repeat(s, wire.block)[:n] * q


def decode_accumulate_q(
    acc: np.ndarray, begin: int, end: int, src: np.ndarray,
    wire: QWire, op: ReduceOp,
) -> None:
    """acc[begin:end] = acc[begin:end] `op` decode(src), in f32 — the
    fused per-step hot path of the quantized ring walk."""
    if not 0 <= begin <= end <= acc.size:
        raise ValueError(
            f"segment [{begin}:{end}) outside buffer of {acc.size} elements"
        )
    count = end - begin
    if src.size != wire_nbytes(count, wire):
        raise ValueError(
            f"encoded payload mismatch: {src.size} bytes for segment "
            f"[{begin}:{end}) of {wire!r} (want {wire_nbytes(count, wire)})"
        )
    seg = acc[begin:end]
    native = _wire_native_q()
    if native is not None:
        native.decode_accumulate_q(seg, src, wire.bits, wire.block, int(op))
        return
    tmp = np.empty(count, np.float32)
    decode_wire_q(tmp, src, wire)
    _NUMPY_OPS[op](seg, tmp, out=seg)


def encode_wire_any(dst: np.ndarray, src: np.ndarray, wire) -> None:
    """Encode under any wire spec (DType or QWire)."""
    if isinstance(wire, QWire):
        encode_wire_q(dst, src, wire)
    else:
        encode_wire(dst, src, wire)


def decode_wire_any(dst: np.ndarray, src: np.ndarray, wire) -> None:
    """Decode under any wire spec (DType or QWire)."""
    if isinstance(wire, QWire):
        decode_wire_q(dst, src, wire)
    else:
        decode_wire(dst, src, wire)


def decode_accumulate_any(
    acc: np.ndarray, begin: int, end: int, src: np.ndarray, wire, op: ReduceOp,
) -> None:
    """Fused decode+reduce under any wire spec (DType or QWire)."""
    if isinstance(wire, QWire):
        decode_accumulate_q(acc, begin, end, src, wire, op)
    else:
        decode_accumulate(acc, begin, end, src, wire, op)


def transform_n(dst: np.ndarray, srcs, op: ReduceOp) -> None:
    """dst = srcs[0] op srcs[1] op ... op srcs[k-1] in ONE memory pass
    (native kernel); dst must not alias any src. The k-1 pairwise
    equivalent re-reads and re-writes dst k-2 extra times — at a STAR
    root this n-ary form is the difference between ~5 and ~2k passes
    over the payload. Falls back to pairwise numpy."""
    if len(srcs) == 1:
        np.copyto(dst, srcs[0])
        return
    native = _load_native()
    if (
        native
        and getattr(native, "has_transform_n", False)
        and native.supported(dst.dtype)
    ):
        native.transform_n(dst, srcs, int(op))
        return
    _NUMPY_OPS[op](srcs[0], srcs[1], out=dst)
    for s in srcs[2:]:
        _NUMPY_OPS[op](dst, s, out=dst)
