"""Host-side reduction kernels.

Capability parity: the reference's ``Transform2`` SIMD reduce
(srcs/go/kungfu/base/op.go:25-36 -> op.cpp ``std_transform_2``, with AVX
F16C for f16 in base/f16.c). Here the hot path is delegated to a small C++
kernel (native/reduce.cpp, loaded via ctypes) when built, with a numpy
fallback that is itself vectorized.

These run on the host only — control-plane collectives and the DCN-level
engine. Device reductions are XLA ``psum`` etc. (kungfu_tpu.ops).
"""

from __future__ import annotations

import enum

import numpy as np


class ReduceOp(enum.IntEnum):
    SUM = 0
    MIN = 1
    MAX = 2
    PROD = 3


_NUMPY_OPS = {
    ReduceOp.SUM: np.add,
    ReduceOp.MIN: np.minimum,
    ReduceOp.MAX: np.maximum,
    ReduceOp.PROD: np.multiply,
}

_native = None


def _load_native():
    """Load the optional C++ reduce kernel (built by native/build.sh)."""
    global _native
    if _native is None:
        try:
            from kungfu_tpu.base import _native_reduce

            _native = _native_reduce
        except (ImportError, OSError):  # missing/stale .so: numpy path
            _native = False
    return _native


def transform2(dst: np.ndarray, x: np.ndarray, y: np.ndarray, op: ReduceOp) -> None:
    """dst = x `op` y, elementwise; dst may alias x or y.

    All three must be 1-D views of equal length and dtype.
    """
    native = _load_native()
    if native and native.supported(x.dtype):
        native.transform2(dst, x, y, int(op))
        return
    _NUMPY_OPS[op](x, y, out=dst)


def reduce_inplace(acc: np.ndarray, incoming: np.ndarray, op: ReduceOp) -> None:
    """acc = acc `op` incoming."""
    transform2(acc, acc, incoming, op)


def _check_segment(buf: np.ndarray, begin: int, end: int,
                   incoming: np.ndarray) -> None:
    """Segment-bounds contract of the ring walks AND the sharded update
    (ISSUE 11): [begin, end) must lie inside the buffer and `incoming`
    must carry exactly end-begin elements. The native transform kernels
    take raw pointers and do NOT shape-check, so a shard-layout drift
    between sender and receiver (e.g. tensors that don't divide by k,
    partitioned differently on each side) must fail HERE, loudly — not
    corrupt adjacent segments silently. The layout itself is
    single-sourced in plan.topology.owned_segment_bounds/even_partition."""
    if not 0 <= begin <= end <= buf.size:
        raise ValueError(
            f"segment [{begin}:{end}) outside buffer of {buf.size} elements"
        )
    if incoming.size != end - begin:
        raise ValueError(
            f"segment payload mismatch: got {incoming.size} elements for "
            f"segment [{begin}:{end}) of {end - begin} — sender and "
            "receiver partitioned the payload differently"
        )


def reduce_segment(
    acc: np.ndarray, begin: int, end: int, incoming: np.ndarray, op: ReduceOp
) -> None:
    """acc[begin:end] = acc[begin:end] `op` incoming, in place.

    Offset segment reduction for the segmented ring walk: the accumulator
    is a zero-copy view into the full recv buffer, so per-step reduction
    touches only the 1/k segment on the wire — no staging copies, no
    full-payload passes."""
    _check_segment(acc, begin, end, incoming)
    seg = acc[begin:end]
    transform2(seg, seg, incoming, op)


def copy_segment(
    dst: np.ndarray, begin: int, end: int, incoming: np.ndarray
) -> None:
    """dst[begin:end] = incoming (all-gather phase: overwrite, no reduce)."""
    _check_segment(dst, begin, end, incoming)
    np.copyto(dst[begin:end], incoming)


# ---------------------------------------------------------------------------
# wire codec: f32 payloads travel the host plane as bf16/f16
# ---------------------------------------------------------------------------
#
# The collective engine encodes f32 workspaces to a 2-byte wire dtype
# before the transport and accumulates every incoming segment into the
# f32 buffer (fused decode+reduce), so each transmitted value is
# quantized exactly once and no rounding ever happens in 16-bit storage.
# Native kernels when built (kf_encode_wire / kf_decode_wire /
# kf_decode_accumulate, guarded like kf_transform_n); the numpy fallback
# is pure bit manipulation for bf16 (no ml_dtypes dependency) and astype
# for f16 — both round to nearest-even, bit-matching the native path.

from kungfu_tpu.base.dtype import DType

WIRE_DTYPES = (DType.BF16, DType.F16)


def _wire_native():
    native = _load_native()
    if native and getattr(native, "has_wire_codec", False):
        return native
    return None


def _check_wire(wire: DType) -> None:
    if wire not in WIRE_DTYPES:
        raise ValueError(f"unsupported wire dtype: {wire!r}")


def encode_wire(dst: np.ndarray, src: np.ndarray, wire: DType) -> None:
    """dst_u16 = encode(src_f32): round-to-nearest-even narrowing to the
    wire dtype. dst is a uint16 array of the same length as src."""
    _check_wire(wire)
    native = _wire_native()
    if native is not None:
        native.encode_wire(dst, src, int(wire))
        return
    if wire == DType.F16:
        # overflow-to-inf is the codec contract (matches the native
        # kernel); numpy warns on the cast, so silence just that
        with np.errstate(over="ignore"):
            dst[:] = src.astype(np.float16).view(np.uint16)
        return
    bits = src.view(np.uint32)
    # bf16 fold with RNE: (bits + 0x7fff + lsb-of-result) >> 16
    dst[:] = ((bits + np.uint32(0x7FFF) + ((bits >> np.uint32(16)) & np.uint32(1)))
              >> np.uint32(16)).astype(np.uint16)


def decode_wire(dst: np.ndarray, src: np.ndarray, wire: DType) -> None:
    """dst_f32 = decode(src_u16): exact widening from the wire dtype."""
    _check_wire(wire)
    native = _wire_native()
    if native is not None:
        native.decode_wire(dst, src, int(wire))
        return
    if wire == DType.F16:
        dst[:] = src.view(np.float16)
        return
    dst.view(np.uint32)[:] = src.astype(np.uint32) << np.uint32(16)


def decode_accumulate(
    acc: np.ndarray, begin: int, end: int, src: np.ndarray,
    wire: DType, op: ReduceOp,
) -> None:
    """acc[begin:end] = acc[begin:end] `op` decode(src), in f32.

    The per-step hot path of the compressed ring walk: the native kernel
    fuses decode and reduce into one pass over the segment so the wire
    payload is read once; the fallback decodes into a temporary then
    reduces (two passes, still f32 accumulation)."""
    _check_wire(wire)
    _check_segment(acc, begin, end, src)
    seg = acc[begin:end]
    native = _wire_native()
    if native is not None:
        native.decode_accumulate(seg, src, int(wire), int(op))
        return
    tmp = np.empty(seg.size, np.float32)
    decode_wire(tmp, src, wire)
    _NUMPY_OPS[op](seg, tmp, out=seg)


def transform_n(dst: np.ndarray, srcs, op: ReduceOp) -> None:
    """dst = srcs[0] op srcs[1] op ... op srcs[k-1] in ONE memory pass
    (native kernel); dst must not alias any src. The k-1 pairwise
    equivalent re-reads and re-writes dst k-2 extra times — at a STAR
    root this n-ary form is the difference between ~5 and ~2k passes
    over the payload. Falls back to pairwise numpy."""
    if len(srcs) == 1:
        np.copyto(dst, srcs[0])
        return
    native = _load_native()
    if (
        native
        and getattr(native, "has_transform_n", False)
        and native.supported(dst.dtype)
    ):
        native.transform_n(dst, srcs, int(op))
        return
    _NUMPY_OPS[op](srcs[0], srcs[1], out=dst)
    for s in srcs[2:]:
        _NUMPY_OPS[op](dst, s, out=dst)
