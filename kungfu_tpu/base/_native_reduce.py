"""ctypes loader for the native reduce kernel (native/reduce.cpp).

Exposes supported(dtype) and transform2(dst, x, y, op) used by
kungfu_tpu.base.ops; absent or failed builds fall back to numpy there.
"""

from __future__ import annotations

import ctypes
import os

import numpy as np

from kungfu_tpu.base.dtype import DType

_LIB_PATH = os.path.join(os.path.dirname(__file__), "libkfnative.so")

if not os.path.exists(_LIB_PATH):
    raise ImportError(f"native kernel not built: {_LIB_PATH}")

_lib = ctypes.CDLL(_LIB_PATH)
_lib.kf_transform2.restype = ctypes.c_int
_lib.kf_transform2.argtypes = [
    ctypes.c_void_p,
    ctypes.c_void_p,
    ctypes.c_void_p,
    ctypes.c_int64,
    ctypes.c_int32,
    ctypes.c_int32,
]


# Guarded: a libkfnative.so built before this symbol existed must not
# take down transform2 with it (ops._load_native treats any import-time
# error as "no native kernels at all")
try:
    _lib.kf_transform_n.restype = ctypes.c_int
    _lib.kf_transform_n.argtypes = [
        ctypes.c_void_p,
        ctypes.POINTER(ctypes.c_void_p),
        ctypes.c_int32,
        ctypes.c_int64,
        ctypes.c_int32,
        ctypes.c_int32,
    ]
    has_transform_n = True
except AttributeError:
    has_transform_n = False

# Same guard for the wire-codec kernels (f32 <-> bf16/f16 converters and
# the fused decode-accumulate): a stale .so degrades to the numpy codec
# in ops.py, not to an AttributeError mid-collective.
try:
    _lib.kf_encode_wire.restype = ctypes.c_int
    _lib.kf_encode_wire.argtypes = [
        ctypes.c_void_p,
        ctypes.c_void_p,
        ctypes.c_int64,
        ctypes.c_int32,
    ]
    _lib.kf_decode_wire.restype = ctypes.c_int
    _lib.kf_decode_wire.argtypes = [
        ctypes.c_void_p,
        ctypes.c_void_p,
        ctypes.c_int64,
        ctypes.c_int32,
    ]
    _lib.kf_decode_accumulate.restype = ctypes.c_int
    _lib.kf_decode_accumulate.argtypes = [
        ctypes.c_void_p,
        ctypes.c_void_p,
        ctypes.c_int64,
        ctypes.c_int32,
        ctypes.c_int32,
    ]
    has_wire_codec = True
except AttributeError:
    has_wire_codec = False

# Same guard again for the block-scaled int8/int4 codec (per-block pow2
# absmax scales + packed low-bit payload, f32 accumulation): a stale .so
# degrades to the numpy quantizer in ops.py.
try:
    _lib.kf_encode_wire_q.restype = ctypes.c_int
    _lib.kf_encode_wire_q.argtypes = [
        ctypes.c_void_p,
        ctypes.c_void_p,
        ctypes.c_int64,
        ctypes.c_int32,
        ctypes.c_int32,
    ]
    _lib.kf_decode_wire_q.restype = ctypes.c_int
    _lib.kf_decode_wire_q.argtypes = [
        ctypes.c_void_p,
        ctypes.c_void_p,
        ctypes.c_int64,
        ctypes.c_int32,
        ctypes.c_int32,
    ]
    _lib.kf_decode_accumulate_q.restype = ctypes.c_int
    _lib.kf_decode_accumulate_q.argtypes = [
        ctypes.c_void_p,
        ctypes.c_void_p,
        ctypes.c_int64,
        ctypes.c_int32,
        ctypes.c_int32,
        ctypes.c_int32,
    ]
    has_wire_codec_q = True
except AttributeError:
    has_wire_codec_q = False


def supported(dtype) -> bool:
    try:
        DType.from_numpy(dtype)
        return True
    except ValueError:
        return False


def _ptr(a: np.ndarray):
    return a.ctypes.data_as(ctypes.c_void_p) if a.flags["C_CONTIGUOUS"] else None


def transform2(dst: np.ndarray, x: np.ndarray, y: np.ndarray, op: int) -> None:
    dt = DType.from_numpy(dst.dtype)
    pd, px, py = _ptr(dst), _ptr(x), _ptr(y)
    if pd is None or px is None or py is None:
        raise ValueError("non-contiguous buffer")
    rc = _lib.kf_transform2(pd, px, py, dst.size, int(dt), int(op))
    if rc != 0:
        raise ValueError(f"native transform2 unsupported: dtype={dt}, op={op}")


def transform_n(dst: np.ndarray, srcs, op: int) -> None:
    """dst = srcs[0] op srcs[1] op ... in ONE pass; dst must not alias
    any src (native/reduce.cpp kf_transform_n)."""
    dt = DType.from_numpy(dst.dtype)
    pd = _ptr(dst)
    ptrs = (ctypes.c_void_p * len(srcs))()
    for i, s in enumerate(srcs):
        p = _ptr(s)
        if p is None:
            raise ValueError("non-contiguous buffer")
        ptrs[i] = p
    if pd is None:
        raise ValueError("non-contiguous buffer")
    rc = _lib.kf_transform_n(pd, ptrs, len(srcs), dst.size, int(dt), int(op))
    if rc != 0:
        raise ValueError(f"native transform_n unsupported: dtype={dt}, op={op}")


def encode_wire(dst: np.ndarray, src: np.ndarray, wire: int) -> None:
    """dst_u16 = encode(src_f32) to the wire dtype (DType.BF16/F16)."""
    pd, ps = _ptr(dst), _ptr(src)
    if pd is None or ps is None:
        raise ValueError("non-contiguous buffer")
    rc = _lib.kf_encode_wire(pd, ps, src.size, int(wire))
    if rc != 0:
        raise ValueError(f"native encode_wire unsupported: wire={wire}")


def decode_wire(dst: np.ndarray, src: np.ndarray, wire: int) -> None:
    """dst_f32 = decode(src_u16) from the wire dtype."""
    pd, ps = _ptr(dst), _ptr(src)
    if pd is None or ps is None:
        raise ValueError("non-contiguous buffer")
    rc = _lib.kf_decode_wire(pd, ps, src.size, int(wire))
    if rc != 0:
        raise ValueError(f"native decode_wire unsupported: wire={wire}")


def decode_accumulate(acc: np.ndarray, src: np.ndarray, wire: int, op: int) -> None:
    """acc_f32 = acc_f32 `op` decode(src_u16) — fused decode + reduce in
    one pass over the segment (native/reduce.cpp kf_decode_accumulate)."""
    pa, ps = _ptr(acc), _ptr(src)
    if pa is None or ps is None:
        raise ValueError("non-contiguous buffer")
    rc = _lib.kf_decode_accumulate(pa, ps, acc.size, int(wire), int(op))
    if rc != 0:
        raise ValueError(f"native decode_accumulate unsupported: wire={wire}, op={op}")


def encode_wire_q(dst: np.ndarray, src: np.ndarray, bits: int, block: int) -> None:
    """dst_u8 = [block scales f32][packed int8/int4 payload] of src_f32."""
    pd, ps = _ptr(dst), _ptr(src)
    if pd is None or ps is None:
        raise ValueError("non-contiguous buffer")
    rc = _lib.kf_encode_wire_q(pd, ps, src.size, int(bits), int(block))
    if rc != 0:
        raise ValueError(f"native encode_wire_q unsupported: bits={bits}, block={block}")


def decode_wire_q(dst: np.ndarray, src: np.ndarray, bits: int, block: int) -> None:
    """dst_f32 = decode(src_u8) from the block-scaled low-bit layout.
    Element count comes from dst (the payload length is derived)."""
    pd, ps = _ptr(dst), _ptr(src)
    if pd is None or ps is None:
        raise ValueError("non-contiguous buffer")
    rc = _lib.kf_decode_wire_q(pd, ps, dst.size, int(bits), int(block))
    if rc != 0:
        raise ValueError(f"native decode_wire_q unsupported: bits={bits}, block={block}")


def decode_accumulate_q(acc: np.ndarray, src: np.ndarray, bits: int, block: int,
                        op: int) -> None:
    """acc_f32 = acc_f32 `op` decode(src_u8) — fused block-scaled decode +
    reduce in one pass (native/reduce.cpp kf_decode_accumulate_q)."""
    pa, ps = _ptr(acc), _ptr(src)
    if pa is None or ps is None:
        raise ValueError("non-contiguous buffer")
    rc = _lib.kf_decode_accumulate_q(pa, ps, acc.size, int(bits), int(block), int(op))
    if rc != 0:
        raise ValueError(
            f"native decode_accumulate_q unsupported: bits={bits}, block={block}, op={op}"
        )
