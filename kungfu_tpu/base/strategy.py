"""Collective communication strategies (host/DCN plane).

Capability parity: the reference's strategy enum
(srcs/go/kungfu/base/strategy.go:10-22, srcs/cpp/include/kungfu/strategy.h),
selecting the graph topology used by the host-side collective engine.

On TPU the ICI data plane does not use these (XLA picks collective
algorithms); they drive the host-side (DCN-level) engine used for control
collectives (consensus, barrier, config digests) and CPU-only test clusters.
"""

from __future__ import annotations

import enum


class Strategy(enum.IntEnum):
    STAR = 0
    RING = 1
    CLIQUE = 2
    TREE = 3
    BINARY_TREE = 4
    BINARY_TREE_STAR = 5
    AUTO = 6
    MULTI_BINARY_TREE_STAR = 7
    MULTI_STAR = 8
    # Bandwidth-optimal segmented ring: allreduce runs as a (k-1)-step
    # reduce-scatter over contiguous segments followed by a (k-1)-step
    # all-gather, so each peer moves only 2*(k-1)/k of the payload instead
    # of relaying full copies through tree/star roots. Executed by the
    # engine's dedicated segmented walk, not a graph pair; the residual
    # graph ops (reduce/broadcast/gather) fall back to a rank-0 binary
    # tree (see collective/strategies.py).
    RING_SEGMENTED = 9

    @classmethod
    def parse(cls, name: str) -> "Strategy":
        try:
            return cls[name.strip().upper().replace("-", "_")]
        except KeyError:
            raise ValueError(f"unknown strategy: {name!r}") from None


DEFAULT_STRATEGY = Strategy.BINARY_TREE_STAR
