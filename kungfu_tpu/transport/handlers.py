"""Endpoint handlers: collective rendezvous, p2p store, queues, control.

Capability parity: srcs/go/rchannel/handler/{collective,p2p,queue}.go —
- CollectiveEndpoint: named rendezvous queues; Recv blocks until a message
  with that name arrives (graph-walk collectives pair send/recv by name).
- PeerToPeerEndpoint: request/response over a versioned blob store (the
  PairAveraging model exchange).
- QueueHandler: named FIFO queues between peers.
- ControlHandler: delivers cluster Stage updates to a callback.
"""

from __future__ import annotations

import dataclasses
import json
import threading
from collections import defaultdict, deque
from typing import Callable, Dict, Optional, Tuple

from kungfu_tpu.plan.peer import PeerID
from kungfu_tpu.transport.message import ConnType, Flags, Message


class _Sink:
    """A receiver-registered destination buffer. The transport thread
    delivers a matching payload straight off the socket into `view`
    (zero-copy receive, parity: WAIT_RECV_BUF / handler/collective.go
    RecvInto)."""

    __slots__ = ("view", "state", "flags")
    WAITING, TAKEN, DONE, FAILED, CANCELLED = range(5)

    def __init__(self, view: memoryview):
        self.view = view
        self.state = _Sink.WAITING
        self.flags = Flags.NONE


class _Box:
    """Per-(src, name) mailbox with its own condition — a put wakes only
    this key's waiters (one shared condition would thundering-herd every
    in-flight chunk walk on every message)."""

    __slots__ = ("cond", "msgs", "sinks", "waiters", "dead")

    def __init__(self):
        self.cond = threading.Condition()
        self.msgs: deque = deque()
        self.sinks: deque = deque()
        self.waiters = 0
        self.dead = False

    def idle(self) -> bool:
        return not self.msgs and not self.sinks and self.waiters == 0


class _Rendezvous:
    """Blocking mailboxes per (src, name), with optional registered sinks.

    GC protocol: a box leaves the dict only after being marked ``dead``
    under its own condition (``_gc_locked``), and every writer/waiter
    re-fetches the box when it observes ``dead`` — otherwise a put() that
    grabbed a box reference just before GC would append to an orphan no
    future get() can see, stranding a collective until its timeout."""

    def __init__(self):
        self._lock = threading.Lock()  # guards the box dict only
        self._boxes: Dict[Tuple[PeerID, str], _Box] = {}

    def _box(self, key) -> _Box:
        with self._lock:
            b = self._boxes.get(key)
            if b is None:
                b = self._boxes[key] = _Box()
            return b

    def _gc_locked(self, key, box: _Box) -> None:
        """Drop a drained mailbox (long elastic runs must not accumulate
        dead version/chunk-tagged keys). box.cond MUST be held; the dict
        lock nests inside it (nothing acquires box.cond while holding the
        dict lock, so the order cannot invert)."""
        if box.idle() and not box.dead:
            with self._lock:
                if self._boxes.get(key) is box:
                    box.dead = True
                    del self._boxes[key]

    def put(self, src: PeerID, msg: Message) -> None:
        key = (src, msg.name)
        while True:
            box = self._box(key)
            with box.cond:
                if box.dead:
                    continue  # lost the race with _gc_locked: re-fetch
                box.msgs.append(msg)
                # notify_all: waiters include get() consumers AND
                # get_into() sink-parkers whose predicates differ; per-key
                # wakeups are 1-2 threads, so this is cheap
                box.cond.notify_all()
                return

    def get(self, src: PeerID, name: str, timeout: Optional[float] = None) -> Message:
        key = (src, name)
        while True:
            box = self._box(key)
            with box.cond:
                if box.dead:
                    continue
                box.waiters += 1
                try:
                    ok = box.cond.wait_for(lambda: len(box.msgs) > 0, timeout)
                    if not ok:
                        raise TimeoutError(f"recv timeout: {name} from {src}")
                    return box.msgs.popleft()
                finally:
                    box.waiters -= 1
                    self._gc_locked(key, box)

    # -- zero-copy receive ------------------------------------------------

    def take_sink(self, src: PeerID, name: str, nbytes: int) -> Optional[_Sink]:
        """Transport side: claim a waiting sink of exactly `nbytes`, or None
        (fall back to a buffered Message)."""
        key = (src, name)
        with self._lock:
            box = self._boxes.get(key)
        if box is None:
            return None
        with box.cond:
            # a dead box has no sinks by construction; the loop is empty
            for s in box.sinks:
                if s.state == _Sink.WAITING and s.view.nbytes == nbytes:
                    s.state = _Sink.TAKEN
                    return s
            return None

    def finish_sink(self, src: PeerID, name: str, sink: _Sink, flags: Flags, ok: bool) -> None:
        key = (src, name)
        while True:
            box = self._box(key)
            with box.cond:
                if box.dead:
                    continue
                sink.flags = flags
                sink.state = _Sink.DONE if ok else _Sink.FAILED
                box.cond.notify_all()
                # pathological path: the receiver gave up mid-fill and its
                # box was GC'd; don't let a re-created box linger
                self._gc_locked(key, box)
                return

    def get_into(
        self, src: PeerID, name: str, view: memoryview, timeout: Optional[float]
    ) -> Tuple[Optional[Message], bool]:
        """Receive (src, name), preferring direct delivery into `view`.

        Returns (msg, filled): filled=True means the payload is in `view`
        and msg is None; otherwise msg is a buffered Message (sender raced
        registration, or size mismatch). On timeout with the sink mid-fill
        (TAKEN), the buffer must NOT be reused — the caller leaks it."""
        key = (src, name)
        sink = _Sink(view)
        while True:
            box = self._box(key)
            with box.cond:
                if box.dead:
                    continue  # lost the race with _gc_locked: re-fetch
                box.waiters += 1
                try:
                    if box.msgs:
                        return box.msgs.popleft(), False
                    box.sinks.append(sink)

                    def ready():
                        return sink.state in (_Sink.DONE, _Sink.FAILED) or box.msgs

                    ok = box.cond.wait_for(ready, timeout)
                    if sink.state == _Sink.TAKEN:
                        # transport thread is writing into view RIGHT NOW;
                        # wait for it to finish rather than handing a live
                        # buffer back
                        box.cond.wait_for(
                            lambda: sink.state in (_Sink.DONE, _Sink.FAILED), 30.0
                        )
                    if sink.state == _Sink.DONE:
                        box.sinks.remove(sink)
                        return None, True
                    if sink.state == _Sink.FAILED:
                        box.sinks.remove(sink)
                        raise ConnectionError(
                            f"recv failed mid-frame: {name} from {src}"
                        )
                    if sink.state == _Sink.TAKEN:
                        box.sinks.remove(sink)
                        raise TimeoutError(f"recv stuck mid-frame: {name} from {src}")
                    # WAITING: nothing touched the buffer
                    sink.state = _Sink.CANCELLED
                    box.sinks.remove(sink)
                    if not ok:
                        raise TimeoutError(f"recv timeout: {name} from {src}")
                    return box.msgs.popleft(), False
                finally:
                    box.waiters -= 1
                    self._gc_locked(key, box)


class CollectiveEndpoint:
    """Named rendezvous for graph-walk collectives, with zero-copy sink
    delivery when the receiver is already waiting."""

    def __init__(self):
        self._rdv = _Rendezvous()

    def handle(self, src: PeerID, msg: Message) -> None:
        self._rdv.put(src, msg)

    def recv(self, src: PeerID, name: str, timeout: Optional[float] = None) -> Message:
        return self._rdv.get(src, name, timeout)

    def recv_into(
        self, src: PeerID, name: str, view: memoryview, timeout: Optional[float] = None
    ) -> Tuple[Optional[Message], bool]:
        """(msg, filled) — see _Rendezvous.get_into."""
        return self._rdv.get_into(src, name, view, timeout)

    # transport-side hooks (Server streaming path)
    def take_sink(self, src: PeerID, name: str, nbytes: int):
        return self._rdv.take_sink(src, name, nbytes)

    def finish_sink(self, src: PeerID, name: str, sink, flags: Flags, ok: bool) -> None:
        self._rdv.finish_sink(src, name, sink, flags, ok)


class QueueEndpoint:
    """Named FIFO queues (parity: handler/queue.go)."""

    def __init__(self):
        self._rdv = _Rendezvous()

    def handle(self, src: PeerID, msg: Message) -> None:
        self._rdv.put(src, msg)

    def get(self, src: PeerID, name: str, timeout: Optional[float] = None) -> bytes:
        return self._rdv.get(src, name, timeout).data


class ControlEndpoint:
    """Control messages (cluster updates / exit); parity:
    srcs/go/kungfu/runner/handler.go. The callback runs on the transport
    thread — keep it short."""

    def __init__(self, callback: Callable[[PeerID, Message], None]):
        self._callback = callback

    def handle(self, src: PeerID, msg: Message) -> None:
        self._callback(src, msg)


class P2PEndpoint:
    """Request/response over the blob stores (flat + versioned).

    Parity: srcs/go/rchannel/handler/p2p.go:13-121. A request names a blob,
    optionally with a version selector (``name@#<version>`` or
    ``name@#latest`` on the wire); versioned requests are served from a
    VersionedStore with a bounded GC window, so a reader always gets a
    CONSISTENT published snapshot while the writer publishes the next
    version — the reference's actual consistency contract for
    PairAveraging. Responses come back flagged IS_RESPONSE
    (REQUEST_FAILED when absent).
    """

    VSEP = "@#"  # version selector separator in wire names

    def __init__(self, store, client, self_id: PeerID, vstore=None):
        from kungfu_tpu.store.versioned import VersionedStore

        self.store = store
        self.vstore = vstore if vstore is not None else VersionedStore(window=3)
        self.client = client
        self.self_id = self_id
        self._rdv = _Rendezvous()

    def _lookup(self, wire_name: str) -> Optional[bytes]:
        name, sep, selector = wire_name.partition(self.VSEP)
        if not sep:
            return self.store.get(wire_name)
        if selector == "latest":
            return self.vstore.get_latest(name)
        try:
            return self.vstore.get(int(selector), name)
        except ValueError:
            return None

    def handle(self, src: PeerID, msg: Message) -> None:
        if msg.flags & Flags.IS_RESPONSE:
            self._rdv.put(src, msg)
            return
        # Incoming request: respond OFF the transport read thread. A
        # blocking sendall of a large blob here stops this connection's
        # reads; two peers requesting each other's model simultaneously
        # then deadlock once TCP buffers fill (each side mid-send, nobody
        # reading). Parity: the reference answers requests from worker
        # goroutines while connection readers keep draining.
        from kungfu_tpu.utils.pool import get_pool

        name = msg.name
        get_pool().submit(lambda: self._respond(src, name))

    def _respond(self, src: PeerID, name: str) -> None:
        data = self._lookup(name)
        try:
            if data is None:
                self.client.send(
                    src, name, b"", ConnType.PEER_TO_PEER,
                    Flags.IS_RESPONSE | Flags.REQUEST_FAILED,
                )
            else:
                self.client.send(
                    src, name, data, ConnType.PEER_TO_PEER, Flags.IS_RESPONSE
                )
        except (ConnectionError, OSError):
            # requester vanished (elastic shrink): their retry/timeout
            # handles it; the serving peer must not crash
            pass

    def request(
        self,
        peer: PeerID,
        name: str,
        timeout: float = 30.0,
        version: "Optional[int | str]" = None,
    ) -> Optional[bytes]:
        """Fetch `name` from peer's store; None if the peer doesn't have
        it. version=None targets the flat store; an int (or "latest")
        targets the peer's versioned store."""
        wire = name if version is None else f"{name}{self.VSEP}{version}"
        self.client.send(peer, wire, b"", ConnType.PEER_TO_PEER, Flags.NONE)
        msg = self._rdv.get(peer, wire, timeout)
        if msg.flags & Flags.REQUEST_FAILED:
            return None
        return msg.data

    def save(self, name: str, data: bytes) -> None:
        self.store.put(name, data)

    def save_version(self, version: int, name: str, data: bytes) -> None:
        """Publish an immutable (version, blob); versions beyond the GC
        window (3, parity p2p.go:11) are dropped."""
        self.vstore.put(version, name, data)
