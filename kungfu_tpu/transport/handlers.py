"""Endpoint handlers: collective rendezvous, p2p store, queues, control.

Capability parity: srcs/go/rchannel/handler/{collective,p2p,queue}.go —
- CollectiveEndpoint: named rendezvous queues; Recv blocks until a message
  with that name arrives (graph-walk collectives pair send/recv by name).
- PeerToPeerEndpoint: request/response over a versioned blob store (the
  PairAveraging model exchange).
- QueueHandler: named FIFO queues between peers.
- ControlHandler: delivers cluster Stage updates to a callback.
"""

from __future__ import annotations

import dataclasses
import json
import threading
from collections import defaultdict, deque
from typing import Callable, Dict, Optional, Tuple

from kungfu_tpu.plan.peer import PeerID
from kungfu_tpu.transport.message import ConnType, Flags, Message


class _Rendezvous:
    """A blocking mailbox per (src, name)."""

    def __init__(self):
        self._cond = threading.Condition()
        self._boxes: Dict[Tuple[PeerID, str], deque] = defaultdict(deque)

    def put(self, src: PeerID, msg: Message) -> None:
        with self._cond:
            self._boxes[(src, msg.name)].append(msg)
            self._cond.notify_all()

    def get(self, src: PeerID, name: str, timeout: Optional[float] = None) -> Message:
        key = (src, name)
        with self._cond:
            ok = self._cond.wait_for(lambda: len(self._boxes.get(key, ())) > 0, timeout)
            if not ok:
                raise TimeoutError(f"recv timeout: {name} from {src}")
            box = self._boxes[key]
            msg = box.popleft()
            if not box:
                # names are version/chunk-tagged: drop drained mailboxes so
                # long elastic runs don't accumulate dead keys
                del self._boxes[key]
            return msg


class CollectiveEndpoint:
    """Named rendezvous for graph-walk collectives."""

    def __init__(self):
        self._rdv = _Rendezvous()

    def handle(self, src: PeerID, msg: Message) -> None:
        self._rdv.put(src, msg)

    def recv(self, src: PeerID, name: str, timeout: Optional[float] = None) -> Message:
        return self._rdv.get(src, name, timeout)


class QueueEndpoint:
    """Named FIFO queues (parity: handler/queue.go)."""

    def __init__(self):
        self._rdv = _Rendezvous()

    def handle(self, src: PeerID, msg: Message) -> None:
        self._rdv.put(src, msg)

    def get(self, src: PeerID, name: str, timeout: Optional[float] = None) -> bytes:
        return self._rdv.get(src, name, timeout).data


class ControlEndpoint:
    """Control messages (cluster updates / exit); parity:
    srcs/go/kungfu/runner/handler.go. The callback runs on the transport
    thread — keep it short."""

    def __init__(self, callback: Callable[[PeerID, Message], None]):
        self._callback = callback

    def handle(self, src: PeerID, msg: Message) -> None:
        self._callback(src, msg)


class P2PEndpoint:
    """Request/response over a versioned blob store.

    Parity: srcs/go/rchannel/handler/p2p.go:13-121. Requests name a blob
    (and optionally a version); the remote endpoint reads it from its store
    and sends it back flagged IS_RESPONSE (REQUEST_FAILED when absent).
    """

    def __init__(self, store, client, self_id: PeerID):
        self.store = store
        self.client = client
        self.self_id = self_id
        self._rdv = _Rendezvous()

    def handle(self, src: PeerID, msg: Message) -> None:
        if msg.flags & Flags.IS_RESPONSE:
            self._rdv.put(src, msg)
            return
        # incoming request: look up blob, respond
        data = self.store.get(msg.name)
        if data is None:
            self.client.send(
                src, msg.name, b"", ConnType.PEER_TO_PEER,
                Flags.IS_RESPONSE | Flags.REQUEST_FAILED,
            )
        else:
            self.client.send(
                src, msg.name, data, ConnType.PEER_TO_PEER, Flags.IS_RESPONSE
            )

    def request(self, peer: PeerID, name: str, timeout: float = 30.0) -> Optional[bytes]:
        """Fetch `name` from peer's store; None if the peer doesn't have it."""
        self.client.send(peer, name, b"", ConnType.PEER_TO_PEER, Flags.NONE)
        msg = self._rdv.get(peer, name, timeout)
        if msg.flags & Flags.REQUEST_FAILED:
            return None
        return msg.data

    def save(self, name: str, data: bytes) -> None:
        self.store.put(name, data)
