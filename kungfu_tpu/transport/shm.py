"""Shared-memory data plane for colocated peers.

Capability parity note: the reference's rchannel moves every payload
through TCP/Unix sockets (srcs/go/rchannel/connection/connection.go) —
fine when each peer owns a core, but a kfrun localhost cluster is N
processes sharing a box, and every socket byte costs two kernel copies
plus backpressure coupling: a 29 MiB send blocks the SENDER until the
busy receiver drains a ~208 KiB pipe. Here large payloads ride a
per-(sender->receiver, conn_type) shared-memory ring: the sender memcpys
into the arena and completes immediately; the tiny descriptor frame
{offset, length, advance} travels over the existing framed socket (so
ordering, epochs, and demux are unchanged); the receiver either memcpys
out (sink path) or hands the mapped region zero-copy to the collective
walk (borrow path) and releases it after the reduce.

Ring protocol (SPSC by construction: client.send holds the per-connection
lock; one transport thread serves each connection):
  header page: magic u64 | capacity u64 | alloc_seq u64 | consumed_seq u64
  alloc_seq   monotonically counts bytes allocated (incl. wrap padding);
              written only by the sender.
  consumed_seq counts bytes released; written only by the receiver.
  A region never wraps: if the tail can't fit it, the sender pads to the
  boundary and the descriptor's `advance` covers pad + length.
Releases can complete out of order (the n-ary reduce borrows several
regions at once), so the receiver tracks released intervals and advances
consumed_seq only over a contiguous prefix.

Failure posture: a borrow whose consumer never materializes (a walk that
timed out before claiming the buffered message) leaves a hole the
releaser cannot advance past; the ring then reports no space and every
subsequent large send degrades to the SOCKET frame — slower, still
correct — until the next reconnect/epoch resets both ends. That is the
same containment story as the engine's leaked-scratch policy for
timed-out sink fills.
"""

from __future__ import annotations

import mmap
import os
import struct
import threading
import time
from typing import Dict, Optional, Tuple

import numpy as np

from kungfu_tpu import knobs

MAGIC = 0x4B46534D454D31  # "KFSMEM1"
HEADER = 4096
_HDR = struct.Struct("<QQQQ")  # magic, capacity, alloc_seq, consumed_seq

DEFAULT_CAPACITY = int(knobs.get("KF_CONFIG_SHM_CAPACITY"))
# payloads below this stay on the socket (descriptor overhead + mmap
# bookkeeping beat the copy savings for small frames)
SHM_MIN_BYTES = int(knobs.get("KF_CONFIG_SHM_MIN_BYTES"))

DESC = struct.Struct("<QQQ")  # offset, length, advance


def enabled() -> bool:
    return knobs.get("KF_CONFIG_SHM") and os.path.isdir("/dev/shm")


class ArenaSpaceError(OSError):
    """tmpfs can't back the arena (ENOSPC at creation). ftruncate alone
    only reserves address space — without an upfront allocation the
    first write into an unbacked page on a full /dev/shm is a SIGBUS
    that kills the worker mid-collective. Raised at creation so the
    sender can degrade to the socket path instead."""


def count_alloc_failure() -> None:
    """Count an arena-allocation failure (its own series, NOT
    kungfu_shm_fallback_total: that counter means "the receiver is
    behind" — an operator watching the fallback share to diagnose a
    chronically-slow receiver must not see a full /dev/shm in it)."""
    from kungfu_tpu.telemetry import config as _tcfg

    if _tcfg.metrics_enabled():
        from kungfu_tpu.telemetry import metrics as _tm

        _tm.counter(
            "kungfu_shm_alloc_failures_total",
            "Arena allocations refused (tmpfs full); connection degraded "
            "to socket frames",
        ).inc()


def arena_path(
    recv_host: str, recv_port: int, send_host: str, send_port: int, conn_type: int
) -> str:
    return (
        f"/dev/shm/kfshm-{recv_host}-{recv_port}" f"-{send_host}-{send_port}-{conn_type}"
    )


class SenderArena:
    """Sender side: creates/resets the file, allocates regions, memcpys
    payloads in. One instance per (peer connection); serialized by the
    client's per-connection send lock."""

    def __init__(self, path: str, capacity: int = DEFAULT_CAPACITY):
        self.path = path
        self.capacity = capacity
        # ring-vs-socket accounting (telemetry): a rising fallback share
        # means the receiver is chronically behind and payloads are taking
        # the slower socket path; gated once per arena, zero-cost when off
        self._m_writes = self._m_fallback = None
        from kungfu_tpu.telemetry import config as _tcfg

        if _tcfg.metrics_enabled():
            from kungfu_tpu.telemetry import metrics as _tm

            self._m_writes = _tm.counter(
                "kungfu_shm_writes_total",
                "Payloads delivered via the shared-memory ring",
            )
            self._m_fallback = _tm.counter(
                "kungfu_shm_fallback_total",
                "Ring-full fallbacks to the socket frame path",
            )
        # O_EXCL after unlink: the path is predictable, so opening an
        # existing file could map another local user's pre-planted file
        # (mode 0o600 only applies at creation) — never reuse one
        try:
            os.unlink(path)
        except OSError:
            pass
        fd = os.open(path, os.O_CREAT | os.O_EXCL | os.O_RDWR, 0o600)
        try:
            os.ftruncate(fd, HEADER + capacity)
            # back every page NOW: ftruncate only sizes the file, and on
            # a full tmpfs the first store into an unbacked page is a
            # SIGBUS (uncatchable worker death). posix_fallocate turns
            # "tmpfs is full" into an ENOSPC here, which the client
            # degrades to the socket path (graceful, counted).
            if hasattr(os, "posix_fallocate"):
                try:
                    os.posix_fallocate(fd, 0, HEADER + capacity)
                except OSError as e:
                    raise ArenaSpaceError(
                        e.errno or 0,
                        f"cannot back shm arena {path} "
                        f"({(HEADER + capacity) >> 20} MiB): {e.strerror}",
                    ) from e
            self._mm = mmap.mmap(fd, HEADER + capacity)
        except ArenaSpaceError:
            try:
                os.unlink(path)
            except OSError:
                pass
            raise
        finally:
            os.close(fd)
        self._seq = np.frombuffer(self._mm, np.uint64, 2, offset=16)
        # reset for a fresh epoch: receiver maps lazily after connect, so
        # nobody holds live borrows here
        self._mm[0:16] = struct.pack("<QQ", MAGIC, capacity)
        self._seq[0] = 0
        self._seq[1] = 0
        self._data = memoryview(self._mm)[HEADER:]
        self._alloc = 0  # mirrors _seq[0]; plain int avoids u64 churn
        # memory plane (ISSUE 17): the mapped arena is a long-lived
        # buffer owner — account it under the `arena` bucket for as
        # long as the mapping lives. Report the touched high-water
        # (header + bytes ever allocated, capped at capacity), not the
        # mmap size: untouched tmpfs pages are not resident, and a
        # tracked total above RSS would corrupt the `untracked`
        # remainder. Best-effort, telemetry must never kill transport.
        self._mem_acct = None
        try:
            import weakref as _weakref

            from kungfu_tpu.telemetry import memory as _tmem

            def _acct(ref=_weakref.ref(self)):
                a = ref()
                if a is None:
                    return None
                return HEADER + min(a._alloc, a.capacity)

            self._mem_acct = _tmem.register_accountant(
                f"shm:{os.path.basename(path)}", "arena", _acct,
            )
        # kfcheck: disable=KF400 — byte accounting is best-effort;
        # it must never kill the arena
        except Exception:  # noqa: BLE001
            pass

    def try_write(self, payload, nbytes: int) -> Optional[bytes]:
        """Copy `payload` into the ring; returns the packed descriptor, or
        None when the ring lacks space RIGHT NOW. Never blocks: spinning
        for ring space on a shared core starves the consumer that would
        free it — a full ring means the receiver is behind, and the socket
        path's kernel flow control is the right way to wait for it."""
        cap = self.capacity
        if nbytes > cap:
            # deliberate routing (payload can never fit), not backpressure
            # — excluded from the fallback counter, whose point is "the
            # receiver is behind"
            return None
        off = self._alloc % cap
        pad = cap - off if off + nbytes > cap else 0
        advance = pad + nbytes
        if self._alloc + advance - int(self._seq[1]) > cap:
            if self._m_fallback is not None:
                self._m_fallback.inc()
            return None
        start = 0 if pad else off
        dst = np.frombuffer(self._data, np.uint8, nbytes, offset=start)
        src = np.frombuffer(payload, np.uint8, nbytes)
        np.copyto(dst, src)  # releases the GIL for large copies
        self._alloc += advance
        self._seq[0] = self._alloc
        if self._m_writes is not None:
            self._m_writes.inc()
        return DESC.pack(start, nbytes, advance)

    def close(self) -> None:
        if self._mem_acct is not None:
            self._mem_acct.close()
            self._mem_acct = None
        try:
            self._seq = None
            self._data.release()
            self._mm.close()
        except (BufferError, ValueError, OSError):
            pass
        try:
            os.unlink(self.path)
        except OSError:
            pass


class _OrderedReleaser:
    """Advance consumed_seq over the contiguous prefix of released
    [start, start+advance) intervals (borrows finish out of order)."""

    def __init__(self, seq: np.ndarray):
        self._seq = seq  # consumed_seq lives at index 1
        self._lock = threading.Lock()
        self._next = 0  # next expected start_seq to retire
        self._pending: Dict[int, int] = {}  # start_seq -> advance

    def release(self, start_seq: int, advance: int) -> None:
        with self._lock:
            self._pending[start_seq] = advance
            while self._next in self._pending:
                adv = self._pending.pop(self._next)
                self._next += adv
            self._seq[1] = self._next


class ReceiverArena:
    """Receiver side: maps the sender's file, exposes regions, retires
    them in allocation order."""

    def __init__(self, path: str):
        fd = os.open(path, os.O_RDWR)
        try:
            st = os.fstat(fd)
            if st.st_uid != os.getuid():
                raise ValueError(f"shm arena not owned by us: {path}")
            size = st.st_size
            self._mm = mmap.mmap(fd, size)
        finally:
            os.close(fd)
        magic, cap = struct.unpack("<QQ", self._mm[0:16])
        if magic != MAGIC or HEADER + cap != size:
            raise ValueError(f"bad shm arena: {path}")
        self.capacity = cap
        self._seq = np.frombuffer(self._mm, np.uint64, 2, offset=16)
        self._data = memoryview(self._mm)
        self._releaser = _OrderedReleaser(self._seq)
        self._recv_seq = 0  # bytes of (pad+len) seen, in frame order
        # memory plane (ISSUE 17): the receiver maps the same pages —
        # in ITS OWN process, so it accounts them too. Same high-water
        # rule as the sender: only frames actually seen are resident
        # here, not the whole mapping.
        self._mem_acct = None
        try:
            import weakref as _weakref

            from kungfu_tpu.telemetry import memory as _tmem

            def _acct(ref=_weakref.ref(self)):
                a = ref()
                if a is None:
                    return None
                return HEADER + min(a._recv_seq, a.capacity)

            self._mem_acct = _tmem.register_accountant(
                f"shm:{os.path.basename(path)}", "arena", _acct,
            )
        # kfcheck: disable=KF400 — byte accounting is best-effort;
        # it must never kill the arena
        except Exception:  # noqa: BLE001
            pass

    def region(self, offset: int, length: int, advance: int):
        """(memoryview of the payload, release() callable). Frames arrive
        in allocation order on the single connection, so _recv_seq
        reconstructs each region's start_seq."""
        start_seq = self._recv_seq  # pad (if any) leads the interval
        self._recv_seq += advance
        view = self._data[HEADER + offset : HEADER + offset + length]
        rel = self._releaser

        def release(_done=[False]) -> None:
            if not _done[0]:
                _done[0] = True
                rel.release(start_seq, advance)

        return view, release

    def close(self) -> None:
        if self._mem_acct is not None:
            self._mem_acct.close()
            self._mem_acct = None
        try:
            self._seq = None
            self._data.release()
            self._mm.close()
        except (BufferError, ValueError, OSError):
            pass
