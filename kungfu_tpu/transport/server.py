"""Transport server: listens for peer connections, demuxes to handlers.

Capability parity: srcs/go/rchannel/server/server.go (TCP + Unix-socket
listener for colocated peers) and srcs/go/kungfu/peer/router.go (demux by
ConnType). Token-versioned connections: after an elastic resize bumps the
cluster version, stale connections (old token) are rejected so a new epoch
never consumes old-epoch frames (parity: server.SetToken +
router.ResetConnections, peer/peer.go:148-160).
"""

from __future__ import annotations

import os
import socket
import struct
import threading
import time as _time

import numpy as np
from typing import Callable, Dict, Optional

from kungfu_tpu.plan.peer import PeerID
from kungfu_tpu.transport import shm
from kungfu_tpu.utils import trace
from kungfu_tpu.transport.message import (
    ConnType,
    Flags,
    Message,
    _recv_exact,
    _recv_exact_into,
    recv_frame_header,
    recv_header,
    recv_message,
    send_ack,
)

# handler(src: PeerID, msg: Message) -> None
Handler = Callable[[PeerID, Message], None]


def unix_sock_path(peer: PeerID) -> str:
    # host-qualified: two loopback aliases (127.0.0.1 / 127.0.0.2) may carry
    # the same port on one machine (multi-"host" localhost clusters)
    return f"/tmp/kungfu_tpu-{peer.host}-{peer.port}.sock"


class Server:
    def __init__(self, self_id: PeerID, use_unix: bool = True):
        self.self_id = self_id
        self._handlers: Dict[ConnType, Handler] = {}
        self._token = 0
        self._lock = threading.Lock()
        self._listeners = []
        self._threads = []
        self._stopped = threading.Event()
        self._use_unix = use_unix

    def register(self, conn_type: ConnType, handler: Handler) -> None:
        self._handlers[conn_type] = handler

    def set_token(self, token: int) -> None:
        with self._lock:
            self._token = token

    @property
    def token(self) -> int:
        with self._lock:
            return self._token

    def start(self, bind_timeout: float = 15.0) -> None:
        tcp = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        tcp.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        # Bind to the ADVERTISED host (peers dial exactly that address), so
        # multi-"host" localhost clusters can stack the same port on
        # different loopback aliases; fall back to the wildcard when the
        # advertised name doesn't resolve to a local interface.
        # Bind retry: after an elastic shrink-then-grow, a respawned worker
        # can race the previous incarnation's exit for the same port (the
        # watcher does not serialize spawn against the detached process's
        # teardown).
        import time as _time

        import errno as _errno

        deadline = _time.monotonic() + bind_timeout
        while True:
            try:
                try:
                    tcp.bind((self.self_id.host, self.self_id.port))
                except (socket.gaierror, OSError) as e:
                    if isinstance(e, OSError) and e.errno == _errno.EADDRINUSE:
                        raise
                    tcp.bind(("0.0.0.0", self.self_id.port))
                break
            except OSError as e:
                # only the respawn race is transient; EACCES and friends
                # are real misconfigurations — surface them now
                if e.errno != _errno.EADDRINUSE or _time.monotonic() >= deadline:
                    raise
                _time.sleep(0.25)
        tcp.listen(128)
        self._listeners.append(tcp)
        t = threading.Thread(target=self._accept_loop, args=(tcp,), daemon=True)
        t.start()
        self._threads.append(t)

        if self._use_unix:
            path = unix_sock_path(self.self_id)
            try:
                os.unlink(path)
            except FileNotFoundError:
                pass
            ux = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            ux.bind(path)
            ux.listen(128)
            self._listeners.append(ux)
            t2 = threading.Thread(target=self._accept_loop, args=(ux,), daemon=True)
            t2.start()
            self._threads.append(t2)

    def stop(self) -> None:
        self._stopped.set()
        for l in self._listeners:
            try:
                l.close()
            except OSError:
                pass
        if self._use_unix:
            # NOTE: if a respawned same-port worker already re-bound this
            # path, this unlink removes ITS socket file; clients then fall
            # back to TCP (correct, just slower) until the next epoch.
            try:
                os.unlink(unix_sock_path(self.self_id))
            except FileNotFoundError:
                pass

    def _accept_loop(self, listener: socket.socket) -> None:
        while not self._stopped.is_set():
            try:
                conn, _ = listener.accept()
            except OSError:
                return
            threading.Thread(
                target=self._serve_conn, args=(conn,), daemon=True
            ).start()

    def _serve_conn(self, conn: socket.socket) -> None:
        # shared-memory receive state (lazy: first SHM_REF frame maps the
        # sender's arena; per-connection so epochs reset cleanly)
        rx_state: Dict[str, object] = {}
        try:
            conn_type, src_host, src_port, token = recv_header(conn)
            # Token check: PING and CONTROL are version-independent (they
            # carry the resize protocol itself); data-plane types must match
            # the current epoch.
            if conn_type in (ConnType.COLLECTIVE, ConnType.PEER_TO_PEER, ConnType.QUEUE):
                if token != self.token:
                    conn.close()
                    return
            send_ack(conn, self.token)
            src = PeerID(src_host, src_port)
            handler = self._handlers.get(conn_type)
            if conn_type == ConnType.PING:
                conn.close()
                return
            if handler is None:
                conn.close()
                return
            from kungfu_tpu.monitor import net as _net

            monitor = _net.get_monitor() if _net.enabled() else None

            def shm_region(desc: bytes):
                """Resolve a descriptor frame to (view, release)."""
                off, length, advance = shm.DESC.unpack(bytes(desc))
                arena = rx_state.get("arena")
                if arena is None:
                    arena = shm.ReceiverArena(
                        shm.arena_path(
                            self.self_id.host, self.self_id.port,
                            src.host, src.port, int(conn_type),
                        )
                    )
                    rx_state["arena"] = arena
                return arena.region(off, length, advance)
            # Zero-copy receive: when the registered endpoint exposes the
            # sink protocol (CollectiveEndpoint), read the frame header
            # first and, if a receiver is already parked on (src, name)
            # with a matching buffer, deliver the payload straight off the
            # socket into it (parity: WAIT_RECV_BUF / RecvInto,
            # handler/collective.go:34-65).
            endpoint = getattr(handler, "__self__", None)
            take_sink = getattr(endpoint, "take_sink", None)
            if take_sink is None:
                while not self._stopped.is_set():
                    msg = recv_message(conn)
                    nbytes = len(msg.data)
                    if msg.flags & Flags.SHM_REF:
                        # CONTROL/QUEUE/P2P endpoints buffer messages for
                        # arbitrarily long — copy out of the ring and
                        # release immediately (GIL-free numpy memcpy)
                        view, release = shm_region(msg.data)
                        nbytes = len(view)
                        buf = bytearray(nbytes)
                        np.copyto(
                            np.frombuffer(buf, np.uint8),
                            np.frombuffer(view, np.uint8),
                        )
                        release()
                        msg = Message(
                            name=msg.name,
                            data=buf,
                            flags=msg.flags & ~Flags.SHM_REF,
                        )
                    if monitor is not None:
                        monitor.received(src, nbytes)
                    handler(src, msg)
            else:
                finish_sink = endpoint.finish_sink
                while not self._stopped.is_set():
                    name, flags, data_len = recv_frame_header(conn)
                    if flags & Flags.SHM_REF:
                        desc = _recv_exact(conn, data_len)
                        view, release = shm_region(desc)
                        data_len = len(view)
                        flags &= ~Flags.SHM_REF
                        # always borrow — even when a sink is parked, the
                        # walk reduces straight from the mapped ring, so a
                        # transport-thread copy here would be pure waste
                        handler(
                            src,
                            Message(
                                name=name, data=view, flags=flags,
                                release=release,
                            ),
                        )
                        if monitor is not None:
                            monitor.received(src, data_len)
                        continue
                    sink = take_sink(src, name, data_len) if data_len else None
                    if sink is not None:
                        _t0 = _time.perf_counter()
                        try:
                            _recv_exact_into(conn, sink.view)
                        except BaseException:
                            finish_sink(src, name, sink, flags, ok=False)
                            raise
                        finish_sink(src, name, sink, flags, ok=True)
                        trace.record(
                            "transport.recv_sink", _time.perf_counter() - _t0
                        )
                    else:
                        data = _recv_exact(conn, data_len) if data_len else b""
                        handler(src, Message(name=name, data=data, flags=flags))
                    if monitor is not None:
                        monitor.received(src, data_len)
        except (ConnectionError, OSError):
            pass
        except (ValueError, UnicodeDecodeError, struct.error):
            # malformed frames (bad enum value / undecodable name / short
            # struct): a garbage-sending peer must not take the server down
            pass
        finally:
            arena = rx_state.get("arena")
            if arena is not None:
                arena.close()
            try:
                conn.close()
            except OSError:
                pass
