"""ctypes loader for the native socket pump (native/io_pump.cpp).

The pump runs a whole framed send (writev of header+payload) or an exact
n-byte receive in ONE GIL-released call, replacing per-64KB Python loop
iterations that each re-acquire the GIL under transport-thread contention
(parity target: the reference's goroutine byte loops,
srcs/go/rchannel/connection/connection.go:90-146).

Falls back silently when the shared library hasn't been built — all
callers must guard on `available`.
"""

from __future__ import annotations

import ctypes
import errno
import os
import socket

_LIB_PATH = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "base",
    "libkfnative.so",
)

available = False
_lib = None

try:
    _lib = ctypes.CDLL(_LIB_PATH)
    _lib.kf_send2.restype = ctypes.c_int
    _lib.kf_send2.argtypes = [
        ctypes.c_int,
        ctypes.c_void_p,
        ctypes.c_int64,
        ctypes.c_void_p,
        ctypes.c_int64,
        ctypes.c_int,
    ]
    _lib.kf_recv_exact.restype = ctypes.c_int
    _lib.kf_recv_exact.argtypes = [
        ctypes.c_int,
        ctypes.c_void_p,
        ctypes.c_int64,
        ctypes.c_int,
    ]
    available = True
except (OSError, AttributeError):
    pass


def _timeout_ms(sock: socket.socket) -> int:
    t = sock.gettimeout()
    if t is None:
        return -1  # blocking: the pump polls without deadline
    if t == 0:
        # non-blocking socket: keep it non-blocking. The pump attempts
        # the syscall once and its poll() deadline expires immediately
        # on EAGAIN; _check maps that to BlockingIOError, matching
        # Python socket semantics. (This used to round up to a 1 ms
        # blocking poll — silently turning a non-blocking socket into a
        # blocking one.)
        return 0
    return max(1, int(t * 1000))


def _as_arg(data):
    """(ctypes-passable buffer object, nbytes) for any contiguous buffer,
    without copying. The returned object is passed as a foreign-call
    argument, which keeps it (and the memory it references) alive for the
    duration of the call."""
    view = data if isinstance(data, memoryview) else memoryview(data)
    n = view.nbytes
    if n == 0:
        return None, 0
    if not view.readonly:
        return (ctypes.c_char * n).from_buffer(view), n
    # read-only: bytes expose their internal pointer via c_char_p with no
    # copy; any other read-only exporter is copied (rare on these paths)
    obj = view.obj if isinstance(view.obj, bytes) and view.nbytes == len(view.obj) else view.tobytes()
    return ctypes.c_char_p(obj), n


def _check(rc: int, what: str, timeout_ms: int) -> None:
    if rc == 0:
        return
    if rc == -1:
        raise ConnectionError(f"peer closed connection during {what}")
    if rc == -2:
        if timeout_ms == 0:
            # non-blocking socket, no progress possible right now: the
            # caller asked not to wait, so raise what a non-blocking
            # Python socket would. UNLIKE a single non-blocking
            # recv/send, these are multi-byte LOOPS: a partial frame may
            # already be on the wire (send) or consumed into the buffer
            # (recv) — framed-protocol callers must treat this exactly
            # like a timeout, i.e. a connection-level failure, never a
            # retry-the-same-call signal.
            raise BlockingIOError(
                errno.EAGAIN, f"{what} would block (non-blocking socket)"
            )
        raise socket.timeout(f"timed out during {what}")
    raise OSError(-rc, f"{what}: {os.strerror(-rc)}")


def send2(sock: socket.socket, head: bytes, payload, payload_nbytes: int) -> None:
    """One writev-looped send of [head | payload], GIL released."""
    pbuf, pn = (_as_arg(payload) if payload_nbytes else (None, 0))
    t_ms = _timeout_ms(sock)
    rc = _lib.kf_send2(sock.fileno(), head, len(head), pbuf, pn, t_ms)
    _check(rc, "send", t_ms)


def recv_exact_into(sock: socket.socket, view: memoryview) -> None:
    """Receive exactly len(view) bytes into the writable view, GIL
    released. On timeout/BlockingIOError a PREFIX of the view may
    already be filled (bytes consumed off the socket) — the stream
    position is indeterminate, so treat either as fatal for the
    connection, not as retryable."""
    buf, n = _as_arg(view)
    t_ms = _timeout_ms(sock)
    rc = _lib.kf_recv_exact(sock.fileno(), buf, n, t_ms)
    _check(rc, "recv", t_ms)
