"""Transport client: pooled, token-checked connections with retry.

Capability parity: srcs/go/rchannel/client/{client,connection_pool}.go and
connection.go:90-146 — one persistent connection per (peer, conn_type),
established with a header handshake + token ack, auto-reconnect with
bounded retries; Ping/Wait to probe peer liveness (client.go:29-59).
"""

from __future__ import annotations

import socket
import threading
import time
from typing import Dict, Optional, Tuple

from kungfu_tpu.plan.peer import PeerID

# declared lock hierarchy (kfcheck KF201): the per-peer send lock is
# held across a send; the pool-map lock only guards dict lookups inside
# it and must never be the outer of the two
_KF_LOCK_ORDER = ("lock", "_pool_lock")
from kungfu_tpu.transport import shm
from kungfu_tpu.utils import trace
from kungfu_tpu.transport.message import (
    ConnType,
    Flags,
    Message,
    nbytes_of,
    recv_ack,
    send_header,
    send_message,
)
from kungfu_tpu.transport.server import unix_sock_path

CONN_RETRY_COUNT = 120
# Exponential backoff between dial attempts: an elastic joiner's server
# comes up in tens of ms once warm, so survivors re-dialing it must not
# quantize the whole rebuild barrier to coarse sleep ticks (a flat 250 ms
# put a 250/500 ms floor under every resize). Start fine, cap at
# CONN_RETRY_PERIOD so a genuinely absent peer costs the same as before
# (tests patch PERIOD/COUNT to bound absent-peer waits; read at call time).
CONN_RETRY_PERIOD = 0.25
CONN_RETRY_MIN = 0.01
CONN_RETRY_GROWTH = 1.6


def _retry_delays():
    d = CONN_RETRY_MIN
    for _ in range(CONN_RETRY_COUNT):
        yield min(d, CONN_RETRY_PERIOD)
        d = min(d * CONN_RETRY_GROWTH, CONN_RETRY_PERIOD)


class Client:
    def __init__(self, self_id: PeerID, use_unix: bool = True):
        self.self_id = self_id
        self._token = 0
        self._pool: Dict[Tuple[PeerID, ConnType], socket.socket] = {}
        self._locks: Dict[Tuple[PeerID, ConnType], threading.Lock] = {}
        self._pool_lock = threading.Lock()
        self._use_unix = use_unix
        # shared-memory arenas for colocated peers, one per live
        # connection; (re)created whenever the connection is (re)made so
        # ring sequence numbers reset with the epoch
        self._arenas: Dict[Tuple[PeerID, ConnType], "shm.SenderArena"] = {}
        # egress accounting (parity: monitor.Egress called from the
        # connection send path, srcs/go/monitor/monitor.go:28-72)
        from kungfu_tpu.monitor import net as _net

        self._monitor = _net.get_monitor() if _net.enabled() else None
        # link plane (ISSUE 6): per-destination EWMA bandwidth/latency
        # estimators fed by the real sends below — the k x k matrix's
        # local row; rides the same telemetry gate as the monitor
        from kungfu_tpu.telemetry import link as _link

        self._links = _link.get_table() if _link.enabled() else None
        # shaped-link harness (ISSUE 14; generalizes the old slow-edge
        # injection): per-edge latency/bandwidth/jitter from
        # KF_SHAPE_LINKS, matched against THIS client's own peer id so
        # in-process multi-peer harnesses shape per sender. None in
        # production; parsed once — the knob is static per process.
        from kungfu_tpu.transport import shaping as _shaping

        self._shaper = _shaping.from_env(str(self_id))
        # latency histograms ride the same gate as the byte counters: a
        # histogram observe is a bisect + three adds, but the send path
        # runs per message and stays untouched when telemetry is off
        self._send_hist = self._rtt_hist = None
        if self._monitor is not None:
            from kungfu_tpu.telemetry import metrics as _tmetrics

            self._send_hist = _tmetrics.histogram(
                "kungfu_transport_send_seconds",
                "Host-transport send latency (frame + flush)",
            )
            self._rtt_hist = _tmetrics.histogram(
                "kungfu_transport_rtt_seconds",
                "Ping round-trip time per peer",
                ("peer",),
            )

    def set_token(self, token: int) -> None:
        self._token = token

    def reset_connections(self) -> None:
        """Drop all pooled connections (new epoch after a resize)."""
        with self._pool_lock:
            for sock in self._pool.values():
                try:
                    sock.close()
                except OSError:
                    pass
            self._pool.clear()
            for arena in self._arenas.values():
                if arena is not None:
                    arena.close()
            self._arenas.clear()

    def _colocated(self, peer: PeerID) -> bool:
        def is_loop(h: str) -> bool:
            return h == "localhost" or h.startswith("127.")

        return peer.host == self.self_id.host or (
            is_loop(peer.host) and is_loop(self.self_id.host)
        )

    def _fresh_arena(self, key: Tuple[PeerID, ConnType]):
        """(Re)create the sender arena for a freshly-made connection.
        A full tmpfs (ArenaSpaceError from posix_fallocate) degrades the
        connection to plain socket frames for this epoch — slower, still
        correct — instead of a SIGBUS on the first ring write; the next
        reconnect/resize retries. None in the table records the
        degradation (vs. absent = not attempted yet)."""
        old = self._arenas.pop(key, None)
        if old is not None:
            old.close()
        peer, conn_type = key
        try:
            arena = shm.SenderArena(
                shm.arena_path(
                    peer.host, peer.port,
                    self.self_id.host, self.self_id.port,
                    int(conn_type),
                )
            )
        except shm.ArenaSpaceError as e:
            trace.record("transport.shm_alloc_fail", 0.0)
            shm.count_alloc_failure()
            from kungfu_tpu.telemetry import log as _log

            _log.warn("shm arena unavailable, using sockets to %s: %s", peer, e)
            self._arenas[key] = None
            return None
        self._arenas[key] = arena
        return arena

    def _connect(self, peer: PeerID, conn_type: ConnType) -> socket.socket:
        last_err: Optional[Exception] = None
        for delay in _retry_delays():
            try:
                if self._use_unix and peer.host in ("127.0.0.1", "localhost", self.self_id.host):
                    try:
                        sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
                        sock.connect(unix_sock_path(peer))
                    except (FileNotFoundError, ConnectionRefusedError, OSError):
                        sock = socket.create_connection((peer.host, peer.port), timeout=10)
                else:
                    sock = socket.create_connection((peer.host, peer.port), timeout=10)
                if sock.family == socket.AF_INET:
                    sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                send_header(sock, conn_type, self.self_id.host, self.self_id.port, self._token)
                remote_token = recv_ack(sock)
                if conn_type in (ConnType.COLLECTIVE, ConnType.PEER_TO_PEER, ConnType.QUEUE):
                    if remote_token != self._token:
                        # epoch mismatch: remote hasn't caught up yet
                        sock.close()
                        raise ConnectionError(
                            f"token mismatch with {peer}: {remote_token} != {self._token}"
                        )
                return sock
            except (ConnectionError, OSError) as e:
                last_err = e
                time.sleep(delay)
        raise ConnectionError(f"cannot connect to {peer} ({conn_type.name}): {last_err}")

    def _get(self, peer: PeerID, conn_type: ConnType):
        key = (peer, conn_type)
        with self._pool_lock:
            lock = self._locks.setdefault(key, threading.Lock())
            sock = self._pool.get(key)
        return key, lock, sock

    def send(
        self,
        peer: PeerID,
        name: str,
        data: bytes,
        conn_type: ConnType = ConnType.COLLECTIVE,
        flags: Flags = Flags.NONE,
    ) -> None:
        key, lock, sock = self._get(peer, conn_type)
        data_len = nbytes_of(data)
        shm_conn = (
            conn_type
            in (ConnType.COLLECTIVE, ConnType.PEER_TO_PEER, ConnType.QUEUE)
            and shm.enabled()
            and self._colocated(peer)
        )
        use_shm = shm_conn and data_len >= shm.SHM_MIN_BYTES

        def wire_message() -> Message:
            """Build the on-socket frame; for shm sends this memcpys the
            payload into the ring and frames only the descriptor. A full
            ring falls back to the socket frame (kernel flow control)."""
            if not use_shm:
                return Message(name=name, data=data, flags=flags)
            if key in self._arenas:
                arena = self._arenas[key]
            else:
                arena = self._fresh_arena(key)
            if arena is None:  # degraded: tmpfs couldn't back the ring
                return Message(name=name, data=data, flags=flags)
            desc = arena.try_write(data, data_len)
            if desc is None:
                return Message(name=name, data=data, flags=flags)
            return Message(name=name, data=desc, flags=flags | Flags.SHM_REF)

        dialed = False
        with lock:
            with self._pool_lock:
                sock = self._pool.get(key)
            if sock is None:
                sock = self._connect(peer, conn_type)
                dialed = True
                with self._pool_lock:
                    self._pool[key] = sock
                if shm_conn:
                    self._fresh_arena(key)
            _t0 = time.perf_counter()
            if self._shaper is not None:
                delay = self._shaper.delay(peer, data_len)
                if delay > 0:
                    # inside the timed window on purpose: the shaped
                    # delay must surface everywhere a real slow edge
                    # would — the link table's bandwidth estimate, the
                    # walk profiler's send-blocked split and the step
                    # plane's critical edge
                    # kfcheck: disable=KF200 — deliberate test-only edge shaping: holding the per-connection lock through the delay serializes the edge exactly like a saturated pipe would
                    time.sleep(delay)
            try:
                send_message(sock, wire_message())
            except (ConnectionError, OSError):
                # one reconnect attempt, then fail up; the arena is
                # re-created on EVERY reconnect of a shm-capable conn (not
                # just when this send is large): the new _serve_conn's
                # receiver starts at seq 0, and a stale sender seq would
                # see phantom in-use bytes forever
                try:
                    sock.close()
                except OSError:
                    pass
                sock = self._connect(peer, conn_type)
                dialed = True
                with self._pool_lock:
                    self._pool[key] = sock
                if shm_conn:
                    self._fresh_arena(key)
                send_message(sock, wire_message())
            _dt = time.perf_counter() - _t0
            trace.record("transport.send", _dt)
            if self._send_hist is not None:
                self._send_hist.observe(_dt)
        if self._monitor is not None:
            self._monitor.sent(peer, data_len)
        if self._links is not None:
            # a send that had to dial still counts its bytes, but is no
            # bandwidth sample: connection setup is not link speed
            self._links.observe_send(peer, data_len, 0.0 if dialed else _dt)

    def ping(self, peer: PeerID, timeout: float = 2.0) -> bool:
        try:
            _t0 = time.perf_counter()
            sock = socket.create_connection((peer.host, peer.port), timeout=timeout)
            if self._shaper is not None:
                # shaped message latency inside the timed RTT window:
                # the link table's latency estimate (fed by this ping)
                # must observe the same shape the collective sends do
                delay = self._shaper.latency(peer)
                if delay > 0:
                    time.sleep(delay)
            send_header(sock, ConnType.PING, self.self_id.host, self.self_id.port, 0)
            recv_ack(sock)
            sock.close()
            rtt = time.perf_counter() - _t0
            if self._rtt_hist is not None:
                self._rtt_hist.labels(str(peer)).observe(rtt)
            if self._links is not None:
                self._links.observe_latency(peer, rtt)
            return True
        except (ConnectionError, OSError):
            return False

    def wait_peer(self, peer: PeerID, timeout: float = 300.0) -> bool:
        """Block until peer's server answers pings (parity: router.Wait with
        WaitRunnerTimeout, peer/peer.go:200-209)."""
        deadline = time.monotonic() + timeout
        delay = CONN_RETRY_MIN
        while time.monotonic() < deadline:
            if self.ping(peer):
                return True
            time.sleep(delay)
            delay = min(delay * CONN_RETRY_GROWTH, CONN_RETRY_PERIOD)
        return False

    def close(self) -> None:
        self.reset_connections()
