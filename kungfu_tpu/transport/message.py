"""Wire protocol: framed named messages over TCP/Unix sockets.

Capability parity: srcs/go/rchannel/connection/message.go — connection
header {type, source identity} + token ack; messages are
{name}{flags}{payload} frames; connection types demux to different
handlers (message.go:12-18, :45-68, :80-213).

The DCN control plane uses this for: control messages (cluster updates),
consensus/barrier collectives, p2p weight-store requests, and queues.
Device data NEVER flows here — that is ICI/XLA territory.
"""

from __future__ import annotations

import dataclasses
import enum
import socket
import struct
from typing import Tuple

MAGIC = 0x4B465450  # "KFTP"

try:
    from kungfu_tpu.transport import _native_io as _nio

    _NATIVE = _nio.available
except ImportError:  # pragma: no cover - loader guards its own failures
    _nio = None
    _NATIVE = False


class ConnType(enum.IntEnum):
    PING = 0
    CONTROL = 1
    COLLECTIVE = 2
    PEER_TO_PEER = 3
    QUEUE = 4


class Flags(enum.IntFlag):
    NONE = 0
    WAIT_RECV_BUF = 1  # receiver must deliver into a registered buffer
    IS_RESPONSE = 2
    REQUEST_FAILED = 4
    SHM_REF = 8  # payload is a {offset,len,advance} shm-arena descriptor


@dataclasses.dataclass
class Message:
    name: str
    data: "bytes | bytearray | memoryview"  # any buffer; np.frombuffer-able
    flags: Flags = Flags.NONE
    # borrow protocol: set when `data` is a mapped shm region owned by the
    # ring — the consumer MUST call it exactly once when done with `data`
    release: "object" = None


# ---------------------------------------------------------------------------
# framing
# ---------------------------------------------------------------------------

_HEADER = struct.Struct("<IBHI")  # magic, conn_type, src_port, token
_FRAME = struct.Struct("<III")  # name_len, flags, data_len


def _recv_exact_into(sock: socket.socket, view: memoryview) -> None:
    if _NATIVE:
        # whole receive in one GIL-released call (native/io_pump.cpp)
        _nio.recv_exact_into(sock, view)
        return
    n = len(view)
    got = 0
    while got < n:
        r = sock.recv_into(view[got:], n - got)
        if r == 0:
            raise ConnectionError("peer closed connection")
        got += r


def _recv_exact(sock: socket.socket, n: int) -> bytearray:
    # bytearray, not bytes: spares the final copy; every consumer
    # (np.frombuffer, .decode, struct.unpack) takes any buffer
    buf = bytearray(n)
    _recv_exact_into(sock, memoryview(buf))
    return buf


def send_header(sock: socket.socket, conn_type: ConnType, src_host: str, src_port: int, token: int) -> None:
    host_b = src_host.encode()
    sock.sendall(_HEADER.pack(MAGIC, int(conn_type), src_port, token)
                 + struct.pack("<H", len(host_b)) + host_b)


def recv_header(sock: socket.socket):
    """Returns (conn_type, src_host, src_port, token)."""
    magic, conn_type, src_port, token = _HEADER.unpack(_recv_exact(sock, _HEADER.size))
    if magic != MAGIC:
        raise ConnectionError(f"bad magic: {magic:#x}")
    (host_len,) = struct.unpack("<H", _recv_exact(sock, 2))
    host = _recv_exact(sock, host_len).decode()
    return ConnType(conn_type), host, src_port, token


def send_ack(sock: socket.socket, token: int) -> None:
    sock.sendall(struct.pack("<I", token))


def recv_ack(sock: socket.socket) -> int:
    (token,) = struct.unpack("<I", _recv_exact(sock, 4))
    return token


def send_message(sock: socket.socket, msg: Message) -> None:
    name_b = msg.name.encode()
    data_len = nbytes_of(msg.data)
    head = _FRAME.pack(len(name_b), int(msg.flags), data_len) + name_b
    if _NATIVE:
        # header+payload in one GIL-released writev loop (io_pump.cpp)
        _nio.send2(sock, head, msg.data, data_len)
        return
    # one syscall for frame+name; payload separate (never copy it)
    sock.sendall(head)
    if data_len:
        sock.sendall(msg.data)


def nbytes_of(data) -> int:
    """Byte length of any buffer (len() of a typed memoryview counts
    elements, not bytes)."""
    if isinstance(data, memoryview):
        return data.nbytes
    return len(data)


def recv_frame_header(sock: socket.socket) -> Tuple[str, Flags, int]:
    """Read frame header + name, leaving the payload unread on the socket
    so the caller can deliver it straight into a registered buffer."""
    name_len, flags, data_len = _FRAME.unpack(_recv_exact(sock, _FRAME.size))
    name = _recv_exact(sock, name_len).decode()
    return name, Flags(flags), data_len


def recv_message(sock: socket.socket) -> Message:
    name, flags, data_len = recv_frame_header(sock)
    data = _recv_exact(sock, data_len) if data_len else b""
    return Message(name=name, data=data, flags=flags)
