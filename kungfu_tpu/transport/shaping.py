"""Shaped-link transport harness (ISSUE 14 tentpole, part c).

``KF_SHAPE_LINKS`` generalizes the one-off ``KF_TEST_SLOW_EDGE`` fault
injection into a per-edge latency/bandwidth/jitter shaper, so one box
can emulate a multi-host DCN at k=32–64 and the measured-topology
re-planner has something measurable to win against.

Grammar (documented in docs/knobs.md)::

    KF_SHAPE_LINKS = entry [';' entry]...
    entry          = ['src' '>'] dst '=' param [',' param]...
    param          = 'lat:'    ms        # one-way latency per message
                   | 'bw:'     rate      # token-bucket pacing; rate is
                                         # bytes/sec, with KiB/MiB/GiB
                                         # (optionally 'ps') suffixes
                   | 'jitter:' ms        # deterministic 0..jitter extra

``dst`` (and the optional ``src``) are ``host:port`` peer specs; ``*``
as dst matches every destination (the most specific entry wins: exact
dst beats ``*``). An entry with a ``src`` applies only on the sender
whose peer id matches — in-process multi-peer harnesses match against
each Client's OWN id, not the process env, so one process can host both
ends of an asymmetric shape.

Shared-uplink mode (ISSUE 19 tentpole, part c)::

    entry = 'uplink:' host '=' 'bw:' rate

models what per-edge buckets cannot: ONE host NIC that every sender on
the host drains together (r11's honest control showed per-edge shapes
tie flat vs hierarchical plans at 1.01x — the contention a two-level
plan wins against is the SHARED uplink). ``host`` is a bare hostname
(every sender whose peer id lives on it pays for sends leaving it) or
a ``|``-joined list of ``host:port`` peer specs (the in-process
harness form — all listed peers share one virtual host). The bucket is
a file-locked mmap shared across PROCESSES: tokens drained by any
member are gone for all of them, which is exactly a saturated NIC.

The delay is applied INSIDE the transport's timed send window while the
per-connection lock is held (the caller does the sleeping): exactly
like a saturated pipe, the shaped edge serializes, the link table's
passive bandwidth estimate converges to the shaped rate, the walk
profiler books the time as send-blocked, and the step plane elects the
shaped edge as critical — every observability surface sees the same
link the engine experiences.

Jitter is DETERMINISTIC (an LCG over a per-edge message counter, no
RNG): reruns of a shaped bench see identical delay sequences, so paired
A/B ratios stay drift-free.
"""

from __future__ import annotations

import hashlib
import mmap
import os
import struct
import tempfile
import threading
import time
from typing import Dict, List, Optional, Tuple

# token-bucket burst: how many bytes may pass unpaced after an idle
# period (seconds of credit at the shaped rate). Small enough that a
# steady collective stream converges to the shaped bandwidth within one
# segment, large enough that control frames don't pay a pacing stall.
BURST_SECONDS = 0.02
BURST_MIN_BYTES = 64 << 10

_RATE_SUFFIX = {
    "kib": 1 << 10, "mib": 1 << 20, "gib": 1 << 30,
    "kb": 1000, "mb": 1000_000, "gb": 1000_000_000,
    "k": 1 << 10, "m": 1 << 20, "g": 1 << 30,
}


def _parse_rate(s: str) -> float:
    """`20MiB`/`5mb`/`1.5G`[ps] → bytes/sec."""
    raw = s.strip().lower()
    if raw.endswith("ps"):
        raw = raw[:-2]
    raw = raw.rstrip("/s")
    for suffix in sorted(_RATE_SUFFIX, key=len, reverse=True):
        if raw.endswith(suffix):
            return float(raw[: -len(suffix)]) * _RATE_SUFFIX[suffix]
    return float(raw)


class EdgeShape:
    """Shape parameters of one directed edge."""

    __slots__ = ("lat_s", "bw_bps", "jitter_s")

    def __init__(self, lat_s: float = 0.0, bw_bps: float = 0.0,
                 jitter_s: float = 0.0):
        self.lat_s = float(lat_s)
        self.bw_bps = float(bw_bps)
        self.jitter_s = float(jitter_s)

    def __repr__(self) -> str:
        return (f"EdgeShape(lat={self.lat_s * 1e3:g}ms, "
                f"bw={self.bw_bps:g}B/s, jitter={self.jitter_s * 1e3:g}ms)")


def _parse_entry(entry: str) -> Optional[Tuple[str, str, EdgeShape]]:
    """One `[src>]dst=params` entry → (src or '', dst, EdgeShape)."""
    edge, sep, params = entry.partition("=")
    if not sep:
        raise ValueError(f"missing '=' in {entry!r}")
    src, _, dst = edge.strip().rpartition(">")
    src, dst = src.strip(), dst.strip()
    if not dst:
        raise ValueError(f"missing destination in {entry!r}")
    shape = EdgeShape()
    for param in params.split(","):
        param = param.strip()
        if not param:
            continue
        key, sep, val = param.partition(":")
        if not sep:
            raise ValueError(f"malformed param {param!r} (want key:value)")
        key = key.strip().lower()
        if key == "lat":
            shape.lat_s = float(val) / 1e3
        elif key == "bw":
            shape.bw_bps = _parse_rate(val)
        elif key == "jitter":
            shape.jitter_s = float(val) / 1e3
        else:
            raise ValueError(f"unknown shape key {key!r} in {entry!r}")
    if shape.lat_s < 0 or shape.bw_bps < 0 or shape.jitter_s < 0:
        raise ValueError(f"negative shape value in {entry!r}")
    if shape.lat_s == 0 and shape.bw_bps == 0 and shape.jitter_s == 0:
        return None  # an all-zero entry shapes nothing
    return src, dst, shape


def parse_spec(spec: str, self_spec: str) -> Dict[str, EdgeShape]:
    """Parse a KF_SHAPE_LINKS spec into {dst: EdgeShape} for THIS sender
    (entries whose src doesn't match ``self_spec`` are dropped; dst may
    be '*'; ``uplink:`` entries belong to :func:`parse_uplinks` and are
    skipped here). Malformed entries raise ValueError — callers decide
    whether to warn-and-skip (env path) or fail (tests)."""
    shapes: Dict[str, EdgeShape] = {}
    for entry in spec.split(";"):
        entry = entry.strip()
        if not entry:
            continue
        if entry.split("=", 1)[0].strip().lower().startswith("uplink:"):
            continue
        parsed = _parse_entry(entry)
        if parsed is None:
            continue
        src, dst, shape = parsed
        if src and src != "*" and src != self_spec:
            continue
        shapes[dst] = shape
    return shapes


# ---------------------------------------------------------------------------
# shared-uplink bucket (ISSUE 19 tentpole, part c)
# ---------------------------------------------------------------------------

class SharedBucket:
    """ONE token bucket shared across processes: a 16-byte mmap'd file
    (tokens f64, last-refill CLOCK_MONOTONIC f64 — machine-wide on
    Linux) with ``flock`` around each read-modify-write. Every sender
    on the shaped host drains the same token pool, so concurrent
    senders CONTEND — the physics per-edge buckets cannot model.

    The read-modify-write happens under the file lock; the computed
    deficit is slept off by the CALLER after release (the LinkShaper
    discipline: never sleep holding a lock). Negative debt is carried,
    same as the per-edge bucket."""

    _FMT = "<dd"
    _SIZE = struct.calcsize(_FMT)

    def __init__(self, path: str, bw_bps: float, clock=time.monotonic):
        self.path = path
        self.bw_bps = float(bw_bps)
        self._clock = clock
        self._burst = max(BURST_MIN_BYTES, self.bw_bps * BURST_SECONDS)
        import fcntl  # POSIX-only, like the rest of the transport

        self._flock = fcntl.flock
        self._ex, self._un = fcntl.LOCK_EX, fcntl.LOCK_UN
        self._fd = os.open(path, os.O_RDWR | os.O_CREAT, 0o600)
        self._flock(self._fd, self._ex)
        try:
            if os.fstat(self._fd).st_size < self._SIZE:
                # first member in: size the file and seed a full burst
                os.ftruncate(self._fd, self._SIZE)
                os.pwrite(self._fd, struct.pack(
                    self._FMT, self._burst, self._clock()), 0)
        finally:
            self._flock(self._fd, self._un)
        self._map = mmap.mmap(self._fd, self._SIZE)

    def delay(self, nbytes: int) -> float:
        """Seconds the caller must sleep before ``nbytes`` cross the
        shared uplink (0.0 within burst)."""
        self._flock(self._fd, self._ex)
        try:
            tokens, last = struct.unpack(self._FMT, self._map[:self._SIZE])
            now = self._clock()
            # a peer that seeded the file earlier may carry a stale
            # monotonic stamp from before this boot; clamp refill at
            # one full burst so corruption can't mint infinite credit
            tokens = min(self._burst,
                         tokens + max(0.0, now - last) * self.bw_bps)
            tokens -= nbytes
            self._map[:self._SIZE] = struct.pack(self._FMT, tokens, now)
        finally:
            self._flock(self._fd, self._un)
        return -tokens / self.bw_bps if tokens < 0 else 0.0

    def close(self) -> None:
        try:
            self._map.close()
            os.close(self._fd)
        except OSError:
            pass


class Uplink:
    """One shared-uplink shape: the host group it covers + its bucket."""

    __slots__ = ("token", "hostname", "members", "bw_bps", "bucket")

    def __init__(self, token: str, bw_bps: float,
                 bucket: Optional[SharedBucket] = None):
        self.token = token
        self.bw_bps = float(bw_bps)
        if "|" in token:
            self.members: Optional[frozenset] = frozenset(
                m.strip() for m in token.split("|") if m.strip())
            self.hostname = ""
        else:
            self.members = None
            self.hostname = token
        self.bucket = bucket

    def canonical(self) -> str:
        """Order-independent identity — every member process must map
        the same group to the SAME bucket file."""
        group = ("|".join(sorted(self.members))
                 if self.members is not None else self.hostname)
        return f"uplink:{group}=bw:{self.bw_bps:g}"

    def covers_sender(self, self_spec: str) -> bool:
        if self.members is not None:
            return self_spec in self.members
        return self_spec.rsplit(":", 1)[0] == self.hostname

    def crosses(self, dst: str) -> bool:
        """True when a send to ``dst`` LEAVES the host (intra-host
        traffic never touches the NIC)."""
        if self.members is not None:
            return dst not in self.members
        return dst.rsplit(":", 1)[0] != self.hostname


def _parse_uplink_entry(entry: str) -> Tuple[str, float]:
    """`uplink:host=bw:rate` → (host token, bytes/sec)."""
    edge, sep, params = entry.partition("=")
    token = edge.strip()[len("uplink:"):].strip()
    if not sep or not token:
        raise ValueError(f"malformed uplink entry {entry!r} "
                         "(want uplink:host=bw:rate)")
    bw = 0.0
    for param in params.split(","):
        param = param.strip()
        if not param:
            continue
        key, psep, val = param.partition(":")
        if not psep or key.strip().lower() != "bw":
            raise ValueError(
                f"uplink entries shape bandwidth only (bw:rate), got "
                f"{param!r} in {entry!r}")
        bw = _parse_rate(val)
    if bw <= 0:
        raise ValueError(f"uplink entry {entry!r} needs a positive bw:rate")
    return token, bw


def _bucket_dir() -> str:
    from kungfu_tpu import knobs

    d = knobs.raw("KF_TELEMETRY_DIR").strip()
    return d if d else tempfile.gettempdir()


def parse_uplinks(spec: str, self_spec: str,
                  make_bucket: bool = True) -> List[Uplink]:
    """The ``uplink:`` entries of a KF_SHAPE_LINKS spec that cover THIS
    sender, each backed by its cross-process bucket file (named by a
    digest of the canonical group+rate, under KF_TELEMETRY_DIR or the
    system tempdir — every member lands on the same file). Malformed
    entries raise ValueError, like edge entries."""
    ups: List[Uplink] = []
    for entry in spec.split(";"):
        entry = entry.strip()
        if not entry:
            continue
        if not entry.split("=", 1)[0].strip().lower().startswith("uplink:"):
            continue
        token, bw = _parse_uplink_entry(entry)
        up = Uplink(token, bw)
        if not up.covers_sender(self_spec):
            continue
        if make_bucket:
            digest = hashlib.blake2s(
                up.canonical().encode(), digest_size=8).hexdigest()
            up.bucket = SharedBucket(
                os.path.join(_bucket_dir(), f"kf-uplink-{digest}.bucket"),
                bw)
        ups.append(up)
    return ups


class LinkShaper:
    """Per-destination token-bucket pacer + latency/jitter injector.

    :meth:`delay` computes (under the shaper's own lock — no sleeping
    inside it) how long the CALLER must sleep before a send of
    ``nbytes`` toward ``dst`` so the edge behaves like the shaped link;
    :meth:`latency` is the message-latency-only variant for pings."""

    def __init__(self, shapes: Dict[str, EdgeShape],
                 clock=time.monotonic, uplinks: Tuple[Uplink, ...] = ()):
        self._shapes = dict(shapes)
        self._clock = clock
        self._lock = threading.Lock()
        # per-dst token-bucket state: (tokens, last_refill_ts)
        self._buckets: Dict[str, Tuple[float, float]] = {}
        # per-dst message counter driving the deterministic jitter LCG
        self._counts: Dict[str, int] = {}
        # shared-uplink buckets this sender drains (ISSUE 19)
        self._uplinks = tuple(uplinks)

    def __bool__(self) -> bool:
        return bool(self._shapes or self._uplinks)

    def shape_for(self, dst: str) -> Optional[EdgeShape]:
        """Most specific match: exact dst, else the '*' wildcard."""
        return self._shapes.get(str(dst)) or self._shapes.get("*")

    def _jitter(self, key: str, shape: EdgeShape) -> float:
        """`key` is the counter stream — sends and pings keep SEPARATE
        streams per dst: pings fire on wall-clock schedules, so sharing
        one counter would make the send-side jitter sequence depend on
        ping timing and break the rerun-determinism the module
        guarantees (review finding)."""
        if shape.jitter_s <= 0:
            return 0.0
        n = self._counts.get(key, 0)
        self._counts[key] = n + 1
        # deterministic LCG over the per-edge message counter: identical
        # across reruns (no RNG — the span-sampler discipline)
        frac = ((n * 1103515245 + 12345) % (1 << 31)) / float(1 << 31)
        return shape.jitter_s * frac

    def delay(self, dst, nbytes: int) -> float:
        """Seconds the caller should sleep before sending ``nbytes`` to
        ``dst`` (0.0 when the edge is unshaped or within its burst)."""
        key = str(dst)
        d = 0.0
        shape = self.shape_for(key)
        if shape is not None:
            with self._lock:
                d = shape.lat_s + self._jitter(key, shape)
                if shape.bw_bps > 0:
                    now = self._clock()
                    burst = max(BURST_MIN_BYTES,
                                shape.bw_bps * BURST_SECONDS)
                    tokens, last = self._buckets.get(key, (burst, now))
                    tokens = min(burst, tokens + (now - last) * shape.bw_bps)
                    tokens -= nbytes
                    if tokens < 0:
                        # the caller sleeps the deficit off; KEEP the
                        # debt negative — the sleep period's refill
                        # (next call's elapsed-time credit) pays it
                        # back, so clamping to zero here would
                        # double-credit the sleep and pace ~30% above
                        # the shaped rate
                        d += -tokens / shape.bw_bps
                    self._buckets[key] = (tokens, now)
        # shared uplink (ISSUE 19): sends LEAVING the host also drain
        # the host's one bucket — outside self._lock, the bucket holds
        # its own cross-process file lock
        for up in self._uplinks:
            if up.bucket is not None and up.crosses(key):
                d += up.bucket.delay(nbytes)
        return d

    def latency(self, dst) -> float:
        """Latency+jitter only (ping-sized traffic never pays pacing)."""
        key = str(dst)
        shape = self.shape_for(key)
        if shape is None:
            return 0.0
        with self._lock:
            return shape.lat_s + self._jitter("ping:" + key, shape)


def _slow_edge_as_spec(raw: str) -> str:
    """Translate the DEPRECATED KF_TEST_SLOW_EDGE `[src>]dst=ms` into a
    KF_SHAPE_LINKS entry `[src>]dst=lat:ms`."""
    edge, sep, ms = raw.rpartition("=")
    if not sep or not edge.strip():
        raise ValueError(raw)
    float(ms)  # malformed delay must raise here, not parse as a shape key
    return f"{edge.strip()}=lat:{ms.strip()}"


def from_env(self_spec: str) -> Optional[LinkShaper]:
    """Build the process shaper from KF_SHAPE_LINKS (+ the deprecated
    KF_TEST_SLOW_EDGE alias, which warns but keeps injecting — a stale
    e2e env must not silently become 'no delay'). None when unshaped.
    Malformed specs warn and shape nothing rather than killing the
    worker — but loudly, so a typo'd harness doesn't surface as an
    unexplained timeout two minutes later."""
    from kungfu_tpu import knobs
    from kungfu_tpu.telemetry import log

    spec = knobs.raw("KF_SHAPE_LINKS").strip()
    legacy = knobs.raw("KF_TEST_SLOW_EDGE").strip()
    if legacy:
        try:
            legacy_entry = _slow_edge_as_spec(legacy)
        except ValueError:
            log.warn(
                "KF_TEST_SLOW_EDGE: malformed value %r (want `[src>]dst"
                "=ms`) — no edge delay injected", legacy,
            )
        else:
            dst = legacy_entry.partition("=")[0].rpartition(">")[2].strip()
            host, _, port = dst.rpartition(":")
            if not host or not port.isdigit():
                # the spec names a HOST, not a host:port peer — a
                # per-edge delay keyed on it will never match a real
                # destination; the whole-host intent is the shared
                # uplink's job (ISSUE 19)
                log.warn(
                    "KF_TEST_SLOW_EDGE: %r names a host, not a "
                    "host:port peer — the delay will match nothing. "
                    "To shape a whole host's uplink use KF_SHAPE_LINKS"
                    "=uplink:%s=bw:<rate>", legacy, dst,
                )
            log.warn(
                "KF_TEST_SLOW_EDGE is deprecated — use KF_SHAPE_LINKS="
                "%r", legacy_entry,
            )
            # legacy entries go FIRST: parse_spec is last-wins per dst,
            # so an explicit KF_SHAPE_LINKS entry for the same
            # destination overrides a stale alias, not the other way
            # around (review finding)
            spec = f"{legacy_entry};{spec}" if spec else legacy_entry
    if not spec:
        return None
    try:
        shapes = parse_spec(spec, self_spec)
        uplinks = parse_uplinks(spec, self_spec)
    except ValueError as e:
        log.warn(
            "KF_SHAPE_LINKS: malformed spec (%s) — NO link shaping "
            "injected; fix the spec (`[src>]dst=lat:ms,bw:rate,"
            "jitter:ms; uplink:host=bw:rate; ...`)", e,
        )
        return None
    if not shapes and not uplinks:
        return None
    return LinkShaper(shapes, uplinks=tuple(uplinks))
