from kungfu_tpu.transport.message import ConnType, Flags, Message
from kungfu_tpu.transport.client import Client
from kungfu_tpu.transport.server import Server

__all__ = ["Client", "ConnType", "Flags", "Message", "Server"]
