"""MNIST SLP/MLP — the minimum end-to-end training slice.

Parity: the reference's examples/tf2_mnist_gradient_tape.py +
tests/python/integration/test_mnist_slp.py use a single-layer perceptron as
the smallest real training workload; same role here.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

MLP_PARITY_NOTE = "examples/tf2_mnist_gradient_tape.py equivalent workload"


def init_mlp(key, in_dim: int = 784, hidden: int = 0, out_dim: int = 10):
    """hidden=0 gives the reference's single-layer perceptron."""
    if hidden:
        k1, k2 = jax.random.split(key)
        scale1 = 1.0 / jnp.sqrt(in_dim)
        scale2 = 1.0 / jnp.sqrt(hidden)
        return {
            "w1": jax.random.normal(k1, (in_dim, hidden)) * scale1,
            "b1": jnp.zeros((hidden,)),
            "w2": jax.random.normal(k2, (hidden, out_dim)) * scale2,
            "b2": jnp.zeros((out_dim,)),
        }
    scale = 1.0 / jnp.sqrt(in_dim)
    return {
        "w": jax.random.normal(key, (in_dim, out_dim)) * scale,
        "b": jnp.zeros((out_dim,)),
    }


def mlp_apply(params, x):
    if "w1" in params:
        h = jax.nn.relu(x @ params["w1"] + params["b1"])
        return h @ params["w2"] + params["b2"]
    return x @ params["w"] + params["b"]


def mlp_loss(params, batch):
    x, y = batch
    logits = mlp_apply(params, x)
    logp = jax.nn.log_softmax(logits)
    return -jnp.mean(jnp.sum(jax.nn.one_hot(y, logits.shape[-1]) * logp, axis=-1))
