"""Flagship decoder-only transformer LM with an explicit sharding plan.

TPU-first design notes:
- Params live in a plain pytree with a parallel tree of PartitionSpecs
  (param_pspecs): Megatron-style tensor parallelism over the 'tp' mesh
  axis (column-parallel QKV/FF-in, row-parallel O/FF-out), batch over
  'dp', optional sequence sharding over 'sp' for activations. XLA's SPMD
  partitioner inserts the AllReduce/AllGather collectives over ICI from
  these annotations — nothing is hand-scheduled.
- Compute in bfloat16 (MXU native), params and optimizer state in f32.
- Static shapes everywhere; layers are stacked and scanned-friendly.

The reference has no model code (KungFu is model-agnostic); this model is
the framework's flagship workload for the BERT-config benchmark
(BASELINE.md config 3) and the long-context/sequence-parallel path.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P


@dataclasses.dataclass(frozen=True)
class TransformerConfig:
    vocab_size: int = 32000
    d_model: int = 512
    n_heads: int = 8
    n_layers: int = 4
    d_ff: int = 2048
    max_seq: int = 512
    dtype: Any = jnp.bfloat16

    @property
    def head_dim(self) -> int:
        assert self.d_model % self.n_heads == 0
        return self.d_model // self.n_heads

    @classmethod
    def bert_base(cls) -> "TransformerConfig":
        return cls(vocab_size=30522, d_model=768, n_heads=12, n_layers=12,
                   d_ff=3072, max_seq=512)

    @classmethod
    def tiny(cls) -> "TransformerConfig":
        return cls(vocab_size=256, d_model=64, n_heads=4, n_layers=2,
                   d_ff=128, max_seq=64)


def init_transformer(key, cfg: TransformerConfig) -> Dict:
    """Params in f32; cast to cfg.dtype at apply time."""
    keys = jax.random.split(key, 2 + cfg.n_layers)
    scale = 0.02

    def dense(k, shape):
        return jax.random.normal(k, shape, jnp.float32) * scale

    layers = []
    for i in range(cfg.n_layers):
        lk = jax.random.split(keys[2 + i], 4)
        layers.append({
            "ln1_scale": jnp.ones((cfg.d_model,), jnp.float32),
            "ln2_scale": jnp.ones((cfg.d_model,), jnp.float32),
            "wqkv": dense(lk[0], (cfg.d_model, 3 * cfg.d_model)),
            "wo": dense(lk[1], (cfg.d_model, cfg.d_model)),
            "w_in": dense(lk[2], (cfg.d_model, cfg.d_ff)),
            "w_out": dense(lk[3], (cfg.d_ff, cfg.d_model)),
        })
    # stack layers: leading axis = layer, enables lax.scan over layers
    stacked = jax.tree.map(lambda *xs: jnp.stack(xs), *layers)
    return {
        "embed": dense(keys[0], (cfg.vocab_size, cfg.d_model)),
        "pos_embed": dense(keys[1], (cfg.max_seq, cfg.d_model)),
        "ln_f_scale": jnp.ones((cfg.d_model,), jnp.float32),
        "layers": stacked,
    }


def param_pspecs(cfg: TransformerConfig, tp_axis: str = "tp") -> Dict:
    """PartitionSpec tree matching init_transformer's param tree.

    Column-parallel wqkv/w_in (shard output features over tp), row-parallel
    wo/w_out (shard input features over tp); embedding sharded over vocab.
    Layer-stacked leaves have a leading layer axis (unsharded).
    """
    t = tp_axis
    return {
        "embed": P(t, None),
        "pos_embed": P(),
        "ln_f_scale": P(),
        "layers": {
            "ln1_scale": P(None),
            "ln2_scale": P(None),
            "wqkv": P(None, None, t),
            "wo": P(None, t, None),
            "w_in": P(None, None, t),
            "w_out": P(None, t, None),
        },
    }


def _rmsnorm(x, scale, eps=1e-6):
    var = jnp.mean(jnp.square(x.astype(jnp.float32)), axis=-1, keepdims=True)
    return (x * jax.lax.rsqrt(var + eps)).astype(x.dtype) * scale.astype(x.dtype)


def _full_attention_core(q, k, v):
    """(B, H, S, hd) q/k/v -> causal attention context, same shape."""
    hd = q.shape[-1]
    S = q.shape[2]
    scores = jnp.einsum("bhqd,bhkd->bhqk", q, k) / np.sqrt(hd).astype(q.dtype)
    mask = jnp.tril(jnp.ones((S, S), bool))
    scores = jnp.where(mask, scores, jnp.finfo(scores.dtype).min)
    probs = jax.nn.softmax(scores.astype(jnp.float32), axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bhkd->bhqd", probs, v)


def _attention(x, wqkv, wo, cfg: TransformerConfig, core=_full_attention_core):
    """QKV projection + head reshape around a pluggable (q,k,v)->ctx core
    (full attention by default, the ring core for sequence parallelism —
    ONE copy of the projection plumbing for both paths)."""
    B, S, D = x.shape
    H, hd = cfg.n_heads, cfg.head_dim
    qkv = x @ wqkv  # (B, S, 3D)
    q, k, v = jnp.split(qkv, 3, axis=-1)
    q = q.reshape(B, S, H, hd).transpose(0, 2, 1, 3)
    k = k.reshape(B, S, H, hd).transpose(0, 2, 1, 3)
    v = v.reshape(B, S, H, hd).transpose(0, 2, 1, 3)
    ctx = core(q, k, v)
    ctx = ctx.transpose(0, 2, 1, 3).reshape(B, S, D)
    return ctx @ wo


def _block(x, layer, cfg: TransformerConfig, core=_full_attention_core):
    dt = cfg.dtype
    x = x + _attention(_rmsnorm(x, layer["ln1_scale"]),
                       layer["wqkv"].astype(dt), layer["wo"].astype(dt), cfg,
                       core=core)
    h = _rmsnorm(x, layer["ln2_scale"])
    h = jax.nn.gelu(h @ layer["w_in"].astype(dt))
    return x + h @ layer["w_out"].astype(dt)


def lm_head_loss(params, x, targets, cfg: TransformerConfig):
    """Final norm + tied-embedding LM head + next-token cross-entropy on
    hidden states `x` (..., S, D). The ONE implementation shared by the
    dense, ring (sequence-parallel) and pipeline paths — a loss change
    (label smoothing, z-loss, dtype policy) lands everywhere at once."""
    h = _rmsnorm(x, params["ln_f_scale"])
    logits = h.astype(jnp.float32) @ params["embed"].astype(jnp.float32).T
    logp = jax.nn.log_softmax(logits)
    ll = jnp.take_along_axis(logp, targets[..., None], axis=-1)
    return -jnp.mean(ll)


def transformer_hidden(params, tokens, cfg: TransformerConfig):
    """tokens (B, S) int32 -> final hidden states (B, S, D) pre-norm."""
    B, S = tokens.shape
    dt = cfg.dtype
    x = params["embed"].astype(dt)[tokens] + params["pos_embed"].astype(dt)[:S]

    def body(x, layer):
        return _block(x, layer, cfg), None

    x, _ = jax.lax.scan(body, x, params["layers"])
    return x


def transformer_apply(params, tokens, cfg: TransformerConfig):
    """tokens (B, S) int32 -> logits (B, S, V) in f32."""
    x = transformer_hidden(params, tokens, cfg)
    x = _rmsnorm(x, params["ln_f_scale"])
    return x.astype(jnp.float32) @ params["embed"].astype(jnp.float32).T


def transformer_loss(params, batch, cfg: TransformerConfig):
    """Next-token cross-entropy. batch = tokens (B, S+1) or (tokens, targets)."""
    if isinstance(batch, (tuple, list)):
        tokens, targets = batch
    else:
        tokens, targets = batch[:, :-1], batch[:, 1:]
    x = transformer_hidden(params, tokens, cfg)
    return lm_head_loss(params, x, targets, cfg)


# ---------------------------------------------------------------------------
# sequence-parallel (ring attention) path: the long-context mode. The whole
# forward runs per sequence-SHARD inside a shard_map over (dp, sp) — token
# embedding, norms and FFN are pointwise over positions, so only attention
# needs cross-shard traffic, and that traffic is the K/V ring on ICI
# (ops/ring_attention.py). Peak activation memory per chip scales with
# S/sp instead of S.
# ---------------------------------------------------------------------------


def ring_transformer_apply_shard(params, tokens, cfg: TransformerConfig,
                                 sp_axis: str, sp_size: int):
    """Per-shard forward for shard_map: tokens (B, S_local) is this
    device's sequence chunk; returns per-shard pre-norm hidden states
    (B, S_local, D) — feed them to lm_head_loss."""
    from kungfu_tpu.ops.ring_attention import ring_self_attention

    B, Sl = tokens.shape
    if sp_size * Sl > cfg.max_seq:
        # loud, like the dense path: dynamic_slice would otherwise CLAMP
        # the out-of-range start and silently duplicate positional rows
        raise ValueError(
            f"global sequence {sp_size * Sl} exceeds max_seq {cfg.max_seq}"
        )
    dt = cfg.dtype
    idx = jax.lax.axis_index(sp_axis)
    pos = jax.lax.dynamic_slice(
        params["pos_embed"], (idx * Sl, 0), (Sl, cfg.d_model)
    )
    x = params["embed"].astype(dt)[tokens] + pos.astype(dt)

    def ring_core(q, k, v):
        return ring_self_attention(q, k, v, sp_axis, sp_size, causal=True)

    def body(x, layer):
        # the ONE block implementation, with the ring attention core
        return _block(x, layer, cfg, core=ring_core), None

    x, _ = jax.lax.scan(body, x, params["layers"])
    return x  # pre-final-norm hidden states, like transformer_hidden


def make_ring_transformer_loss(cfg: TransformerConfig, mesh,
                               sp_axis: str = "sp", dp_axis: str = "dp"):
    """Sequence-parallel causal-LM loss: batch = (tokens, targets), both
    (B, S) with B divisible by dp and S by sp. Returns loss_fn(params,
    batch) -> replicated scalar, jit/grad-compatible (shard_map inside)."""
    from kungfu_tpu.parallel._compat import shard_map

    sp_size = mesh.shape[sp_axis]

    def shard_loss(params, batch):
        tokens, targets = batch
        x = ring_transformer_apply_shard(params, tokens, cfg, sp_axis, sp_size)
        loss = lm_head_loss(params, x, targets, cfg)
        return jax.lax.pmean(jax.lax.pmean(loss, sp_axis), dp_axis)

    return shard_map(
        shard_loss,
        mesh=mesh,
        in_specs=(P(), (P(dp_axis, sp_axis), P(dp_axis, sp_axis))),
        out_specs=P(),
        check_vma=False,
    )
