"""Model zoo for the tracked benchmark configs (BASELINE.md).

The reference is model-agnostic (models live in user scripts /
tests/go/fakemodel size lists); here the models double as benchmark
workloads and as sharding showcases:
- mlp: MNIST SLP (the reference's minimum end-to-end example)
- transformer: flagship decoder-only LM with an explicit TP/DP/SP
  sharding plan (BERT-config capable)
- resnet: ResNet-50 (the headline throughput benchmark)
- fake: gradient-size lists for communication benchmarks without real math
  (parity: tests/go/fakemodel/fakemodel.go)
"""

from kungfu_tpu.models.mlp import MLP_PARITY_NOTE, init_mlp, mlp_apply, mlp_loss
from kungfu_tpu.models.transformer import (
    TransformerConfig,
    init_transformer,
    transformer_apply,
    transformer_loss,
    param_pspecs,
)

__all__ = [
    "MLP_PARITY_NOTE",
    "TransformerConfig",
    "init_mlp",
    "init_transformer",
    "mlp_apply",
    "mlp_loss",
    "param_pspecs",
    "transformer_apply",
    "transformer_loss",
]
