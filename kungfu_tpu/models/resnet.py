"""ResNet-50 (flax) — the headline throughput benchmark workload.

Parity: the reference's benchmark model (README "Benchmark": ResNet-50
S-SGD throughput vs Horovod on 16 V100; BASELINE.md north-star metric is
ResNet-50 images/sec/chip). Standard bottleneck-v1.5 architecture.

TPU notes: NHWC layout (XLA-TPU native), bfloat16 compute with f32
batch-norm statistics and params.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

ModuleDef = Any


class BottleneckBlock(nn.Module):
    filters: int
    strides: Tuple[int, int]
    conv: ModuleDef
    norm: ModuleDef

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (1, 1))(x)
        y = self.norm()(y)
        y = nn.relu(y)
        y = self.conv(self.filters, (3, 3), self.strides)(y)
        y = self.norm()(y)
        y = nn.relu(y)
        y = self.conv(self.filters * 4, (1, 1))(y)
        y = self.norm(scale_init=nn.initializers.zeros)(y)
        if residual.shape != y.shape:
            residual = self.conv(self.filters * 4, (1, 1), self.strides, name="conv_proj")(residual)
            residual = self.norm(name="norm_proj")(residual)
        return nn.relu(residual + y)


class SpaceToDepthStem(nn.Module):
    """The 7x7/s2 stem computed via space-to-depth (MLPerf TPU trick).

    A 7x7 conv over 3 input channels uses 3 of the MXU's 128 input lanes;
    block-decomposing the input into 2x2 blocks (12 channels) and the
    zero-padded 8x8 kernel into an equivalent 4x4 kernel over 12 channels
    quadruples MXU occupancy on the stem. The stored parameter stays the
    canonical (7, 7, in, filters) kernel — checkpoints are interchangeable
    with a plain conv stem, and the rewrite is numerically exact (same
    taps, reassociated)."""

    filters: int = 64
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        b, h, w, c = x.shape
        if h % 2 or w % 2:
            raise ValueError(f"space-to-depth stem needs even H/W, got {h}x{w}")
        kernel = self.param(
            "kernel",
            nn.initializers.lecun_normal(),
            (7, 7, c, self.filters),
            jnp.float32,
        ).astype(self.dtype)
        # zero-pad kernel at the front: out[i] = sum_u x[2i-4+u] w8[u]
        w8 = jnp.pad(kernel, ((1, 0), (1, 0), (0, 0), (0, 0)))
        w4 = (
            w8.reshape(4, 2, 4, 2, c, self.filters)
            .transpose(0, 2, 1, 3, 4, 5)
            .reshape(4, 4, 4 * c, self.filters)
        )
        xp = jnp.pad(x, ((0, 0), (4, 4), (4, 4), (0, 0)))
        hb, wb = (h + 8) // 2, (w + 8) // 2
        xs = (
            xp.reshape(b, hb, 2, wb, 2, c)
            .transpose(0, 1, 3, 2, 4, 5)
            .reshape(b, hb, wb, 4 * c)
        )
        out = jax.lax.conv_general_dilated(
            xs, w4, window_strides=(1, 1), padding="VALID",
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
        )
        return out[:, : h // 2, : w // 2, :]


class ResNet(nn.Module):
    stage_sizes: Sequence[int]
    num_classes: int = 1000
    num_filters: int = 64
    dtype: Any = jnp.bfloat16
    # space-to-depth stem: ~5% faster FORWARD on TPU (4x MXU occupancy on
    # conv1) but measured flat on the full train step (XLA already folds
    # stride-2 spatial dims into the conv), so inference configs opt in
    s2d_stem: bool = False

    @nn.compact
    def __call__(self, x, train: bool = True):
        conv = partial(nn.Conv, use_bias=False, dtype=self.dtype)
        norm = partial(
            nn.BatchNorm,
            use_running_average=not train,
            momentum=0.9,
            epsilon=1e-5,
            dtype=self.dtype,
        )
        x = x.astype(self.dtype)
        if self.s2d_stem and x.shape[1] % 2 == 0 and x.shape[2] % 2 == 0:
            x = SpaceToDepthStem(self.num_filters, self.dtype, name="conv_init")(x)
        else:
            x = conv(self.num_filters, (7, 7), (2, 2), padding=[(3, 3), (3, 3)], name="conv_init")(x)
        x = norm(name="bn_init")(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        for i, block_count in enumerate(self.stage_sizes):
            for j in range(block_count):
                strides = (2, 2) if i > 0 and j == 0 else (1, 1)
                x = BottleneckBlock(
                    filters=self.num_filters * 2**i,
                    strides=strides,
                    conv=conv,
                    norm=norm,
                )(x)
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(self.num_classes, dtype=jnp.float32)(x)
        return x.astype(jnp.float32)


def resnet50(num_classes: int = 1000, dtype=jnp.bfloat16) -> ResNet:
    return ResNet(stage_sizes=[3, 4, 6, 3], num_classes=num_classes, dtype=dtype)


def resnet18_thin(num_classes: int = 10, dtype=jnp.bfloat16) -> ResNet:
    """Small variant for CPU-mesh tests."""
    return ResNet(stage_sizes=[1, 1], num_classes=num_classes, num_filters=8, dtype=dtype)


def init_resnet(key, model: ResNet, image_size: int = 224, batch: int = 1):
    dummy = jnp.zeros((batch, image_size, image_size, 3), jnp.float32)
    variables = model.init({"params": key}, dummy, train=False)
    return variables["params"], variables.get("batch_stats", {})


def resnet_loss(model: ResNet, params, batch_stats, batch):
    """Returns (loss, new_batch_stats)."""
    images, labels = batch
    logits, updates = model.apply(
        {"params": params, "batch_stats": batch_stats},
        images,
        train=True,
        mutable=["batch_stats"],
    )
    logp = jax.nn.log_softmax(logits)
    loss = -jnp.mean(
        jnp.sum(jax.nn.one_hot(labels, logits.shape[-1]) * logp, axis=-1)
    )
    return loss, updates["batch_stats"]
