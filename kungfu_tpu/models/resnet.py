"""ResNet-50 (flax) — the headline throughput benchmark workload.

Parity: the reference's benchmark model (README "Benchmark": ResNet-50
S-SGD throughput vs Horovod on 16 V100; BASELINE.md north-star metric is
ResNet-50 images/sec/chip). Standard bottleneck-v1.5 architecture.

TPU notes: NHWC layout (XLA-TPU native), bfloat16 compute with f32
batch-norm statistics and params.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

ModuleDef = Any


class BottleneckBlock(nn.Module):
    filters: int
    strides: Tuple[int, int]
    conv: ModuleDef
    norm: ModuleDef

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (1, 1))(x)
        y = self.norm()(y)
        y = nn.relu(y)
        y = self.conv(self.filters, (3, 3), self.strides)(y)
        y = self.norm()(y)
        y = nn.relu(y)
        y = self.conv(self.filters * 4, (1, 1))(y)
        y = self.norm(scale_init=nn.initializers.zeros)(y)
        if residual.shape != y.shape:
            residual = self.conv(self.filters * 4, (1, 1), self.strides, name="conv_proj")(residual)
            residual = self.norm(name="norm_proj")(residual)
        return nn.relu(residual + y)


class ResNet(nn.Module):
    stage_sizes: Sequence[int]
    num_classes: int = 1000
    num_filters: int = 64
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = True):
        conv = partial(nn.Conv, use_bias=False, dtype=self.dtype)
        norm = partial(
            nn.BatchNorm,
            use_running_average=not train,
            momentum=0.9,
            epsilon=1e-5,
            dtype=self.dtype,
        )
        x = x.astype(self.dtype)
        x = conv(self.num_filters, (7, 7), (2, 2), padding=[(3, 3), (3, 3)], name="conv_init")(x)
        x = norm(name="bn_init")(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        for i, block_count in enumerate(self.stage_sizes):
            for j in range(block_count):
                strides = (2, 2) if i > 0 and j == 0 else (1, 1)
                x = BottleneckBlock(
                    filters=self.num_filters * 2**i,
                    strides=strides,
                    conv=conv,
                    norm=norm,
                )(x)
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(self.num_classes, dtype=jnp.float32)(x)
        return x.astype(jnp.float32)


def resnet50(num_classes: int = 1000, dtype=jnp.bfloat16) -> ResNet:
    return ResNet(stage_sizes=[3, 4, 6, 3], num_classes=num_classes, dtype=dtype)


def resnet18_thin(num_classes: int = 10, dtype=jnp.bfloat16) -> ResNet:
    """Small variant for CPU-mesh tests."""
    return ResNet(stage_sizes=[1, 1], num_classes=num_classes, num_filters=8, dtype=dtype)


def init_resnet(key, model: ResNet, image_size: int = 224, batch: int = 1):
    dummy = jnp.zeros((batch, image_size, image_size, 3), jnp.float32)
    variables = model.init({"params": key}, dummy, train=False)
    return variables["params"], variables.get("batch_stats", {})


def resnet_loss(model: ResNet, params, batch_stats, batch):
    """Returns (loss, new_batch_stats)."""
    images, labels = batch
    logits, updates = model.apply(
        {"params": params, "batch_stats": batch_stats},
        images,
        train=True,
        mutable=["batch_stats"],
    )
    logp = jax.nn.log_softmax(logits)
    loss = -jnp.mean(
        jnp.sum(jax.nn.one_hot(labels, logits.shape[-1]) * logp, axis=-1)
    )
    return loss, updates["batch_stats"]
