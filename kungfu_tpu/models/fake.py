"""Fake models: gradient-size lists for communication benchmarks.

Capability parity: tests/go/fakemodel/fakemodel.go:12-27 and the C++ twins
(tests/cpp/integration/{resnet50_info,vgg_info,bert}.hpp) — emulate a
model's gradient exchange with no real math, so collective paths can be
tested and benchmarked without an ML workload.
"""

from __future__ import annotations

from typing import Dict, List

import numpy as np

# Parameter-tensor sizes (elements) representative of each model's gradient
# set; same role as the reference's size lists.
FAKE_MODELS: Dict[str, List[int]] = {
    "tiny": [1, 10, 100],
    "slp-mnist": [784 * 10, 10],
    "resnet50-imagenet": (
        [64 * 3 * 7 * 7]
        + [256 * 64, 64 * 64 * 9, 64 * 256] * 3
        + [512 * 128, 128 * 128 * 9, 128 * 512] * 4
        + [1024 * 256, 256 * 256 * 9, 256 * 1024] * 6
        + [2048 * 512, 512 * 512 * 9, 512 * 2048] * 3
        + [2048 * 1000, 1000]
    ),
    "vgg16-imagenet": [
        64 * 3 * 9, 64 * 64 * 9,
        128 * 64 * 9, 128 * 128 * 9,
        256 * 128 * 9, 256 * 256 * 9, 256 * 256 * 9,
        512 * 256 * 9, 512 * 512 * 9, 512 * 512 * 9,
        512 * 512 * 9, 512 * 512 * 9, 512 * 512 * 9,
        25088 * 4096, 4096 * 4096, 4096 * 1000,
    ],
    "bert": [1024 * 1024] * 24 * 6 + [30522 * 1024, 512 * 1024],
    # 4 MiB in one tensor: sized for shaped-link benches (ISSUE 14) —
    # big enough that per-segment sends clear the link-plane bw gate at
    # k<=32, small enough that a 16 MiB/s shaped edge stays affordable
    "mlp-4mib": [1 << 20],
}


def fake_gradients(name: str, dtype=np.float32, seed: int = 0) -> List[np.ndarray]:
    """Materialize double buffers for a named fake model."""
    sizes = FAKE_MODELS[name]
    rng = np.random.default_rng(seed)
    return [rng.standard_normal(s).astype(dtype) for s in sizes]


def total_size_bytes(name: str, dtype=np.float32) -> int:
    return sum(FAKE_MODELS[name]) * np.dtype(dtype).itemsize
