"""Worker process spawning and log streaming.

Capability parity: srcs/go/proc/proc.go + srcs/go/utils/runner/local
(parallel local exec with colored per-proc log prefixes and per-worker log
files) and srcs/go/kungfu/job/job.go (env construction).
"""

from __future__ import annotations

import collections
import os
import subprocess
import sys
import threading
from typing import Dict, List, Optional

# last-words ring per worker: postmortems include output even when the
# flight journal is missing or empty (ISSUE 3 satellite)
OUTPUT_TAIL_LINES = 200

from kungfu_tpu.telemetry import log

_COLORS = [31, 32, 33, 34, 35, 36, 91, 92, 93, 94, 95, 96]

# Orphan protection: children get SIGTERM when the runner dies
# (PR_SET_PDEATHSIG), so a hard-killed runner (SIGKILL, OOM) cannot leave
# workers or warm standbys lingering (an idle orphan can even pin the TPU
# tunnel claim). The arming must NOT happen via preexec_fn — calling into
# ctypes between fork and exec in a threaded runner deadlocks
# intermittently on locks held by threads that don't exist in the child
# (observed ~1/3 of spawns under a jax-threaded parent). Instead a tiny
# exec shim (native/pdeathsig.c, built by native/build.sh) arms the
# signal in a fresh single-threaded process and execvp's the real
# command; python -m kungfu_tpu.runner.standby additionally arms itself
# in-process, covering standbys even without the shim.
_PDEATHSIG_SHIM = os.path.join(os.path.dirname(__file__), "kf-pdeathsig")
_warned_no_shim = False
_shim_broken = False  # set after the first exec failure: skip doomed retries


def _shim_argv(argv: List[str]) -> List[str]:
    if not _shim_broken and os.access(_PDEATHSIG_SHIM, os.X_OK):
        return [_PDEATHSIG_SHIM] + list(argv)
    global _warned_no_shim
    if not _warned_no_shim and os.name == "posix":
        _warned_no_shim = True
        log.warn(
            "kfrun: kf-pdeathsig shim not built (native/build.sh); workers "
            "will not be reaped if this runner is hard-killed"
        )
    return list(argv)


def _color(i: int, s: str) -> str:
    if not sys.stdout.isatty():
        return s
    return f"\x1b[{_COLORS[i % len(_COLORS)]}m{s}\x1b[0m"


class WorkerProc:
    def __init__(
        self,
        name: str,
        argv: List[str],
        env: Dict[str, str],
        rank: int = 0,
        logdir: Optional[str] = None,
        quiet: bool = False,
        cpus: Optional[List[int]] = None,
    ):
        self.name = name
        self.argv = argv
        self.env = env
        self.rank = rank
        self.logdir = logdir
        self.quiet = quiet
        self.cpus = cpus  # CPU affinity mask (runner/affinity.py plan)
        self.proc: Optional[subprocess.Popen] = None
        self._threads: List[threading.Thread] = []
        self._tail: "collections.deque[str]" = collections.deque(
            maxlen=OUTPUT_TAIL_LINES
        )
        self._tail_lock = threading.Lock()

    def start(self) -> None:
        full_env = dict(os.environ)
        full_env.update(self.env)
        # explicit runner pid for the shim/standby died-before-arm check
        full_env["KF_RUNNER_PID"] = str(os.getpid())
        argv = _shim_argv(self.argv)
        try:
            self.proc = subprocess.Popen(
                argv,
                env=full_env,
                stdout=subprocess.PIPE,
                stderr=subprocess.PIPE,
                text=True,
                bufsize=1,
            )
        except OSError as e:
            import errno as _errno

            if argv is self.argv or argv == list(self.argv):
                raise
            if e.errno not in (_errno.ENOEXEC, _errno.EACCES, _errno.ENOENT):
                # transient spawn failure (EMFILE/ENOMEM/EAGAIN): NOT the
                # shim's fault — surface it, don't latch protection off
                raise
            # the committed shim binary doesn't run on this platform/arch:
            # degrade to unprotected spawns — loudly, and only once
            global _shim_broken
            if not _shim_broken:
                _shim_broken = True
                log.warn(
                    "kfrun: kf-pdeathsig unusable (%s); spawning workers "
                    "WITHOUT orphan protection (rebuild via native/build.sh)",
                    e,
                )
            self.proc = subprocess.Popen(
                list(self.argv),
                env=full_env,
                stdout=subprocess.PIPE,
                stderr=subprocess.PIPE,
                text=True,
                bufsize=1,
            )
        if self.cpus:
            from kungfu_tpu.runner.affinity import apply_affinity

            if apply_affinity(self.proc.pid, self.cpus) and not self.quiet:
                log.info("[%s] pinned to cpus %s", self.name, self.cpus)
        logfile = None
        if self.logdir:
            os.makedirs(self.logdir, exist_ok=True)
            logfile = open(os.path.join(self.logdir, f"{self.name}.log"), "w")
        for stream, tag in ((self.proc.stdout, ""), (self.proc.stderr, "!")):
            t = threading.Thread(
                target=self._pump, args=(stream, tag, logfile), daemon=True
            )
            t.start()
            self._threads.append(t)

    def _pump(self, stream, tag: str, logfile) -> None:
        for line in stream:
            # prefix computed per line: a standby proc is renamed to its
            # worker identity on activation
            prefix = _color(self.rank, f"[{self.name}{tag}] ")
            with self._tail_lock:
                self._tail.append(f"[{tag or ' '}] {line.rstrip()}")
            if logfile:
                logfile.write(f"[{tag or ' '}] {line}")
                logfile.flush()
            if not self.quiet:
                sys.stdout.write(prefix + line)
                sys.stdout.flush()

    def output_tail(self) -> List[str]:
        """The worker's last ~200 stdout/stderr lines ('[ ]'/'[!]'
        prefixed), for postmortems."""
        with self._tail_lock:
            return list(self._tail)

    def wait(self, timeout: Optional[float] = None) -> int:
        rc = self.proc.wait(timeout)
        for t in self._threads:
            t.join(1)
        return rc

    def kill(self) -> None:
        if self.proc and self.proc.poll() is None:
            self.proc.terminate()
            try:
                self.proc.wait(5)
            except subprocess.TimeoutExpired:
                self.proc.kill()
                try:
                    # reap, so returncode reads -SIGKILL instead of a
                    # stale None in the postmortem that follows
                    self.proc.wait(5)
                except subprocess.TimeoutExpired:
                    pass

    @property
    def running(self) -> bool:
        return self.proc is not None and self.proc.poll() is None


def run_all(procs: List[WorkerProc]) -> List[int]:
    """Start all procs and wait; on first failure kill the rest (parity:
    local.RunAll semantics)."""
    for p in procs:
        p.start()
    codes = [None] * len(procs)
    try:
        for i, p in enumerate(procs):
            # kfcheck: disable=KF301 — a training worker legitimately
            # runs unboundedly; KeyboardInterrupt kills the batch below
            codes[i] = p.wait()
    except KeyboardInterrupt:
        for p in procs:
            p.kill()
        raise
    if any(c != 0 for c in codes):
        for p in procs:
            p.kill()
    return [c if c is not None else -1 for c in codes]
