"""Worker environment contract.

Capability parity: srcs/go/kungfu/env/envs.go:4-20 + config.go:53-140 —
the runner passes cluster topology to workers via env vars; a worker
started without them becomes a single-process cluster of itself.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Optional

from kungfu_tpu.base.strategy import DEFAULT_STRATEGY, Strategy
from kungfu_tpu.plan.peer import PeerID, PeerList

SELF_SPEC = "KF_SELF_SPEC"
INIT_PEERS = "KF_INIT_PEERS"
INIT_RUNNERS = "KF_INIT_RUNNERS"
PARENT_ID = "KF_PARENT_ID"
INIT_CLUSTER_VERSION = "KF_INIT_CLUSTER_VERSION"
ALLREDUCE_STRATEGY = "KF_ALLREDUCE_STRATEGY"
CONFIG_SERVER = "KF_CONFIG_SERVER"
ELASTIC_MODE = "KF_ELASTIC_MODE"
INIT_PROGRESS = "KF_INIT_PROGRESS"
DEVICE_SLOTS = "KF_DEVICE_SLOTS"
# tuning (parity: config/config.go:24-67)
ENABLE_MONITORING = "KF_CONFIG_ENABLE_MONITORING"
ENABLE_STALL_DETECTION = "KF_CONFIG_ENABLE_STALL_DETECTION"
LOG_LEVEL = "KF_CONFIG_LOG_LEVEL"

ALL_ENV_NAMES = [
    SELF_SPEC, INIT_PEERS, INIT_RUNNERS, PARENT_ID, INIT_CLUSTER_VERSION,
    ALLREDUCE_STRATEGY, CONFIG_SERVER, ELASTIC_MODE, INIT_PROGRESS,
    DEVICE_SLOTS, ENABLE_MONITORING, ENABLE_STALL_DETECTION, LOG_LEVEL,
]


@dataclasses.dataclass
class WorkerConfig:
    self_id: PeerID
    peers: PeerList
    runners: PeerList
    parent: Optional[PeerID]
    cluster_version: int
    strategy: Strategy
    config_server: str
    elastic_mode: str  # "" (delta) | "reload"
    init_progress: int
    single_process: bool = False
    # chip ids this worker may open (empty = unrestricted); parity:
    # job/gpu_resource.go slot assignment via CUDA_VISIBLE_DEVICES
    device_slots: tuple = ()


def parse_config_from_env(environ=None) -> WorkerConfig:
    env = environ if environ is not None else os.environ
    self_spec = env.get(SELF_SPEC, "")
    if not self_spec:
        # single-process fallback (parity: config.go:131-140)
        me = PeerID("127.0.0.1", 10000)
        return WorkerConfig(
            self_id=me,
            peers=PeerList([me]),
            runners=PeerList(),
            parent=None,
            cluster_version=0,
            strategy=DEFAULT_STRATEGY,
            config_server=env.get(CONFIG_SERVER, ""),
            elastic_mode=env.get(ELASTIC_MODE, ""),
            init_progress=int(env.get(INIT_PROGRESS, "0") or 0),
            single_process=True,
        )
    slots_raw = env.get(DEVICE_SLOTS, "")
    return WorkerConfig(
        self_id=PeerID.parse(self_spec),
        peers=PeerList.parse(env.get(INIT_PEERS, self_spec)),
        runners=PeerList.parse(env.get(INIT_RUNNERS, "")),
        parent=PeerID.parse(env[PARENT_ID]) if env.get(PARENT_ID) else None,
        cluster_version=int(env.get(INIT_CLUSTER_VERSION, "0") or 0),
        strategy=Strategy.parse(env.get(ALLREDUCE_STRATEGY, DEFAULT_STRATEGY.name)),
        config_server=env.get(CONFIG_SERVER, ""),
        elastic_mode=env.get(ELASTIC_MODE, ""),
        init_progress=int(env.get(INIT_PROGRESS, "0") or 0),
        device_slots=tuple(int(s) for s in slots_raw.split(",") if s.strip()),
    )


def worker_env(
    self_id: PeerID,
    peers: PeerList,
    runners: PeerList,
    parent: Optional[PeerID],
    cluster_version: int = 0,
    strategy: Strategy = DEFAULT_STRATEGY,
    config_server: str = "",
    elastic_mode: str = "",
    init_progress: int = 0,
    device_slots=None,
) -> dict:
    """Env block a runner sets for a spawned worker (parity: job.go:35-80)."""
    env = {
        SELF_SPEC: str(self_id),
        INIT_PEERS: ",".join(str(p) for p in peers),
        INIT_RUNNERS: ",".join(str(r) for r in runners),
        PARENT_ID: str(parent) if parent is not None else "",
        INIT_CLUSTER_VERSION: str(cluster_version),
        ALLREDUCE_STRATEGY: strategy.name,
        INIT_PROGRESS: str(init_progress),
    }
    if config_server:
        env[CONFIG_SERVER] = config_server
    if elastic_mode:
        env[ELASTIC_MODE] = elastic_mode
    if device_slots:
        ids = ",".join(str(i) for i in device_slots)
        env[DEVICE_SLOTS] = ids
        # the TPU analog of CUDA_VISIBLE_DEVICES (job.go:35-80): libtpu
        # initializes only these chips in each worker process
        env["TPU_VISIBLE_DEVICES"] = ids
    return env
