"""kfrun — the per-host launcher CLI.

Capability parity: srcs/go/cmd/kungfu-run (app/kungfu-run.go:18-117) +
runner/flags.go:30-145:
  kfrun -np 4 python3 train.py              # simple run, localhost
  kfrun -np 4 -H h1:2,h2:2 ...              # multi-host plan (this host's
                                            # workers only; start kfrun per host)
  kfrun -w -config-server URL ...           # elastic watch mode
  kfrun -np 4 -auto-recover 10s ...         # failure auto-recovery
  kfrun -builtin-config-port 9100 ...       # embedded config server
"""

from __future__ import annotations

import argparse
import os
import signal
import socket
import sys
import time
from typing import List, Optional

from kungfu_tpu.base.strategy import DEFAULT_STRATEGY, Strategy
from kungfu_tpu.plan.cluster import Cluster
from kungfu_tpu.plan.hostspec import HostList, parse_hostfile
from kungfu_tpu.plan.peer import PeerID, PeerList
from kungfu_tpu.runner import env as kfenv
from kungfu_tpu.runner.proc import WorkerProc, run_all

DEFAULT_RUNNER_PORT = 38080


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        "kfrun", description="TPU-native KungFu launcher", allow_abbrev=False
    )
    p.add_argument("-np", type=int, default=1, help="number of workers")
    p.add_argument("-H", dest="hosts", default="", help="host list ip:slots[:pub],...")
    p.add_argument("-hostfile", default="", help="hostfile path")
    p.add_argument("-self", dest="self_host", default="", help="this host's address")
    p.add_argument("-platform", default="",
                   help="self-discover hosts: tpu-vm | gce | auto "
                        "(parity: platforms/modelarts)")
    p.add_argument("-strategy", default="AUTO", help=f"one of {[s.name for s in Strategy]}")
    p.add_argument("-port-range", default="38000-38999")
    p.add_argument("-runner-port", type=int, default=DEFAULT_RUNNER_PORT)
    p.add_argument("-w", "--watch", action="store_true", help="elastic watch mode")
    p.add_argument("-config-server", default="", help="config server URL")
    p.add_argument("-builtin-config-port", type=int, default=-1,
                   help="embed a config server on this port (0 = ephemeral)")
    p.add_argument("-elastic-mode", default="", choices=["", "reload"])
    p.add_argument("-auto-recover", default="", help="e.g. 10s: heartbeat auto-recovery")
    p.add_argument("-monitor-port", type=int, default=7756,
                   help="heartbeat monitor port (0 = ephemeral)")
    p.add_argument("-monitor-peers", default="",
                   help="all runners' monitor host:port list (default: "
                        "every runner host on -monitor-port)")
    p.add_argument("-warm-spares", type=int, default=1,
                   help="standby workers kept warm per runner in -w mode "
                        "(0 disables); activation replaces cold joiner "
                        "spawn+import during an elastic grow")
    p.add_argument("-standby-preload", default="auto",
                   help="comma-separated modules standbys pre-import; "
                        "'auto' (default) pre-imports the device stack "
                        "(jax) since this framework's agents are jax-"
                        "based; 'none' disables")
    p.add_argument("-use-affinity", action="store_true",
                   help="pin each local worker to a disjoint, NUMA-aligned "
                        "CPU slice (parity: KUNGFU_USE_AFFINITY)")
    p.add_argument("-devices-per-host", type=int, default=0,
                   help="partition this many chip ids among local workers "
                        "(TPU_VISIBLE_DEVICES pinning; 0 = no pinning)")
    p.add_argument("-debug-port", type=int, default=-1,
                   help="HTTP endpoint: Stage dumps + /cluster/{metrics,"
                        "trace,health,links} telemetry (0 = ephemeral)")
    p.add_argument("-logdir", default="")
    p.add_argument("-q", "--quiet", action="store_true")
    p.add_argument("-delay", type=float, default=0.0)
    p.add_argument("-timeout", type=float, default=0.0, help="kill workers after this many seconds")
    p.add_argument("cmd", nargs=argparse.REMAINDER, help="worker command")
    return p


def infer_self_host(hosts: HostList) -> str:
    """Pick this host's address from the host list (parity:
    runner.InferSelfIPv4; hostname/IP matching instead of NIC scanning)."""
    candidates = {h.host for h in hosts}
    if "127.0.0.1" in candidates or "localhost" in candidates:
        return "127.0.0.1" if "127.0.0.1" in candidates else "localhost"
    names = {socket.gethostname(), socket.getfqdn()}
    try:
        names.add(socket.gethostbyname(socket.gethostname()))
    except OSError:
        pass
    for h in hosts:
        if h.host in names:
            return h.host
    raise SystemExit(f"cannot find self among hosts {sorted(candidates)}; use -self")


def parse_port_range(s: str):
    a, _, b = s.partition("-")
    return (int(a), int(b or a))


def main(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    cmd = args.cmd
    if cmd and cmd[0] == "--":
        cmd = cmd[1:]
    if not cmd:
        print("kfrun: no worker command given", file=sys.stderr)
        return 2

    try:
        if args.platform:
            from kungfu_tpu.runner.platform import detect

            pc = detect(args.platform)
            if pc is None:
                print(f"kfrun: platform {args.platform!r} not detected", file=sys.stderr)
                return 2
            import dataclasses as _dc

            slots = max(1, -(-args.np // len(pc.hosts)))  # spread np over hosts
            hosts = HostList(_dc.replace(h, slots=slots) for h in pc.hosts)
            if not args.self_host:
                args.self_host = pc.self_host
        elif args.hostfile:
            with open(args.hostfile) as f:
                hosts = parse_hostfile(f.read())
        elif args.hosts:
            hosts = HostList.parse(args.hosts)
        else:
            hosts = HostList.parse(f"127.0.0.1:{args.np}")

        port_range = parse_port_range(args.port_range)
        workers = hosts.gen_peer_list(args.np, port_range)
        runners = hosts.gen_runner_list(args.runner_port)
        cluster = Cluster(runners=runners, workers=workers)
        cluster.validate()
        self_host = args.self_host or infer_self_host(hosts)
        strategy = Strategy.parse(args.strategy)
        # device-slot share is sized by host CAPACITY, stable across resizes
        args.host_capacity = next(
            (h.slots for h in hosts if h.host == self_host), 1
        )
        if 0 < args.devices_per_host < args.host_capacity:
            # at full capacity every local worker needs >= 1 chip, or a
            # later elastic grow would exhaust the watcher's slot pool
            raise ValueError(
                f"-devices-per-host {args.devices_per_host} < host capacity "
                f"{args.host_capacity}: not every worker could get a chip"
            )
    except (ValueError, OSError) as e:
        print(f"kfrun: {e}", file=sys.stderr)
        return 2

    # flight-recorder run dir (ISSUE 3): minted once per run and
    # inherited by every worker via the environment, so all the peer
    # journals and the runner's postmortems land under one directory.
    # An operator-set KF_TELEMETRY_DIR wins; the default base is pruned
    # so unattended loops don't grow /tmp forever.
    from kungfu_tpu.telemetry import flight

    from kungfu_tpu import knobs

    if not knobs.raw(flight.DIR_ENV):
        flight.prune_runs()
        os.environ[flight.DIR_ENV] = flight.default_run_dir()

    config_server_url = args.config_server
    builtin_server = None
    if args.builtin_config_port >= 0:
        from kungfu_tpu.elastic.configserver import ConfigServer

        builtin_server = ConfigServer(args.builtin_config_port, cluster)
        builtin_server.start()
        config_server_url = f"http://{self_host}:{builtin_server.port}/config"

    if args.delay:
        time.sleep(args.delay)

    if args.debug_port >= 0 and not args.watch:
        print(
            "kfrun: -debug-port (Stage dumps + /cluster telemetry) needs "
            "watch mode (-w); ignoring",
            file=sys.stderr,
        )

    try:
        if args.auto_recover and not args.watch:
            from kungfu_tpu.runner.monitored import monitored_run

            return monitored_run(args, cmd, cluster, self_host, strategy)
        if args.watch:
            from kungfu_tpu.runner.watch import watch_run

            return watch_run(args, cmd, cluster, self_host, strategy, config_server_url)
        return simple_run(args, cmd, cluster, self_host, strategy, config_server_url)
    finally:
        if builtin_server:
            builtin_server.stop()


def make_one_worker_proc(
    args, cmd, cluster: Cluster, worker: PeerID, self_host: str,
    strategy: Strategy, config_server_url: str = "", version: int = 0,
    progress: int = 0, device_slots=None,
) -> WorkerProc:
    rank = cluster.workers.rank(worker)
    env = kfenv.worker_env(
        self_id=worker,
        peers=cluster.workers,
        runners=cluster.runners,
        parent=PeerID(self_host, args.runner_port),
        cluster_version=version,
        strategy=strategy,
        config_server=config_server_url,
        elastic_mode=args.elastic_mode,
        init_progress=progress,
        device_slots=device_slots,
    )
    env["KF_LOG_PREFIX"] = f"{rank}/{len(cluster.workers)}"
    env["KF_SPAWN_TS"] = str(time.time())
    return WorkerProc(
        name=f"{rank}/{len(cluster.workers)}",
        argv=list(cmd),
        env=env,
        rank=rank,
        logdir=args.logdir,
        quiet=args.quiet,
    )


def make_worker_procs(
    args, cmd, cluster: Cluster, self_host: str, strategy: Strategy,
    config_server_url: str = "", version: int = 0, progress: int = 0,
) -> List[WorkerProc]:
    local = [w for w in cluster.workers if w.host == self_host]
    slot_parts: List[Optional[list]] = [None] * len(local)
    n_dev = getattr(args, "devices_per_host", 0)
    if n_dev > 0 and local:
        from kungfu_tpu.runner.slots import partition

        if len(local) > n_dev:
            raise SystemExit(
                f"kfrun: {len(local)} local workers but only {n_dev} device slots"
            )
        # static membership (simple/monitored runs): rank-major stripes
        slot_parts = partition(n_dev, len(local))
    cpu_parts: List[Optional[list]] = [None] * len(local)
    if getattr(args, "use_affinity", False) and local:
        from kungfu_tpu.runner.affinity import plan_affinity

        cpu_parts = plan_affinity(len(local))
    procs = [
        make_one_worker_proc(
            args, cmd, cluster, w, self_host, strategy, config_server_url,
            version, progress, device_slots=slot_parts[i],
        )
        for i, w in enumerate(local)
    ]
    for p, cpus in zip(procs, cpu_parts):
        p.cpus = cpus
    return procs


def simple_run(args, cmd, cluster, self_host, strategy, config_server_url="") -> int:
    procs = make_worker_procs(args, cmd, cluster, self_host, strategy, config_server_url)
    if args.timeout:
        def on_alarm(sig, frame):
            for p in procs:
                p.kill()
        signal.signal(signal.SIGALRM, on_alarm)
        signal.alarm(int(args.timeout))
    codes = run_all(procs)
    bad = [c for c in codes if c != 0]
    if bad:
        print(f"kfrun: {len(bad)}/{len(codes)} workers failed", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
