"""kf-distribute: one-command multi-host launch over SSH.

Capability parity: srcs/go/cmd/kungfu-distribute/kungfu-distribute.go +
srcs/go/utils/ssh/ssh.go (and kungfu-rrun) — start a command on every host
of a hostfile from one terminal, stream back per-host prefixed logs,
propagate exit codes, and tear everything down on Ctrl-C.

The command may contain ``{host}`` / ``{index}`` placeholders substituted
per host — the usual pattern launches one kfrun per machine:

    python -m kungfu_tpu.runner.distribute -H 10.0.0.1:4,10.0.0.2:4 -- \
        python -m kungfu_tpu.runner.cli -np 8 -H 10.0.0.1:4,10.0.0.2:4 \
        -self {host} python train.py

``-ssh`` overrides the transport program (default ``ssh`` with batch-mode
options); tests substitute a local shim, the reference's approach to
exercising the fan-out without a real fleet.
"""

from __future__ import annotations

import argparse
import shlex
import signal
import subprocess
import sys
import threading
from typing import List, Optional

from kungfu_tpu.plan.hostspec import HostList, parse_hostfile
from kungfu_tpu.telemetry import log

DEFAULT_SSH = "ssh -o StrictHostKeyChecking=no -o BatchMode=yes"

_COLORS = [31, 32, 33, 34, 35, 36, 91, 92, 93, 94, 95, 96]


def _color(i: int, s: str) -> str:
    if not sys.stdout.isatty():
        return s
    return f"\x1b[{_COLORS[i % len(_COLORS)]}m{s}\x1b[0m"


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        "kf-distribute", description="run a command on every host over SSH",
        allow_abbrev=False,
    )
    p.add_argument("-H", dest="hosts", default="", help="host list ip:slots,...")
    p.add_argument("-hostfile", default="", help="hostfile path")
    p.add_argument("-ssh", default=DEFAULT_SSH,
                   help="transport program prefix (argv prefix before host)")
    p.add_argument("-timeout", type=float, default=0.0,
                   help="kill the fan-out after this many seconds")
    p.add_argument("-q", "--quiet", action="store_true")
    p.add_argument("cmd", nargs=argparse.REMAINDER, help="command template")
    return p


class HostProc:
    """One ssh child streaming prefixed logs (parity: iostream coloring in
    utils/runner/remote)."""

    def __init__(self, index: int, host: str, argv: List[str], quiet: bool):
        self.index = index
        self.host = host
        self.argv = argv
        self.quiet = quiet
        self.proc: Optional[subprocess.Popen] = None

    def start(self) -> None:
        self.proc = subprocess.Popen(
            self.argv,
            stdout=subprocess.PIPE,
            stderr=subprocess.PIPE,
            stdin=subprocess.DEVNULL,
            text=True,
            bufsize=1,
        )
        for stream, tag in ((self.proc.stdout, ""), (self.proc.stderr, "!")):
            threading.Thread(
                target=self._pump, args=(stream, tag), daemon=True
            ).start()

    def _pump(self, stream, tag: str) -> None:
        prefix = _color(self.index, f"[{self.host}{tag}] ")
        for line in stream:
            if not self.quiet:
                sys.stdout.write(prefix + line)
                sys.stdout.flush()

    def wait(self, timeout: Optional[float] = None) -> int:
        return self.proc.wait(timeout)

    def kill(self) -> None:
        if self.proc and self.proc.poll() is None:
            self.proc.terminate()
            try:
                self.proc.wait(5)
            except subprocess.TimeoutExpired:
                self.proc.kill()


def host_argv(ssh: str, host: str, index: int, cmd: List[str]) -> List[str]:
    """ssh argv for one host: transport prefix + host + quoted command with
    {host}/{index} substituted."""
    filled = [
        c.replace("{host}", host).replace("{index}", str(index)) for c in cmd
    ]
    return shlex.split(ssh) + [host, " ".join(shlex.quote(c) for c in filled)]


def main(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    cmd = args.cmd
    if cmd and cmd[0] == "--":
        cmd = cmd[1:]
    if not cmd:
        log.error("kf-distribute: no command given")
        return 2
    try:
        if args.hostfile:
            with open(args.hostfile) as f:
                hosts = parse_hostfile(f.read())
        elif args.hosts:
            hosts = HostList.parse(args.hosts)
        else:
            raise ValueError("one of -H / -hostfile is required")
    except (ValueError, OSError) as e:
        log.error("kf-distribute: %s", e)
        return 2

    procs = [
        HostProc(i, h.host, host_argv(args.ssh, h.host, i, cmd), args.quiet)
        for i, h in enumerate(hosts)
    ]

    stop = threading.Event()

    def teardown(sig=None, frame=None):
        if not stop.is_set():
            stop.set()
            live = [p for p in procs if p.proc and p.proc.poll() is None]
            if live:
                log.warn("kf-distribute: tearing down %d hosts", len(live))
            for p in live:
                p.kill()

    old_int = signal.signal(signal.SIGINT, teardown)
    old_term = signal.signal(signal.SIGTERM, teardown)
    if args.timeout:
        signal.signal(signal.SIGALRM, teardown)
        # setitimer keeps sub-second precision; int() would turn a
        # timeout < 1s into alarm(0), silently disabling it
        signal.setitimer(signal.ITIMER_REAL, float(args.timeout))
    try:
        for p in procs:
            p.start()
        codes = []
        for p in procs:
            try:
                # kfcheck: disable=KF301 — waiting for the remote worker
                # to finish IS the job; SIGTERM/SIGALRM teardown() and
                # KeyboardInterrupt bound it from outside
                codes.append(p.wait())
            except KeyboardInterrupt:
                teardown()
                return 130
        bad = [(p.host, c) for p, c in zip(procs, codes) if c != 0]
        if bad:
            log.error("kf-distribute: failed on %s", bad)
            return 1
        return 0
    finally:
        teardown()
        signal.signal(signal.SIGINT, old_int)
        signal.signal(signal.SIGTERM, old_term)


if __name__ == "__main__":
    sys.exit(main())
