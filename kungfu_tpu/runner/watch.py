"""Elastic watcher: runner-side supervisor for membership changes.

Capability parity: srcs/go/kungfu/runner/watch.go:24-171 + handler.go —
the runner hosts a control endpoint; workers send Stage{Version, Progress,
Cluster} updates during a resize. The watcher diffs the local worker set:
waits removed procs, spawns added ones (delta mode), or restarts everything
from the carried progress (reload mode). Duplicate versions are deduped;
inconsistent duplicates abort (handler.go:90-103 safety check).
"""

from __future__ import annotations

import collections
import json
import queue
import sys
import threading
import time
from typing import Dict, List, Optional

from kungfu_tpu.plan.cluster import Cluster
from kungfu_tpu.plan.peer import PeerID, PeerList
from kungfu_tpu.runner.proc import WorkerProc
from kungfu_tpu.transport.message import ConnType, Message
from kungfu_tpu.transport.server import Server


class Stage:
    def __init__(self, version: int, progress: int, cluster: Cluster, reload: bool = False):
        self.version = version
        self.progress = progress
        self.cluster = cluster
        self.reload = reload

    @classmethod
    def from_json(cls, obj: dict) -> "Stage":
        return cls(
            version=int(obj["Version"]),
            progress=int(obj.get("Progress", 0)),
            cluster=Cluster.from_json(obj["Cluster"]),
            reload=bool(obj.get("Reload", False)),
        )

    def digest(self) -> bytes:
        return self.cluster.digest() + str(self.version).encode()


class DebugServer:
    """HTTP endpoint dumping the Stages this runner has seen (parity:
    -debug-port, runner/handler.go:118-124)."""

    def __init__(self, watcher: "Watcher", port: int):
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def do_GET(inner):
                body = json.dumps(watcher.debug_dump(), indent=2).encode()
                inner.send_response(200)
                inner.send_header("Content-Type", "application/json")
                inner.send_header("Content-Length", str(len(body)))
                inner.end_headers()
                inner.wfile.write(body)

        self.httpd = ThreadingHTTPServer(("0.0.0.0", port), Handler)
        self.port = self.httpd.server_address[1]

    def start(self):
        threading.Thread(target=self.httpd.serve_forever, daemon=True).start()

    def stop(self):
        self.httpd.shutdown()


class Watcher:
    def __init__(self, args, cmd, self_host: str, strategy, config_server_url: str):
        self.args = args
        self.cmd = cmd
        self.self_host = self_host
        self.strategy = strategy
        self.config_server_url = config_server_url
        self.stage_q: "queue.Queue[Stage]" = queue.Queue()
        self.current: Dict[PeerID, WorkerProc] = {}
        self.seen_versions: Dict[int, bytes] = {}
        # [-debug-port] one entry per Stage; bounded so a long elastic run
        # without a debug reader doesn't grow a buffer forever
        self.stage_log: "deque" = collections.deque(maxlen=512)
        self.done = threading.Event()
        self.exit_code = 0
        self._gone: List[WorkerProc] = []
        # guards current/stage_log: mutated on the watcher + control
        # threads, read by -debug-port HTTP handler threads
        self._state_lock = threading.Lock()
        # device-slot pool (parity: job/gpu_resource.go): joiners draw from
        # it, leavers return to it, so workers sharing this host never open
        # the same chips across resizes. Share size is fixed by the host's
        # slot CAPACITY (not current np) so surviving workers — whose env
        # cannot change — keep valid stripes as the cluster grows.
        self.slot_pool = None
        self.chips_per_worker = 0
        self._worker_slots: Dict[PeerID, list] = {}
        n_dev = getattr(args, "devices_per_host", 0)
        if n_dev > 0:
            from kungfu_tpu.runner.slots import SlotPool

            cap = max(1, getattr(args, "host_capacity", 0))
            self.chips_per_worker = max(1, n_dev // cap)
            self.slot_pool = SlotPool.of_size(n_dev)

    def debug_dump(self) -> dict:
        # runs on HTTP handler threads: snapshot under the state lock so a
        # concurrent apply_delta/record_stage can't mutate mid-iteration
        with self._state_lock:
            workers = dict(self.current)
            stages = list(self.stage_log)
        return {
            "self": self.self_host,
            "stages": stages,
            "workers": {
                str(w): ("running" if p.running else f"exit:{p.proc.returncode}")
                for w, p in workers.items()
            },
        }

    def record_stage(self, stage: Stage) -> None:
        entry = {
            "version": stage.version,
            "progress": stage.progress,
            "reload": stage.reload,
            "workers": [str(w) for w in stage.cluster.workers],
            "digest": stage.digest().hex(),
        }
        with self._state_lock:
            self.stage_log.append(entry)

    # -- control endpoint ----------------------------------------------
    def handle_control(self, src: PeerID, msg: Message) -> None:
        if msg.name == "exit":
            self.done.set()
            return
        if msg.name != "update":
            return
        stage = Stage.from_json(json.loads(msg.data.decode()))
        digest = stage.digest()
        if stage.version in self.seen_versions:
            if self.seen_versions[stage.version] != digest:
                # diverged proposals for the same version: unrecoverable
                print(
                    f"kfrun: inconsistent cluster for version {stage.version}; aborting",
                    file=sys.stderr,
                )
                self.exit_code = 1
                self.done.set()
            return
        self.seen_versions[stage.version] = digest
        self.record_stage(stage)
        self.stage_q.put(stage)

    # -- proc management -----------------------------------------------
    def _spawn(self, w: PeerID, stage: Stage) -> None:
        from kungfu_tpu.runner.cli import make_one_worker_proc

        slots = None
        if self.slot_pool is not None:
            try:
                slots = self.slot_pool.get(self.chips_per_worker)
                self._worker_slots[w] = slots
            except RuntimeError as e:
                # a growing host exceeding its chip budget must not crash
                # the runner mid-resize: spawn unpinned and say so (the
                # upfront cli check makes this unreachable for valid plans)
                print(f"kfrun: {e}; spawning {w} unpinned", file=sys.stderr)
                slots = None
        p = make_one_worker_proc(
            self.args, self.cmd, stage.cluster, w, self.self_host, self.strategy,
            self.config_server_url, version=stage.version, progress=stage.progress,
            device_slots=slots,
        )
        p.start()
        with self._state_lock:
            self.current[w] = p

    def _release_slots(self, w: PeerID) -> None:
        if self.slot_pool is not None and w in self._worker_slots:
            self.slot_pool.put(self._worker_slots.pop(w))

    def apply_delta(self, stage: Stage) -> None:
        new_local = {w for w in stage.cluster.workers if w.host == self.self_host}
        with self._state_lock:
            old_local = set(self.current)
        for w in old_local - new_local:
            with self._state_lock:
                proc = self.current.pop(w)
            self._gone.append(proc)  # worker exits itself on detach
            self._release_slots(w)
        for w in sorted(new_local - old_local):
            self._spawn(w, stage)

    def apply_full(self, stage: Stage) -> None:
        """Reload mode: stop everything, restart from stage.progress."""
        with self._state_lock:
            doomed = list(self.current.items())
            self.current.clear()
        for w, proc in doomed:
            proc.kill()
            self._release_slots(w)
        for w in stage.cluster.workers:
            if w.host == self.self_host:
                self._spawn(w, stage)

    def run(self, initial: Stage) -> int:
        server = Server(PeerID(self.self_host, self.args.runner_port), use_unix=False)
        server.register(ConnType.CONTROL, self.handle_control)
        server.start()
        debug = None
        if getattr(self.args, "debug_port", -1) >= 0:
            debug = DebugServer(self, self.args.debug_port)
            debug.start()
            print(f"kfrun: debug endpoint on :{debug.port}", file=sys.stderr)
        idle_since: Optional[float] = None
        try:
            self.apply_delta(initial)
            while not self.done.is_set():
                try:
                    stage = self.stage_q.get(timeout=0.5)
                except queue.Empty:
                    # Exit when all local workers have finished. In reload
                    # mode only, wait out a drain grace first: workers
                    # notify the runner and exit immediately, so the final
                    # Stage can still be in flight when the last proc dies —
                    # concluding too early drops the reload and strands the
                    # cluster. Delta-mode exits stay prompt.
                    grace = 2.0 if self.args.elastic_mode == "reload" else 0.0
                    if self.current and all(not p.running for p in self.current.values()):
                        if idle_since is None:
                            idle_since = time.monotonic()
                        if time.monotonic() - idle_since >= grace:
                            codes = [p.proc.returncode for p in self.current.values()]
                            self.exit_code = 0 if all(c == 0 for c in codes) else 1
                            break
                    else:
                        idle_since = None
                    # reap detached workers
                    self._gone = [p for p in self._gone if p.running]
                    continue
                idle_since = None
                if stage.reload:
                    self.apply_full(stage)
                else:
                    self.apply_delta(stage)
            return self.exit_code
        finally:
            for p in self.current.values():
                p.kill()
            for p in self._gone:
                p.kill()
            server.stop()
            if debug is not None:
                debug.stop()


def watch_run(args, cmd, cluster: Cluster, self_host: str, strategy, config_server_url: str) -> int:
    watcher = Watcher(args, cmd, self_host, strategy, config_server_url)
    initial = Stage(version=0, progress=0, cluster=cluster)
    watcher.seen_versions[0] = initial.digest()
    watcher.record_stage(initial)
    return watcher.run(initial)
