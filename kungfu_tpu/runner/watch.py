"""Elastic watcher: runner-side supervisor for membership changes.

Capability parity: srcs/go/kungfu/runner/watch.go:24-171 + handler.go —
the runner hosts a control endpoint; workers send Stage{Version, Progress,
Cluster} updates during a resize. The watcher diffs the local worker set:
waits removed procs, spawns added ones (delta mode), or restarts everything
from the carried progress (reload mode). Duplicate versions are deduped;
inconsistent duplicates abort (handler.go:90-103 safety check).
"""

from __future__ import annotations

import collections
import json
import os
import queue
import subprocess
import threading
import time
from typing import Dict, List, Optional

from kungfu_tpu.plan.cluster import Cluster
from kungfu_tpu.telemetry import audit, log
from kungfu_tpu.plan.peer import PeerID, PeerList
from kungfu_tpu.runner.proc import WorkerProc
from kungfu_tpu.transport.message import ConnType, Message
from kungfu_tpu.transport.server import Server


class Stage:
    def __init__(self, version: int, progress: int, cluster: Cluster, reload: bool = False):
        self.version = version
        self.progress = progress
        self.cluster = cluster
        self.reload = reload

    @classmethod
    def from_json(cls, obj: dict) -> "Stage":
        return cls(
            version=int(obj["Version"]),
            progress=int(obj.get("Progress", 0)),
            cluster=Cluster.from_json(obj["Cluster"]),
            reload=bool(obj.get("Reload", False)),
        )

    def digest(self) -> bytes:
        return self.cluster.digest() + str(self.version).encode()


class DebugServer:
    """HTTP endpoint on the runner: Stage dumps (parity: -debug-port,
    runner/handler.go:118-124) plus the cluster observability plane
    (ISSUE 2) when the watcher carries a TelemetryAggregator:

    - ``/cluster/metrics`` federated Prometheus exposition (peer labels)
    - ``/cluster/trace``   cross-peer merged Chrome trace
    - ``/cluster/health``  per-peer step rate / straggler JSON
    - ``/cluster/links``   k×k link matrix (per-edge bandwidth/latency)
    - ``/cluster/steps``   merged per-step critical-path records
    - ``/cluster/decisions`` merged adaptation-decision ledger
    - ``/cluster/resources`` merged per-thread CPU attribution view
    - ``/cluster/memory``  merged per-subsystem byte attribution view
    - anything else        the Stage/worker debug dump (old contract)
    """

    def __init__(self, watcher: "Watcher", port: int):
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        from kungfu_tpu.telemetry.cluster import CLUSTER_ROUTES

        # dispatch built from CLUSTER_ROUTES (ISSUE 18 satellite): the
        # aggregator, this server and the endpoint-doc lint (KF606)
        # share one route registry, so adding an aggregator view can't
        # silently miss the server or the docs. /cluster/metrics is the
        # text/plain exception; trace/audit serve compact JSON (multi-MB
        # documents an indent would double).
        renderers = {
            "/cluster/metrics": lambda agg: (
                agg.cluster_metrics(), "text/plain; version=0.0.4"
            ),
            "/cluster/trace": lambda agg: (
                json.dumps(agg.cluster_trace()), "application/json"
            ),
            "/cluster/audit": lambda agg: (
                json.dumps(agg.cluster_audit()), "application/json"
            ),
        }
        for route in CLUSTER_ROUTES:
            if route in renderers:
                continue
            method = "cluster_" + route.rsplit("/", 1)[1]
            renderers[route] = lambda agg, m=method: (
                json.dumps(getattr(agg, m)(), indent=2),
                "application/json",
            )

        def cluster_view(path: str):
            agg = getattr(watcher, "aggregator", None)
            if agg is None:
                return None
            render = renderers.get(path)
            return None if render is None else render(agg)

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def do_GET(inner):
                from urllib.parse import urlsplit

                # strip query/fragment before matching: a dashboard's
                # cache-buster (?t=...) must not demote /cluster/health
                # to the Stage dump
                path = urlsplit(inner.path).path.rstrip("/")
                try:
                    if path.startswith("/cluster"):
                        view = cluster_view(path)
                        if view is None and getattr(
                            watcher, "aggregator", None
                        ) is not None:
                            # unknown /cluster/* with a live plane: a
                            # typo deserves a 404, not the wrong document
                            inner.send_response(404)
                            inner.end_headers()
                            return
                    else:
                        view = None
                    if view is not None:
                        body_s, ctype = view
                    else:
                        body_s, ctype = (
                            json.dumps(watcher.debug_dump(), indent=2),
                            "application/json",
                        )
                except Exception as e:  # noqa: BLE001 - a broken view is a 500, not a crash
                    inner.send_response(500)
                    inner.end_headers()
                    inner.wfile.write(str(e).encode())
                    return
                body = body_s.encode()
                inner.send_response(200)
                inner.send_header("Content-Type", ctype)
                inner.send_header("Content-Length", str(len(body)))
                inner.end_headers()
                inner.wfile.write(body)

        self.httpd = ThreadingHTTPServer(("0.0.0.0", port), Handler)
        self.port = self.httpd.server_address[1]

    def start(self):
        threading.Thread(target=self.httpd.serve_forever, daemon=True).start()

    def stop(self):
        self.httpd.shutdown()


class Watcher:
    REFILL_DELAY = 3.0  # seconds after an activation before warming a spare

    def __init__(self, args, cmd, self_host: str, strategy, config_server_url: str):
        self.args = args
        self.cmd = cmd
        self.self_host = self_host
        self.strategy = strategy
        self.config_server_url = config_server_url
        self.stage_q: "queue.Queue[Stage]" = queue.Queue()
        self.current: Dict[PeerID, WorkerProc] = {}
        self.seen_versions: Dict[int, bytes] = {}
        # [-debug-port] one entry per Stage; bounded so a long elastic run
        # without a debug reader doesn't grow a buffer forever
        self.stage_log: "deque" = collections.deque(maxlen=512)
        self.done = threading.Event()
        self.exit_code = 0
        self._gone: List[WorkerProc] = []
        # guards current/stage_log: mutated on the watcher + control
        # threads, read by -debug-port HTTP handler threads
        self._state_lock = threading.Lock()
        # device-slot pool (parity: job/gpu_resource.go): joiners draw from
        # it, leavers return to it, so workers sharing this host never open
        # the same chips across resizes. Share size is fixed by the host's
        # slot CAPACITY (not current np) so surviving workers — whose env
        # cannot change — keep valid stripes as the cluster grows.
        self.slot_pool = None
        self.chips_per_worker = 0
        self._worker_slots: Dict[PeerID, list] = {}
        n_dev = getattr(args, "devices_per_host", 0)
        if n_dev > 0:
            from kungfu_tpu.runner.slots import SlotPool

            cap = max(1, getattr(args, "host_capacity", 0))
            self.chips_per_worker = max(1, n_dev // cap)
            self.slot_pool = SlotPool.of_size(n_dev)
        # warm spares: pre-imported standby processes that turn elastic-join
        # spawn+import (seconds of CPU) into a FIFO write
        self.standby_pool = None
        n_spares = getattr(args, "warm_spares", 0)
        if n_spares > 0:
            from kungfu_tpu.runner.standby import StandbyPool, resolve_preload

            self.standby_pool = StandbyPool(
                n_spares,
                logdir=getattr(args, "logdir", ""),
                quiet=getattr(args, "quiet", False),
                preload=resolve_preload(getattr(args, "standby_preload", "")),
            )
            self.standby_pool.refill()
        self._initial_done = False
        self._refill_at: Optional[float] = None
        # -w + -auto-recover composition: a worker that DIES (nonzero exit
        # without a Stage removing it) triggers a reload at a shrunk
        # cluster instead of stranding the survivors in blocked
        # collectives (parity goal: monitored.go generalized to elastic
        # membership — the preemptible-TPU-VM story)
        self.auto_recover = bool(getattr(args, "auto_recover", ""))
        self.failure_restarts = 0
        self.last_stage: Optional[Stage] = None
        # flight-recorder plane (ISSUE 3): the run dir every worker
        # journals under (kfrun cli minted it into the environment);
        # postmortems of dead workers are harvested from it. The seen
        # set keys on (peer, pid) so a respawned-then-dead-again peer
        # gets a fresh postmortem but one death is never double-counted.
        from kungfu_tpu import knobs

        self.telemetry_dir = knobs.raw("KF_TELEMETRY_DIR")
        self._postmortemed: set = set()
        # cluster observability plane (ISSUE 2): rides the -debug-port
        # endpoint; scrapes every worker's /metrics|/trace|/audit and
        # serves the merged /cluster/* views + straggler signals
        self.aggregator = None
        self.cluster_health_url = ""
        if getattr(args, "debug_port", -1) >= 0:
            from kungfu_tpu.telemetry.cluster import (
                TelemetryAggregator,
                set_aggregator,
            )

            self.aggregator = TelemetryAggregator()
            set_aggregator(self.aggregator)
        self.hb_state = None
        self.monitor = None
        self.grace = 0.0
        if self.auto_recover:
            # the monitored-mode heartbeat server, composed into the
            # elastic watcher: workers report begin/end/epoch so recovery
            # carries REAL progress and hung (not just dead) workers are
            # detected by the same grace rule
            from kungfu_tpu.runner.monitored import (
                HeartbeatState,
                MonitorServer,
                parse_duration,
            )

            self.hb_state = HeartbeatState()
            self.monitor = MonitorServer(self.hb_state, port=0)
            self.monitor.start()
            self.grace = parse_duration(args.auto_recover)

    def debug_dump(self) -> dict:
        # runs on HTTP handler threads: snapshot under the state lock so a
        # concurrent apply_delta/record_stage can't mutate mid-iteration
        with self._state_lock:
            workers = dict(self.current)
            stages = list(self.stage_log)
        return {
            "self": self.self_host,
            "stages": stages,
            "workers": {
                str(w): ("running" if p.running else f"exit:{p.proc.returncode}")
                for w, p in workers.items()
            },
        }

    def record_stage(self, stage: Stage) -> None:
        entry = {
            "version": stage.version,
            "progress": stage.progress,
            "reload": stage.reload,
            "workers": [str(w) for w in stage.cluster.workers],
            "digest": stage.digest().hex(),
        }
        with self._state_lock:
            self.stage_log.append(entry)

    # -- control endpoint ----------------------------------------------
    def handle_control(self, src: PeerID, msg: Message) -> None:
        if msg.name == "exit":
            self.done.set()
            return
        if msg.name != "update":
            return
        stage = Stage.from_json(json.loads(msg.data.decode()))
        digest = stage.digest()
        if stage.version in self.seen_versions:
            if self.seen_versions[stage.version] != digest:
                # diverged proposals for the same version: unrecoverable
                log.error(
                    "kfrun: inconsistent cluster for version %s; aborting",
                    stage.version,
                )
                self.exit_code = 1
                self.done.set()
            return
        self.seen_versions[stage.version] = digest
        self.record_stage(stage)
        self.stage_q.put(stage)

    # -- proc management -----------------------------------------------
    def _spawn(self, w: PeerID, stage: Stage) -> None:
        from kungfu_tpu.runner.cli import make_one_worker_proc

        _t_spawn0 = time.monotonic()
        slots = None
        if self.slot_pool is not None:
            try:
                slots = self.slot_pool.get(self.chips_per_worker)
                self._worker_slots[w] = slots
            except RuntimeError as e:
                # a growing host exceeding its chip budget must not crash
                # the runner mid-resize: spawn unpinned and say so (the
                # upfront cli check makes this unreachable for valid plans)
                log.warn("kfrun: %s; spawning %s unpinned", e, w)
                slots = None
        p = make_one_worker_proc(
            self.args, self.cmd, stage.cluster, w, self.self_host, self.strategy,
            self.config_server_url, version=stage.version, progress=stage.progress,
            device_slots=slots,
        )
        if self.monitor is not None:
            from kungfu_tpu.runner.monitored import MONITOR_ADDR_ENV

            p.env[MONITOR_ADDR_ENV] = f"{self.self_host}:{self.monitor.port}"
        if self.cluster_health_url:
            # workers poll this for the straggler/skew signals that feed
            # PolicyContext.metrics (monitor.cluster_health)
            from kungfu_tpu.telemetry.cluster import HEALTH_URL_ENV

            p.env[HEALTH_URL_ENV] = self.cluster_health_url
        # standbys serve post-initial joins only (at t0 a cold spawn is
        # concurrent with everything else anyway, and the just-spawned
        # standbys may not have opened their FIFOs yet)
        if self.standby_pool is not None and self._initial_done:
            # refill DEFERRED in every branch (success, dead slot, empty
            # pool): a replacement standby's imports would compete with
            # the joiner for CPU during the rebuild barrier — and a branch
            # without a refill would drain the pool permanently
            self._refill_at = time.monotonic() + self.REFILL_DELAY
            slot = self.standby_pool.take()
            if slot is not None:
                _t_act0 = time.monotonic()
                if slot.activate(p.env, p.argv, p.name, p.rank):
                    log.info(
                        "kfrun: warm standby activated as %s"
                        " (prep %.1f ms, activate %.1f ms)",
                        p.name,
                        (_t_act0 - _t_spawn0) * 1e3,
                        (time.monotonic() - _t_act0) * 1e3,
                    )
                    with self._state_lock:
                        self.current[w] = slot.proc
                    return
                # unreachable fifo: the standby is dead or wedged — never
                # reusable, don't leak it
                log.warn("kfrun: standby unreachable; cold spawning %s", p.name)
                slot.proc.kill()
        p.start()
        with self._state_lock:
            self.current[w] = p

    def _release_slots(self, w: PeerID) -> None:
        if self.slot_pool is not None and w in self._worker_slots:
            self.slot_pool.put(self._worker_slots.pop(w))

    def _reset_heartbeats(self, stage: Stage) -> None:
        """Any membership change invalidates heartbeat rank bookkeeping:
        ranks are re-assigned by the new peer list, and a leaver killed
        mid-batch would otherwise stay 'stuck' forever and get a HEALTHY
        worker at its old rank killed later."""
        if self.hb_state is not None:
            self.hb_state.reset(stage.progress)

    def _update_aggregator(self, stage: Stage) -> None:
        """Point the scrape set at the new membership (the aggregator
        learns the cluster from Stages, never from a static list)."""
        if self.aggregator is not None:
            self.aggregator.set_peers(
                self.aggregator.targets_for_workers(stage.cluster.workers)
            )

    def apply_delta(self, stage: Stage) -> None:
        self.last_stage = stage
        self._update_aggregator(stage)
        self._reset_heartbeats(stage)
        new_local = {w for w in stage.cluster.workers if w.host == self.self_host}
        with self._state_lock:
            old_local = set(self.current)
        for w in old_local - new_local:
            with self._state_lock:
                proc = self.current.pop(w)
            self._gone.append(proc)  # worker exits itself on detach
            self._release_slots(w)
        for w in sorted(new_local - old_local):
            self._spawn(w, stage)

    def apply_full(self, stage: Stage) -> None:
        """Reload mode: stop everything, restart from stage.progress."""
        self.last_stage = stage
        self._update_aggregator(stage)
        self._reset_heartbeats(stage)
        with self._state_lock:
            doomed = list(self.current.items())
            self.current.clear()
        for w, proc in doomed:
            proc.kill()
            self._release_slots(w)
        for w in stage.cluster.workers:
            if w.host == self.self_host:
                self._spawn(w, stage)

    def record_postmortems(self, dead: List[PeerID]) -> List[dict]:
        """Crash forensics for workers that died with nonzero exit:
        harvest each one's flight journal + faulthandler file + output
        tail into a `worker_postmortem` audit event, the durable
        <run-dir>/postmortems.jsonl, and the aggregator's
        /cluster/postmortem view. Best-effort by contract — a worker
        that left nothing behind still yields the runner-side facts."""
        from kungfu_tpu.telemetry import flight

        out: List[dict] = []
        for w in dead:
            with self._state_lock:
                proc = self.current.get(w)
            if proc is not None and proc.proc is not None:
                # reap a just-killed child so the postmortem records
                # -SIGKILL, not a stale None
                try:
                    proc.proc.wait(timeout=1.0)
                except (subprocess.TimeoutExpired, OSError):
                    # still running, or already reaped elsewhere
                    proc.proc.poll()
            code = proc.proc.returncode if proc is not None and proc.proc else None
            key = (str(w), proc.proc.pid if proc is not None and proc.proc else None)
            if key in self._postmortemed:
                continue
            self._postmortemed.add(key)
            try:
                # empty telemetry_dir (no KF_TELEMETRY_DIR plumbed, e.g.
                # an embedded Watcher) -> runner-side facts only; the
                # workers journal under their own self-minted run dirs
                # this runner can't know
                pm = flight.harvest_postmortem(
                    self.telemetry_dir,
                    str(w),
                    exit_code=code,
                    output_tail=proc.output_tail() if proc is not None else None,
                )
            except Exception as e:  # noqa: BLE001 - forensics must never block recovery
                log.warn("kfrun: postmortem harvest for %s failed: %s", w, e)
                continue
            audit.record_event(
                "worker_postmortem",
                peer=str(w),
                trigger="worker_death",
                death=pm["death"],
                exit_code=code,
                last_step=pm.get("last_step"),
                last_record_age_s=pm.get("last_record_age_s"),
                clean_exit=pm.get("clean_exit"),
                journal_records=pm.get("journal_records"),
            )
            if self.telemetry_dir:
                flight.append_postmortem(self.telemetry_dir, pm)
            if self.aggregator is not None:
                self.aggregator.add_postmortem(str(w), pm)
            log.warn(
                "kfrun: worker_postmortem recorded for %s (%s, last step %s)",
                w, pm["death"], pm.get("last_step"),
            )
            out.append(pm)
        return out

    def _dead_workers(self) -> List[PeerID]:
        """Local workers that died WITHOUT a Stage removing them: exit
        code != 0 while still a cluster member = a real failure (normal
        completion exits 0, and leavers are moved to _gone first)."""
        with self._state_lock:
            return [
                w for w, p in self.current.items()
                if not p.running and p.proc.returncode not in (0, None)
            ]

    def _put_config(self, cluster: Cluster) -> None:
        if not self.config_server_url:
            return
        import urllib.request

        req = urllib.request.Request(
            self.config_server_url, data=cluster.dumps().encode(), method="PUT"
        )
        try:
            with urllib.request.urlopen(req, timeout=5) as resp:
                resp.read()
        except OSError as e:
            log.warn("kfrun: config-server PUT failed: %s", e)

    def recover_from_failure(self, dead: List[PeerID]) -> None:
        """Shrink the dead workers out and reload the survivors from the
        last known progress. The recovery Stage is applied locally,
        broadcast to every other runner's control endpoint, and published
        to the config server so later elastic polls don't resize the
        corpses back in."""
        self.failure_restarts += 1
        self.record_postmortems(dead)
        codes = {
            str(w): (self.current[w].proc.returncode if w in self.current else "?")
            for w in dead
        }
        if self.failure_restarts > 10:
            log.error("kfrun: too many failure recoveries, giving up")
            # on the record, not just a log line: the cluster audit log
            # (and /cluster/audit) must say why the run died
            audit.record_event(
                "run_abort",
                trigger="failure_recovery_limit",
                restarts=self.failure_restarts,
                exit_codes=codes,
            )
            self.exit_code = 1
            self.done.set()
            return
        base = self.last_stage
        survivors = [w for w in base.cluster.workers if w not in set(dead)]
        log.warn(
            "kfrun: workers %s died; reloading at size %d", codes, len(survivors)
        )
        if not survivors:
            audit.record_event(
                "run_abort", trigger="no_survivors", exit_codes=codes
            )
            self.exit_code = 1
            self.done.set()
            return
        progress = base.progress
        if self.hb_state is not None:
            n_local = sum(
                1 for w in base.cluster.workers if w.host == self.self_host
            )
            progress = max(progress, self.hb_state.min_epoch(n_local))
        cluster = Cluster(runners=base.cluster.runners, workers=PeerList(survivors))
        # version skewed by this runner's index so two hosts detecting
        # failures in the same window mint DIFFERENT versions instead of
        # colliding on max+1 with different clusters (which the diverged-
        # digest safety check would abort the whole job over). Both reload
        # stages then apply in version order; if each removed only its own
        # corpse, the later one still carries the other corpse and the next
        # detection round (restart cap 10) converges.
        runners = list(base.cluster.runners)
        self_idx = next(
            (i for i, r in enumerate(runners) if r.host == self.self_host), 0
        )
        stage = Stage(
            version=max(self.seen_versions) + 1 + self_idx,
            progress=progress,
            cluster=cluster,
            reload=True,
        )
        self.seen_versions[stage.version] = stage.digest()
        self.record_stage(stage)
        self._put_config(cluster)
        # fan the reload out to the other runners (their workers are
        # blocked in collectives against the corpse)
        others = [r for r in cluster.runners if r.host != self.self_host]
        if others:
            import json as _json

            from kungfu_tpu.transport.client import Client

            payload = _json.dumps({
                "Version": stage.version,
                "Progress": stage.progress,
                "Cluster": cluster.to_json(),
                "Reload": True,
            }).encode()
            cl = Client(PeerID(self.self_host, self.args.runner_port))
            for r in others:
                try:
                    cl.send(r, "update", payload, ConnType.CONTROL)
                except (ConnectionError, OSError) as e:
                    log.warn("kfrun: notify %s failed: %s", r, e)
            cl.close()
        self.apply_full(stage)

    def run(self, initial: Stage) -> int:
        server = Server(PeerID(self.self_host, self.args.runner_port), use_unix=False)
        server.register(ConnType.CONTROL, self.handle_control)
        server.start()
        debug = None
        if getattr(self.args, "debug_port", -1) >= 0:
            debug = DebugServer(self, self.args.debug_port)
            debug.start()
            log.info("kfrun: debug endpoint on :%d", debug.port)
        if self.aggregator is not None and debug is not None:
            host = self.self_host or "127.0.0.1"
            self.cluster_health_url = (
                f"http://{host}:{debug.port}/cluster/health"
            )
            self._update_aggregator(initial)
            self.aggregator.start()
            log.info(
                "kfrun: cluster telemetry: /cluster/{metrics,trace,health,links} "
                "on :%d (scrape every %.1fs)",
                debug.port, self.aggregator.interval,
            )
        idle_since: Optional[float] = None
        try:
            self.apply_delta(initial)
            self._initial_done = True
            while not self.done.is_set():
                try:
                    stage = self.stage_q.get(timeout=0.5)
                except queue.Empty:
                    # Exit when all local workers have finished. In reload
                    # mode only, wait out a drain grace first: workers
                    # notify the runner and exit immediately, so the final
                    # Stage can still be in flight when the last proc dies —
                    # concluding too early drops the reload and strands the
                    # cluster. Delta-mode exits stay prompt.
                    grace = 2.0 if self.args.elastic_mode == "reload" else 0.0
                    if self.auto_recover:
                        dead = self._dead_workers()
                        if (
                            not dead
                            and self.hb_state is not None
                            and self.last_stage is not None
                        ):
                            # hung (not dead) workers: same grace rule as
                            # monitored mode; kill them so recovery treats
                            # them as dead
                            stuck = self.hb_state.stuck_ranks(self.grace)
                            workers = self.last_stage.cluster.workers
                            for r in stuck:
                                if 0 <= r < len(workers):
                                    w = workers[r]
                                    with self._state_lock:
                                        proc = self.current.get(w)
                                    if proc is not None:
                                        log.warn(
                                            "kfrun: worker %s stuck > %ss; killing",
                                            w, self.grace,
                                        )
                                        proc.kill()
                                        dead.append(w)
                        if dead and any(
                            p.running for p in self.current.values()
                        ):
                            # partial death: recover NOW (survivors are
                            # stuck); a full death falls through to the
                            # normal all-exited handling below, where
                            # uniform nonzero exits also recover
                            self.recover_from_failure(dead)
                            continue
                    if self.current and all(not p.running for p in self.current.values()):
                        if idle_since is None:
                            idle_since = time.monotonic()
                        if time.monotonic() - idle_since >= grace:
                            codes = [p.proc.returncode for p in self.current.values()]
                            if (
                                self.auto_recover
                                and any(c != 0 for c in codes)
                                and self.last_stage is not None
                                and any(
                                    w.host != self.self_host
                                    for w in self.last_stage.cluster.workers
                                )
                            ):
                                # every local worker is gone but remote
                                # hosts still train: shrink this host out
                                # instead of abandoning them mid-collective
                                self.recover_from_failure(self._dead_workers())
                                idle_since = None
                                continue
                            self.exit_code = 0 if all(c == 0 for c in codes) else 1
                            if self.exit_code != 0:
                                # even without auto-recover, a crashed
                                # worker leaves its black box behind
                                self.record_postmortems(self._dead_workers())
                            break
                    else:
                        idle_since = None
                    # reap detached workers
                    self._gone = [p for p in self._gone if p.running]
                    if (
                        self._refill_at is not None
                        and time.monotonic() >= self._refill_at
                    ):
                        self._refill_at = None
                        self.standby_pool.refill()
                    continue
                idle_since = None
                if stage.reload:
                    self.apply_full(stage)
                else:
                    self.apply_delta(stage)
            return self.exit_code
        finally:
            for p in self.current.values():
                p.kill()
            for p in self._gone:
                p.kill()
            if self.standby_pool is not None:
                self.standby_pool.kill_all()
            if self.monitor is not None:
                self.monitor.stop()
            if self.aggregator is not None:
                self.aggregator.stop()
                from kungfu_tpu.telemetry.cluster import set_aggregator

                set_aggregator(None)
            server.stop()
            if debug is not None:
                debug.stop()


def watch_run(args, cmd, cluster: Cluster, self_host: str, strategy, config_server_url: str) -> int:
    watcher = Watcher(args, cmd, self_host, strategy, config_server_url)
    initial = Stage(version=0, progress=0, cluster=cluster)
    watcher.seen_versions[0] = initial.digest()
    watcher.record_stage(initial)
    return watcher.run(initial)
