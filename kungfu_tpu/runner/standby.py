"""Warm-spare workers: pre-spawned processes that pay the interpreter +
numpy import cost BEFORE a resize needs them.

The dominant term of elastic-join latency is joiner startup (python +
numpy import is seconds of CPU on a busy host); the reference hides the
equivalent cost behind its always-resident Go runner. TPU-native design: the
elastic watcher keeps N standby processes alive; activating one costs a
FIFO write instead of a cold exec.

Protocol:
- the watcher spawns ``python -m kungfu_tpu.runner.standby`` with
  ``KF_STANDBY_FIFO=<path>`` (and optional ``KF_STANDBY_PRELOAD`` — extra
  comma-separated modules to import while waiting);
- the standby opens the FIFO for reading IMMEDIATELY (so activation can be
  written at any point, even mid-warmup), warms its imports, then blocks
  on the FIFO;
- activation is one JSON line ``{"env": {...}, "argv": [...]}``: the
  standby applies the env, sets sys.argv and runs the worker command
  in-process (runpy) when it is a python invocation, exec()-ing otherwise.
"""

from __future__ import annotations

import errno
import json
import os
import sys
import time
from typing import List, Optional


def _is_python(arg: str) -> bool:
    base = os.path.basename(arg)
    return base.startswith("python") or arg == sys.executable


def run_activated(spec: dict) -> None:
    """Apply an activation spec and run the worker command in-process."""
    import runpy

    os.environ.update(spec.get("env", {}))
    argv: List[str] = list(spec["argv"])
    if argv and _is_python(argv[0]):
        argv = argv[1:]
    if argv and argv[0] == "-u":
        argv = argv[1:]
    if len(argv) >= 2 and argv[0] == "-m":
        sys.argv = argv[1:]
        runpy.run_module(argv[1], run_name="__main__", alter_sys=True)
        return
    if len(argv) >= 2 and argv[0] == "-c":
        sys.argv = ["-c"] + argv[2:]
        exec(compile(argv[1], "<kf-standby>", "exec"), {"__name__": "__main__"})
        return
    if argv and argv[0].endswith(".py"):
        sys.argv = argv
        runpy.run_path(argv[0], run_name="__main__")
        return
    # not a python command: fall back to exec (warmth is lost, behavior
    # is preserved)
    os.execvpe(argv[0], argv, dict(os.environ))


class StandbySlot:
    """Watcher-side handle to one standby process."""

    def __init__(self, proc, fifo: str):
        self.proc = proc
        self.fifo = fifo

    @property
    def alive(self) -> bool:
        return self.proc.running

    def activate(
        self, env: dict, argv: List[str], name: str, rank: int,
        wait: float = 2.0,
    ) -> bool:
        """Hand the standby its worker identity; False if it died (caller
        falls back to a cold spawn). A just-spawned standby may not have
        opened its FIFO yet (python exec in flight) — retry for up to
        `wait` seconds while the process is alive, since even a not-yet-
        warm standby beats a cold spawn."""
        deadline = time.time() + wait
        while True:
            try:
                fd = os.open(self.fifo, os.O_WRONLY | os.O_NONBLOCK)
                break
            except OSError as e:
                if e.errno not in (errno.ENXIO, errno.ENOENT):
                    raise
                if not self.alive:
                    self._unlink_fifo()
                    return False
                if time.time() >= deadline:
                    # the standby is alive but slow (python startup under
                    # load): do NOT unlink — it has yet to open this path,
                    # and removing it would crash a healthy standby. The
                    # pool tempdir sweep owns cleanup for this case.
                    return False
                time.sleep(0.05)
        try:
            env = dict(env)
            # activation instant (CLOCK_MONOTONIC is machine-wide, so the
            # activated process can compute its own wakeup latency)
            env["KF_ACTIVATED_TS"] = str(time.monotonic())
            spec = json.dumps({"env": env, "argv": list(argv)}) + "\n"
            os.write(fd, spec.encode())
        except OSError:
            return False
        finally:
            os.close(fd)
            # single-shot: nothing opens this path again (the standby
            # holds its read fd), so the file can go now
            self._unlink_fifo()
        self.proc.name = name
        self.proc.rank = rank
        return True

    def _unlink_fifo(self) -> None:
        try:
            os.unlink(self.fifo)
        except OSError:
            pass


def resolve_preload(spec: str) -> str:
    """Map the -standby-preload spellings to a concrete module list:
    'auto' -> the device stack (jax — this framework's agents are
    jax-based; import only, no backend init), 'none'/'' -> nothing."""
    if spec == "auto":
        return "jax"
    if spec == "none":
        return ""
    return spec


class StandbyPool:
    """Keeps up to `n` warm standbys; `take()` pops one for activation and
    the caller refills asynchronously via `refill()` (Popen returns fast;
    the replacement warms while training continues)."""

    def __init__(self, n: int, logdir: str = "", quiet: bool = False,
                 preload: str = ""):
        import tempfile

        from kungfu_tpu.runner.proc import WorkerProc

        self._WorkerProc = WorkerProc
        self.n = n
        self.logdir = logdir
        self.quiet = quiet
        self.preload = preload
        self._dir = tempfile.mkdtemp(prefix="kf-standby-")
        self._seq = 0
        self.slots: List[StandbySlot] = []

    def refill(self) -> None:
        live = []
        for s in self.slots:
            if s.alive:
                live.append(s)
            else:
                s._unlink_fifo()
        self.slots = live
        while len(self.slots) < self.n:
            fifo = os.path.join(self._dir, f"standby-{self._seq}.fifo")
            os.mkfifo(fifo)
            env = {"KF_STANDBY_FIFO": fifo}
            if self.preload:
                env["KF_STANDBY_PRELOAD"] = self.preload
            p = self._WorkerProc(
                name=f"standby-{self._seq}",
                argv=[sys.executable, "-m", "kungfu_tpu.runner.standby"],
                env=env,
                rank=self._seq,
                logdir=self.logdir or None,
                quiet=self.quiet,
            )
            p.start()
            self.slots.append(StandbySlot(p, fifo))
            self._seq += 1

    def take(self) -> Optional[StandbySlot]:
        while self.slots:
            s = self.slots.pop(0)
            if s.alive:
                return s
            s._unlink_fifo()
        return None

    def kill_all(self) -> None:
        import shutil

        for s in self.slots:
            s.proc.kill()
        self.slots = []
        shutil.rmtree(self._dir, ignore_errors=True)


def _die_with_parent() -> None:
    """Arm PR_SET_PDEATHSIG in-process (safe: we are past exec, single-
    threaded). Belt-and-braces with the kf-pdeathsig exec shim — this
    covers standbys even when the shim binary hasn't been built."""
    try:
        import ctypes
        import signal as _signal

        libc = ctypes.CDLL(None, use_errno=True)
        libc.prctl(1, _signal.SIGTERM, 0, 0, 0)  # 1 = PR_SET_PDEATHSIG
        # died-before-arm check against the EXPLICIT runner pid (a
        # getppid()==1 heuristic misfires when the runner IS pid 1,
        # e.g. a container entrypoint)
        from kungfu_tpu import knobs

        runner_pid = int(knobs.get("KF_RUNNER_PID"))
        if runner_pid > 0 and os.getppid() != runner_pid:
            sys.exit(0)  # runner died before the arm
    except Exception as e:  # noqa: BLE001 - non-Linux: best-effort only
        from kungfu_tpu.telemetry import log

        log.debug("kf-standby: pdeathsig arm skipped: %s", e)


def main() -> None:
    _die_with_parent()
    from kungfu_tpu import knobs

    fifo = knobs.raw("KF_STANDBY_FIFO")
    if not fifo:
        from kungfu_tpu.telemetry import log

        log.error("kf-standby: KF_STANDBY_FIFO not set")
        sys.exit(2)
    # open for reading BEFORE warming so the watcher's nonblocking
    # open-for-write succeeds from the moment we exist
    try:
        fd = os.open(fifo, os.O_RDONLY | os.O_NONBLOCK)
    except FileNotFoundError:
        # the pool already swept this slot (watcher teardown raced us)
        from kungfu_tpu.telemetry import log

        log.warn("kf-standby: fifo gone before open; exiting")
        sys.exit(0)
    # warm imports: the bulk of cold-join latency
    import numpy  # noqa: F401

    import kungfu_tpu.api  # noqa: F401
    import kungfu_tpu.monitor.net  # noqa: F401  (Peer.__init__ pulls it)
    from kungfu_tpu.telemetry import log as _log

    # "auto"/"none" are resolved by the POOL (resolve_preload); an unset
    # or empty env means no extra preloads — "" must stay a working
    # disable spelling for direct StandbyPool users
    for mod in knobs.get("KF_STANDBY_PRELOAD"):
        try:
            __import__(mod)
        except ImportError as e:
            _log.warn("kf-standby: preload %s failed: %s", mod, e)
    _log.echo("kf-standby: warm")
    # block until the activation line arrives
    import select

    buf = b""
    while b"\n" not in buf:
        select.select([fd], [], [])
        chunk = os.read(fd, 65536)
        if chunk:
            buf += chunk
        else:
            # writer not connected yet (or closed without data): avoid a
            # busy loop
            time.sleep(0.05)
    os.close(fd)
    spec = json.loads(buf.split(b"\n", 1)[0].decode())
    run_activated(spec)


if __name__ == "__main__":
    main()
