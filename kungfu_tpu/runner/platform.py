"""Cloud platform adapters: self-discover the host list on managed clusters.

Capability parity: srcs/go/platforms/modelarts/modelarts.go — the reference
parses Huawei ModelArts' injected env (DLS_TASK_INDEX / DLS_TASK_NUMBER /
BATCH_CUSTOM<i>_HOSTS) into a PeerList so kungfu-run needs no -H flag. The
TPU-native analog targets Cloud TPU VMs: a pod slice's workers learn their
index and the full worker hostname list from the TPU runtime env
(TPU_WORKER_ID / TPU_WORKER_HOSTNAMES, set by the TPU VM image) or from the
GCE metadata server's instance attributes (agent-worker-number /
worker-network-endpoints).

Usage: ``kfrun -platform tpu-vm ...`` — the adapter supplies the HostList
and this host's identity; everything downstream (peer lists, runners,
elastic) is unchanged.
"""

from __future__ import annotations

import dataclasses
import json
import os
import urllib.request
from typing import Callable, Optional

from kungfu_tpu.plan.hostspec import HostList, HostSpec

# TPU VM runtime env (set by the Cloud TPU VM image on every worker)
TPU_WORKER_ID = "TPU_WORKER_ID"
TPU_WORKER_HOSTNAMES = "TPU_WORKER_HOSTNAMES"

METADATA_BASE = "http://metadata.google.internal/computeMetadata/v1"
_ATTR = "/instance/attributes/"
# GCE/TPU-VM metadata attribute names
ATTR_WORKER_NUMBER = "agent-worker-number"
ATTR_NETWORK_ENDPOINTS = "worker-network-endpoints"


@dataclasses.dataclass(frozen=True)
class PlatformCluster:
    hosts: HostList
    self_host: str
    self_index: int


def _metadata_fetcher(base: str = METADATA_BASE) -> Callable[[str], str]:
    def fetch(attr: str) -> str:
        req = urllib.request.Request(
            base + _ATTR + attr, headers={"Metadata-Flavor": "Google"}
        )
        with urllib.request.urlopen(req, timeout=5) as resp:
            return resp.read().decode()

    return fetch


def from_tpu_env(environ=None, slots_per_host: int = 1) -> Optional[PlatformCluster]:
    """Parse the TPU VM worker env; None when not on a TPU VM.

    TPU_WORKER_HOSTNAMES is a comma-separated list ordered by worker id;
    TPU_WORKER_ID is this worker's index into it (the same contract
    jax's cloud_tpu_init consumes).
    """
    env = environ if environ is not None else os.environ
    hostnames = env.get(TPU_WORKER_HOSTNAMES, "")
    if not hostnames:
        return None
    names = [h.strip() for h in hostnames.split(",") if h.strip()]
    idx = int(env.get(TPU_WORKER_ID, "0") or 0)
    if not 0 <= idx < len(names):
        raise ValueError(
            f"{TPU_WORKER_ID}={idx} out of range for {len(names)} workers"
        )
    hosts = HostList(HostSpec(n, slots_per_host) for n in names)
    return PlatformCluster(hosts=hosts, self_host=names[idx], self_index=idx)


def from_gce_metadata(
    fetch: Optional[Callable[[str], str]] = None, slots_per_host: int = 1
) -> Optional[PlatformCluster]:
    """Parse the GCE metadata server's TPU attributes; None when absent.

    worker-network-endpoints is the TPU runtime's canned JSON-ish list:
    one ``ip:uid:port`` (or bare ip) entry per worker, comma-separated and
    ordered by worker number; agent-worker-number is this worker's index.
    """
    fetch = fetch or _metadata_fetcher()
    try:
        endpoints_raw = fetch(ATTR_NETWORK_ENDPOINTS)
        idx_raw = fetch(ATTR_WORKER_NUMBER)
    except (OSError, ValueError):
        return None
    ips = []
    for entry in endpoints_raw.split(","):
        entry = entry.strip()
        if not entry:
            continue
        # entry forms seen in the wild: "ip", "ip:port", "ip:uid:port"
        ips.append(entry.split(":")[0])
    if not ips:
        return None
    idx = int(idx_raw.strip())
    if not 0 <= idx < len(ips):
        raise ValueError(
            f"{ATTR_WORKER_NUMBER}={idx} out of range for {len(ips)} workers"
        )
    hosts = HostList(HostSpec(ip, slots_per_host) for ip in ips)
    return PlatformCluster(hosts=hosts, self_host=ips[idx], self_index=idx)


def detect(
    name: str = "auto",
    environ=None,
    fetch: Optional[Callable[[str], str]] = None,
    slots_per_host: int = 1,
) -> Optional[PlatformCluster]:
    """Resolve a platform adapter by name: 'tpu-vm' (env), 'gce'
    (metadata server), or 'auto' (env first, then metadata)."""
    if name in ("tpu-vm", "auto"):
        got = from_tpu_env(environ, slots_per_host)
        if got is not None or name == "tpu-vm":
            return got
    if name in ("gce", "auto"):
        return from_gce_metadata(fetch, slots_per_host)
    raise ValueError(f"unknown platform {name!r} (expected tpu-vm, gce, auto)")
