"""Worker CPU affinity: partition host CPUs across local workers.

Capability parity: srcs/cpp/src/numa/placement.cpp:6-17 (select_cpus:
partition the host's CPU list evenly across local workers, NUMA-aware) +
init.cpp:21-26 (enabled via KUNGFU_USE_AFFINITY). On a TPU-VM host running
several workers, unpinned input pipelines fight over cores; pinning gives
each worker a disjoint slice, aligned to NUMA nodes when the topology is
visible under /sys/devices/system/node.

Enabled with the kfrun ``-use-affinity`` flag; the runner sets each child's
mask right after spawn (os.sched_setaffinity on the child pid — inherited
by all of the worker's threads from then on).
"""

from __future__ import annotations

import os
import re
from typing import Dict, List, Optional, Sequence

NODE_DIR = "/sys/devices/system/node"


def parse_cpulist(text: str) -> List[int]:
    """Parse a kernel cpulist ("0-3,8,10-11") into sorted cpu ids."""
    cpus: List[int] = []
    for part in text.strip().split(","):
        if not part:
            continue
        if "-" in part:
            lo, hi = part.split("-")
            cpus.extend(range(int(lo), int(hi) + 1))
        else:
            cpus.append(int(part))
    return sorted(set(cpus))


def numa_nodes(node_dir: str = NODE_DIR) -> List[List[int]]:
    """CPU lists per NUMA node, or [] when the topology isn't exposed."""
    try:
        entries = sorted(
            e for e in os.listdir(node_dir) if re.fullmatch(r"node\d+", e)
        )
    except OSError:
        return []
    nodes = []
    for e in entries:
        try:
            with open(os.path.join(node_dir, e, "cpulist")) as f:
                cpus = parse_cpulist(f.read())
        except OSError:
            continue
        if cpus:
            nodes.append(cpus)
    return nodes


def partition(cpus: Sequence[int], n: int) -> List[List[int]]:
    """Split cpus into n disjoint, near-equal, contiguous slices."""
    cpus = list(cpus)
    q, r = divmod(len(cpus), n)
    out, begin = [], 0
    for i in range(n):
        end = begin + q + (1 if i < r else 0)
        out.append(cpus[begin:end])
        begin = end
    return out


def plan_affinity(
    n_workers: int,
    cpus: Optional[Sequence[int]] = None,
    nodes: Optional[List[List[int]]] = None,
) -> List[List[int]]:
    """Disjoint CPU sets, one per local worker.

    NUMA-aware: workers are spread across nodes round-robin, and each
    worker's slice stays inside one node whenever workers >= nodes (the
    reference's placement: a worker never straddles a socket). Without
    visible topology, an even split of the process's allowed CPUs."""
    if n_workers <= 0:
        return []
    if cpus is None:
        cpus = sorted(os.sched_getaffinity(0))
    if nodes is None:
        nodes = numa_nodes()
    allowed = set(cpus)
    nodes = [[c for c in node if c in allowed] for node in nodes]
    nodes = [n_ for n_ in nodes if n_]
    if len(nodes) <= 1 or n_workers < len(nodes):
        return partition(list(cpus), n_workers)
    # workers per node, then split each node's cpus among its workers
    per_node = partition(list(range(n_workers)), len(nodes))
    out: List[List[int]] = [[] for _ in range(n_workers)]
    for node_cpus, workers in zip(nodes, per_node):
        if not workers:
            continue
        for w, cpuset in zip(workers, partition(node_cpus, len(workers))):
            out[w] = cpuset
    return out


def apply_affinity(pid: int, cpus: Sequence[int]) -> bool:
    """Pin `pid` to `cpus`; best-effort (False when unsupported/denied)."""
    if not cpus:
        return False
    try:
        os.sched_setaffinity(pid, set(cpus))
        return True
    except (OSError, AttributeError):
        return False
