"""Per-worker device-slot allocation.

Capability parity: srcs/go/kungfu/job/gpu_resource.go (per-host GPU slot
pool) + job.go's CUDA_VISIBLE_DEVICES — N workers sharing a host must each
see a DISJOINT set of accelerators instead of all opening the same chips.

TPU mapping: the runner partitions the host's chip ids among its local
workers and exports per-process visibility env:
- ``KF_DEVICE_SLOTS``  — the framework's own contract (comma-separated ids),
  readable via WorkerConfig.device_slots;
- ``TPU_VISIBLE_DEVICES`` — consumed by libtpu so each process initializes
  only its chips (the TPU analog of CUDA_VISIBLE_DEVICES).
The elastic watcher draws/returns slots from one pool across resizes, so a
joiner never doubles up on a surviving worker's chips.
"""

from __future__ import annotations

import threading
from typing import List, Sequence


class SlotPool:
    """Host-local pool of device ids (parity: GPUPool.Get/Put)."""

    def __init__(self, ids: Sequence[int]):
        self._lock = threading.Lock()
        self._free = sorted(set(int(i) for i in ids))
        self._cap = len(self._free)

    @classmethod
    def of_size(cls, n: int) -> "SlotPool":
        return cls(range(n))

    @property
    def capacity(self) -> int:
        return self._cap

    @property
    def available(self) -> int:
        with self._lock:
            return len(self._free)

    def get(self, n: int) -> List[int]:
        """Take n ids (lowest first); raises when the pool is short."""
        with self._lock:
            if n > len(self._free):
                raise RuntimeError(
                    f"device slot pool exhausted: want {n}, have {len(self._free)}"
                )
            taken, self._free = self._free[:n], self._free[n:]
            return taken

    def put(self, ids: Sequence[int]) -> None:
        with self._lock:
            back = set(int(i) for i in ids)
            dup = back & set(self._free)
            if dup:
                raise ValueError(f"double free of device slots {sorted(dup)}")
            self._free = sorted(set(self._free) | back)


def partition(n_devices: int, n_workers: int) -> List[List[int]]:
    """Even rank-major partition of device ids over local workers (worker
    i of k gets a contiguous stripe; remainders go to the first workers)."""
    if n_workers <= 0:
        return []
    base, rem = divmod(n_devices, n_workers)
    out, off = [], 0
    for i in range(n_workers):
        take = base + (1 if i < rem else 0)
        out.append(list(range(off, off + take)))
        off += take
    return out
