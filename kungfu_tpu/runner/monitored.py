"""Failure detection + auto-recovery (checkpoint-restart).

Capability parity: srcs/go/kungfu/runner/monitorserver/monitor.go:17-198 +
monitored.go:18-75 — a per-host HTTP monitor receives worker heartbeats
(``begin:<rank>`` / ``end:<rank>`` / ``epoch:<rank>`` / ``trainend:<rank>``);
a worker that stays inside a batch longer than the grace period is declared
stuck, all workers are killed and relaunched with ``--restart 1`` appended
so the training script reloads its checkpoint and continues from the last
completed epoch.

Cross-host protocol (parity: monitor.go:103-140): when any host's monitor
detects a LOCAL stuck worker it broadcasts ``otherdown:<minEpoch>`` to
every other runner's monitor, so hosts whose own workers look merely idle
(blocked in a collective without an outstanding batch) restart in lockstep
instead of waiting out their own grace period. The reference only lets the
MAIN (first) host broadcast; here any detecting host does — a hang on a
non-main host still converges, just via the main host's own detection, but
broadcasting from the detector is strictly faster.

Worker contract:
- KF_MONITOR_ADDR (set by the runner): where send_heartbeat POSTs.
- On relaunch the runner appends ``--restart 1`` (once) to the command and
  sets KF_RECOVER_EPOCH=<min completed epoch> so scripts without their own
  checkpoint bookkeeping know where to resume (the reference edits the
  script's --n-epochs flag instead; an env var doesn't assume a flag
  naming convention).
"""

from __future__ import annotations

import os
import sys
import threading
import time
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional, Tuple

from kungfu_tpu.telemetry import log

MONITOR_PORT = 7756
DEFAULT_GRACE = 10.0
MONITOR_ADDR_ENV = "KF_MONITOR_ADDR"
RECOVER_EPOCH_ENV = "KF_RECOVER_EPOCH"


class HeartbeatState:
    def __init__(self):
        self._lock = threading.Lock()
        self.in_batch: Dict[int, float] = {}  # rank -> batch begin time
        self.epochs: Dict[int, int] = {}  # per-incarnation completed epochs
        self.base_epoch = 0  # cluster-wide min at the start of this incarnation
        self.train_ended: Dict[int, bool] = {}
        self.other_down: Optional[int] = None  # min epoch from a remote host
        self.other_finish = False

    def signal(self, kind: str, rank: int) -> None:
        now = time.monotonic()
        with self._lock:
            if kind == "begin":
                self.in_batch[rank] = now
            elif kind == "end":
                self.in_batch.pop(rank, None)
            elif kind == "epoch":
                self.epochs[rank] = self.epochs.get(rank, 0) + 1
            elif kind == "trainend":
                self.train_ended[rank] = True
                self.in_batch.pop(rank, None)
            elif kind == "otherdown":
                self.other_down = rank  # value is the min epoch, not a rank
            elif kind == "otherfinish":
                self.other_finish = True

    def stuck_ranks(self, grace: float):
        now = time.monotonic()
        with self._lock:
            return [r for r, t0 in self.in_batch.items() if now - t0 > grace]

    def min_epoch(self, n_expected: int = 0) -> int:
        """Safe resume epoch: base + the min epochs completed THIS
        incarnation. A rank that hasn't signalled yet contributes 0 — its
        checkpoint may predate everyone else's — so when n_expected is
        given and some rank is silent, the increment is 0."""
        with self._lock:
            if not self.epochs or (n_expected and len(self.epochs) < n_expected):
                return self.base_epoch
            return self.base_epoch + min(self.epochs.values())

    def all_done(self, n: int) -> bool:
        with self._lock:
            return len(self.train_ended) >= n and all(self.train_ended.values())

    def reset(self, base_epoch: int = 0) -> None:
        """Wipe per-incarnation state before a respawn. Epoch counts are
        per-incarnation (a worker that crashed before its checkpoint write
        must not inflate the resume point across restarts) and other_finish
        must clear or every post-finish restart would skip straight to the
        wait-for-exit branch, disabling stuck detection."""
        with self._lock:
            self.in_batch.clear()
            self.train_ended.clear()
            self.epochs.clear()
            self.base_epoch = base_epoch
            self.other_down = None
            self.other_finish = False


class MonitorServer:
    """HTTP endpoint for worker heartbeats and peer-monitor control
    messages (parity: the :7756 server)."""

    def __init__(self, state: HeartbeatState, port: int = MONITOR_PORT):
        self.state = state

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def do_POST(inner):
                n = int(inner.headers.get("Content-Length", 0))
                body = inner.rfile.read(n).decode().strip()
                kind, _, value = body.partition(":")
                try:
                    self.state.signal(kind, int(value))
                    inner.send_response(200)
                except ValueError:
                    inner.send_response(400)
                inner.end_headers()

        self.httpd = ThreadingHTTPServer(("0.0.0.0", port), Handler)
        self.port = self.httpd.server_address[1]

    def start(self):
        threading.Thread(target=self.httpd.serve_forever, daemon=True).start()

    def stop(self):
        self.httpd.shutdown()


def parse_duration(s: str) -> float:
    s = s.strip()
    if s.endswith("ms"):
        return float(s[:-2]) / 1000
    if s.endswith("s"):
        return float(s[:-1])
    if s.endswith("m"):
        return float(s[:-1]) * 60
    return float(s)


def _post(addr: str, body: str, timeout: float = 3.0) -> bool:
    req = urllib.request.Request(
        f"http://{addr}/signal", data=body.encode(), method="POST"
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            resp.read()
        return True
    except OSError:
        return False


def _monitor_peers(args, cluster, self_host: str) -> List[str]:
    """Other runners' monitor addresses. -monitor-peers overrides (needed
    for multi-runner localhost tests where ports must differ); default =
    every other runner host on this runner's monitor port."""
    spec = getattr(args, "monitor_peers", "") or ""
    if spec:
        peers = [p.strip() for p in spec.split(",") if p.strip()]
        # exclude self (match on host:port)
        me = f"{self_host}:{getattr(args, 'monitor_port', MONITOR_PORT)}"
        return [p for p in peers if p != me]
    port = getattr(args, "monitor_port", MONITOR_PORT) or MONITOR_PORT
    return [
        f"{r.host}:{port}" for r in cluster.runners if r.host != self_host
    ]


def monitored_run(args, cmd, cluster, self_host: str, strategy) -> int:
    """Launch-and-relaunch loop (parity: MonitoredRun, monitored.go:18-75)."""
    from kungfu_tpu.runner.cli import make_worker_procs

    import subprocess

    grace = parse_duration(args.auto_recover) if args.auto_recover else DEFAULT_GRACE
    state = HeartbeatState()
    monitor_port = getattr(args, "monitor_port", MONITOR_PORT)
    monitor = MonitorServer(state, monitor_port)
    monitor.start()
    peers = _monitor_peers(args, cluster, self_host)
    n_local = sum(1 for w in cluster.workers if w.host == self_host)
    restart = 0
    recover_epoch = 0  # min completed epoch across local + otherdown info
    try:
        while True:
            worker_cmd = list(cmd)
            if restart > 0:
                worker_cmd += ["--restart", "1"]
            procs = make_worker_procs(args, worker_cmd, cluster, self_host, strategy)
            state.reset(recover_epoch)  # before spawn: a begin must never race the wipe
            for p in procs:
                p.env[MONITOR_ADDR_ENV] = f"{self_host}:{monitor.port}"
                if restart > 0:
                    p.env[RECOVER_EPOCH_ENV] = str(recover_epoch)
                p.start()
            failed = False
            local_down = False
            while True:
                if all(not p.running for p in procs):
                    codes = [p.proc.returncode for p in procs]
                    if all(c == 0 for c in codes):
                        return 0
                    failed = True
                    log.warn("kfrun: workers exited %s; restarting", codes)
                    recover_epoch = state.min_epoch(n_local)
                    break
                if state.stuck_ranks(grace):
                    recover_epoch = state.min_epoch(n_local)
                    log.warn(
                        "kfrun: worker stuck > %ss at epoch %s; restarting",
                        grace, recover_epoch,
                    )
                    failed = True
                    local_down = True
                    break
                if state.other_down is not None:
                    # the broadcast carries the DETECTING host's min epoch:
                    # every host must resume from the cluster-wide min, not
                    # its own (a fast host would otherwise skip ahead)
                    recover_epoch = min(state.min_epoch(n_local), state.other_down)
                    log.warn(
                        "kfrun: otherdown:%s received; restarting",
                        state.other_down,
                    )
                    failed = True
                    break
                if state.all_done(n_local) or state.other_finish:
                    # trainend heartbeats (or a remote all-finish) arrived:
                    # let local procs run to completion and judge by their
                    # exit codes — never report success over a failure
                    codes = []
                    for p in procs:
                        try:
                            codes.append(p.wait(600))
                        except subprocess.TimeoutExpired:
                            p.kill()
                            codes.append(-1)
                    if all(c == 0 for c in codes):
                        # broadcast only after exit codes confirm success:
                        # a premature otherfinish would let peers shut down
                        # while this host restarts into an empty cluster
                        if peers and state.all_done(n_local):
                            for addr in peers:
                                _post(addr, "otherfinish:0")
                        return 0
                    failed = True
                    recover_epoch = state.min_epoch(n_local)
                    log.warn(
                        "kfrun: workers exited %s after trainend; restarting",
                        codes,
                    )
                    break
                time.sleep(0.25)
            if local_down and peers:
                # tell the other hosts before tearing down locally so the
                # whole cluster restarts in lockstep (parity: otherdown
                # broadcast, monitor.go:103-140)
                body = f"otherdown:{recover_epoch}"
                for addr in peers:
                    _post(addr, body)
            for p in procs:
                p.kill()
            if not failed:
                return 0
            restart += 1
            if restart > 100:
                log.error("kfrun: too many restarts, giving up")
                return 1
    finally:
        monitor.stop()


def send_heartbeat(
    kind: str, rank: int, host: str = "", port: int = 0
) -> None:
    """Worker-side heartbeat (parity: kungfu.cmd.monitor_batch_begin etc.).

    Address resolution: explicit host and/or port args (a bare port targets
    localhost), else KF_MONITOR_ADDR (set by the monitored runner), else
    localhost:7756. Best-effort: a missing monitor is not an error (scripts
    run unchanged without -auto-recover).
    """
    if host or port:
        addr = f"{host or '127.0.0.1'}:{port or MONITOR_PORT}"
    else:
        from kungfu_tpu import knobs

        addr = knobs.raw(MONITOR_ADDR_ENV) or f"127.0.0.1:{MONITOR_PORT}"
    _post(addr, f"{kind}:{rank}", timeout=2.0)
