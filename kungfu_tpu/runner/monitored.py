"""Failure detection + auto-recovery (checkpoint-restart).

Capability parity: srcs/go/kungfu/runner/monitorserver/monitor.go:17-198 +
monitored.go:18-75 — a per-host HTTP monitor receives worker heartbeats
(``begin:<rank>`` / ``end:<rank>`` / ``epoch:<rank>`` / ``trainend:<rank>``);
a worker that stays inside a batch longer than the grace period is declared
stuck, all workers are killed and relaunched with ``--restart 1`` appended
so the training script reloads its checkpoint and continues from the last
completed epoch.
"""

from __future__ import annotations

import sys
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, Optional

MONITOR_PORT = 7756
DEFAULT_GRACE = 10.0


class HeartbeatState:
    def __init__(self):
        self._lock = threading.Lock()
        self.in_batch: Dict[int, float] = {}  # rank -> batch begin time
        self.epochs: Dict[int, int] = {}
        self.train_ended: Dict[int, bool] = {}

    def signal(self, kind: str, rank: int) -> None:
        now = time.monotonic()
        with self._lock:
            if kind == "begin":
                self.in_batch[rank] = now
            elif kind == "end":
                self.in_batch.pop(rank, None)
            elif kind == "epoch":
                self.epochs[rank] = self.epochs.get(rank, 0) + 1
            elif kind == "trainend":
                self.train_ended[rank] = True
                self.in_batch.pop(rank, None)

    def stuck_ranks(self, grace: float):
        now = time.monotonic()
        with self._lock:
            return [r for r, t0 in self.in_batch.items() if now - t0 > grace]

    def min_epoch(self) -> int:
        with self._lock:
            return min(self.epochs.values()) if self.epochs else 0

    def all_done(self, n: int) -> bool:
        with self._lock:
            return len(self.train_ended) >= n and all(self.train_ended.values())

    def reset(self) -> None:
        with self._lock:
            self.in_batch.clear()
            self.train_ended.clear()


class MonitorServer:
    """HTTP endpoint workers POST heartbeats to (parity: :7756 server)."""

    def __init__(self, state: HeartbeatState, port: int = MONITOR_PORT):
        self.state = state

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def do_POST(inner):
                n = int(inner.headers.get("Content-Length", 0))
                body = inner.rfile.read(n).decode().strip()
                kind, _, rank = body.partition(":")
                try:
                    self.state.signal(kind, int(rank))
                    inner.send_response(200)
                except ValueError:
                    inner.send_response(400)
                inner.end_headers()

        self.httpd = ThreadingHTTPServer(("0.0.0.0", port), Handler)
        self.port = self.httpd.server_address[1]

    def start(self):
        threading.Thread(target=self.httpd.serve_forever, daemon=True).start()

    def stop(self):
        self.httpd.shutdown()


def parse_duration(s: str) -> float:
    s = s.strip()
    if s.endswith("ms"):
        return float(s[:-2]) / 1000
    if s.endswith("s"):
        return float(s[:-1])
    if s.endswith("m"):
        return float(s[:-1]) * 60
    return float(s)


def monitored_run(args, cmd, cluster, self_host: str, strategy) -> int:
    """Launch-and-relaunch loop (parity: MonitoredRun, monitored.go:18-75)."""
    from kungfu_tpu.runner.cli import make_worker_procs

    grace = parse_duration(args.auto_recover) if args.auto_recover else DEFAULT_GRACE
    state = HeartbeatState()
    monitor = MonitorServer(state, MONITOR_PORT)
    monitor.start()
    n_local = sum(1 for w in cluster.workers if w.host == self_host)
    restart = 0
    try:
        while True:
            worker_cmd = list(cmd)
            if restart > 0:
                worker_cmd += ["--restart", "1"]
            procs = make_worker_procs(args, worker_cmd, cluster, self_host, strategy)
            for p in procs:
                p.start()
            state.reset()
            failed = False
            while True:
                if all(not p.running for p in procs):
                    codes = [p.proc.returncode for p in procs]
                    if all(c == 0 for c in codes):
                        return 0
                    failed = True
                    break
                if state.stuck_ranks(grace):
                    print(
                        f"kfrun: worker stuck > {grace}s at epoch {state.min_epoch()}; restarting",
                        file=sys.stderr,
                    )
                    failed = True
                    break
                if state.all_done(n_local):
                    for p in procs:
                        p.wait(30)
                    return 0
                time.sleep(0.5)
            for p in procs:
                p.kill()
            if not failed:
                return 0
            restart += 1
            if restart > 100:
                print("kfrun: too many restarts, giving up", file=sys.stderr)
                return 1
    finally:
        monitor.stop()


def send_heartbeat(kind: str, rank: int, host: str = "127.0.0.1", port: int = MONITOR_PORT) -> None:
    """Worker-side heartbeat (parity: kungfu.cmd.monitor_batch_begin etc.)."""
    import urllib.request

    req = urllib.request.Request(
        f"http://{host}:{port}/signal", data=f"{kind}:{rank}".encode(), method="POST"
    )
    try:
        with urllib.request.urlopen(req, timeout=2) as resp:
            resp.read()
    except OSError:
        pass  # monitor absent: heartbeats are best-effort
