"""ZeRO-1 sharded weight update on the ring (ISSUE 11 tentpole).

Replaces allreduce-then-replicated-update with the sharded dataflow of
"Automatic Cross-Replica Sharding of Weight Update in Data-Parallel
Training" (PAPERS.md, arXiv:2004.13336), mapped onto the machinery PRs
4–8 built:

1. **reduce-scatter** (the RS half of the segmented ring walk,
   ``HostSession.reduce_scatter``) leaves each rank holding the fully
   reduced 1/k gradient segment it already owns per
   ``plan.topology.owned_segment_bounds`` — (k-1)/k·N bytes per peer,
   f32-exact;
2. the rank runs the **optimizer update on only that shard** and holds
   optimizer state (momentum) plus the f32 **master weights** for only
   that shard — state and update FLOPs drop k-fold;
3. an **all-gather of updated weights**
   (``HostSession.all_gather_shards``, bf16 on the wire where the codec
   wins — EQuARX's motivation, arXiv:2506.17615) broadcasts the result:
   (k-1)/k·N raw, (k-1)/k·N/2 compressed.

Total per step: (k-1)/k·N + (k-1)/k·N/2 wire bytes with bf16 weights vs
2·(k-1)/k·N for the replicated allreduce path.

**Master weights.** Each rank keeps an f32 master copy of its OWNED
shard; the update always applies to the master and the (possibly
bf16-quantized) all-gather result is only the cluster-identical forward
mirror. Without this, a compressed weight all-gather would trap weights
on the bf16 grid and silently drop updates smaller than one ULP; with
it, the quantization error per step is bounded by one wire step of the
weight and does not accumulate. With the codec off, mirror shard ==
master bit for bit.

**Bit-identity contract** (tests/test_zero.py): for plain SGD with the
codec off, the sharded step is bit-identical to the replicated path —
the RS half produces exactly the partial sums the full segmented
allreduce produces, the update applies the same elementwise float ops,
and the AG relays exact f32 segments.

**Scheduler integration.** With ``KF_CONFIG_ASYNC`` on, gradients are
submitted per tensor as they become ready and this object acts as the
scheduler's *sharded-unit handler*: the scheduler drives
``pack → reduce_and_update → gather → scatter`` per bucket across its
pipeline stages, so bucket 0's weight all-gather walks while bucket 1's
shard is still updating, and the tail all-gathers overlap the NEXT
step's forward (``flush()`` returns once every shard updated;
``wait_params()`` — `CollectiveScheduler.wait_gather` — blocks only for
gathers still in flight, call it before the next forward consumes the
params).

**Elastic resize.** Shard ownership is a function of k, so optimizer
state must re-shard when the cluster resizes: call
:meth:`ShardedUpdateSession.export_state` BEFORE the resize (a one-shot
exact state all-gather — every peer leaves with the identical full
blob), then rebuild the session on the new epoch with
``restore_state=blob``; the in-flight scheduler work drains through the
existing ``Peer._update_to`` → ``HostSession.close()`` path. Joining
peers receive the blob via the usual elastic state sync
(``broadcast_bytes``).
"""

from __future__ import annotations

import threading
import time
import weakref
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from kungfu_tpu.base.ops import ReduceOp
from kungfu_tpu.base.serialize import pack_leaves, unpack_leaves
from kungfu_tpu.base.workspace import Workspace
from kungfu_tpu.plan import topology as topo
from kungfu_tpu.telemetry import config as tconfig
from kungfu_tpu.telemetry import metrics as tmetrics
from kungfu_tpu.utils import trace


def bucket_layout(sizes: Sequence[int], cap_bytes: int,
                  itemsize: int = 4) -> List[List[int]]:
    """Greedy order-preserving packing of param indices into buckets of
    <= `cap_bytes` — THE bucket layout of the sharded update, shared by
    ShardedUpdateSession and the torch frontend's replicated state
    import/export so a KF_CONFIG_ZERO flip across a resize can still
    parse the other mode's state blob (the layout is a pure function of
    the param sizes and the cluster-agreed cap)."""
    out: List[List[int]] = []
    cur: List[int] = []
    cur_bytes = 0
    for i, n in enumerate(sizes):
        nbytes = int(n) * itemsize
        if cur and cur_bytes + nbytes > cap_bytes:
            out.append(cur)
            cur, cur_bytes = [], 0
        cur.append(i)
        cur_bytes += nbytes
    if cur:
        out.append(cur)
    return out


class ShardedSGD:
    """SGD (optional momentum) over a contiguous f32 shard. The same
    elementwise formula as the replicated reference path — ``g *= 1/k;
    buf = momentum·buf + g; p -= lr·buf`` — so sharded and replicated
    updates are bit-identical where the inputs are (tests assert this).
    State (the momentum buffer) exists for the SHARD only: the k-fold
    state cut of ZeRO-1."""

    def __init__(self, lr: float, momentum: float = 0.0):
        self.lr = float(lr)
        self.momentum = float(momentum)

    def state_names(self) -> Tuple[str, ...]:
        """Deterministic state-leaf order (export/restore layout)."""
        return ("momentum",) if self.momentum else ()

    def init(self, n: int) -> Dict[str, np.ndarray]:
        return {name: np.zeros(n, np.float32) for name in self.state_names()}

    def apply(
        self,
        params: np.ndarray,
        grads: np.ndarray,
        state: Dict[str, np.ndarray],
        scale: float,
    ) -> None:
        """In-place update of the param shard; `grads` is staging and is
        consumed (mutated). `scale` is the gradient-averaging factor."""
        np.multiply(grads, np.float32(scale), out=grads)
        if self.momentum:
            buf = state["momentum"]
            np.multiply(buf, np.float32(self.momentum), out=buf)
            np.add(buf, grads, out=buf)
            grads = buf
        # temp of shard size; the rounding (f32 multiply then f32
        # subtract) matches the replicated reference formula exactly
        np.subtract(params, np.float32(self.lr) * grads, out=params)


class _ZeroItem:
    """One in-flight sharded bucket as it moves through the scheduler
    stages (or the synchronous step loop): the walk-naming identity plus
    the round's POOLED gradient staging buffer. Gradients stage in a
    pooled buffer — not a persistent one — because the launcher packs
    round r+1 while the walker may still be reduce-scattering round r's
    buffer for the same bucket (the queue-depth overlap the scheduler
    exists to create); pooled buffers give each round its own, exactly
    like the fused allreduce pipeline. Returned to the pool after the
    shard update consumes it; dropped to GC on abort (the pool's
    documented policy for buffers a worker may still touch)."""

    __slots__ = ("zindex", "rnd", "tag", "gbuf", "garr")

    def __init__(self, zindex: int, rnd: int, tag: str, gbuf, garr):
        self.zindex = zindex
        self.rnd = rnd
        self.tag = tag  # "r" scheduler rounds / "s" sync rounds
        self.gbuf = gbuf
        self.garr = garr


class _Bucket:
    """One fused sharded-update bucket: contiguous members in param
    order, a persistent full-size weight mirror W (the all-gather
    buffer, cluster-identical after every step), grad staging G, and the
    SHARD-ONLY master weights + optimizer state."""

    __slots__ = (
        "index", "names", "params", "sizes", "offsets", "total",
        "W", "ob", "oe", "master", "state", "settled", "wres",
    )

    def __init__(self, index: int, names, params, opt: ShardedSGD,
                 bounds: Tuple[int, int]):
        self.index = index
        self.names = list(names)
        self.params = list(params)
        self.sizes = [p.size for p in self.params]
        self.offsets = list(np.cumsum([0] + self.sizes[:-1]))
        self.total = int(sum(self.sizes))
        self.W = np.empty(self.total, np.float32)
        off = 0
        for p in self.params:
            self.W[off:off + p.size] = p
            off += p.size
        # round-ordering gate for the weight mirror: round r's gather +
        # scatter read W while round r+1's update would write it — the
        # update waits for `settled` (set by scatter, cleared after each
        # update) so a slow all-gather can never interleave with the
        # next round's shard write on the same bucket
        self.settled = threading.Event()
        self.settled.set()
        # the owned-shard bounds under the session's CURRENT ring plan
        # (HostSession.owned_bounds — the single layout source); a
        # measured re-plan re-slices them through reshard_bounds
        self.ob, self.oe = bounds
        # f32 master of the owned shard: the update's source of truth.
        # The mirror W may be bf16-quantized by the weight all-gather;
        # the master integrates sub-ULP updates the mirror would lose.
        self.master = self.W[self.ob:self.oe].copy()
        self.state = opt.init(self.oe - self.ob)
        # error-feedback residual of the quantized weight all-gather
        # (ISSUE 20): the masters hold the exact weights, so the mirror's
        # per-step quantization error telescopes instead of compounding.
        # Per-shard (the gather names are round-stamped, so the session's
        # name-keyed store would never re-hit); reset to zero on every
        # re-shard — post_replan restores exact masters, so a zero
        # residual is the deterministic restart on every peer.
        self.wres = np.zeros(self.oe - self.ob, np.float32)

    def state_bytes(self) -> int:
        n = self.master.nbytes
        for arr in self.state.values():
            n += arr.nbytes
        return n

    def reshard_bounds(self, opt: ShardedSGD, bounds: Tuple[int, int]) -> None:
        """Re-slice this bucket's shard to new owned bounds (a measured
        re-plan moved the segment layout). The caller restores master/
        state contents from an exported full-state blob immediately
        after — the freshly sized arrays here are pure allocation."""
        self.ob, self.oe = bounds
        self.master = np.empty(self.oe - self.ob, np.float32)
        self.state = opt.init(self.oe - self.ob)
        self.wres = np.zeros(self.oe - self.ob, np.float32)


class ShardedUpdateSession:
    """Owner of the shard ↔ full-param mapping for one model's ZeRO-1
    update (module docstring has the dataflow). `params` are 1-D
    contiguous f32 numpy views of the model weights — scatter writes the
    gathered results back into them in place (the torch frontend passes
    zero-copy tensor views). Buckets follow the param order under the
    cluster-agreed ``KF_CONFIG_GROUP_BUCKET_BYTES`` cap, so every peer
    derives the identical layout without negotiation.

    Drive it one of two ways:

    * synchronous (``KF_CONFIG_ASYNC`` off): :meth:`step` per training
      step — pack, reduce-scatter, shard update, weight all-gather,
      scatter, inline;
    * through the async scheduler: :meth:`submit_grad` per tensor as
      gradients become ready (this object is the scheduler's sharded
      handler), :meth:`flush` at step end (returns once every shard
      updated — weight all-gathers keep walking), :meth:`wait_params`
      before the next forward consumes the params.
    """

    def __init__(
        self,
        params: Sequence[np.ndarray],
        opt: ShardedSGD,
        name: str = "zero",
        session=None,
        restore_state: Optional[bytes] = None,
    ):
        if session is None:
            from kungfu_tpu.peer import get_default_peer

            session = get_default_peer().current_session()
        self.sess = session
        self.opt = opt
        self.name = name
        self._prefix = f"kungfu::zero:{name}"
        k = session.size
        self._scale = 1.0 / k
        views: List[np.ndarray] = []
        for i, p in enumerate(params):
            a = np.asarray(p)
            if a.dtype != np.float32:
                raise ValueError(
                    f"sharded update params must be float32, got "
                    f"{a.dtype} at index {i}"
                )
            if not a.flags["C_CONTIGUOUS"]:
                raise ValueError(
                    f"sharded update params must be C-contiguous "
                    f"(param {i}) — scatter writes them back in place"
                )
            views.append(a.reshape(-1))
        if not views:
            raise ValueError("sharded update needs at least one param")
        self._views = views
        self._member_names = [f"{self._prefix}:{i}" for i in range(len(views))]
        self._buckets: List[_Bucket] = []
        self._member_bucket: Dict[str, Tuple[int, int]] = {}
        for idxs in bucket_layout([v.size for v in views],
                                  session.GROUP_BUCKET_BYTES):
            self._add_bucket([self._member_names[i] for i in idxs],
                             [views[i] for i in idxs])
        # measured-topology re-planning (ISSUE 14): a plan adoption
        # moves the owned-segment layout, so this session must re-shard
        # its masters/state exactly — pre_replan exports the full state
        # under the OLD layout, post_replan re-slices under the new
        if hasattr(session, "add_replan_listener"):
            session.add_replan_listener(self)
        # quantized-codec residual lifecycle (ISSUE 20): any session
        # flush (wire-mode flip, precision vote, re-plan) must reach the
        # per-shard weight residuals too — stale residuals measure the
        # old codec/layout and would corrupt the next gather
        if hasattr(session, "add_ef_flush_listener"):
            session.add_ef_flush_listener(self._reset_weight_residuals)
        self._sync_round = 0
        self._export_seq = 0
        self._lock = threading.Lock()
        if restore_state is not None:
            self._restore(restore_state)
        if tconfig.metrics_enabled():
            self._state_gauge = tmetrics.gauge(
                "kungfu_sharded_update_state_bytes",
                "Optimizer-held bytes of the ZeRO-1 sharded update on "
                "this peer (shard master weights + shard optimizer "
                "state) — ~1/k of the replicated path's full-size state",
            )
            self._update_ctr = tmetrics.counter(
                "kungfu_sharded_update_seconds_total",
                "Seconds spent in the shard-local optimizer update "
                "(the k-fold-reduced update FLOPs of ZeRO-1)",
            )
            self._state_gauge.set(self.state_bytes())
        else:
            self._state_gauge = None
            self._update_ctr = None
        # memory plane (ISSUE 17): shard masters + optimizer state +
        # the full-size reduce mirrors are long-lived buffer owners.
        # Weakref so the registry never pins a session across an
        # elastic resize — the entry self-drops when the session dies.
        try:
            from kungfu_tpu.telemetry import memory as _tmem

            def _acct(ref=weakref.ref(self)) -> Optional[int]:
                zs = ref()
                if zs is None:
                    return None
                return zs.state_bytes() + sum(
                    b.W.nbytes for b in zs._buckets
                )

            _tmem.register_accountant(
                f"zero:{name}", "zero_state", _acct
            )
        # kfcheck: disable=KF400 — byte accounting is best-effort;
        # it must never kill the update path
        except Exception:  # noqa: BLE001
            pass

    def _add_bucket(self, names, params) -> None:
        total = int(sum(p.size for p in params))
        b = _Bucket(len(self._buckets), names, params, self.opt,
                    self._owned_bounds(total))
        for j, n in enumerate(names):
            self._member_bucket[n] = (b.index, j)
        self._buckets.append(b)

    def _owned_bounds(self, total: int) -> Tuple[int, int]:
        """The session's plan-aware owned bounds (falls back to the
        naive layout for bare/mock sessions without the accessor)."""
        if hasattr(self.sess, "owned_bounds"):
            return self.sess.owned_bounds(total)
        return topo.owned_segment_bounds(total, self.sess.size, self.sess.rank)

    # ------------------------------------------------------------------
    # introspection
    # ------------------------------------------------------------------

    def state_bytes(self) -> int:
        """Bytes of optimizer-held state on THIS peer (shard masters +
        shard optimizer state) — the number the
        `kungfu_sharded_update_state_bytes` gauge exports. The
        replicated equivalent is full-size state on every peer."""
        return sum(b.state_bytes() for b in self._buckets)

    def total_elems(self) -> int:
        return sum(b.total for b in self._buckets)

    def bucket_count(self) -> int:
        return len(self._buckets)

    def _check_epoch(self) -> None:
        if getattr(self.sess, "_epoch_closed", False):
            raise RuntimeError(
                "sharded update session's epoch ended (elastic resize): "
                "export_state() BEFORE the resize and rebuild "
                "ShardedUpdateSession(restore_state=...) on the new "
                "session"
            )

    def _grad_views(self, grads: Sequence[np.ndarray]) -> List[np.ndarray]:
        if len(grads) != len(self._views):
            raise ValueError(
                f"expected {len(self._views)} gradients, got {len(grads)}"
            )
        out = []
        for i, (g, p) in enumerate(zip(grads, self._views)):
            a = np.asarray(g)
            if a.dtype != np.float32 or a.size != p.size:
                raise ValueError(
                    f"grad {i} mismatch: {a.dtype}/{a.size} vs param "
                    f"float32/{p.size}"
                )
            out.append(np.ascontiguousarray(a).reshape(-1))
        return out

    # ------------------------------------------------------------------
    # synchronous step path (KF_CONFIG_ASYNC off)
    # ------------------------------------------------------------------

    def step(self, grads: Sequence[np.ndarray]) -> None:
        """One synchronous ZeRO-1 step over the full gradient set (param
        order): per bucket pack → reduce-scatter → shard update → weight
        all-gather → scatter back into the params. Wire names carry a
        process-local round counter (peers call in identical program
        order, so it agrees) — a fast peer's next step can never be
        consumed by a slower peer still in this one."""
        self._check_epoch()
        views = self._grad_views(grads)
        with self._lock:
            rnd = self._sync_round
            self._sync_round += 1
        for b in self._buckets:
            item = self._pack_views(b, views, rnd, "s")
            self.reduce_and_update(item)
            self.gather(item)
            self.scatter(item)

    def _pack_into(self, b: _Bucket, rnd: int, tag: str,
                   source) -> _ZeroItem:
        """Shared staging pack of one bucket's gradients into a pooled
        buffer (one implementation behind BOTH the sync step and the
        scheduler's launcher stage — the sync-vs-async bit-identity
        contract depends on identical staging). `source(name, j)`
        returns member j's gradient array."""
        from kungfu_tpu.utils.pool import get_buffer_pool

        gbuf = get_buffer_pool().get(b.total * 4)
        garr = np.frombuffer(gbuf, np.float32, b.total)
        for j, n in enumerate(b.names):
            off = b.offsets[j]
            garr[off:off + b.sizes[j]] = source(n, j)
        return _ZeroItem(b.index, rnd, tag, gbuf, garr)

    def _pack_views(self, b: _Bucket, views, rnd: int, tag: str) -> _ZeroItem:
        return self._pack_into(
            b, rnd, tag,
            lambda n, j: views[int(n.rsplit(":", 1)[1])],
        )

    # ------------------------------------------------------------------
    # async path (the scheduler drives the handler protocol below)
    # ------------------------------------------------------------------

    def submit_grad(self, i: int, grad: np.ndarray) -> None:
        """Hand gradient `i` (param order) to the async scheduler as it
        becomes ready. The workspace's recv is NOT written — the
        gradient is consumed by the shard update; the deliverable is the
        updated params, scattered back by the scheduler's unpack stage.
        `priority=i` pins the negotiated registration order to param
        order on every peer regardless of arrival order."""
        self._check_epoch()
        g = np.ascontiguousarray(np.asarray(grad)).reshape(-1)
        if i < 0 or i >= len(self._views):
            raise IndexError(f"param index {i} outside 0..{len(self._views) - 1}")
        if g.dtype != np.float32 or g.size != self._views[i].size:
            raise ValueError(
                f"grad {i} mismatch: {g.dtype}/{g.size} vs param "
                f"float32/{self._views[i].size}"
            )
        self.sess.scheduler().submit(
            Workspace(send=g, recv=g, op=ReduceOp.SUM,
                      name=self._member_names[i]),
            priority=i,
            handler=self,
        )

    def flush(self, timeout: Optional[float] = None) -> None:
        """End the gradient round: returns once every bucket's shard has
        been reduced and updated (gradient buffers are consumable
        again). Weight all-gathers may still be walking — they overlap
        the caller's next-step compute; see :meth:`wait_params`."""
        self.sess.scheduler().flush(timeout=timeout)

    def wait_params(self, timeout: Optional[float] = None) -> None:
        """Block until every in-flight weight all-gather has landed and
        been scattered into the params. Call before the next forward
        consumes the params (the start-of-step barrier of the
        overlapped loop)."""
        self.sess.scheduler().wait_gather(timeout=timeout)

    # ------------------------------------------------------------------
    # scheduler sharded-handler protocol
    # ------------------------------------------------------------------

    def plan_units(self, zero_keys) -> List[list]:
        """Map the scheduler's registered sharded keys onto this
        session's bucket layout: one launch unit per bucket, members in
        bucket (== param) order. Pure function of the consensus-checked
        registry and this object's deterministic layout, so every peer
        derives the identical plan. A registered set that doesn't match
        the declared params is a configuration error — fail fast."""
        by_name = {k[0]: k for k in zero_keys}
        if len(by_name) != len(zero_keys):
            raise ValueError("duplicate sharded tensor names registered")
        expected = set(self._member_names)
        got = set(by_name)
        if expected != got:
            missing = sorted(expected - got)[:4]
            rogue = sorted(got - expected)[:4]
            raise ValueError(
                "registered sharded tensors do not match the "
                f"ShardedUpdateSession params (missing {missing}, "
                f"unexpected {rogue}) — submit every param's gradient "
                "exactly once per round through submit_grad"
            )
        for k in zero_keys:
            bi, j = self._member_bucket[k[0]]
            if k[1] != self._buckets[bi].sizes[j]:
                raise ValueError(
                    f"sharded tensor {k[0]!r} registered with size "
                    f"{k[1]} but the param has {self._buckets[bi].sizes[j]}"
                )
        return [[by_name[n] for n in b.names] for b in self._buckets]

    def pack(self, zindex: int, members: List[Workspace], rnd: int) -> _ZeroItem:
        """Launcher stage: pack the round's submitted gradient
        workspaces (unit-key order == bucket member order) into a POOLED
        staging buffer — the walker may still be reduce-scattering the
        previous round's buffer for this bucket."""
        b = self._buckets[zindex]
        by_name = {}
        for w in members:
            bi, _ = self._member_bucket[w.name]
            if bi != zindex:
                raise ValueError(
                    f"tensor {w.name!r} landed in bucket {zindex}, "
                    f"belongs to {bi}"
                )
            by_name[w.name] = w.send
        with trace.span("zero.pack", bucket=zindex):
            return self._pack_into(b, rnd, "r", lambda n, j: by_name[n])

    def reduce_and_update(self, item: _ZeroItem,
                          cancel: Optional[threading.Event] = None) -> _ZeroItem:
        """Walker stage: reduce-scatter the bucket's gradients (raw f32,
        (k-1)/k·N bytes), then run the optimizer on the owned shard —
        update FLOPs and state touched are 1/k of the replicated path.
        The update applies to the f32 master; the mirror shard is
        refreshed from it for the all-gather. Waits for the PREVIOUS
        round's gather+scatter of this bucket to land before touching
        the mirror (the `settled` gate)."""
        from kungfu_tpu.utils.pool import get_buffer_pool

        b = self._buckets[item.zindex]
        ws = Workspace(
            send=item.garr, recv=item.garr, op=ReduceOp.SUM,
            name=f"{self._prefix}:zrs:{item.tag}{item.rnd}:b{item.zindex}",
        )
        ob, oe = self.sess.reduce_scatter(ws, cancel=cancel)
        if (ob, oe) != (b.ob, b.oe):
            raise RuntimeError(
                f"shard layout drift: walk owns [{ob}:{oe}), optimizer "
                f"holds [{b.ob}:{b.oe}) — owned_segment_bounds must be "
                "the single layout source"
            )
        # abort-aware settled wait: a hard-cancel (scheduler close past
        # its drain budget) must unblock this thread within one poll
        # interval, not leave it parked for the full walk timeout — the
        # close() join budget is seconds, and an old-epoch thread must
        # not outlive the epoch (the KF303 drain contract)
        deadline = time.monotonic() + self.sess.timeout
        while not b.settled.wait(0.2):
            if cancel is not None and cancel.is_set():
                raise TimeoutError(
                    f"sharded update cancelled: bucket {b.index}"
                )
            if time.monotonic() >= deadline:
                raise TimeoutError(
                    f"bucket {b.index}'s previous weight all-gather "
                    "never landed — cannot start the next shard update"
                )
        if cancel is not None and cancel.is_set():
            raise TimeoutError(
                f"sharded update cancelled: bucket {b.index}"
            )
        t0 = time.perf_counter()
        with trace.span("zero.update", bucket=item.zindex,
                        elems=int(b.oe - b.ob)):
            self.opt.apply(b.master, item.garr[b.ob:b.oe], b.state,
                           self._scale)
            np.copyto(b.W[b.ob:b.oe], b.master)
        b.settled.clear()
        if self._update_ctr is not None:
            self._update_ctr.inc(time.perf_counter() - t0)
        # the gradients are consumed: return the staging buffer
        get_buffer_pool().put(item.gbuf)
        item.gbuf = item.garr = None
        return item

    def gather(self, item: _ZeroItem,
               cancel: Optional[threading.Event] = None) -> _ZeroItem:
        """Gather stage: all-gather the bucket's updated weights around
        the ring — bf16 on the wire when the codec wins ((k-1)/k·N/2
        bytes), f32 otherwise. After it W is complete and identical on
        every peer, owner included."""
        b = self._buckets[item.zindex]
        self.sess.all_gather_shards(
            b.W,
            f"{self._prefix}:zag:{item.tag}{item.rnd}:b{item.zindex}",
            cancel=cancel,
            ef=b.wres,
        )
        return item

    def scatter(self, item: _ZeroItem,
                cancel: Optional[threading.Event] = None) -> None:
        """Unpack stage: scatter the gathered weights back into the
        caller's param views (in place — torch tensors see the update
        without a copy), then release the bucket's `settled` gate so the
        next round's update may write the mirror. A set `cancel`
        (scheduler hard-abort) skips the write — the epoch is ending and
        the params are restored by the elastic state sync, so a late
        scatter must not race the caller (KF703); the `settled` gate
        stays cleared, matching the driver's skip path."""
        if cancel is not None and cancel.is_set():
            return
        b = self._buckets[item.zindex]
        with trace.span("zero.scatter", bucket=item.zindex):
            for j, p in enumerate(b.params):
                off = b.offsets[j]
                np.copyto(p, b.W[off:off + b.sizes[j]])
        b.settled.set()

    # ------------------------------------------------------------------
    # elastic re-shard (resize support)
    # ------------------------------------------------------------------

    def export_state(self) -> bytes:
        """One-shot EXACT state all-gather: reconstruct the full master
        weights and full optimizer state from every peer's shards and
        serialize them. Every peer leaves with the identical blob — run
        it BEFORE a resize (on the old session), then rebuild with
        ``restore_state=blob`` on the new epoch; shard ownership is a
        function of k, so the new session re-slices its own shard.
        Never wire-compressed: re-sharded state must be bit-identical
        to what a fresh replicated run would hold. Call at a step
        boundary — after ``flush()`` + ``wait_params()`` — so no
        scheduler stage is concurrently touching the masters/state."""
        self._check_epoch()
        with self._lock:
            seq = self._export_seq
            self._export_seq += 1
        leaves: List[np.ndarray] = []
        for b in self._buckets:
            for li, name in enumerate(("master",) + self.opt.state_names()):
                full = np.zeros(b.total, np.float32)
                shard = b.master if name == "master" else b.state[name]
                full[b.ob:b.oe] = shard
                self.sess.all_gather_shards(
                    full,
                    f"{self._prefix}:state:{seq}:b{b.index}:{li}",
                    allow_wire=False,
                )
                leaves.append(full)
        return pack_leaves(leaves)

    def _reset_weight_residuals(self, reason: str) -> None:
        """Session ef-flush hook (ISSUE 20): zero every bucket's weight
        all-gather residual. Deterministic on every peer — the masters
        stay exact, so dropping the carried remainder costs at most one
        quantization step on the NEXT gather, never correctness."""
        for b in self._buckets:
            b.wres[:] = 0.0

    # ------------------------------------------------------------------
    # measured-topology re-plan hooks (ISSUE 14)
    # ------------------------------------------------------------------

    def pre_replan(self) -> bytes:
        """Replan-listener hook, called by ``HostSession.adopt_replan``
        BEFORE the plan swap (in lockstep on every peer, at a step
        boundary): quiesce in-flight weight all-gathers, then export the
        full exact state under the OLD shard layout. The returned blob
        feeds :meth:`post_replan`."""
        if self.sess._scheduler is not None:
            self.wait_params()
        return self.export_state()

    def post_replan(self, blob: bytes) -> None:
        """Replan-listener hook, called AFTER the plan swap: re-slice
        every bucket's shard to the session's NEW owned bounds and
        restore masters/state from the pre-swap export — bit-exact
        re-sharding, the same contract as an elastic resize
        (``export_state``/``restore_state``), just without changing k."""
        for b in self._buckets:
            b.reshard_bounds(self.opt, self._owned_bounds(b.total))
        self._restore(blob)
        if self._state_gauge is not None:
            self._state_gauge.set(self.state_bytes())

    def _restore(self, blob: bytes) -> None:
        per_bucket = 1 + len(self.opt.state_names())
        leaves = unpack_leaves(blob, per_bucket * len(self._buckets))
        it = iter(leaves)
        for b in self._buckets:
            for name in ("master",) + self.opt.state_names():
                full = np.asarray(next(it), np.float32).reshape(-1)
                if full.size != b.total:
                    raise ValueError(
                        f"restore_state bucket {b.index} leaf {name!r} "
                        f"has {full.size} elements, expected {b.total} — "
                        "param set or bucket knobs changed across the "
                        "resize"
                    )
                if name == "master":
                    # the exported masters ARE the true f32 weights:
                    # refresh the mirror and the caller's params from
                    # them (survivors' mirrors may hold bf16-rounded
                    # values; every peer restores the same blob, so the
                    # cluster stays consistent)
                    np.copyto(b.W, full)
                    b.master = full[b.ob:b.oe].copy()
                    for j, p in enumerate(b.params):
                        off = b.offsets[j]
                        # kfcheck: disable=KF703 — quiesced restore: runs
                        # at construction or inside a lockstep re-plan
                        # adoption (post_replan), both with no walk in
                        # flight, so no abort scope exists; the params
                        # are ours to (re)initialize before the next step
                        np.copyto(p, b.W[off:off + b.sizes[j]])
                else:
                    np.copyto(b.state[name], full[b.ob:b.oe])
