"""Wire-codec layer of the host collective engine (ISSUE 5), factored
out of host_session.py (ISSUE 10 prerequisite refactor).

Owns everything codec-*policy*: the KF_CONFIG_WIRE mode table, the
per-workspace compress-or-bypass decision (:class:`WireCodec` mixin on
:class:`~kungfu_tpu.collective.host_session.HostSession`) and the
deferred-decode handle the fused pipeline uses to merge the walk-end
decode into bucket unpack. The codec *mechanics* (encode/decode/
decode-accumulate kernels) stay in base/ops.py + native/reduce.cpp.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from kungfu_tpu import knobs
from kungfu_tpu.base.dtype import DType
from kungfu_tpu.base.ops import decode_wire
from kungfu_tpu.base.workspace import Workspace
from kungfu_tpu.utils.pool import get_buffer_pool

# Wire codec (ISSUE 5 tentpole): f32 allreduce payloads travel the
# transport as bf16/f16 while every reduce step accumulates into the f32
# buffer. Like KF_CONFIG_ALGO this is a cluster-agreed runtime knob (it
# decides message SIZES, so a disagreeing peer would read short/long
# frames) — fail-fast enforced by check_knob_consensus at session start.
# `auto` currently resolves to bf16 for eligible payloads (the TPU-native
# format: f32-identical exponent range, so no overflow surprises); it is
# a distinct mode so later heuristics (payload- or link-aware) can slot
# in without an env change.
WIRE_MODES = ("off", "bf16", "f16", "auto")

WIRE_DTYPE = {"bf16": DType.BF16, "f16": DType.F16, "auto": DType.BF16}


def wire_override() -> str:
    """Parse KF_CONFIG_WIRE (read per session epoch, not import time).
    The registry's strict choice parser raises on a typo and resolves
    unset/empty to "off"."""
    return knobs.get("KF_CONFIG_WIRE")


class DeferredDecode:
    """Handle to a compressed segmented walk's all-gather wire buffer,
    returned instead of the walk-end f32 decode when the caller asked to
    defer it (`_allreduce_ws(defer_decode=True)`). The fused pipeline's
    unpacker decodes straight from this buffer into each member's recv —
    fusing decode with unpack saves one full f32 pass over the bucket on
    the hot path. Call `decode_into(dst, begin, end)` per member, then
    `close()` exactly once to return the buffer to the pool."""

    __slots__ = ("wire", "_buf", "_arr")

    def __init__(self, wire: DType, buf, arr: np.ndarray):
        self.wire = wire
        self._buf = buf
        self._arr = arr

    def decode_into(self, dst: np.ndarray, begin: int, end: int) -> None:
        seg = self._arr[begin:end]
        if dst.flags["C_CONTIGUOUS"]:
            decode_wire(dst, seg, self.wire)
        else:
            tmp = np.empty(end - begin, np.float32)
            decode_wire(tmp, seg, self.wire)
            np.copyto(dst, tmp)

    def close(self) -> None:
        if self._buf is not None:
            get_buffer_pool().put(self._buf)
            self._buf = None


class WireCodec:
    """Codec-policy mixin for HostSession: resolves the RUNNING wire
    mode (config + lockstep adaptive votes) and decides per workspace
    whether a walk compresses or bypasses. Relies on session state
    (`wire_mode`, `_candidates`, `adaptive`, `_tree_override`,
    `WIRE_MIN_BYTES`) owned by the facade's constructor."""

    # Codec floor: encoding pays two passes (encode + decode) to halve
    # the wire bytes, which only wins once the payload dwarfs the fixed
    # per-walk costs; tiny control collectives also stay exact this way.
    # Cluster-agreed like SEGMENT_MIN_BYTES (it decides message sizes).
    WIRE_MIN_BYTES = int(knobs.get("KF_CONFIG_WIRE_MIN_BYTES"))

    def _active_wire_mode(self) -> str:
        """The RUNNING codec mode: the active adaptive candidate's wire
        member, or the configured mode under a set_tree override (an
        explicit forest replaces the graphs, not the codec)."""
        if self._tree_override:
            return self.wire_mode
        return self._candidates[self.adaptive.active][1]

    def _codec_bypass(self, reason: str, w: Workspace) -> None:
        """Audit (once per (reason, dtype) per session epoch) that a
        workspace bypassed an enabled codec — exact semantics preserved
        for consensus lanes, variance probes and tiny residuals."""
        key = (reason, w.send.dtype.str)
        if key in self._codec_bypass_seen:
            return
        self._codec_bypass_seen.add(key)
        from kungfu_tpu.telemetry import audit as _audit

        _audit.record_event(
            "wire_codec_bypass",
            peer=str(self.self_id),
            reason=reason,
            dtype=w.send.dtype.str,
            name=w.name,
            nbytes=int(w.recv.nbytes),
        )

    def _wire_codec_for(self, w: Workspace) -> Optional[DType]:
        """Codec decision for one allreduce workspace, or None (raw).

        MUST depend only on cluster-agreed inputs — the resolved wire
        mode (env + lockstep adaptive votes) and workspace properties
        identical on every peer — because it decides the byte count of
        every message in the walk. Non-f32 payloads (consensus lanes,
        int gradients) and sub-WIRE_MIN_BYTES residuals bypass with an
        audit event, never an error."""
        mode = self._active_wire_mode()
        if mode == "off":
            return None
        if w.send.dtype != np.float32:
            self._codec_bypass("non_f32", w)
            return None
        if w.recv.nbytes < self.WIRE_MIN_BYTES:
            self._codec_bypass("below_min_bytes", w)
            return None
        return WIRE_DTYPE[mode]
