"""Wire-codec layer of the host collective engine (ISSUE 5), factored
out of host_session.py (ISSUE 10 prerequisite refactor).

Owns everything codec-*policy*: the KF_CONFIG_WIRE mode table, the
per-workspace compress-or-bypass decision (:class:`WireCodec` mixin on
:class:`~kungfu_tpu.collective.host_session.HostSession`) and the
deferred-decode handle the fused pipeline uses to merge the walk-end
decode into bucket unpack. The codec *mechanics* (encode/decode/
decode-accumulate kernels) stay in base/ops.py + native/reduce.cpp.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from kungfu_tpu import knobs
from kungfu_tpu.base.dtype import DType
from kungfu_tpu.base.ops import QWire, decode_wire
from kungfu_tpu.base.workspace import Workspace
from kungfu_tpu.telemetry import log
from kungfu_tpu.utils.pool import get_buffer_pool

# Wire codec (ISSUE 5 tentpole): f32 allreduce payloads travel the
# transport as bf16/f16 while every reduce step accumulates into the f32
# buffer. Like KF_CONFIG_ALGO this is a cluster-agreed runtime knob (it
# decides message SIZES, so a disagreeing peer would read short/long
# frames) — fail-fast enforced by check_knob_consensus at session start.
# `auto` currently resolves to bf16 for eligible payloads (the TPU-native
# format: f32-identical exponent range, so no overflow surprises); it is
# a distinct mode so later heuristics (payload- or link-aware) can slot
# in without an env change.
#
# ISSUE 20 grows the table with block-scaled int8/int4 (one f32 pow2
# absmax scale per KF_WIRE_BLOCK elements, error-feedback residuals on
# the segmented paths so per-step rounding telescopes). Same consensus
# discipline: the mode AND the block size decide message byte counts.
WIRE_MODES = ("off", "bf16", "f16", "auto", "int8", "int4")

WIRE_DTYPE = {"bf16": DType.BF16, "f16": DType.F16, "auto": DType.BF16}

_WIRE_Q_BITS = {"int8": 8, "int4": 4}


def wire_override() -> str:
    """Parse KF_CONFIG_WIRE (read per session epoch, not import time).
    The registry's strict choice parser raises on a typo and resolves
    unset/empty to "off"."""
    return knobs.get("KF_CONFIG_WIRE")


class DeferredDecode:
    """Handle to a compressed segmented walk's all-gather wire buffer,
    returned instead of the walk-end f32 decode when the caller asked to
    defer it (`_allreduce_ws(defer_decode=True)`). The fused pipeline's
    unpacker decodes straight from this buffer into each member's recv —
    fusing decode with unpack saves one full f32 pass over the bucket on
    the hot path. Call `decode_into(dst, begin, end)` per member, then
    `close()` exactly once to return the buffer to the pool."""

    __slots__ = ("wire", "_buf", "_arr")

    def __init__(self, wire: DType, buf, arr: np.ndarray):
        self.wire = wire
        self._buf = buf
        self._arr = arr

    def decode_into(self, dst: np.ndarray, begin: int, end: int) -> None:
        seg = self._arr[begin:end]
        if dst.flags["C_CONTIGUOUS"]:
            decode_wire(dst, seg, self.wire)
        else:
            tmp = np.empty(end - begin, np.float32)
            decode_wire(tmp, seg, self.wire)
            np.copyto(dst, tmp)

    def close(self) -> None:
        if self._buf is not None:
            get_buffer_pool().put(self._buf)
            self._buf = None


class WireCodec:
    """Codec-policy mixin for HostSession: resolves the RUNNING wire
    mode (config + lockstep adaptive votes) and decides per workspace
    whether a walk compresses or bypasses. Relies on session state
    (`wire_mode`, `_candidates`, `adaptive`, `_tree_override`,
    `WIRE_MIN_BYTES`) owned by the facade's constructor."""

    # Codec floor: encoding pays two passes (encode + decode) to halve
    # the wire bytes, which only wins once the payload dwarfs the fixed
    # per-walk costs; tiny control collectives also stay exact this way.
    # Cluster-agreed like SEGMENT_MIN_BYTES (it decides message sizes).
    WIRE_MIN_BYTES = int(knobs.get("KF_CONFIG_WIRE_MIN_BYTES"))

    # Elements per absmax scale block of the quantized codec. Cluster-
    # agreed (KF701: in engine_knobs AND consensus=True) — it decides
    # the byte length of every int8/int4 message.
    WIRE_BLOCK = int(knobs.get("KF_WIRE_BLOCK"))

    def _active_wire_mode(self) -> str:
        """The RUNNING codec mode: the active adaptive candidate's wire
        member, or the configured mode under a set_tree override (an
        explicit forest replaces the graphs, not the codec)."""
        if self._tree_override:
            return self.wire_mode
        return self._candidates[self.adaptive.active][1]

    def active_wire_mode(self) -> str:
        """Public accessor of the RUNNING codec mode — what `info links`
        renders and the precision policy compares its target against."""
        return self._active_wire_mode()

    def _codec_bypass(self, reason: str, w: Workspace) -> None:
        """Audit (once per (reason, dtype) per session epoch) that a
        workspace bypassed an enabled codec — exact semantics preserved
        for consensus lanes, variance probes and tiny residuals."""
        key = (reason, w.send.dtype.str)
        if key in self._codec_bypass_seen:
            return
        self._codec_bypass_seen.add(key)
        from kungfu_tpu.telemetry import audit as _audit

        _audit.record_event(
            "wire_codec_bypass",
            peer=str(self.self_id),
            reason=reason,
            dtype=w.send.dtype.str,
            name=w.name,
            nbytes=int(w.recv.nbytes),
        )

    def _wire_codec_for(self, w: Workspace):
        """Codec decision for one allreduce workspace: a ``DType``
        (2-byte codec), a :class:`QWire` (block-scaled int8/int4), or
        None (raw).

        MUST depend only on cluster-agreed inputs — the resolved wire
        mode (env + lockstep adaptive votes) and workspace properties
        identical on every peer — because it decides the byte count of
        every message in the walk. Non-f32 payloads (consensus lanes,
        int gradients) and sub-WIRE_MIN_BYTES residuals bypass with an
        audit event, never an error. An UNKNOWN mode string on this
        lenient path (the strict knob parser can't be the only guard:
        ``wire_mode`` and the candidate table are plain session state a
        version-skewed vote or embedder could corrupt) warns loudly and
        runs exact — never silently quantize."""
        mode = self._active_wire_mode()
        if mode != self._ef_mode:
            # any precision flip (adaptive vote, candidate toggle,
            # rollback) invalidates carried error-feedback residuals:
            # they measure the OLD codec's rounding
            self._flush_residuals(f"wire mode {self._ef_mode!r} -> {mode!r}")
            self._ef_mode = mode
        if mode == "off":
            return None
        if w.send.dtype != np.float32:
            self._codec_bypass("non_f32", w)
            return None
        if w.recv.nbytes < self.WIRE_MIN_BYTES:
            self._codec_bypass("below_min_bytes", w)
            return None
        bits = _WIRE_Q_BITS.get(mode)
        if bits is not None:
            return QWire(bits, self.WIRE_BLOCK)
        codec = WIRE_DTYPE.get(mode)
        if codec is None:
            if mode not in self._unknown_wire_warned:
                self._unknown_wire_warned.add(mode)
                log.warning(
                    "wire codec: unknown mode %r reached the running "
                    "session — running EXACT (no compression). Valid "
                    "modes: %s", mode, ", ".join(WIRE_MODES),
                )
            self._codec_bypass("unknown_mode", w)
            return None
        return codec

    # --- error-feedback residual store (quantized codec only) ----------
    #
    # One full-size f32 residual per workspace name: the un-transmitted
    # remainder of the last quantized send, added back into the next
    # send so rounding telescopes (sum of decodes = sum of inputs +
    # r_first - r_last) instead of compounding. Lifecycle: lazily
    # zeroed; FLUSHED on any wire-mode change and on re-plan adoption
    # (segment ownership moved — a residual computed against the old
    # bounds would correct the wrong slice); dies with the session on
    # elastic resize. ZeRO's per-shard residuals live in zero.py but
    # register a flush listener here so every flush reaches them too.

    def _ef_residual(self, key: str, size: int) -> np.ndarray:
        r = self._ef_store.get(key)
        if r is None or r.size != size:
            r = np.zeros(size, np.float32)
            self._ef_store[key] = r
        return r

    def _flush_residuals(self, reason: str) -> None:
        if self._ef_store:
            log.debug("wire codec: flushing %d error-feedback residuals (%s)",
                      len(self._ef_store), reason)
        self._ef_store.clear()
        for cb in tuple(self._ef_flush_listeners):
            try:
                cb(reason)
            except Exception as e:  # noqa: BLE001 - flush must reach the rest
                log.warning("wire codec: residual flush listener failed: %s", e)

    def add_ef_flush_listener(self, cb) -> None:
        """Register `cb(reason)` to run on every residual flush — the
        hook ZeRO uses to reset its per-shard residuals in lockstep
        with the session store."""
        self._ef_flush_listeners.append(cb)
