"""Adaptive strategy control: throughput stats, interference detection,
consensus strategy switching.

Capability parity: the reference's adaptation subsystem —
- per-strategy throughput stats updated by monitored collectives
  (srcs/go/kungfu/session/monitoring.go:15-35, CalcStats/LogStats in
  session/adaptiveStrategies.go:18-55);
- interference detection: when the monitored throughput falls below
  0.8x the reference window, peers vote via an allreduce and, on a
  cluster-wide majority, everyone advances to the next strategy in the
  same deterministic order (adaptiveStrategies.go:61-121).

TPU mapping: this governs the HOST plane (DCN collectives between
TPU-VM hosts, where congestion/interference is real). The ICI plane is
compiled; its "strategy" is the mesh layout, switched only by
recompilation, so adaptation operates on the host engine exactly where
the reference adapts its TCP graphs.
"""

from __future__ import annotations

import dataclasses
import time
from typing import List, Optional

INTERFERENCE_THRESHOLD = 0.8  # parity: reference's 0.8x window check
WARMUP_SAMPLES = 8
EMA_DECAY = 0.7


@dataclasses.dataclass
class StrategyStat:
    """Throughput accounting for one active strategy list."""

    total_bytes: int = 0
    total_seconds: float = 0.0
    count: int = 0
    ema_throughput: float = 0.0  # bytes/sec
    best_throughput: float = 0.0  # reference window

    def update(self, nbytes: int, seconds: float) -> None:
        if seconds <= 0:
            return
        self.total_bytes += nbytes
        self.total_seconds += seconds
        self.count += 1
        tp = nbytes / seconds
        if self.ema_throughput == 0.0:
            self.ema_throughput = tp
        else:
            self.ema_throughput = (
                EMA_DECAY * self.ema_throughput + (1 - EMA_DECAY) * tp
            )
        if self.count >= WARMUP_SAMPLES // 2:
            self.best_throughput = max(self.best_throughput, self.ema_throughput)

    def suspect_interference(self) -> bool:
        """Local suspicion: warmed up AND ema below 0.8x the best window."""
        return (
            self.count >= WARMUP_SAMPLES
            and self.best_throughput > 0
            and self.ema_throughput < INTERFERENCE_THRESHOLD * self.best_throughput
        )

    def summary(self) -> dict:
        avg = self.total_bytes / self.total_seconds if self.total_seconds else 0.0
        return {
            "count": self.count,
            "total_bytes": self.total_bytes,
            "avg_throughput": avg,
            "ema_throughput": self.ema_throughput,
            "best_throughput": self.best_throughput,
        }


class AdaptiveState:
    """Tracks stats per candidate strategy and the active index.

    The candidate order is identical on every peer (derived from the
    cluster), so a majority vote can switch everyone in lockstep without
    exchanging the choice itself — only the vote count.
    """

    def __init__(self, n_candidates: int, names: Optional[List[str]] = None):
        self.n_candidates = max(1, n_candidates)
        self.active = 0
        # display names, e.g. "RING_SEGMENTED/bf16": candidates are
        # (strategy, wire-codec) pairs since the codec joined the
        # adaptive set — stats summaries label them for operators
        self.names: List[str] = list(names or [])[: self.n_candidates]
        self.stats: List[StrategyStat] = [StrategyStat() for _ in range(self.n_candidates)]
        self.switch_count = 0
        self.last_switch_time: Optional[float] = None

    @property
    def current(self) -> StrategyStat:
        return self.stats[self.active]

    def advance(self) -> int:
        """Move to the next candidate (wrapping), reset its window."""
        self.active = (self.active + 1) % self.n_candidates
        self.stats[self.active] = StrategyStat()
        self.switch_count += 1
        self.last_switch_time = time.monotonic()
        return self.active

    def summary(self) -> dict:
        stats = []
        for i, s in enumerate(self.stats):
            d = s.summary()
            if i < len(self.names):
                d["candidate"] = self.names[i]
            stats.append(d)
        return {
            "active": self.active,
            "switches": self.switch_count,
            "stats": stats,
        }
