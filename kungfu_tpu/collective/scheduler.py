"""Async collective scheduler: readiness-ordered, backprop-overlapped
group allreduce (ISSUE 10 tentpole).

The synchronous step loop launches `group_all_reduce` at step end, so
the engine idles through the whole backprop and then burns a serial
walk (BENCH_HOST_r06/r07: the bert walk's 43s→27s came entirely from
engine work, none from overlap). The reference's L4 NCCL scheduler
(PAPER.md §1) orders collectives by gradient readiness and overlaps
them with backprop; arXiv:1810.11112 measures that overlap as the
dominant scale lever. This is the host-plane equivalent:

- callers :meth:`~CollectiveScheduler.submit` one workspace per tensor
  as its gradient becomes ready and :meth:`~CollectiveScheduler.flush`
  once per step;
- a background launcher assembles the SAME deterministic buckets the
  fused pipeline builds (pipeline.py `_make_buckets`, driven by
  ``KF_CONFIG_GROUP_BUCKET_BYTES``/``KF_CONFIG_GROUP_FUSE_MIN``) and
  launches each bucket's pack → walk → unpack as soon as its members
  arrived — while the caller is still producing later gradients.

**Ordering guarantee.** Readiness order is local (peers' backprops
interleave differently), but peers must walk identical bucket
sequences. So the launch order is negotiated ONCE per session epoch:
the first round's submission order (shaped by the optional ``priority``
argument) becomes the **registered tensor order**, the bucket plan is
derived from it exactly like the synchronous path, and a consensus
assert (the `check_knob_consensus` machinery: `_bytes_agree` over the
knob-independent star walk) verifies every peer registered the
identical ordered set — a diverging peer raises a named RuntimeError
instead of deadlocking on mismatched rendezvous names. After
registration, submissions may arrive in ANY order; buckets launch in
registered order as they complete, with walk names stamped by a round
counter so back-to-back rounds can never collide on the wire.

**Results are bit-identical to the synchronous path**: same bucket
membership, same pack layout, same walk engine, same unpack — only the
launch *time* moves (asserted by tests/test_scheduler.py at
np ∈ {2,3,4} on exact payloads under out-of-order submission).

**Epoch lifecycle.** The scheduler lives exactly as long as its
session: `Peer._update_to` calls `HostSession.close()` before swapping
sessions, which drains in-flight buckets (bounded) and cancels the
rest, so nothing from the old epoch keeps walking — or writing caller
buffers — once the new session exists. Adaptive votes apply at bucket
boundaries by construction: walks launch one at a time from the walker
thread and re-read the active (strategy, wire) candidate per workspace,
and every vote runs at a step boundary (after `flush()`), when no
bucket is in flight.

**Sharded (ZeRO-1) units** (ISSUE 11). A submission carrying a
``handler`` (a :class:`~kungfu_tpu.collective.zero.ShardedUpdateSession`)
registers as a *sharded* tensor: its buckets run
reduce-scatter → shard-optimizer-update → weight-all-gather → scatter
instead of allreduce → unpack, driven across a 4-stage pipeline
(launcher packs, walker reduce-scatters and updates, a dedicated
gatherer walks the weight all-gather, the unpacker scatters weights).
Completion splits in two: ``flush()`` returns once every sharded
bucket's SHARD has updated (gradients consumed — the step barrier),
while weight all-gathers keep walking and overlap the caller's
next-step compute; :meth:`~CollectiveScheduler.wait_gather` is the
barrier for those (call it before the next forward consumes the
params). The submission kind is part of the registered identity and the
registration consensus, and sharded walk names carry their own
round-stamped wire names (``:zrs:r{n}`` / ``:zag:r{n}``), so sharded
and allreduce traffic of adjacent rounds can never collide.

Telemetry: `kungfu_scheduler_queued_buckets` /
`kungfu_scheduler_overlap_seconds_total` /
`kungfu_scheduler_flush_wait_seconds` plus `sched.pack` / `sched.walk`
/ `sched.gather` / `sched.unpack` / `sched.flush` spans
(docs/telemetry.md).
"""

from __future__ import annotations

import threading
import time
import weakref
from typing import Dict, List, Optional, Tuple

import numpy as np

from kungfu_tpu import knobs
from kungfu_tpu.base.workspace import Workspace
from kungfu_tpu.telemetry import config as tconfig
from kungfu_tpu.telemetry import metrics as tmetrics
from kungfu_tpu.telemetry import steptrace
from kungfu_tpu.utils import trace
from kungfu_tpu.utils.handoff import HandoffQueue
from kungfu_tpu.utils.stall import stall_detect

# kfcheck KF303: every thread this module starts must be declared here
# (the abort-protocol joinable set) — close() joins exactly these, so a
# future stage cannot silently outlive a session epoch.
_KF_JOINABLE_THREADS = (
    "kf-sched-launch", "kf-sched-walk", "kf-sched-gather", "kf-sched-unpack",
)

# registered-tensor identity: rendezvous-relevant properties only (the
# consensus digest is built from these, so any cross-peer divergence in
# name, length, dtype, op or submission KIND — "ar" allreduce vs "zero"
# sharded-update, which walk entirely different dataflows — is caught
# at registration)
_Key = Tuple[str, int, str, int, str]


def _key_of(w: Workspace, kind: str = "ar") -> _Key:
    return (w.name, int(w.send.size), w.send.dtype.str, int(w.op), kind)


class SchedulerClosed(RuntimeError):
    """Raised by submit/flush after the session epoch ended (resize or
    explicit close): the caller must fetch the NEW session's scheduler."""


class _Unit:
    """One launch unit of the negotiated plan: a fused allreduce bucket
    (>= the fusion threshold, same dtype/op, <= the bucket byte cap), a
    single workspace, or a sharded-update (ZeRO-1) bucket whose layout
    the registered handler owns. Derived purely from the registered
    order, the cluster-agreed knobs and the handler's deterministic
    bucket layout, so every peer computes the identical plan."""

    __slots__ = ("index", "keys", "fused", "kind", "zindex")

    def __init__(self, index: int, keys: List[_Key], fused: bool,
                 kind: str = "ar", zindex: int = -1):
        self.index = index
        self.keys = keys
        self.fused = fused
        self.kind = kind  # "ar" | "zero"
        self.zindex = zindex  # handler bucket index for zero units


class CollectiveScheduler:
    """Per-session background scheduler for asynchronous group
    allreduce. Thread-safe submit; one flush caller per round."""

    def __init__(self, sess):
        self.sess = sess
        self.queue_depth = max(1, int(knobs.get("KF_CONFIG_ASYNC_QUEUE")))
        # step plane (ISSUE 13): the session epoch every timeline and
        # step-stamped span carries — the CLUSTER version, identical on
        # every peer of the epoch, so the aggregator can group timelines
        # cross-peer (a local session counter would diverge for joiners)
        self.epoch_id = int(getattr(sess, "cluster_version", 0))
        # current round's step recorder (None: sampled out / round 0 /
        # plane off); per-unit metadata derived from the plan so lanes
        # can be labelled without touching workspaces off-thread
        self._steprec: Optional[steptrace.StepRecorder] = None
        self._key_unit: Dict[_Key, int] = {}
        self._unit_meta: Dict[int, Tuple[str, str, int, int]] = {}
        self._cond = threading.Condition()
        self._abort = threading.Event()
        self._errors: List[BaseException] = []
        self._closed = False
        # registration (per session epoch, negotiated at first flush)
        self._registry: Optional[List[_Key]] = None
        self._known: set = set()
        self._plan: List[_Unit] = []
        # (prio, seq, workspace, kind) of pre-registration submissions
        self._first_round: List[Tuple[int, int, Workspace, str]] = []
        # the sharded-update handler (ZeRO-1): one per scheduler epoch,
        # bound by the first submit that carries it; owns the sharded
        # buckets' layout, buffers and optimizer state
        self._handler = None
        # per-round state (all under _cond)
        self._round = 0
        self._pending: Dict[_Key, Workspace] = {}
        self._submitted: set = set()
        self._next_unit = 0
        # flush barrier: units whose GRADIENT work finished this round —
        # allreduce units at unpack, sharded units once their shard
        # updated (their weight all-gather keeps walking past flush)
        self._grad_done = 0
        # sharded units whose weight all-gather + scatter has not landed
        # yet (spans round boundaries; wait_gather's barrier)
        self._gather_outstanding = 0
        self._busy_s = 0.0  # pack+walk+gather+unpack seconds this round
        self._queued = 0  # units packed but not yet unpacked (gauge)
        self._inflight_bytes = 0  # payload bytes of those queued units
        # lifetime stats (for the bench OVERLAP report)
        self._stat = {
            "rounds": 0, "units": 0, "buckets": 0, "zero_units": 0,
            "flush_wait_s": 0.0, "busy_s": 0.0, "overlap_s": 0.0,
        }
        self._threads: List[threading.Thread] = []
        self._walkq = HandoffQueue(maxsize=self.queue_depth, abort=self._abort)
        self._gatherq = HandoffQueue(maxsize=1, abort=self._abort)
        self._unpackq = HandoffQueue(maxsize=1, abort=self._abort)
        if tconfig.metrics_enabled():
            self._queued_gauge = tmetrics.gauge(
                "kungfu_scheduler_queued_buckets",
                "Async-scheduler launch units currently packed or "
                "walking (not yet unpacked)",
            )
            self._overlap_ctr = tmetrics.counter(
                "kungfu_scheduler_overlap_seconds_total",
                "Scheduler engine-busy seconds that overlapped caller "
                "compute (busy time minus flush wait, per round)",
            )
            self._flush_wait_ctr = tmetrics.counter(
                "kungfu_scheduler_flush_wait_seconds",
                "Seconds flush() blocked waiting for in-flight buckets",
            )
        else:
            self._queued_gauge = None
            self._overlap_ctr = None
            self._flush_wait_ctr = None
        # memory plane (ISSUE 17): in-flight unit payloads are the
        # scheduler's share of RSS. Weakref — the registry must never
        # pin a closed scheduler epoch past its resize.
        try:
            from kungfu_tpu.telemetry import memory as _tmem

            def _acct(ref=weakref.ref(self)) -> Optional[int]:
                sched = ref()
                return (
                    sched.inflight_bytes() if sched is not None else None
                )

            _tmem.register_accountant(
                f"scheduler:e{self.epoch_id}", "sched_inflight", _acct
            )
        # kfcheck: disable=KF400 — byte accounting is best-effort;
        # it must never kill the engine
        except Exception:  # noqa: BLE001
            pass

    def inflight_bytes(self) -> int:
        """Payload bytes of units packed but not yet unpacked (the
        memory plane's `sched_inflight` bucket)."""
        with self._cond:
            return self._inflight_bytes

    def _unit_nbytes(self, unit) -> int:
        meta = self._unit_meta.get(unit.index)
        return meta[2] if meta else 0

    # ------------------------------------------------------------------
    # public API
    # ------------------------------------------------------------------

    def submit(self, w: Workspace, priority: Optional[int] = None,
               handler=None) -> None:
        """Hand one tensor's workspace to the scheduler as it becomes
        ready. Thread-safe; returns immediately (the walk happens on the
        scheduler threads). `w.recv` must stay valid until the round's
        `flush()` returns, and `w.name` must be STABLE across rounds —
        it is this tensor's registered identity (the scheduler stamps
        its own round counter into wire names).

        `priority` shapes the negotiated launch order during the FIRST
        round only (lower launches earlier, default = arrival order);
        after registration the cluster-wide registered order governs and
        the argument is ignored.

        `handler` (a ShardedUpdateSession) marks this tensor as a
        sharded-update (ZeRO-1) gradient: its bucket runs
        reduce-scatter → shard update → weight all-gather instead of an
        allreduce, and `w.recv` is NOT written (the deliverable is the
        updated params, scattered by the handler). The kind is part of
        the registered identity — pass the handler on EVERY submit of a
        sharded tensor."""
        if w.is_empty:
            return
        kind = "ar" if handler is None else "zero"
        key = _key_of(w, kind)
        with self._cond:
            self._raise_if_dead_locked()
            if handler is not None:
                if self._handler is None:
                    self._handler = handler
                elif self._handler is not handler:
                    raise ValueError(
                        "a scheduler epoch supports ONE sharded-update "
                        "handler — rebuild the ShardedUpdateSession "
                        "instead of mixing two"
                    )
            if self._registry is None:
                seq = len(self._first_round)
                prio = seq if priority is None else int(priority)
                self._first_round.append((prio, seq, w, kind))
                return
            if key not in self._known:
                raise ValueError(
                    f"submit of unregistered tensor {key[0]!r} "
                    f"(size={key[1]}, dtype={key[2]}, op={key[3]}, "
                    f"kind={key[4]}) — the registered set is negotiated "
                    "at the first flush and fixed for the session epoch; "
                    "resize to change it"
                )
            if key in self._submitted:
                raise ValueError(
                    f"tensor {key[0]!r} submitted twice in round "
                    f"{self._round} — call flush() between rounds"
                )
            self._submitted.add(key)
            self._pending[key] = w
            # step plane: the round's recorder begins at its FIRST
            # submission (subject to KF_TELEMETRY_SPAN_SAMPLE — a
            # sampled-out round allocates nothing and every note below
            # is a no-op via the None guard)
            if len(self._submitted) == 1 and self._plan:
                self._steprec = steptrace.get_store().begin_step(
                    self.epoch_id, self._round
                )
            rec = self._steprec
            if rec is not None:
                ui = self._key_unit.get(key)
                if ui is not None:
                    kind, label, nbytes, nmem = self._unit_meta[ui]
                    rec.bucket(ui, kind, label, nbytes, nmem).note_submit(
                        time.perf_counter() * 1e6
                    )
            self._cond.notify_all()

    def flush(self, timeout: Optional[float] = None) -> None:
        """Block until every workspace submitted this round has been
        reduced and scattered back (`w.recv` holds the result), then
        advance the round. Re-raises the scheduler's REAL error (walk
        failure, abort) if one occurred. The first flush of a session
        epoch performs the registration handshake (see module doc)."""
        t0 = time.perf_counter()
        with trace.span("sched.flush"), stall_detect("scheduler.flush"):
            with self._cond:
                # a dead handle reports its real state even on would-be
                # no-op flushes: a cleanly-flushed round followed by a
                # resize must surface SchedulerClosed, not silence
                self._raise_if_dead_locked()
                if self._registry is None and not self._first_round:
                    # nothing was ever submitted: a defensive flush must
                    # NOT register an empty set (that would freeze the
                    # epoch's registry as {} and poison every later
                    # submit) — true no-op
                    return
                if self._registry is not None and not self._submitted:
                    # clean round boundary, zero submissions: no-op —
                    # "every registered tensor exactly once per round"
                    # applies to rounds, and an empty flush isn't one
                    return
            if self._registry is None:
                self._register()
            with self._cond:
                # a dead scheduler reports its REAL state (error /
                # closed epoch) before complaining about round shape
                self._raise_if_dead_locked()
                missing = self._known - self._submitted
                if missing:
                    names = sorted(k[0] for k in missing)[:8]
                    raise RuntimeError(
                        f"flush() with {len(missing)} registered tensors "
                        f"not submitted this round (e.g. {names}) — every "
                        "registered tensor must be submitted exactly once "
                        "per round"
                    )
            if timeout is None:
                timeout = self.sess.timeout * max(1, len(self._plan))
            deadline = time.monotonic() + timeout
            with self._cond:
                while True:
                    if self._errors:
                        raise self._errors[0]
                    if self._closed:
                        raise SchedulerClosed(
                            "collective scheduler closed (session epoch "
                            "ended) during flush"
                        )
                    if self._grad_done >= len(self._plan):
                        break
                    if time.monotonic() >= deadline:
                        self._abort.set()
                        raise TimeoutError(
                            f"scheduler flush timed out: "
                            f"{self._grad_done}/{len(self._plan)} units "
                            f"done in round {self._round}"
                        )
                    self._cond.wait(0.2)
                # advance the round (sharded units' weight all-gathers
                # may still be walking — wait_gather is their barrier;
                # round-stamped wire names keep them collision-free)
                wait = time.perf_counter() - t0
                busy = self._busy_s
                self._round += 1
                self._pending.clear()
                self._submitted.clear()
                self._next_unit = 0
                self._grad_done = 0
                self._busy_s = 0.0
                self._stat["rounds"] += 1
                self._stat["flush_wait_s"] += wait
                self._stat["busy_s"] += busy
                self._stat["overlap_s"] += max(0.0, busy - wait)
                # seal the step timeline (the ring holds the recorder,
                # so a ZeRO gather tail landing after this still writes
                # its lane — rendered at export time)
                rec, self._steprec = self._steprec, None
                if rec is not None:
                    rec.finish(flush_wait_s=wait, busy_s=busy)
                self._cond.notify_all()
        if self._flush_wait_ctr is not None:
            self._flush_wait_ctr.inc(wait)
        if self._overlap_ctr is not None:
            self._overlap_ctr.inc(max(0.0, busy - wait))

    def round_index(self) -> int:
        """The current (not-yet-flushed) round number. A submission
        made now belongs to this round; pair it with
        :meth:`flush_round`."""
        with self._cond:
            return self._round

    def flush_round(self, round_index: Optional[int],
                    timeout: Optional[float] = None) -> None:
        """Flush only if round `round_index` has not been flushed yet —
        the idempotent form behind AsyncGroupResult.wait(): several
        handles of one round each call this, the first flushes, the
        rest observe the advanced round and return. `None` flushes
        unconditionally."""
        if round_index is not None:
            with self._cond:
                if self._round > round_index:
                    return
        self.flush(timeout=timeout)

    def wait_gather(self, timeout: Optional[float] = None) -> None:
        """Barrier for the sharded units' weight all-gathers (ISSUE 11):
        block until every in-flight gather has walked and its weights
        have been scattered back. ``flush()`` deliberately does NOT wait
        for these — they overlap the caller's next-step compute the way
        gradient buckets overlap backward — so call this before the
        next forward consumes the params. No-op when nothing sharded is
        in flight; re-raises the scheduler's real error like flush."""
        if timeout is None:
            timeout = self.sess.timeout * max(1, len(self._plan))
        deadline = time.monotonic() + timeout
        with trace.span("sched.wait_gather"), stall_detect("scheduler.wait_gather"):
            with self._cond:
                while True:
                    if self._errors:
                        raise self._errors[0]
                    if self._gather_outstanding == 0:
                        return
                    if self._closed:
                        raise SchedulerClosed(
                            "collective scheduler closed (session epoch "
                            "ended) with weight all-gathers in flight — "
                            "the resize drained or cancelled them; "
                            "restore params via the elastic state sync"
                        )
                    if time.monotonic() >= deadline:
                        self._abort.set()
                        raise TimeoutError(
                            f"wait_gather timed out with "
                            f"{self._gather_outstanding} weight "
                            "all-gathers in flight"
                        )
                    self._cond.wait(0.2)

    def stats(self) -> dict:
        """Lifetime scheduler stats (bench OVERLAP report): rounds,
        units/buckets walked, flush-wait vs engine-busy seconds and the
        overlapped share."""
        with self._cond:
            out = dict(self._stat)
        busy = out["busy_s"]
        out["overlap_frac"] = out["overlap_s"] / busy if busy > 0 else 0.0
        return out

    def close(self, timeout: float = 30.0) -> None:
        """End the scheduler: drain in-flight units (bounded by
        `timeout`), cancel everything not yet launched, join the worker
        threads. Idempotent; called by `HostSession.close()` on every
        session swap (elastic resize) and at peer stop. Pending
        workspaces that never launched are dropped — the new epoch's
        caller resubmits against the new session."""
        with self._cond:
            if self._closed:
                started = False
            else:
                self._closed = True
                started = bool(self._threads)
            self._cond.notify_all()
        if not started:
            return
        deadline = time.monotonic() + max(1.0, timeout)
        for t in self._threads:
            t.join(max(0.1, deadline - time.monotonic()))
        if any(t.is_alive() for t in self._threads):
            # drain exceeded its budget: hard-cancel (in-flight walks
            # observe the abort before mutating caller buffers) and give
            # the threads a short grace to unwind
            self._abort.set()
            for t in self._threads:
                t.join(5.0)
        with self._cond:
            self._cond.notify_all()

    # ------------------------------------------------------------------
    # registration (once per session epoch)
    # ------------------------------------------------------------------

    def _register(self) -> None:
        """First flush: freeze the submission order into the registered
        tensor order, consensus-assert it across peers, derive the
        bucket plan, and start the worker threads."""
        with self._cond:
            self._raise_if_dead_locked()
            if self._registry is not None:
                return
            snapshot = list(self._first_round)
            entries = sorted(snapshot, key=lambda e: (e[0], e[1]))
            registry = [_key_of(w, kd) for _, _, w, kd in entries]
            if len(set(registry)) != len(registry):
                dupes = sorted(
                    {k[0] for k in registry if registry.count(k) > 1}
                )[:4]
                raise ValueError(
                    f"duplicate tensors in first round: {dupes} — "
                    "registered names must be unique"
                )
            if any(k[4] == "zero" for k in registry) and self._handler is None:
                raise ValueError(
                    "sharded tensors registered without a sharded-update "
                    "handler — submit them through "
                    "ShardedUpdateSession.submit_grad"
                )
        # consensus OUTSIDE the lock: this runs real collectives on the
        # knob-independent star walk (check_knob_consensus machinery) —
        # the walk must not serialize behind the scheduler's own lock
        digest = ";".join(
            f"{n}:{s}:{d}:{o}:{kd}" for n, s, d, o, kd in registry
        ).encode()
        if not self.sess._bytes_agree(
            digest, ":sched:registry", self.sess._fixed_allreduce
        ):
            raise RuntimeError(
                "async scheduler registration diverged across peers: the "
                "first round's (name, size, dtype, op) submission order "
                "must be identical cluster-wide — it becomes the "
                "negotiated launch order (check tensor naming and "
                "per-rank model divergence)"
            )
        plan = self._build_plan(registry)
        known = set(registry)
        # step-plane lane metadata: pure function of the plan, computed
        # once so the submit hot path only does dict lookups
        key_unit: Dict[_Key, int] = {}
        unit_meta: Dict[int, Tuple[str, str, int, int]] = {}
        for u in plan:
            label = u.keys[0][0]
            if len(u.keys) > 1:
                label += f"+{len(u.keys) - 1}"
            nbytes = sum(
                k[1] * np.dtype(k[2]).itemsize for k in u.keys
            )
            unit_meta[u.index] = (u.kind, label, nbytes, len(u.keys))
            for k in u.keys:
                key_unit[k] = u.index
        with self._cond:
            # validate EVERYTHING before committing any state: raising
            # after self._registry is set but before the threads start
            # would leave a registered scheduler whose flush() waits on
            # workers that do not exist. Submissions that raced into the
            # (unlocked) consensus window are checked against the
            # registry they were not part of — a silently dropped
            # tensor would leave stale recv data behind a clean flush.
            pending: Dict[_Key, Workspace] = {}
            submitted: set = set()
            for _, _, w, kd in snapshot:
                pending[_key_of(w, kd)] = w
                submitted.add(_key_of(w, kd))
            for _, _, w, kd in self._first_round[len(snapshot):]:
                key = _key_of(w, kd)
                if key not in known:
                    raise ValueError(
                        f"tensor {key[0]!r} submitted during the "
                        "registration handshake but absent from the "
                        "negotiated set — quiesce submissions around "
                        "the first flush()"
                    )
                if key in submitted:
                    raise ValueError(
                        f"tensor {key[0]!r} submitted twice in the "
                        "registration round"
                    )
                pending[key] = w
                submitted.add(key)
            self._registry = registry
            self._known = known
            self._plan = plan
            self._key_unit = key_unit
            self._unit_meta = unit_meta
            self._pending.update(pending)
            self._submitted |= submitted
            self._first_round.clear()
            self._start_threads_locked()
            self._cond.notify_all()

    def _build_plan(self, registry: List[_Key]) -> List[_Unit]:
        """The synchronous path's grouping, expressed over registered
        indices: same-(dtype, op) runs of >= FUSE_MIN_TENSORS fuse into
        <= GROUP_BUCKET_BYTES buckets (pipeline._make_buckets' greedy
        order-preserving packing); smaller groups launch as singles.
        Sharded ("zero") tensors instead map onto the handler's OWN
        deterministic bucket layout — the handler holds their persistent
        buffers and shard state, so its layout is authoritative and is
        validated against the registered set. Pure function of
        (registry, cluster-agreed knobs, handler layout) — every peer
        derives the identical plan from the consensus-checked registry.
        Units launch ordered by their first member's registered index
        (deterministic, and readiness-shaped: early-registered =
        early-ready gradients launch first)."""
        sess = self.sess
        groups: Dict[Tuple[str, int], List[_Key]] = {}
        zero_keys: List[_Key] = []
        for key in registry:
            if key[4] == "zero":
                zero_keys.append(key)
            else:
                groups.setdefault((key[2], key[3]), []).append(key)
        units: List[_Unit] = []
        singles: List[_Key] = []
        for members in groups.values():
            if len(members) < sess.FUSE_MIN_TENSORS:
                singles.extend(members)
                continue
            # greedy order-preserving byte-cap packing (mirrors
            # pipeline._make_buckets, over keys instead of workspaces)
            cur: List[_Key] = []
            cur_bytes = 0
            isize = np.dtype(members[0][2]).itemsize
            for key in members:
                nbytes = key[1] * isize
                if cur and cur_bytes + nbytes > sess.GROUP_BUCKET_BYTES:
                    units.append(_Unit(len(units), cur, fused=True))
                    cur, cur_bytes = [], 0
                cur.append(key)
                cur_bytes += nbytes
            if cur:
                units.append(_Unit(len(units), cur, fused=True))
        for key in singles:
            units.append(_Unit(len(units), [key], fused=False))
        if zero_keys:
            for zi, keys in enumerate(self._handler.plan_units(zero_keys)):
                units.append(
                    _Unit(len(units), list(keys), fused=False,
                          kind="zero", zindex=zi)
                )
        pos = {k: i for i, k in enumerate(registry)}
        units.sort(key=lambda u: pos[u.keys[0]])
        for i, u in enumerate(units):
            u.index = i
        return units

    # ------------------------------------------------------------------
    # worker threads (the KF303 joinable set)
    # ------------------------------------------------------------------

    def _start_threads_locked(self) -> None:
        self._spawn_registered("kf-sched-launch", self._launch_loop)
        self._spawn_registered("kf-sched-walk", self._walk_loop)
        self._spawn_registered("kf-sched-gather", self._gather_loop)
        self._spawn_registered("kf-sched-unpack", self._unpack_loop)

    def _spawn_registered(self, name: str, target) -> None:
        """The ONLY place this module may construct a thread (kfcheck
        KF303): the name must be declared in `_KF_JOINABLE_THREADS` and
        the thread lands in `self._threads`, which `close()` joins — so
        a future stage cannot silently outlive the session epoch."""
        t = threading.Thread(target=target, name=name, daemon=True)
        self._threads.append(t)
        t.start()

    def _record_error(self, e: BaseException) -> None:
        with self._cond:
            self._errors.append(e)
            self._cond.notify_all()
        self._abort.set()

    def _raise_if_dead_locked(self) -> None:
        if self._errors:
            raise self._errors[0]
        if self._closed:
            raise SchedulerClosed(
                "collective scheduler closed (session epoch ended) — "
                "fetch the current session's scheduler and resubmit"
            )

    def _claim_next(self):
        """Launcher: block until the next unit in plan order has all its
        members submitted; returns (unit, members) or None to exit
        (close/abort). Launch STRICTLY in registered order — that is the
        cross-peer determinism contract."""
        with self._cond:
            while True:
                if self._abort.is_set():
                    return None
                if self._closed:
                    # drain semantics: stop LAUNCHING; in-flight units
                    # finish downstream
                    return None
                if self._next_unit < len(self._plan):
                    unit = self._plan[self._next_unit]
                    if all(k in self._pending for k in unit.keys):
                        self._next_unit += 1
                        members = [self._pending.pop(k) for k in unit.keys]
                        # the recorder captured here travels WITH the
                        # unit through the stage queues: a ZeRO gather
                        # tail lands after flush advanced the round, and
                        # must still write the round it belongs to
                        return unit, members, self._round, self._steprec
                self._cond.wait(0.2)

    def _launch_loop(self) -> None:
        try:
            while True:
                claimed = self._claim_next()
                if claimed is None:
                    return
                unit, members, rnd, rec = claimed
                lane = (
                    rec.bucket(unit.index, *self._unit_meta[unit.index])
                    if rec is not None else None
                )
                if lane is not None:
                    lane.note_launch(time.perf_counter() * 1e6)
                t0 = time.perf_counter()
                with trace.step_scope(self.epoch_id, rnd):
                    if unit.kind == "zero":
                        with trace.span("sched.pack", unit=unit.index):
                            # the handler packs into its persistent
                            # bucket staging and stamps its own round-
                            # qualified wire names (:zrs:/:zag:)
                            item = self._handler.pack(
                                unit.zindex, members, rnd
                            )
                    elif unit.fused:
                        with trace.span("sched.pack", unit=unit.index):
                            # round-stamped fused name: back-to-back
                            # rounds must not collide on the wire (a
                            # fast peer's round r+1 sends must never be
                            # consumed by a slow peer still walking
                            # round r)
                            item = self.sess._pack_bucket(
                                unit.index, members, name_prefix=f"r{rnd}:"
                            )
                    else:
                        w = members[0]
                        item = (
                            Workspace(
                                send=w.send, recv=w.recv, op=w.op,
                                name=f"{w.name}::as:r{rnd}",
                            ),
                            None, None, members,
                        )
                self._add_busy(
                    time.perf_counter() - t0, queued=+1,
                    nbytes=self._unit_nbytes(unit),
                )
                if not self._walkq.put((unit, lane, rnd, item)):
                    return  # aborted while the queue was full
        except BaseException as e:  # noqa: BLE001 - channeled to flush()
            self._record_error(e)
        finally:
            self._walkq.put(None)

    def _walk_loop(self) -> None:
        try:
            while True:
                got = self._walkq.get()
                if got is None:
                    return
                if self._abort.is_set():
                    continue  # drain to the sentinel
                unit, lane, rnd, item = got
                t0 = time.perf_counter()
                if unit.kind == "zero":
                    with trace.step_scope(self.epoch_id, rnd), \
                            trace.span("sched.walk", unit=unit.index), \
                            steptrace.walk_sink(lane):
                        item = self._handler.reduce_and_update(
                            item, cancel=self._abort
                        )
                    dt = time.perf_counter() - t0
                    if lane is not None:
                        lane.note_walk_span(t0 * 1e6, dt * 1e6)
                    self._add_busy(dt)
                    # the shard is updated: gradients are consumed, so
                    # this unit passes the flush barrier NOW — its
                    # weight all-gather continues downstream and
                    # overlaps the caller's next-step compute
                    with self._cond:
                        self._grad_done += 1
                        self._gather_outstanding += 1
                        self._cond.notify_all()
                    if not self._gatherq.put((unit, lane, rnd, item)):
                        return
                    continue
                with trace.step_scope(self.epoch_id, rnd), \
                        trace.span("sched.walk", unit=unit.index), \
                        steptrace.walk_sink(lane):
                    if unit.fused:
                        deferred = self.sess._allreduce_ws(
                            item[0], cancel=self._abort, defer_decode=True
                        )
                    else:
                        self.sess._allreduce_ws(item[0], cancel=self._abort)
                        deferred = None
                dt = time.perf_counter() - t0
                if lane is not None:
                    lane.note_walk_span(t0 * 1e6, dt * 1e6)
                self._add_busy(dt)
                if not self._gatherq.put((unit, lane, rnd, item + (deferred,))):
                    return
        except BaseException as e:  # noqa: BLE001 - channeled to flush()
            self._record_error(e)
        finally:
            self._gatherq.put(None)

    def _gather_loop(self) -> None:
        """Weight all-gather stage (sharded units only; allreduce units
        pass straight through so the launch→walk→gather→unpack chain
        stays linear and sentinel propagation stays single-producer)."""
        try:
            while True:
                got = self._gatherq.get()
                if got is None:
                    return
                if self._abort.is_set():
                    continue  # drain to the sentinel
                unit, lane, rnd, item = got
                if unit.kind == "zero":
                    t0 = time.perf_counter()
                    with trace.step_scope(self.epoch_id, rnd), \
                            trace.span("sched.gather", unit=unit.index), \
                            steptrace.walk_sink(lane, gather=True):
                        item = self._handler.gather(item, cancel=self._abort)
                    dt = time.perf_counter() - t0
                    if lane is not None:
                        lane.note_gather_span(t0 * 1e6, dt * 1e6)
                    self._add_busy(dt)
                if not self._unpackq.put((unit, lane, rnd, item)):
                    return
        except BaseException as e:  # noqa: BLE001 - channeled to flush()
            self._record_error(e)
        finally:
            self._unpackq.put(None)

    def _unpack_loop(self) -> None:
        try:
            while True:
                got = self._unpackq.get()
                if got is None:
                    return
                if self._abort.is_set():
                    continue  # aborted: must not touch caller buffers
                unit, lane, rnd, item = got
                t0 = time.perf_counter()
                if unit.kind == "zero":
                    with trace.step_scope(self.epoch_id, rnd), \
                            trace.span("sched.unpack", unit=unit.index):
                        self._handler.scatter(item, cancel=self._abort)
                    dt = time.perf_counter() - t0
                    if lane is not None:
                        lane.note_unpack(dt * 1e6)
                    self._add_busy(
                        dt, queued=-1, nbytes=-self._unit_nbytes(unit)
                    )
                    with self._cond:
                        self._gather_outstanding -= 1
                        self._stat["units"] += 1
                        self._stat["zero_units"] += 1
                        self._cond.notify_all()
                    continue
                with trace.step_scope(self.epoch_id, rnd):
                    if unit.fused:
                        with trace.span("sched.unpack", unit=unit.index):
                            self.sess._unpack_bucket(item, self._abort)
                    else:
                        # single: the walk wrote w.recv in place (the
                        # wrapper workspace shares the caller's
                        # buffers); nothing to scatter
                        deferred = item[4]
                        if deferred is not None:
                            deferred.close()
                dt = time.perf_counter() - t0
                if lane is not None:
                    lane.note_unpack(dt * 1e6)
                self._add_busy(
                    dt, queued=-1, nbytes=-self._unit_nbytes(unit)
                )
                with self._cond:
                    self._grad_done += 1
                    self._stat["units"] += 1
                    if unit.fused:
                        self._stat["buckets"] += 1
                    self._cond.notify_all()
        except BaseException as e:  # noqa: BLE001 - channeled to flush()
            self._record_error(e)

    def _add_busy(
        self, seconds: float, queued: int = 0, nbytes: int = 0
    ) -> None:
        with self._cond:
            self._busy_s += seconds
            if queued:
                self._queued += queued
                # in-flight payload accounting rides the same mutation
                # sites (pack=+, unpack=-) so the byte gauge can never
                # drift from the unit gauge
                self._inflight_bytes = max(0, self._inflight_bytes + nbytes)
            q = self._queued
        if queued and self._queued_gauge is not None:
            self._queued_gauge.set(q)
