"""Collective critical-path profiler and span sampling (ISSUE 6),
factored out of host_session.py (ISSUE 10 prerequisite refactor).

Everything here is walk *measurement*: per-walk wait/send accumulation
(:class:`WalkProfile`), the deterministic per-step span sampler
(:class:`SpanSampler`) and the process-global :class:`WalkProfiler`
that attributes every allreduce walk's wall time and scores it against
the link plane's bandwidth estimates. The walk engines (walks.py) feed
it; benchmarks and PolicyContext read it.
"""

from __future__ import annotations

import threading
from typing import Dict, Optional, Tuple

from kungfu_tpu.telemetry import config as tconfig
from kungfu_tpu.telemetry import metrics as tmetrics


class WalkProfile:
    """Per-walk critical-path accumulator (one walk = one thread running
    one segmented ring or one chunk's graph pair): seconds the walk
    thread spent blocked on receives and blocked on sends. Everything
    else — reduce/codec kernels, pack/unpack memcpys, Python overhead —
    is compute by construction (wall − wait − send), so the three
    fractions always sum to 1."""

    __slots__ = ("wait", "send")

    def __init__(self):
        self.wait = 0.0
        self.send = 0.0


class SpanSampler:
    """Deterministic walk sampler for per-step spans
    (KF_TELEMETRY_SPAN_SAMPLE): emits per-step spans for walk n iff the
    integer part of n*rate advances — exactly rate*N of any N walks,
    evenly spaced, identical across reruns (no RNG)."""

    __slots__ = ("rate", "_n", "_lock")

    def __init__(self, rate: float):
        self.rate = rate
        self._n = 0
        self._lock = threading.Lock()

    def sample(self) -> bool:
        if self.rate >= 1.0:
            return True
        if self.rate <= 0.0:
            return False
        with self._lock:
            self._n += 1
            n = self._n
        return int(n * self.rate) != int((n - 1) * self.rate)


class WalkProfiler:
    """Collective critical-path profiler (ISSUE 6 tentpole, part b).

    Aggregates every allreduce walk's wall-time attribution per
    (public collective, executing strategy): fractions of walk time
    spent wait-on-recv vs reduce/codec compute vs send-blocked, the
    achieved throughput against the 2·(k−1)/k·N bandwidth-optimal
    bound, and — when the link plane has a bandwidth estimate for the
    links the walk used — an **efficiency ratio**:

        efficiency = (2·(k−1)/k·N / link_bw) / wall
                   = optimal transfer time / achieved wall time

    1.0 means the walk moved its optimal byte volume at full measured
    link speed; the gap to 1.0 is the overhead the async scheduler and
    topology re-planner (ROADMAP items 2/5) have to harvest. Exported
    as ``kungfu_collective_efficiency_ratio`` gauges and
    ``kungfu_collective_walk_seconds_total{phase}`` counters; process-
    global (sessions are rebuilt every elastic epoch, the attribution
    series must survive them).

    Attribution caveats (documented, not bugs): on graph walks the
    pairwise receive path folds its in-place reduce into the timed
    receive block (the n-ary fan-in path separates them), and wire-mode
    fan-out encodes land in compute while the transport part of the
    fan-out lands in send. The fractions describe the walk *thread*;
    pool-thread work overlapped with a timed block is deliberately not
    double-counted.
    """

    _ALPHA = 0.2  # EWMA for the efficiency series, matches the link plane

    def __init__(self):
        self._lock = threading.Lock()
        self._acc: Dict[Tuple[str, str], dict] = {}

    def record(
        self,
        collective: str,
        strategy: str,
        k: int,
        payload_bytes: int,
        wall: float,
        wait: float,
        send: float,
        link_bw: Optional[float] = None,
    ) -> None:
        if wall <= 0.0 or k < 2 or payload_bytes <= 0:
            return
        # clamp measurement jitter so per-walk phases never exceed wall
        # (fractions must sum to 1 by construction)
        blocked = wait + send
        if blocked > wall:
            scale = wall / blocked
            wait *= scale
            send *= scale
        opt_bytes = 2.0 * (k - 1) / k * payload_bytes
        eff = None
        if link_bw is not None and link_bw > 0:
            eff = (opt_bytes / link_bw) / wall
        key = (collective, strategy)
        with self._lock:
            a = self._acc.get(key)
            if a is None:
                a = self._acc[key] = {
                    "walks": 0, "wall": 0.0, "wait": 0.0, "send": 0.0,
                    "payload_bytes": 0.0, "opt_bytes": 0.0,
                    "eff": None, "eff_samples": 0,
                    # EWMAs of RECENT walks, for signals(): the cumulative
                    # sums above describe the whole run (snapshot/bench),
                    # but an adaptation signal weighted by all-time sums
                    # goes inert after hours — a link that degrades at
                    # walk 50,000 must move the signal within ~10 walks,
                    # like the link plane's own bandwidth EWMA does
                    "wait_frac_ewma": None, "wall_ewma": None,
                }
            a["walks"] += 1
            a["wall"] += wall
            a["wait"] += wait
            a["send"] += send
            a["payload_bytes"] += payload_bytes
            a["opt_bytes"] += opt_bytes
            wf = wait / wall
            a["wait_frac_ewma"] = (
                wf if a["wait_frac_ewma"] is None
                else self._ALPHA * wf + (1.0 - self._ALPHA) * a["wait_frac_ewma"]
            )
            a["wall_ewma"] = (
                wall if a["wall_ewma"] is None
                else self._ALPHA * wall + (1.0 - self._ALPHA) * a["wall_ewma"]
            )
            if eff is not None:
                a["eff"] = (
                    eff if a["eff"] is None
                    else self._ALPHA * eff + (1.0 - self._ALPHA) * a["eff"]
                )
                a["eff_samples"] += 1
                ewma = a["eff"]
            else:
                ewma = None
        self._publish(collective, strategy, wall, wait, send, ewma)

    def _publish(self, collective, strategy, wall, wait, send, eff) -> None:
        # re-read the gate every walk (once per walk, not per step):
        # the profiler is process-global and outlives session epochs,
        # so a one-shot cache would freeze a pre-enable() answer forever
        if not tconfig.metrics_enabled():
            return
        phases = tmetrics.counter(
            "kungfu_collective_walk_seconds_total",
            "Walk wall time attributed to wait-on-recv / reduce+codec "
            "compute / send-blocked, per collective and strategy",
            ("collective", "strategy", "phase"),
        )
        phases.labels(collective, strategy, "wait").inc(wait)
        phases.labels(collective, strategy, "send").inc(send)
        phases.labels(collective, strategy, "compute").inc(
            max(wall - wait - send, 0.0)
        )
        if eff is not None:
            tmetrics.gauge(
                "kungfu_collective_efficiency_ratio",
                "EWMA of achieved walk time vs the 2(k-1)/k*N bandwidth-"
                "optimal bound at measured link speed (1.0 = optimal)",
                ("collective", "strategy"),
            ).labels(collective, strategy).set(eff)

    def snapshot(self) -> Dict[str, dict]:
        """Per-'collective/strategy' attribution summary; fractions sum
        to ~1.0 (compute is the residual)."""
        with self._lock:
            items = {k: dict(v) for k, v in self._acc.items()}
        out: Dict[str, dict] = {}
        for (collective, strategy), a in sorted(items.items()):
            wall = a["wall"]
            if wall <= 0:
                continue
            wait_f = a["wait"] / wall
            send_f = a["send"] / wall
            out[f"{collective}/{strategy}"] = {
                "walks": a["walks"],
                "wall_s": wall,
                "payload_bytes": a["payload_bytes"],
                "wait_frac": wait_f,
                "send_frac": send_f,
                "compute_frac": max(1.0 - wait_f - send_f, 0.0),
                "achieved_gib_s": a["opt_bytes"] / wall / (1 << 30),
                "efficiency": a["eff"],
                "efficiency_samples": a["eff_samples"],
            }
        return out

    def signals(self) -> Dict[str, float]:
        """Adaptation-facing summary for PolicyContext.metrics: the
        EWMA wait fraction and efficiency of RECENT walks, weighted
        across walk families by each family's recent wall time (a family
        that stopped running stops steering the signal; one that turned
        slow dominates it — all-time sums would go inert on long runs)."""
        with self._lock:
            # copy under the lock (like snapshot): the per-key dicts are
            # mutated by record() on walk threads, and the sums below
            # must read one consistent state
            items = [dict(v) for v in self._acc.values()]
        items = [a for a in items if a["wall_ewma"]]
        wall = sum(a["wall_ewma"] for a in items)
        if wall <= 0:
            return {}
        out: Dict[str, float] = {
            "collective/wait_frac": (
                sum(a["wall_ewma"] * a["wait_frac_ewma"] for a in items) / wall
            ),
        }
        eff_wall = sum(a["wall_ewma"] for a in items if a["eff"] is not None)
        if eff_wall > 0:
            out["collective/efficiency"] = (
                sum(
                    a["wall_ewma"] * a["eff"]
                    for a in items
                    if a["eff"] is not None
                )
                / eff_wall
            )
        return out

    def reset(self) -> None:
        with self._lock:
            self._acc.clear()


_walk_profiler = WalkProfiler()


def get_walk_profiler() -> WalkProfiler:
    return _walk_profiler
