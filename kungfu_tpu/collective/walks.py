"""Walk engines of the host collective plane, factored out of
host_session.py (ISSUE 10 prerequisite refactor).

Two walk families execute every allreduce:

- the bandwidth-optimal **segmented ring** (`_run_segmented`, ISSUE 4):
  (k-1)-step reduce-scatter + (k-1)-step all-gather, exactly
  2·(k-1)/k·N bytes per peer;
- chunk-striped **graph walks** (`_run_strategies` → `_run_graphs`,
  parity: runGraphs, session.go:231-299) over (reduce, bcast) pairs.

Both live on the :class:`WalkEngine` mixin of
:class:`~kungfu_tpu.collective.host_session.HostSession`, sharing the
receive protocol (`_recv_collective`), the wire-byte accounting and the
critical-path profiler feeds, so the fused pipeline (pipeline.py) and
the async scheduler (scheduler.py) drive the exact same engine.
"""

from __future__ import annotations

import threading
import time
from typing import List, Optional, Sequence

import numpy as np

from kungfu_tpu import knobs
from kungfu_tpu.base.dtype import DType
from kungfu_tpu.base.ops import (
    QWire,
    copy_segment,
    decode_accumulate_any,
    decode_wire_any,
    encode_wire_any,
    reduce_inplace,
    reduce_segment,
    transform_n,
)
from kungfu_tpu.base.ops import wire_nbytes as _wire_payload_nbytes
from kungfu_tpu.base.strategy import Strategy
from kungfu_tpu.base.workspace import Workspace, even_partition
from kungfu_tpu.collective import strategies as st
from kungfu_tpu.collective.codec import DeferredDecode
from kungfu_tpu.collective.profiler import WalkProfile, get_walk_profiler
from kungfu_tpu.telemetry import steptrace
from kungfu_tpu.plan import topology as topo
from kungfu_tpu.plan.graph import Graph
from kungfu_tpu.plan.peer import PeerID
from kungfu_tpu.transport.message import ConnType, Flags
from kungfu_tpu.utils import trace
from kungfu_tpu.utils.handoff import parallel_run as _par
from kungfu_tpu.utils.pool import get_buffer_pool, get_pool

# Chunking (parity: session.go chunkSize, but self-tuned): the optimal
# trades chunk-walk overhead (fewer, bigger chunks) against striping/
# pipelining (more, smaller chunks) and depends on host core count —
# concurrent chunk walks only pay when cores exist to run them; on a
# 1-core host every extra in-flight chunk is pure context-switch cost.
# KF_CONFIG_CHUNK_BYTES overrides the heuristic.
CHUNK_BYTES = int(knobs.get("KF_CONFIG_CHUNK_BYTES"))
_CHUNK_MIN = 1 << 20
_CHUNK_MAX = 32 << 20
DEFAULT_TIMEOUT = 120.0

# A/B algorithm override (benchmarks, operators): forces the engine onto
# one family regardless of the configured/AUTO strategy. Like every other
# engine knob it MUST agree cluster-wide (peers that resolved different
# algorithms would wait on each other's rendezvous names forever).
_ALGO_STRATEGY = {
    "": None,
    "auto": Strategy.AUTO,
    "tree": Strategy.BINARY_TREE,
    "segmented": Strategy.RING_SEGMENTED,
}


def algo_override() -> Optional[Strategy]:
    """Parse KF_CONFIG_ALGO (read per session epoch, not import time).
    The registry's strict choice parser raises on a typo — fail fast,
    not silently diverge the cluster."""
    return _ALGO_STRATEGY[knobs.get("KF_CONFIG_ALGO")]


def choose_chunk_bytes(total: int) -> int:
    """Chunk size for a `total`-byte collective: honour the env override,
    else ~8 chunks per collective, clamped to [1 MiB, 32 MiB].

    MUST depend only on cluster-agreed inputs (the workspace size): chunk
    workspaces are named '<name>[i/k]', so peers that computed different
    k would wait forever on each other's chunk names. That rules out
    os.cpu_count() here (heterogeneous hosts); measured on the 1-core
    box, 8 in-flight walks of >=1 MiB is within noise of the per-core
    optimum anyway."""
    if CHUNK_BYTES > 0:
        return CHUNK_BYTES
    c = total // 8
    return max(_CHUNK_MIN, min(_CHUNK_MAX, c))


def _buf(arr: np.ndarray):
    """Zero-copy byte view of a contiguous array (tobytes() fallback)."""
    try:
        return arr.data.cast("B")
    except (ValueError, TypeError, AttributeError):
        return arr.tobytes()


class WalkEngine:
    """Walk-engine mixin for HostSession: owns engine dispatch
    (`_allreduce_ws`), the segmented ring walk, the chunked graph walks
    and the shared receive/accounting/profiling plumbing. Relies on
    session state (peers, client, endpoint, timeout, candidates,
    adaptive, metrics handles) owned by the facade's constructor."""

    # Segmentation pays only when the per-step segment amortizes the
    # 2*(k-1) serialized message latencies; below this the rank-0 binary
    # tree fallback graphs win. MUST be cluster-agreed (it decides which
    # rendezvous names a peer waits on) — like CHUNK_BYTES, the default
    # is a constant and the env override must be set fleet-wide.
    SEGMENT_MIN_BYTES = int(knobs.get("KF_CONFIG_SEGMENT_MIN_BYTES"))

    # adopted two-level plan (ISSUE 19): set in lockstep by
    # adopt_replan, None = flat ring. Class default so the mixin is
    # safe before the facade constructor runs.
    _hier_plan = None
    # intra-leg wire-label override (see _run_hier): the two-level
    # walk's intra star legs run through _run_graphs by design, not as
    # a fallback — they must neither fire the segmented_fallback audit
    # nor pollute the RING_SEGMENTED/BINARY_TREE series.
    _wire_label_override: Optional[str] = None

    def _segmented_active(self) -> bool:
        return (
            not self._tree_override
            and self.size >= 2
            and self._candidates[self.adaptive.active][0]
            == Strategy.RING_SEGMENTED
        )

    def _allreduce_ws(
        self,
        w: Workspace,
        cancel: Optional[threading.Event] = None,
        defer_decode: bool = False,
    ) -> Optional[DeferredDecode]:
        """Engine dispatch for one allreduce workspace: the segmented
        ring walk when RING_SEGMENTED is active and the payload is worth
        segmenting, else chunked graph walks. `cancel` (group/window
        scope) propagates so an abandoned walk observes the caller's
        timeout before mutating recv buffers.

        With `defer_decode=True` a compressed segmented walk skips its
        walk-end decode and returns the wire buffer as a
        DeferredDecode (w.recv is then NOT fully written!); every
        other path returns None and w.recv holds the result."""
        wire = self._wire_codec_for(w)
        if self._segmented_active() and w.recv.nbytes >= self.SEGMENT_MIN_BYTES:
            if self._hier_plan is not None:
                self._run_hier(w, cancel=cancel, wire=wire)
                return None
            return self._run_segmented(
                w, cancel=cancel, wire=wire, defer_decode=defer_decode
            )
        self._run_strategies(w, self.global_strategies, cancel, wire=wire)
        return None

    # ------------------------------------------------------------------
    # accounting / profiling plumbing
    # ------------------------------------------------------------------

    def _count_wire(
        self, nbytes: int, strategy_label: str, codec: str = "off",
        raw_bytes: int = 0,
    ) -> None:
        if self._wire_ctr is not None and nbytes:
            self._wire_ctr.labels(self._wire_kind, strategy_label, codec).inc(nbytes)
        if (
            self._wire_saved_ctr is not None
            and codec != "off"
            and raw_bytes > nbytes
        ):
            self._wire_saved_ctr.labels(self._wire_kind, codec).inc(
                raw_bytes - nbytes
            )

    def _record_walk(
        self,
        strategy_label: str,
        k: int,
        payload_bytes: int,
        wall: float,
        prof: WalkProfile,
        dsts=None,
        sink=None,
    ) -> None:
        """Feed one finished allreduce walk to the process profiler,
        scored against the slowest link the walk used (all estimated
        links when `dsts` is None — graph walks fan out over many).
        `sink` (a captured steptrace sink, ISSUE 13) additionally gets
        the same attribution with the walk's dominant edge — the ring's
        successor when the walk names one, else the slowest estimated
        link — so the step timeline can name the blocking edge."""
        # (shared by the flat segmented walk, the graph walks and the
        # two-level walk's inter leg)
        link_dst = link_bw = None
        if self._links is not None:
            link_dst, link_bw = self._links.min_bandwidth(dsts)
        get_walk_profiler().record(
            self._wire_kind, strategy_label, k, payload_bytes,
            wall, prof.wait, prof.send, link_bw,
        )
        if sink is not None:
            edge = str(dsts[0]) if dsts else link_dst
            steptrace.note_walk(
                sink, strategy_label, wall, prof.wait, prof.send, edge
            )

    def _walk_label(self) -> str:
        """Strategy label for graph-walk wire accounting. Labels the
        graphs that actually EXECUTED: when RING_SEGMENTED is active but
        a payload fell below SEGMENT_MIN_BYTES (or a non-allreduce graph
        consumer — reduce/broadcast/gather — walked the strategy table's
        fallback pair), the walk ran the binary-tree fallback graphs and
        must not pollute the RING_SEGMENTED series (it is the one the
        optimality assertion reads). The first such fallback per session
        epoch is audited (`segmented_fallback`) so the by-design
        tree-under-segmented path is visible, not silent (ISSUE 14
        satellite; PR 4's counter-purity rule)."""
        if self._wire_label_override is not None:
            return self._wire_label_override
        if self._tree_override:
            return "SET_TREE"
        active = self._candidates[self.adaptive.active][0]
        if active == Strategy.RING_SEGMENTED:
            if not self._segmented_fallback_noted and not self._in_fixed_walk:
                self._segmented_fallback_noted = True
                from kungfu_tpu.telemetry import audit as _audit

                _audit.record_event(
                    "segmented_fallback",
                    peer=str(self.self_id),
                    collective=self._wire_kind,
                    wire_label=Strategy.BINARY_TREE.name,
                    threshold_bytes=self.SEGMENT_MIN_BYTES,
                )
            return Strategy.BINARY_TREE.name
        return active.name

    def _recv_collective(
        self, peer: PeerID, name: str, nbytes: int, dtype, count: int,
        timeout: float,
    ):
        """Receive (peer, name) into a pooled scratch buffer — delivered
        straight off the socket when we're parked first (sink path), else
        from the buffered Message (possibly a zero-copy shm borrow).
        Returns (ndarray view, scratch-or-None to return to the pool,
        release-or-None to call once the view has been consumed). Shared
        by the graph walk and the segmented walk so the borrow/release/
        leak-on-timeout contract lives in ONE place. On error the scratch
        is deliberately NOT returned to the pool: a timed-out sink may
        still be mid-fill by the transport thread."""
        bufpool = get_buffer_pool()
        scratch = bufpool.get(nbytes)
        msg, filled = self.endpoint.recv_into(
            peer, name, memoryview(scratch), timeout
        )
        if filled:
            return np.frombuffer(scratch, dtype, count), scratch, None
        bufpool.put(scratch)  # unused: sender raced us or size mismatch
        return np.frombuffer(msg.data, dtype, count), None, msg.release

    # ------------------------------------------------------------------
    # segmented ring walk
    # ------------------------------------------------------------------

    def _run_segmented(
        self,
        w: Workspace,
        ranks: Optional[Sequence[int]] = None,
        cancel: Optional[threading.Event] = None,
        wire=None,
        defer_decode: bool = False,
        phase: str = "all",
        ef_owned: Optional[np.ndarray] = None,
    ) -> Optional[DeferredDecode]:
        """Bandwidth-optimal segmented walk: a (k-1)-step reduce-scatter
        over contiguous segments followed by a (k-1)-step all-gather
        around a ring (arXiv:1810.11112 §3; the TPU-pod MLPerf stack
        leans on the same segmented summation, arXiv:1909.09756). Each
        step sends ONE ~N/k segment to the ring successor and reduces
        (or, in the gather phase, copies) the segment arriving from the
        predecessor in place — zero-copy views into the recv buffer, no
        full-payload relays, ~2*(k-1)/k*N bytes moved per peer total.

        With `wire` set (the codec, ISSUE 5) each segment crosses the
        transport as bf16/f16 — half the bytes, 2*(k-1)/k*N/2 per peer:

        * reduce-scatter: the sender encodes its f32 partial into a
          pooled wire scratch; the receiver decode-accumulates into the
          f32 buffer in one fused pass, so every transmitted value is
          quantized exactly once and no rounding compounds in 16-bit
          storage across the (k-1) steps;
        * all-gather: segments STAY in wire dtype in a walk-local wire
          buffer — each already-reduced segment is quantized once by its
          owner, relayed untouched, and decoded exactly once per peer at
          walk end (the owner decodes its own encoding too, so every
          peer lands on bit-identical results).

        Contracts shared with the graph walk: receives prefer the
        zero-copy sink/shm-borrow path (`recv_into`) and release borrows
        after the in-place reduce; one deadline bounds the WHOLE walk (not
        per step); a timed-out scratch buffer is never returned to the
        pool (the transport thread may still be mid-fill); empty segments
        (payload < k elements) are skipped identically on both ends of
        every edge, so no peer waits on a message that never departs.

        `ranks` restricts the ring to a subset (hierarchical cross-host
        mode); non-members just forward send into recv. With
        `defer_decode` (compressed walks only) the walk-end decode is
        skipped and the wire buffer returned — see DeferredDecode.

        `phase` selects which half of the walk runs (ISSUE 11):

        * ``"all"`` — the full allreduce (default, behavior unchanged);
        * ``"rs"``  — stop after the reduce-scatter: ``w.recv`` holds the
          fully reduced OWNED segment (``topo.owned_segment_bounds``) and
          partial garbage elsewhere. Always raw — the reduce leg of the
          sharded update keeps f32 exactness (the codec's win goes to the
          weight all-gather), so ``wire`` is ignored;
        * ``"ag"``  — the standalone all-gather: the caller already
          placed this rank's segment into ``w.recv`` (use an INPLACE
          workspace; ``forward()`` degenerates to a no-op) and the walk
          relays every segment around the ring, wire-encoded when `wire`
          is set (each segment quantized once by its owner, decoded once
          per peer at walk end — every peer, owner included, lands on
          bit-identical values).

        `wire` accepts a :class:`~kungfu_tpu.base.dtype.DType` (bf16/
        f16) or a :class:`~kungfu_tpu.base.ops.QWire` (block-scaled
        int8/int4). The quantized codec additionally carries
        error-feedback residuals: full walks use the session store
        (keyed by workspace name); the standalone ``"ag"`` phase takes
        the caller's per-shard residual via `ef_owned` (sized to the
        OWNED segment — ZeRO's weight leg). Quantized walks never defer
        the walk-end decode (member bounds don't align with the
        block-scaled layout), so `defer_decode` is ignored for them."""
        if phase not in ("all", "rs", "ag"):
            raise ValueError(f"unknown segmented phase: {phase!r}")
        if phase == "rs":
            wire = None  # the reduce leg stays exact f32 (see docstring)
        if w.is_empty:
            w.forward()
            return None
        # measured-topology plan (ISSUE 14): the GLOBAL ring follows the
        # adopted plan's order and segment weights; subset rings
        # (hierarchical cross-host mode) stay naive — the plan indexes
        # the full rank space. Read once per walk: adoption happens in
        # lockstep at step boundaries, so no walk straddles a flip.
        plan = self._ring_plan if ranks is None else None
        if plan is not None:
            members = list(plan.order)
            weights = plan.weights
        else:
            members = list(range(self.size)) if ranks is None else list(ranks)
            weights = None
        k = len(members)
        if self.rank not in members or k == 1:
            w.forward()
            return None
        # capture the step-plane sink on THIS thread before any work:
        # the attribution calls at walk end run here too, but capturing
        # once keeps the contract identical to the graph walk's (whose
        # chunk jobs hop to pool threads)
        steptrace_sink = steptrace.current_sink()
        sched = topo.gen_segmented_schedule(members, members.index(self.rank))
        bounds = topo.segment_bounds(w.recv.size, k, weights)
        w.forward()  # seed the accumulator with own contribution
        acc = w.recv
        send_peer = self.peers[sched.send_peer]
        recv_peer = self.peers[sched.recv_peer]
        itemsize = acc.itemsize
        codec_label = wire.name.lower() if wire is not None else "off"

        def seg_wire_nbytes(count: int) -> int:
            """Bytes segment `count` elements occupy on the wire."""
            if wire is None:
                return count * itemsize
            return _wire_payload_nbytes(count, wire)

        bufpool = get_buffer_pool()
        deadline = time.monotonic() + self.timeout
        wire_bytes = 0
        raw_bytes = 0
        # critical-path attribution for this walk (profiler, ISSUE 6):
        # wait-on-recv and send-blocked seconds of THIS thread; the
        # reduce/codec compute is the residual against walk wall time
        prof = WalkProfile()
        emit_steps = self._span_sampler.sample()
        # all-gather wire buffer: segments stay encoded here from the
        # owner's single quantization until the walk-end decode. Leaked
        # (not pool-returned) on any error — the transport may still be
        # mid-fill into a timed-out sink slice. 16-bit codecs index it
        # by element (2 bytes each); the block-scaled quantizer's
        # variable-length segments get per-segment byte offsets (scales
        # + packed payload, blocks relative to each segment start — the
        # segment's single owner encodes every one of its scale blocks).
        wirebuf: Optional[bytearray] = None
        wirearr: Optional[np.ndarray] = None
        qoff: Optional[List[int]] = None
        if isinstance(wire, QWire):
            qoff = [0]
            for b, e in bounds:
                qoff.append(qoff[-1] + seg_wire_nbytes(e - b))
            wirebuf = bufpool.get(qoff[-1])
            wirearr = np.frombuffer(wirebuf, np.uint8, qoff[-1])
        elif wire is not None:
            wirebuf = bufpool.get(acc.size * 2)
            wirearr = np.frombuffer(wirebuf, np.uint16, acc.size)

        def ag_slice(seg: int) -> np.ndarray:
            """The wire buffer slice holding segment `seg`'s encoding."""
            b, e = bounds[seg]
            if qoff is not None:
                return wirearr[qoff[seg]:qoff[seg + 1]]
            return wirearr[b:e]

        # error feedback (quantized codec only): the un-transmitted
        # remainder of each quantized send, added back into the next
        # one. Full walks carry a session-store residual keyed by the
        # workspace name (flushed on mode changes and re-plans, dead on
        # resize); the standalone all-gather takes the caller's
        # per-shard buffer (`ef_owned`, ZeRO's weight leg). RS sends and
        # the AG seed touch DISJOINT slices (a peer never RS-sends the
        # segment it ends up owning), so each element's residual is
        # written at most once per walk — pool-thread encodes included.
        ef_full: Optional[np.ndarray] = None
        if isinstance(wire, QWire) and phase == "all":
            ef_full = self._ef_residual(w.name, acc.size)

        def encode_seg(payload: np.ndarray, sb: int, se: int,
                       ef: Optional[np.ndarray]) -> None:
            """Quantize acc[sb:se] into `payload`, folding the carried
            residual in and banking the new remainder (EF). Exact for
            the 16-bit codecs' callers too (ef is None there)."""
            if ef is None:
                encode_wire_any(payload, acc[sb:se], wire)
                return
            corrected = acc[sb:se] + ef
            encode_wire_any(payload, corrected, wire)
            decoded = np.empty(se - sb, np.float32)
            decode_wire_any(decoded, payload, wire)
            np.subtract(corrected, decoded, out=ef)

        def do_send(name: str, sb: int, se: int, buf) -> None:
            """Deadline-bounded send: a frozen successor (full shm ring
            -> socket fallback -> full TCP buffer) would otherwise block
            sendall forever and the walk-wide deadline — checked only in
            do_recv — would never fire. Dispatch + event-wait costs tens
            of µs per step, noise against the segment memcpy. A timed-out
            send thread is abandoned exactly like the graph walk's _par
            send threads; the buffer stays valid because the caller
            raises out of the walk without touching acc again."""
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                raise TimeoutError(f"segmented walk timed out: {name}")
            done = threading.Event()
            errs: List[BaseException] = []

            def run() -> None:
                try:
                    # zero-copy: segments are disjoint and steps
                    # sequential per workspace, so this view cannot be
                    # mutated mid-sendall
                    self.client.send(
                        send_peer, name, _buf(buf), ConnType.COLLECTIVE
                    )
                except BaseException as e:  # noqa: BLE001 - re-raised below
                    errs.append(e)
                finally:
                    done.set()

            _t_send = time.perf_counter()
            get_pool().submit(run)
            ok = done.wait(remaining)
            prof.send += time.perf_counter() - _t_send
            if not ok:
                raise TimeoutError(f"segmented send timed out: {name}")
            if errs:
                raise errs[0]

        def start_send_wire(name: str, sb: int, se: int, buf, ef=None):
            """Async wire-mode send: encode (when `buf` is an f32 view)
            and transport copy run on the pool thread so they OVERLAP
            the blocking predecessor recv — the codec's encode would
            otherwise sit on the ring's serialized critical path, which
            a time-sliced multi-worker host punishes step after step.
            Safe because a step's send and recv segments are disjoint by
            schedule construction, so the thread reads acc[sb:se] (or a
            wirearr slice) and writes the disjoint residual slice `ef`
            while the main thread fills a different segment. Returns
            (done, errs) for finish_send; the encode scratch is
            pool-returned by the thread itself (never while anything can
            still read it)."""
            done = threading.Event()
            errs: List[BaseException] = []

            def run() -> None:
                try:
                    if buf.dtype != np.float32:
                        payload = buf  # all-gather: already wire-encoded
                        scratch = None
                    else:
                        nb = seg_wire_nbytes(se - sb)
                        scratch = bufpool.get(nb)
                        if qoff is not None:
                            payload = np.frombuffer(scratch, np.uint8, nb)
                        else:
                            payload = np.frombuffer(scratch, np.uint16, se - sb)
                        encode_seg(payload, sb, se, ef)
                    self.client.send(
                        send_peer, name, _buf(payload), ConnType.COLLECTIVE
                    )
                    if scratch is not None:
                        bufpool.put(scratch)
                except BaseException as e:  # noqa: BLE001 - re-raised below
                    errs.append(e)
                finally:
                    done.set()

            get_pool().submit(run)
            return done, errs

        def finish_send(pending, name: str) -> None:
            done, errs = pending
            remaining = deadline - time.monotonic()
            _t_send = time.perf_counter()
            ok = remaining > 0 and done.wait(remaining)
            prof.send += time.perf_counter() - _t_send
            if not ok:
                raise TimeoutError(f"segmented send timed out: {name}")
            if errs:
                raise errs[0]

        def recv_rs(name: str, rb: int, re_: int) -> None:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                raise TimeoutError(f"segmented walk timed out: {name}")
            nb = seg_wire_nbytes(re_ - rb)
            if qoff is not None:
                recv_dtype, recv_count = np.dtype(np.uint8), nb
            elif wire is not None:
                recv_dtype, recv_count = np.dtype(np.uint16), re_ - rb
            else:
                recv_dtype, recv_count = acc.dtype, re_ - rb
            _t_recv = time.perf_counter()
            incoming, scratch, release = self._recv_collective(
                recv_peer, name, nb, recv_dtype, recv_count, remaining,
            )
            prof.wait += time.perf_counter() - _t_recv
            try:
                if cancel is not None and cancel.is_set():
                    # caller-scope timeout fired while we were blocked:
                    # the recv buffer may already be reused — a late
                    # arrival must not be reduced into it
                    raise TimeoutError(f"collective cancelled: {name}")
                if wire is not None:
                    # fused decode + f32 accumulate: one pass, one
                    # quantization deep (the sender's encode)
                    decode_accumulate_any(acc, rb, re_, incoming, wire, w.op)
                else:
                    reduce_segment(acc, rb, re_, incoming, w.op)
            finally:
                del incoming
                if release is not None:
                    release()
            if scratch is not None:
                bufpool.put(scratch)

        def recv_ag(name: str, seg: int, rb: int, re_: int) -> None:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                raise TimeoutError(f"segmented walk timed out: {name}")
            if wire is None:
                _t_recv = time.perf_counter()
                incoming, scratch, release = self._recv_collective(
                    recv_peer, name, (re_ - rb) * itemsize, acc.dtype,
                    re_ - rb, remaining,
                )
                prof.wait += time.perf_counter() - _t_recv
                try:
                    if cancel is not None and cancel.is_set():
                        raise TimeoutError(f"collective cancelled: {name}")
                    copy_segment(acc, rb, re_, incoming)
                finally:
                    del incoming
                    if release is not None:
                        release()
                if scratch is not None:
                    bufpool.put(scratch)
                return
            # wire mode: deliver straight into the wire buffer slice —
            # no scratch, no decode (the segment is relayed as-is and
            # decoded once at walk end)
            if qoff is not None:
                byte_lo, byte_hi = qoff[seg], qoff[seg + 1]
            else:
                byte_lo, byte_hi = rb * 2, re_ * 2
            _t_recv = time.perf_counter()
            msg, filled = self.endpoint.recv_into(
                recv_peer, name, memoryview(wirebuf)[byte_lo:byte_hi],
                remaining,
            )
            prof.wait += time.perf_counter() - _t_recv
            if cancel is not None and cancel.is_set():
                if msg is not None and msg.release is not None:
                    msg.release()
                raise TimeoutError(f"collective cancelled: {name}")
            if not filled:
                try:
                    if qoff is not None:
                        np.copyto(
                            wirearr[byte_lo:byte_hi],
                            np.frombuffer(msg.data, np.uint8,
                                          byte_hi - byte_lo),
                        )
                    else:
                        np.copyto(
                            wirearr[rb:re_],
                            np.frombuffer(msg.data, np.uint16, re_ - rb),
                        )
                finally:
                    if msg.release is not None:
                        msg.release()

        def step(phase: str, s: int, send_seg: int, recv_seg: int) -> None:
            nonlocal wire_bytes, raw_bytes
            sb, se = bounds[send_seg]
            rb, re_ = bounds[recv_seg]
            name = f"{w.name}:{phase}{s}"
            if cancel is not None and cancel.is_set():
                raise TimeoutError(f"collective cancelled: {name}")
            # empty segments (payload < k elements) are skipped on BOTH
            # ends: sender and receiver compute identical bounds.
            # RAW mode: send-then-recv is deliberately SEQUENTIAL — the
            # send returns once the payload is in the shm ring / kernel
            # buffer, so the wire is already busy while we block on the
            # predecessor, and a _par pair per step measured 15% slower
            # on the 2-core bench box (thread dispatch + GIL beat the
            # overlap). WIRE mode: the encode pass makes the send phase
            # heavy enough to flip that trade — encode+send run async on
            # the pool thread and overlap the predecessor wait, awaited
            # at step end (disjoint segments make this safe).
            if se > sb:
                wire_bytes += seg_wire_nbytes(se - sb)
                raw_bytes += (se - sb) * itemsize
            if wire is not None:
                pending = None
                if se > sb:
                    if phase == "rs":
                        ef = ef_full[sb:se] if ef_full is not None else None
                        pending = start_send_wire(name, sb, se, acc[sb:se], ef)
                    else:
                        pending = start_send_wire(name, sb, se,
                                                  ag_slice(send_seg))
                if re_ > rb:
                    if phase == "rs":
                        recv_rs(name, rb, re_)
                    else:
                        recv_ag(name, recv_seg, rb, re_)
                if pending is not None:
                    finish_send(pending, name)
                return
            if se > sb:
                do_send(name, sb, se, acc[sb:se])
            if re_ > rb:
                if phase == "rs":
                    recv_rs(name, rb, re_)
                else:
                    recv_ag(name, recv_seg, rb, re_)

        def timed_step(span_name: str, phase: str, s: int, snd: int, rcv: int) -> None:
            """One ring step, with a per-step span (subject to
            KF_TELEMETRY_SPAN_SAMPLE) annotated with how long the step
            was blocked waiting on its predecessor vs its successor."""
            if not emit_steps:
                step(phase, s, snd, rcv)
                return
            w0, s0 = prof.wait, prof.send
            with trace.span(span_name, step=s, k=k) as sp:
                step(phase, s, snd, rcv)
                sp.args["wait_us"] = round((prof.wait - w0) * 1e6)
                sp.args["send_us"] = round((prof.send - s0) * 1e6)

        _t0 = time.perf_counter()
        if phase != "ag":
            for s, (snd, rcv) in enumerate(sched.rs_steps):
                timed_step("host.rs.step", "rs", s, snd, rcv)
        if phase == "rs":
            self._count_wire(
                wire_bytes, Strategy.RING_SEGMENTED.name, "off", raw_bytes
            )
            wall = time.perf_counter() - _t0
            trace.record(f"host.rs[{w.recv.nbytes >> 20}MiB]", wall)
            # half walks move (k-1)/k·N = the optimal 2(k-1)/k volume of
            # HALF the payload: score against the halved payload so the
            # profiler's efficiency ratio stays meaningful
            self._record_walk(
                Strategy.RING_SEGMENTED.name, k, w.recv.nbytes // 2, wall,
                prof, dsts=[send_peer], sink=steptrace_sink,
            )
            return None
        if wire is not None:
            # seed the all-gather: quantize the owned (fully reduced)
            # segment ONCE; every peer — self included — will decode
            # this same encoding, so results stay bit-identical ringwide
            ob, oe = bounds[sched.owned_segment]
            if oe > ob:
                ef = None
                if isinstance(wire, QWire):
                    if ef_owned is not None and ef_owned.size != oe - ob:
                        raise ValueError(
                            f"ef residual of {ef_owned.size} elements for "
                            f"owned segment [{ob}:{oe}) — caller sharded "
                            "differently"
                        )
                    ef = ef_owned
                    if ef is None and ef_full is not None:
                        ef = ef_full[ob:oe]
                encode_seg(ag_slice(sched.owned_segment), ob, oe, ef)
        for s, (snd, rcv) in enumerate(sched.ag_steps):
            timed_step("host.ag.step", "ag", s, snd, rcv)
        if cancel is not None and cancel.is_set():
            # KF703: a sibling in the group scope timed out while our
            # steps completed — acc may belong to a caller that already
            # raised, so observe the abort before the walk-end decode
            # writes it (wirebuf deliberately leaks, pool policy)
            raise TimeoutError(f"collective cancelled: {w.name}")
        deferred: Optional[DeferredDecode] = None
        if wire is not None:
            if defer_decode and qoff is None:
                deferred = DeferredDecode(wire, wirebuf, wirearr)
            elif qoff is not None:
                # block-scaled: segments decode individually (each one's
                # scale blocks are relative to its own start)
                with trace.span("host.wire.decode", bytes=int(qoff[-1])):
                    for i, (b, e) in enumerate(bounds):
                        if e > b:
                            decode_wire_any(acc[b:e], ag_slice(i), wire)
                bufpool.put(wirebuf)
            else:
                with trace.span("host.wire.decode", bytes=int(acc.size * 2)):
                    decode_wire_any(acc, wirearr, wire)
                bufpool.put(wirebuf)
        self._count_wire(
            wire_bytes, Strategy.RING_SEGMENTED.name, codec_label, raw_bytes
        )
        wall = time.perf_counter() - _t0
        trace.record(f"host.segmented[{w.recv.nbytes >> 20}MiB]", wall)
        # the ring's only outgoing edge is the successor: score this walk
        # against that link's measured bandwidth (half walks against the
        # correspondingly halved payload, see the rs return above)
        self._record_walk(
            Strategy.RING_SEGMENTED.name, k,
            w.recv.nbytes if phase == "all" else w.recv.nbytes // 2,
            wall, prof, dsts=[send_peer], sink=steptrace_sink,
        )
        return deferred

    # ------------------------------------------------------------------
    # two-level (hierarchical) walk — ISSUE 19
    # ------------------------------------------------------------------

    def _run_hier(
        self,
        w: Workspace,
        cancel: Optional[threading.Event] = None,
        wire: Optional[DType] = None,
    ) -> None:
        """Two-level allreduce over the adopted :class:`HierPlan`
        (arXiv:1909.09756's 2D shape): (1) intra-host star reduce of
        every contributing member onto its host head — the fast
        shm/loopback links, always exact f32; (2) segmented ring
        allreduce over the heads only (`_run_segmented`'s subset
        variant) — the DCN leg, wire-codec-eligible; (3) intra-host
        star broadcast of the result back to every member, demoted
        peers included.

        Demoted ranks (:attr:`HierPlan.demoted`) contribute NOTHING —
        they skip phases 1–2 and receive the result in phase 3, so a
        persistent straggler stops serializing the ring (the source
        paper's adaptive peer selection). On exact payloads the result
        is bit-identical to the flat segmented walk over the active
        set; with the codec, phase 2's once-per-owner quantization
        keeps heads bit-identical and phase 3 relays those exact f32
        bytes.

        Messages reuse the flat walk's naming discipline: intra legs
        rendezvous on ``w.name`` (directions disambiguate reduce vs
        broadcast, like the graph walks' (reduce, bcast) pairs), the
        inter ring on ``w.name:x:{rs,ag}{step}`` — disjoint from any
        flat walk name, so a peer that missed the lockstep adoption
        fails on a named rendezvous, never reduces into the wrong
        buffer."""
        plan = self._hier_plan
        if plan is None or plan.size != self.size:
            # stale plan (resize raced the flip) — the flat walk is
            # always correct
            self._run_segmented(w, cancel=cancel, wire=wire)
            return
        if w.is_empty:
            w.forward()
            return
        dem = set(plan.demoted)
        n = self.size
        heads = list(plan.heads)
        # phase 1: intra star reduce, members → head (exact f32)
        reduce_g = Graph(n)
        for head, grp in zip(plan.heads, plan.groups):
            members = [r for r in grp if r != head and r not in dem]
            if members:
                reduce_g.add_edge(head, head)
                for r in members:
                    reduce_g.add_edge(r, head)
        prev_label = self._wire_label_override
        self._wire_label_override = "HIER_INTRA"
        try:
            self._run_graphs(w, [reduce_g], cancel, None)
        finally:
            self._wire_label_override = prev_label
        # phase 2: segmented ring over the heads, INPLACE over the
        # group-reduced recv (non-heads forward(), a no-op inplace)
        wx = Workspace(send=w.recv, recv=w.recv, op=w.op,
                       name=f"{w.name}:x")
        self._run_segmented(wx, ranks=heads, cancel=cancel, wire=wire)
        # phase 3: intra star broadcast, head → every member (demoted
        # included), inplace so the head's forward() keeps its result
        bcast_g = Graph(n)
        for head, grp in zip(plan.heads, plan.groups):
            for r in grp:
                if r != head:
                    bcast_g.add_edge(head, r)
        wb = Workspace(send=w.recv, recv=w.recv, op=w.op, name=w.name)
        self._wire_label_override = "HIER_INTRA"
        try:
            self._run_graphs(wb, [bcast_g], cancel, None)
        finally:
            self._wire_label_override = prev_label

    # ------------------------------------------------------------------
    # chunked graph walks
    # ------------------------------------------------------------------

    def _run_strategies(
        self,
        w: Workspace,
        strategies: List[st.StrategyPair],
        cancel: Optional[threading.Event] = None,
        wire: Optional[DType] = None,
    ) -> None:
        """`wire` is decided ONCE on the whole workspace (in
        _allreduce_ws) and inherited by every chunk — a per-chunk
        decision would let a residual chunk fall below WIRE_MIN_BYTES
        and mix wire formats inside one collective (still cluster-
        consistent, but pointlessly branchy on the hot path)."""
        total = w.recv.size * w.recv.itemsize
        k = max(1, -(-total // choose_chunk_bytes(total)))
        chunks = w.split(even_partition, k) if k > 1 else [w]
        if cancel is None:
            cancel = threading.Event()
        # capture the step-plane sink HERE (the submitting walk thread):
        # chunk jobs execute on pool threads, where the thread-local
        # sink of the scheduler's walker would be invisible
        sink = steptrace.current_sink()
        if k == 1:
            pair = strategies[0]
            self._run_graphs(
                chunks[0], [pair.reduce_graph, pair.bcast_graph], cancel,
                wire, profile=True, sink=sink,
            )
            return
        jobs = []
        for i, chunk in enumerate(chunks):
            pair = st.choose(strategies, i)
            jobs.append(
                lambda c=chunk, p=pair: self._run_graphs(
                    c, [p.reduce_graph, p.bcast_graph], cancel, wire,
                    profile=True, sink=sink,
                )
            )
        _par(jobs, self.timeout, cancel)

    def _run_graphs(
        self,
        w: Workspace,
        graphs: List[Graph],
        cancel: Optional[threading.Event] = None,
        wire: Optional[DType] = None,
        profile: bool = False,
        sink=None,
    ) -> None:
        """The hot walk; parity: runGraphs (session.go:231-299).

        `profile=True` (the allreduce paths, via _run_strategies) feeds
        this walk's wait/send/compute attribution to the process
        WalkProfiler; direct reduce/broadcast/gather walks skip it (the
        2(k-1)/k*N allreduce bound doesn't describe them).

        `cancel` is shared across every thread touching this workspace: once
        any part of the collective times out, late-arriving receives must not
        write into (possibly reused) caller buffers.

        With `wire` set, every send encodes the f32 buffer into a pooled
        bf16/f16 scratch and every receive decode-accumulates (reduce
        phase) or decodes (bcast phase) back into f32 — accumulation
        never happens in 16-bit storage. Relays re-encode values that
        are already wire-quantized, which is exact (encode of an
        exactly-representable value is the identity), so the quantized
        result every peer converges on is bit-identical."""
        if w.is_empty:
            return
        if all(g.is_isolated(self.rank) for g in graphs):
            w.forward()
            return
        if cancel is None:
            cancel = threading.Event()
        _t_walk = time.perf_counter()
        prof = WalkProfile() if profile else None

        state = {"recv_count": 0}
        lock = threading.Lock()

        def effective() -> np.ndarray:
            if state["recv_count"] > 0 or w.is_inplace:
                return w.recv
            return w.send

        wire_label = self._walk_label()
        codec_label = wire.name.lower() if wire is not None else "off"

        def send_to(peer: PeerID, flags: Flags = Flags.NONE) -> None:
            # zero-copy: the walk's phases are sequential per chunk, so the
            # buffer cannot be mutated while sendall drains it
            self.client.send(
                peer, w.name, _buf(effective()), ConnType.COLLECTIVE, flags
            )
            self._count_wire(wire_nbytes, wire_label, codec_label, nbytes)

        def send_all(peers: List[PeerID], flags: Flags = Flags.NONE) -> None:
            """Fan-out send of the current effective() buffer. Wire mode
            encodes ONCE into a shared scratch for the whole fan-out —
            every edge carries identical bytes, so per-peer encodes (a
            full payload pass each) would be pure waste at STAR/CLIQUE
            fan-outs. The scratch returns to the pool only on success:
            after a timeout an abandoned send thread may still be
            draining it."""
            if not peers:
                return
            if wire is None:
                _t_send = time.perf_counter()
                _par([lambda p=p: send_to(p, flags) for p in peers],
                     self.timeout, cancel)
                if prof is not None:
                    prof.send += time.perf_counter() - _t_send
                return
            scratch = bufpool.get(wire_nbytes)
            enc = np.frombuffer(scratch, wire_np_dtype, wire_count)
            # the fan-out encode is codec COMPUTE (the residual bucket),
            # so only the transport fan-out below is timed as send.
            # Quantized payloads re-encode idempotently (pow2 scales):
            # a relay that decoded q-bytes re-produces those exact
            # bytes, so graph fan-outs need no error feedback to stay
            # bit-identical.
            encode_wire_any(enc, effective(), wire)

            def send_enc(peer: PeerID) -> None:
                self.client.send(
                    peer, w.name, _buf(enc), ConnType.COLLECTIVE, flags
                )
                self._count_wire(wire_nbytes, wire_label, codec_label, nbytes)

            _t_send = time.perf_counter()
            _par([lambda p=p: send_enc(p) for p in peers], self.timeout, cancel)
            if prof is not None:
                prof.send += time.perf_counter() - _t_send
            bufpool.put(scratch)

        bufpool = get_buffer_pool()
        nbytes = w.recv.size * w.recv.itemsize
        wire_nbytes = (
            _wire_payload_nbytes(w.recv.size, wire) if wire is not None
            else nbytes
        )
        if isinstance(wire, QWire):
            # block-scaled payload: scales + packed bytes, u8-framed
            wire_np_dtype, wire_count = np.dtype(np.uint8), wire_nbytes
        elif wire is not None:
            wire_np_dtype, wire_count = np.dtype(np.uint16), w.recv.size
        else:
            wire_np_dtype, wire_count = w.send.dtype, w.recv.size

        def recv_payload(peer: PeerID):
            """See _recv_collective (shared with the segmented walk)."""
            return self._recv_collective(
                peer, w.name, wire_nbytes, wire_np_dtype, wire_count,
                self.timeout
            )

        def recv_onto(peer: PeerID) -> None:
            incoming, scratch, release = recv_payload(peer)
            try:
                with lock:
                    if cancel.is_set():
                        # abort the whole walk: a late arrival must neither
                        # write the workspace nor let the send phase relay
                        # stale data
                        raise TimeoutError(f"collective cancelled: {w.name}")
                    if wire is not None:
                        if state["recv_count"] == 0 and not w.is_inplace:
                            # first arrival: recv = decode(incoming), then
                            # fold own send in f32 (ops are commutative)
                            decode_wire_any(w.recv, incoming, wire)
                            reduce_inplace(w.recv, w.send, w.op)
                        else:
                            decode_accumulate_any(
                                w.recv, 0, w.recv.size, incoming, wire, w.op
                            )
                    elif state["recv_count"] == 0 and not w.is_inplace:
                        # first arrival: recv = send (op) incoming
                        from kungfu_tpu.base.ops import transform2

                        transform2(w.recv, w.send, incoming, w.op)
                    else:
                        reduce_inplace(w.recv, incoming, w.op)
                    state["recv_count"] += 1
            finally:
                del incoming
                if release is not None:
                    release()
            if scratch is not None:
                bufpool.put(scratch)

        def recv_all_onto(peers: List[PeerID]) -> None:
            """Accumulate phase: receive every prev, then reduce them all
            in ONE n-ary pass (kf_transform_n). Pairwise-on-arrival
            overlaps receive with reduce, which pays when cores are free;
            the n-ary pass minimizes memory traffic, which wins outright
            on busy/low-core hosts — and the receives themselves still
            overlap each other."""
            got: List = [None] * len(peers)

            def grab(i: int, p: PeerID) -> None:
                res = recv_payload(p)
                if cancel.is_set():
                    # the walk already timed out and its finally block may
                    # have run: release the borrow here or nobody will
                    if res[2] is not None:
                        res[2]()
                    return
                got[i] = res

            try:
                _t_recv = time.perf_counter()
                _par(
                    [lambda i=i, p=p: grab(i, p) for i, p in enumerate(peers)],
                    self.timeout,
                    cancel,
                )
                if prof is not None:
                    prof.wait += time.perf_counter() - _t_recv
                with lock:
                    if cancel.is_set():
                        raise TimeoutError(f"collective cancelled: {w.name}")
                    if wire is not None:
                        # decode-accumulate each arrival into f32 (the
                        # fused kernel; no n-ary variant exists for mixed
                        # wire/f32 sources and the tree fan-in is small)
                        if not w.is_inplace:
                            w.forward()
                        for incoming, _, _ in got:
                            decode_accumulate_any(
                                w.recv, 0, w.recv.size, incoming, wire, w.op
                            )
                    elif w.is_inplace:
                        for incoming, _, _ in got:
                            reduce_inplace(w.recv, incoming, w.op)
                    else:
                        transform_n(
                            w.recv,
                            [w.send] + [inc for inc, _, _ in got],
                            w.op,
                        )
                    state["recv_count"] += len(peers)
            finally:
                for item in got:
                    if item is not None and item[2] is not None:
                        item[2]()
            for item in got:
                if item is not None and item[1] is not None:
                    bufpool.put(item[1])

        def recv_into(peer: PeerID) -> None:
            incoming, scratch, release = recv_payload(peer)
            try:
                with lock:
                    if cancel.is_set():
                        raise TimeoutError(f"collective cancelled: {w.name}")
                    if wire is not None:
                        decode_wire_any(w.recv, incoming, wire)
                    else:
                        np.copyto(w.recv, incoming)
                    state["recv_count"] += 1
            finally:
                del incoming
                if release is not None:
                    release()
            if scratch is not None:
                bufpool.put(scratch)

        for g in graphs:
            prevs = [self.peers[r] for r in g.prevs(self.rank)]
            nexts = [self.peers[r] for r in g.nexts(self.rank)]
            if g.is_self_loop(self.rank):
                # accumulate: receive from all prevs, n-ary reduce, send on
                if prevs and state["recv_count"] == 0:
                    recv_all_onto(prevs)
                elif prevs:
                    # pairwise path: the pool threads fold their reduce
                    # into this timed block (profiler caveat, see
                    # WalkProfiler) — receives dominate it
                    _t_recv = time.perf_counter()
                    _par([lambda p=p: recv_onto(p) for p in prevs], self.timeout, cancel)
                    if prof is not None:
                        prof.wait += time.perf_counter() - _t_recv
                send_all(nexts)
            else:
                # pass-through node: take value from single prev (or forward
                # own), relay to nexts
                if not prevs and state["recv_count"] == 0:
                    w.forward()
                else:
                    _t_recv = time.perf_counter()
                    for p in prevs:
                        recv_into(p)
                    if prof is not None:
                        prof.wait += time.perf_counter() - _t_recv
                send_all(nexts, Flags.WAIT_RECV_BUF)
        if cancel.is_set():
            # KF703: the group scope aborted while this walk's own edges
            # completed — w.recv may already be reused by the caller that
            # raised, so the root's codec roundtrip below must not touch it
            raise TimeoutError(f"collective cancelled: {w.name}")
        if wire is not None and not graphs[-1].prevs(self.rank):
            # the bcast root never receives a wire message, so it would
            # keep its full-precision f32 result while every other peer
            # decodes the quantized broadcast: roundtrip the root's recv
            # through the codec so all peers land on bit-identical values
            scratch = bufpool.get(wire_nbytes)
            enc = np.frombuffer(scratch, wire_np_dtype, wire_count)
            encode_wire_any(enc, w.recv, wire)
            decode_wire_any(w.recv, enc, wire)
            bufpool.put(scratch)
        wall = time.perf_counter() - _t_walk
        trace.record(f"host.walk[{w.recv.nbytes >> 20}MiB]", wall)
        if prof is not None:
            # graph walks fan out over many edges: score against the
            # slowest estimated link overall (dsts=None)
            self._record_walk(
                wire_label, self.size, w.recv.nbytes, wall, prof, sink=sink
            )
