"""Strategy lists: (reduce, bcast) graph pairs per Strategy enum.

Capability parity: srcs/go/kungfu/session/strategy.go:58-210 — a strategy
is a (reduceGraph, bcastGraph) pair; multi-root strategies (RING, CLIQUE,
MULTI_STAR, MULTI_BINARY_TREE_STAR) return one pair per root so chunked
collectives can stripe chunks across roots; AUTO picks STAR on a single
host and BINARY_TREE_STAR across hosts (strategy.go:165-174).
"""

from __future__ import annotations

import dataclasses
import hashlib
import os
from typing import List

from kungfu_tpu.base.strategy import Strategy
from kungfu_tpu.plan.graph import Graph
from kungfu_tpu.plan.peer import PeerList
from kungfu_tpu.plan import topology as topo


@dataclasses.dataclass
class StrategyPair:
    reduce_graph: Graph
    bcast_graph: Graph

    @classmethod
    def from_bcast(cls, bcast: Graph) -> "StrategyPair":
        return cls(topo.gen_default_reduce_graph(bcast), bcast)

    def digest(self) -> bytes:
        return self.reduce_graph.digest() + self.bcast_graph.digest()


StrategyList = List[StrategyPair]


def digest(sl: StrategyList) -> bytes:
    h = hashlib.blake2b(digest_size=16)
    for s in sl:
        h.update(s.digest())
    return h.digest()


def choose(sl: StrategyList, i: int) -> StrategyPair:
    return sl[i % len(sl)]


# cgroup CPU-limit files; module constants so tests can point them at
# fixtures. v2 first (the unified hierarchy every modern container
# runtime mounts), v1 cfs_quota as fallback.
CGROUP_V2_CPU_MAX = "/sys/fs/cgroup/cpu.max"
CGROUP_V1_QUOTA = "/sys/fs/cgroup/cpu/cpu.cfs_quota_us"
CGROUP_V1_PERIOD = "/sys/fs/cgroup/cpu/cpu.cfs_period_us"


def _cgroup_cpu_quota() -> float:
    """CPU quota in cores from the cgroup limit, or 0.0 when unlimited
    or unreadable. v2: ``cpu.max`` is "<quota> <period>" or "max ...";
    v1: cfs_quota_us / cfs_period_us, quota -1 meaning unlimited."""
    try:
        with open(CGROUP_V2_CPU_MAX) as f:
            quota_s, _, period_s = f.read().strip().partition(" ")
        if quota_s != "max":
            quota = float(quota_s) / float(period_s or 100000)
            if quota > 0:
                return quota
    except (OSError, ValueError, ZeroDivisionError):
        pass
    try:
        with open(CGROUP_V1_QUOTA) as f:
            quota_us = int(f.read().strip())
        if quota_us > 0:
            with open(CGROUP_V1_PERIOD) as f:
                period_us = int(f.read().strip())
            if period_us > 0:
                return quota_us / period_us
    except (OSError, ValueError):
        pass
    return 0.0


def effective_cpu_count() -> int:
    """Cores this process can actually burn: os.cpu_count() capped by
    the affinity mask AND the cgroup CPU quota. In a CPU-quota'd
    container os.cpu_count() reports the host's cores — phantom
    parallelism that made auto_select pick k concurrent root walks on
    what is effectively a 1-core box."""
    cores = os.cpu_count() or 1
    if hasattr(os, "sched_getaffinity"):
        try:
            cores = min(cores, len(os.sched_getaffinity(0)))
        except OSError:
            pass
    quota = _cgroup_cpu_quota()
    if quota > 0:
        cores = min(cores, int(quota))
    return max(1, cores)


def auto_select(peers: PeerList) -> Strategy:
    """Single host, k >= 4: RING_SEGMENTED — the bandwidth-optimal
    segmented reduce-scatter/all-gather moves only 2*(k-1)/k of the
    payload per peer (tree/star roots carry ~2*(k-1)x), and its walk is
    sequential per peer so it needs no spare cores for concurrent chunk
    walks (unlike CLIQUE striping, which loses on 1-2 core hosts).
    k == 3: segmented saves little (2/3 vs full relays through a 3-node
    tree are close) and costs 4 serialized latency steps, so keep the
    old striping-vs-tree core-count choice. k <= 2: STAR (one hop).
    Pair 0 of every generated list stays rank-0-rooted, preserving the
    gather/broadcast root contract. Multi-host: one binary-tree-star per
    host master (striping across hosts; the hierarchical path owns the
    cross-host segmented variant)."""
    if peers.host_count() == 1:
        if len(peers) <= 2:
            return Strategy.STAR
        if len(peers) >= 4:
            return Strategy.RING_SEGMENTED
        return (
            Strategy.CLIQUE
            if effective_cpu_count() >= 4
            else Strategy.BINARY_TREE
        )
    return Strategy.MULTI_BINARY_TREE_STAR


def _star(peers: PeerList) -> StrategyList:
    return [StrategyPair.from_bcast(topo.gen_star_bcast_graph(len(peers), 0))]


def _multi_star(peers: PeerList) -> StrategyList:
    return [StrategyPair.from_bcast(g) for g in topo.gen_multi_stars(peers)]


def _clique(peers: PeerList) -> StrategyList:
    k = len(peers)
    return [StrategyPair.from_bcast(topo.gen_star_bcast_graph(k, r)) for r in range(k)]


def _ring(peers: PeerList) -> StrategyList:
    k = len(peers)
    return [StrategyPair(*topo.gen_circular_graph_pair(k, r)) for r in range(k)]


def _tree(peers: PeerList) -> StrategyList:
    return [StrategyPair.from_bcast(topo.gen_tree(peers))]


def _binary_tree(peers: PeerList) -> StrategyList:
    return [StrategyPair.from_bcast(topo.gen_binary_tree(len(peers)))]


def _binary_tree_star(peers: PeerList) -> StrategyList:
    return [StrategyPair.from_bcast(topo.gen_binary_tree_star(peers))]


def _multi_binary_tree_star(peers: PeerList) -> StrategyList:
    return [StrategyPair.from_bcast(g) for g in topo.gen_multi_binary_tree_star(peers)]


_GENERATORS = {
    Strategy.STAR: _star,
    Strategy.MULTI_STAR: _multi_star,
    Strategy.CLIQUE: _clique,
    Strategy.RING: _ring,
    Strategy.TREE: _tree,
    Strategy.BINARY_TREE: _binary_tree,
    Strategy.BINARY_TREE_STAR: _binary_tree_star,
    Strategy.MULTI_BINARY_TREE_STAR: _multi_binary_tree_star,
    # RING_SEGMENTED's allreduce runs the engine's dedicated segmented
    # walk (walks._run_segmented), not these graphs. The pair here backs
    # the RESIDUAL graph consumers — reduce/broadcast/gather and
    # allreduce payloads below KF_CONFIG_SEGMENT_MIN_BYTES — with a
    # rank-0 binary tree: latency-optimal for the small control
    # collectives that hit it. This fallback is BY DESIGN but not
    # silent: the first graph walk per session epoch under an active
    # RING_SEGMENTED emits a `segmented_fallback` audit event, and its
    # wire bytes are labeled BINARY_TREE (WalkEngine._walk_label — PR
    # 4's counter-purity rule: the RING_SEGMENTED series is what the
    # 2·(k-1)/k·N optimality assertion reads, so fallback traffic must
    # never pollute it).
    Strategy.RING_SEGMENTED: _binary_tree,
}


def gen_global_strategies(peers: PeerList, strategy: Strategy) -> StrategyList:
    if strategy == Strategy.AUTO:
        strategy = auto_select(peers)
    return _GENERATORS[strategy](peers)


def gen_local_strategies(peers: PeerList) -> StrategyList:
    """Intra-host forest: each host master broadcasts to colocated peers."""
    masters, master_of = peers.partition_by_host()
    bcast, roots, ok = Graph.from_forest_array(master_of)
    if not ok or roots != len(masters):
        raise ValueError(f"invalid host partition forest: {master_of}")
    return [StrategyPair.from_bcast(bcast)]


def gen_cross_strategies(peers: PeerList, strategy: Strategy) -> StrategyList:
    """Inter-host strategies over host masters only (hierarchical allreduce)."""
    n = len(peers)
    masters, _ = peers.partition_by_host()
    if strategy == Strategy.RING:
        return [
            StrategyPair(*topo.gen_subset_circular_graph_pair(n, masters, r))
            for r in range(len(masters))
        ]
    return [StrategyPair.from_bcast(topo.gen_subset_binary_tree(n, masters))]


def from_forest_array(fathers: List[int]) -> StrategyList:
    """Strategy from a runtime-supplied father array (SubsetAllReduce /
    AllReduceWith / set_tree; session/allreduce.go:14-44)."""
    bcast, _, ok = Graph.from_forest_array(fathers)
    if not ok:
        raise ValueError(f"invalid forest array: {fathers}")
    return [StrategyPair.from_bcast(bcast)]
