from kungfu_tpu.collective.strategies import (
    StrategyPair,
    auto_select,
    gen_cross_strategies,
    gen_global_strategies,
    gen_local_strategies,
)

__all__ = [
    "StrategyPair",
    "auto_select",
    "gen_cross_strategies",
    "gen_global_strategies",
    "gen_local_strategies",
]
