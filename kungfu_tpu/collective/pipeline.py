"""Group-allreduce fusion pipeline, factored out of host_session.py
(ISSUE 10 prerequisite refactor).

Owns the bucket side of `group_all_reduce`: deterministic same-
(dtype, op) bucketing (`_make_buckets`), the pack / walk / unpack
stages, and the 3-stage software pipeline that overlaps them. The
stages are exactly what the async scheduler (scheduler.py) drives
per-bucket as gradients become ready — one implementation, two
drivers (step-end batch here, readiness-ordered there).

The stage queues are :class:`~kungfu_tpu.utils.handoff.HandoffQueue`
(ISSUE 10 satellite): bounded, abort-aware, shared with the scheduler.
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, List, Optional, Sequence

import numpy as np

from kungfu_tpu import knobs
from kungfu_tpu.base.workspace import Workspace
from kungfu_tpu.collective.strategies import effective_cpu_count
from kungfu_tpu.utils import trace
from kungfu_tpu.utils.handoff import HandoffQueue, parallel_run as _par
from kungfu_tpu.utils.pool import get_buffer_pool
from kungfu_tpu.utils.stall import stall_detect


class GroupFusion:
    """Group-collective mixin for HostSession: windowed singles plus
    fused buckets through the pack/walk/unpack pipeline. Relies on
    session state (timeout, codec decision, walk engine) owned by the
    facade's constructor."""

    # concurrent workspaces per batch in group ops: concurrency only pays
    # when cores exist to run the walks (on a 1-core host it just adds
    # context switches), so the default scales with the cgroup-aware
    # core count — os.cpu_count() reports the HOST's cores inside a
    # CPU-quota'd container, the phantom-parallelism trap auto_select
    # already avoids; KF_CONFIG_GROUP_WINDOW overrides
    GROUP_WINDOW = int(
        knobs.get("KF_CONFIG_GROUP_WINDOW")
        or max(1, min(8, effective_cpu_count()))
    )

    # Gradient bucketing: fuse same-(dtype, op) workspaces into ONE
    # contiguous walk. A 160-tensor gradient set otherwise pays the fixed
    # per-walk cost (rendezvous conditions, pool dispatch, ~6 framed
    # messages) 160 times — on a host-plane reduce that overhead rivals
    # the byte-copy time itself. Two extra memcpy passes (pack + unpack)
    # buy a ~160x cut in message count. The reference runs one collective
    # per tensor and leans on cheap goroutines instead; bucketing is the
    # standard DDP/Horovod answer and is strictly better here.
    FUSE_MIN_TENSORS = int(knobs.get("KF_CONFIG_GROUP_FUSE_MIN"))

    # Fused-bucket size cap: fused groups split into buckets that pack /
    # walk / unpack as a 3-stage pipeline, so the cap trades per-walk
    # fixed cost (bigger buckets) against pack/unpack overlap (smaller
    # buckets start their walk sooner and unpack while the next bucket is
    # on the wire). Measured on the 2-core bench box: 8 MiB buckets pay
    # 12 walks' fixed cost for resnet50 and run 2x SLOWER than one big
    # bucket; 64 MiB is within noise of a single bucket while still
    # pipelining multi-hundred-MB sets (bert ~700 MB -> 11 buckets).
    # Part of the fused workspace name, so it MUST be cluster-agreed
    # like CHUNK_BYTES (which also rules out core-count scaling here).
    GROUP_BUCKET_BYTES = int(knobs.get("KF_CONFIG_GROUP_BUCKET_BYTES"))

    def group_all_reduce(self, ws: Sequence[Workspace]) -> None:
        """Allreduce of many workspaces as one windowed group op (parity:
        the reference reduces a whole gradient set per session.run —
        srcs/python/kungfu/tensorflow/v1/benchmarks). Fused buckets run
        through the 3-stage pipeline while the singles windows walk
        concurrently — neither waits for the other to finish."""
        if not ws:
            return
        with self._collected(
            "group_all_reduce", sum(w.recv.nbytes for w in ws)
        ), stall_detect(f"group_all_reduce[{len(ws)}]"):
            singles: List[Workspace] = []
            groups: Dict[tuple, List[Workspace]] = {}
            for w in ws:
                if w.is_empty:
                    continue
                groups.setdefault((w.send.dtype.str, int(w.op)), []).append(w)
            buckets: List[List[Workspace]] = []
            for members in groups.values():
                if len(members) < self.FUSE_MIN_TENSORS:
                    singles.extend(members)
                else:
                    buckets.extend(self._make_buckets(members))
            jobs: List[Callable[[], None]] = []
            # the group deadline scales with the number of walks it
            # covers — the serial predecessor allowed one self.timeout
            # PER fused walk / singles window, and a large healthy group
            # on a slow link must not trip a single flat budget
            windows = -(-len(singles) // self.GROUP_WINDOW)
            group_timeout = self.timeout * max(1, len(buckets) + windows)
            # shared cancel: a group-level timeout must also abort the
            # pipeline stages, or a lingering unpacker would keep writing
            # caller recv buffers after this call already raised (the
            # late-write hazard _par's contract exists to prevent)
            cancel = threading.Event()
            if buckets:
                jobs.append(
                    lambda: self._fused_pipeline(buckets, group_timeout, cancel)
                )
            if singles:
                jobs.append(lambda: self._singles_windows(singles, cancel))
            _par(jobs, group_timeout, cancel)

    def _make_buckets(
        self, members: List[Workspace]
    ) -> List[List[Workspace]]:
        """Greedy, order-preserving packing of same-(dtype, op)
        workspaces into <= GROUP_BUCKET_BYTES buckets. Derived only from
        the caller's tensor order and the byte cap, so every peer computes
        the same layout (the fused name encodes it); an oversized single
        tensor gets a bucket of its own."""
        buckets: List[List[Workspace]] = []
        cur: List[Workspace] = []
        cur_bytes = 0
        for w in members:
            if cur and cur_bytes + w.send.nbytes > self.GROUP_BUCKET_BYTES:
                buckets.append(cur)
                cur, cur_bytes = [], 0
            cur.append(w)
            cur_bytes += w.send.nbytes
        if cur:
            buckets.append(cur)
        return buckets

    def _singles_windows(
        self,
        singles: List[Workspace],
        cancel: Optional[threading.Event] = None,
    ) -> None:
        for i in range(0, len(singles), self.GROUP_WINDOW):
            if cancel is not None and cancel.is_set():
                # the group already raised (timeout, or a pipeline-stage
                # error that set the shared cancel): stop launching
                # windows, but return QUIETLY — raising here would race
                # the real error to _par's errs[0] and misreport a
                # deterministic failure as 'cancelled'
                return
            batch = singles[i : i + self.GROUP_WINDOW]
            _par(
                [lambda w=w: self._allreduce_ws(w, cancel) for w in batch],
                self.timeout,
                cancel,
            )

    def _pack_bucket(self, bi: int, members: List[Workspace],
                     name_prefix: str = ""):
        """Pack one bucket into pooled contiguous buffers. Workspace
        order is the caller's tensor order, identical on every peer, so
        the fused name and layout agree cluster-wide. `name_prefix`
        namespaces the fused rendezvous (the async scheduler stamps its
        round counter here so back-to-back rounds cannot collide).

        When the wire codec will compress this bucket, members are
        packed straight into ONE buffer that doubles as the walk's f32
        accumulator (an inplace workspace): all wire staging already
        happens in pooled 2-byte scratches inside the walk, so the
        second full-size f32 buffer (and its memcpy) of the raw path
        buys nothing. Inplace fused workspaces are valid on every walk
        path, so a mid-flight adaptive codec toggle stays correct."""
        dtype = members[0].send.dtype
        op = members[0].op
        total = sum(w.send.size for w in members)
        nbytes = total * dtype.itemsize
        pool = get_buffer_pool()
        single = (
            self._active_wire_mode() != "off"
            and dtype == np.float32
            and nbytes >= self.WIRE_MIN_BYTES
        )
        send_b = pool.get(nbytes)
        recv_b = None if single else pool.get(nbytes)
        with trace.span("host.fuse.pack"):
            send = np.frombuffer(send_b, dtype, total)
            recv = send if single else np.frombuffer(recv_b, dtype, total)
            off = 0
            for w in members:
                send[off : off + w.send.size] = w.send
                off += w.send.size
        fused = Workspace(
            send=send,
            recv=recv,
            op=op,
            name=f"{members[0].name}::fused:{name_prefix}"
                 f"b{bi}:{len(members)}x{total}",
        )
        return (fused, send_b, recv_b, members)

    def _unpack_bucket(self, item, abort: Optional[threading.Event] = None) -> None:
        fused, send_b, recv_b, members, deferred = item
        if abort is not None and abort.is_set():
            # KF703: the group/scheduler scope aborted while this bucket
            # was in flight — the member recv buffers may already be
            # reused by the caller that raised, so drop the bucket (its
            # pooled staging goes to GC, the pool's policy for buffers a
            # worker may still touch)
            return
        pool = get_buffer_pool()
        try:
            with trace.span("host.fuse.unpack"):
                off = 0
                if deferred is not None:
                    # fused decode+unpack: the compressed walk handed us
                    # its wire buffer instead of decoding into the fused
                    # recv first — one full f32 pass saved per bucket
                    for w in members:
                        deferred.decode_into(w.recv, off, off + w.recv.size)
                        off += w.recv.size
                else:
                    for w in members:
                        np.copyto(w.recv, fused.recv[off : off + w.recv.size])
                        off += w.recv.size
        finally:
            if deferred is not None:
                deferred.close()
            pool.put(send_b)
            if recv_b is not None:
                pool.put(recv_b)

    def _fused_pipeline(
        self,
        buckets: List[List[Workspace]],
        timeout: float,
        cancel: Optional[threading.Event] = None,
    ) -> None:
        """3-stage software pipeline over fused buckets: pack bucket i+1
        and unpack bucket i-1 while bucket i is on the wire. The serial
        predecessor (all packs, then all walks, then all unpacks per
        bucket) left the wire idle during every memcpy phase. Depth-1
        handoff queues bound live pooled buffers at 5 buckets (one per
        stage + one per queue) — x2 buffers x GROUP_BUCKET_BYTES, well
        under the serial path's single whole-group buffer pair for big
        sets. The queues are abort-aware HandoffQueues sharing one abort
        event, so any stage's failure (or a dropped sentinel after one)
        unblocks the other two and the REAL error propagates out of
        _par; aborted in-flight buffers are dropped to GC (the pool's
        documented policy for buffers a worker may still touch)."""
        # the caller's cancel event doubles as the abort flag: _par sets
        # it on timeout, so every stage (unpacker included) stops before
        # touching caller buffers again
        abort = cancel if cancel is not None else threading.Event()
        packed = HandoffQueue(maxsize=1, abort=abort)
        unpackq = HandoffQueue(maxsize=1, abort=abort)

        def packer():
            try:
                for bi, members in enumerate(buckets):
                    if abort.is_set():
                        return
                    if not packed.put(self._pack_bucket(bi, members)):
                        return
            except BaseException:
                abort.set()
                raise
            finally:
                packed.put(None)

        def walker():
            try:
                while True:
                    item = packed.get()
                    if item is None:
                        return
                    if abort.is_set():
                        continue  # drain to the sentinel
                    with trace.span("host.fuse.walk"):
                        # defer the codec's walk-end decode to the
                        # unpacker, which fuses it with the member
                        # scatter (an aborted in-flight wire buffer is
                        # dropped to GC like every other staging buffer)
                        deferred = self._allreduce_ws(
                            item[0], defer_decode=True
                        )
                    if not unpackq.put(item + (deferred,)):
                        return
            except BaseException:
                abort.set()
                raise
            finally:
                unpackq.put(None)

        def unpacker():
            try:
                while True:
                    item = unpackq.get()
                    if item is None:
                        return
                    if abort.is_set():
                        continue  # aborted: must not touch caller buffers
                    self._unpack_bucket(item, abort)
            except BaseException:
                abort.set()
                raise

        _par([packer, walker, unpacker], timeout, abort)
