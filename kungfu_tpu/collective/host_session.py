"""Host-side collective engine facade: one session per cluster epoch.

Capability parity: srcs/go/kungfu/session/session.go — an immutable
peer-list epoch running Barrier / Consensus / Reduce / Broadcast / Gather /
AllReduce by walking (reduce, bcast) graph pairs, with 1 MiB chunking
striped across multi-root strategies (runStrategies, session.go:301-330)
and SIMD reduction on receive (base.Transform2).

Role in the TPU build: this engine runs on HOSTS over DCN for control
collectives (consensus on cluster configs, barriers, progress sync) and for
CPU-only test clusters — the device data plane is XLA over ICI
(kungfu_tpu.ops). It is the direct replacement for the reference's
rchannel data plane.

Layering (ISSUE 10 refactor — this file is the facade, the engine lives
in sibling modules so the async scheduler composes instead of accretes):

- walks.py     — the walk engines (segmented ring, chunked graph walks)
  and shared receive/accounting plumbing (:class:`WalkEngine` mixin);
- codec.py     — wire-format policy: compress-or-bypass decisions,
  deferred decode (:class:`WireCodec` mixin);
- pipeline.py  — group fusion: deterministic bucketing and the 3-stage
  pack/walk/unpack pipeline (:class:`GroupFusion` mixin);
- profiler.py  — the process-global critical-path profiler and span
  sampler;
- scheduler.py — the async collective scheduler (per-session, lazily
  created; drives the same pack/walk/unpack stages by readiness order).

HostSession owns the per-epoch STATE (peers, strategies, adaptive
candidates, metric handles) and the public collective API; the mixins
own the mechanics.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional, Sequence, Tuple, TYPE_CHECKING

import numpy as np

from kungfu_tpu import knobs
from kungfu_tpu.base.ops import ReduceOp
from kungfu_tpu.base.strategy import Strategy
from kungfu_tpu.base.workspace import Workspace
from kungfu_tpu.collective import strategies as st
from kungfu_tpu.collective.adaptive import AdaptiveState
from kungfu_tpu.collective.codec import WIRE_MODES, WireCodec, wire_override
from kungfu_tpu.collective.pipeline import GroupFusion
from kungfu_tpu.collective.profiler import (  # noqa: F401 - back-compat re-exports
    SpanSampler,
    SpanSampler as _SpanSampler,
    WalkProfiler,
    get_walk_profiler,
)
from kungfu_tpu.collective.walks import (  # noqa: F401 - back-compat re-exports
    CHUNK_BYTES,
    DEFAULT_TIMEOUT,
    WalkEngine,
    algo_override,
    choose_chunk_bytes,
    _buf,
)
from kungfu_tpu.plan import replan as rp
from kungfu_tpu.plan import topology as topo
from kungfu_tpu.plan.graph import Graph
from kungfu_tpu.plan.peer import PeerID, PeerList
from kungfu_tpu.telemetry import config as tconfig
from kungfu_tpu.telemetry import link as tlink
from kungfu_tpu.telemetry import metrics as tmetrics
from kungfu_tpu.transport.client import Client
from kungfu_tpu.transport.handlers import CollectiveEndpoint
from kungfu_tpu.transport.message import ConnType
from kungfu_tpu.utils import trace
from kungfu_tpu.utils.handoff import parallel_run as _par
from kungfu_tpu.utils.stall import stall_detect

if TYPE_CHECKING:
    from kungfu_tpu.collective.scheduler import CollectiveScheduler


class _CollectiveScope:
    """Span + latency-histogram wrapper around one public collective
    (plain classes end to end — tracing._Span underneath is also
    class-based — so the per-call telemetry cost stays at two clock
    reads, a deque append and an optional histogram observe)."""

    __slots__ = ("_sess", "_kind", "_span", "_t0", "_prev_kind")

    def __init__(self, sess: "HostSession", kind: str, nbytes: int):
        self._sess = sess
        self._kind = kind
        self._span = trace.span(
            f"collective.{kind}", bytes=int(nbytes), size=sess.size
        )

    def __enter__(self):
        self._t0 = time.perf_counter()
        # label wire-byte counts with the public collective that caused
        # them (walks run on pool threads, so this lives on the session;
        # rare concurrent collectives of different kinds may cross-label
        # a few bytes, which accounting tolerates)
        self._prev_kind = self._sess._wire_kind
        self._sess._wire_kind = self._kind
        self._span.__enter__()
        return self

    def __exit__(self, *exc):
        self._span.__exit__(*exc)
        self._sess._wire_kind = self._prev_kind
        hist = self._sess._coll_hist
        if hist is not None:
            hist.labels(self._kind).observe(time.perf_counter() - self._t0)
        return False


class HostSession(WalkEngine, WireCodec, GroupFusion):
    """One collective epoch over a fixed PeerList."""

    def __init__(
        self,
        strategy: Strategy,
        self_id: PeerID,
        peers: PeerList,
        client: Client,
        endpoint: CollectiveEndpoint,
        timeout: float = DEFAULT_TIMEOUT,
        cluster_version: int = 0,
    ):
        rank = peers.rank(self_id)
        if rank is None:
            raise ValueError(f"{self_id} not in peer list {peers}")
        self.self_id = self_id
        # the elastic cluster version this epoch serves (peer.py passes
        # it; 0 for bare sessions) — the step plane's session_epoch
        # stamp, identical on every peer of the epoch by construction
        self.cluster_version = int(cluster_version)
        self.peers = peers
        self.rank = rank
        self.local_rank = peers.local_rank(self_id)
        self.local_size = peers.local_size(self_id)
        self.host_count = peers.host_count()
        self.client = client
        self.endpoint = endpoint
        self.timeout = timeout
        forced = algo_override()
        if forced is not None:
            strategy = forced
        if strategy == Strategy.AUTO:
            strategy = st.auto_select(peers)
        self.strategy = strategy
        self.global_strategies = st.gen_global_strategies(peers, strategy)
        self.local_strategies = st.gen_local_strategies(peers)
        self.cross_strategies = st.gen_cross_strategies(peers, strategy)
        # ring order for the cross-host segmented walk (hierarchical mode)
        self._masters, _ = peers.partition_by_host()
        # per-root star graph cache (satellite: reduce/broadcast with
        # root != 0 regenerated star + default-reduce on every call);
        # sessions are rebuilt each epoch, so invalidation is automatic
        self._root_graphs: Dict[int, Tuple[Graph, Graph]] = {}
        # wire codec knob: resolved once per session epoch like the
        # strategy; the ACTIVE codec can differ when adaptation toggles it
        self.wire_mode = wire_override()
        # async scheduler knob: resolved once per session epoch; the
        # scheduler itself is created lazily on first use (most sessions
        # — control planes, tests — never submit asynchronously)
        self.async_mode = knobs.get("KF_CONFIG_ASYNC")
        # measured-topology re-planning knob (ISSUE 14): resolved once
        # per epoch like the other engine modes; the ADOPTED plan (ring
        # order + segment weights) starts naive and changes only through
        # the lockstep check_replan/adopt_replan rounds below. Cluster-
        # agreed — every peer must run the same re-plan rounds and the
        # plan decides every segmented walk's bounds.
        self.replan_mode = knobs.get("KF_CONFIG_REPLAN")
        self._ring_plan: Optional[rp.RingPlan] = None
        # two-level plan state (ISSUE 19): the adopted HierPlan (None =
        # flat), the cluster-agreed demoted set it carries, and the
        # demotion patience every peer must share (it gates the lockstep
        # demote rounds, so it rides the knob consensus)
        self._hier_plan: Optional[rp.HierPlan] = None
        self._demoted: Tuple[int, ...] = ()
        self.demote_patience = int(knobs.get("KF_REPLAN_DEMOTE_PATIENCE"))
        self._replan_seq = 0
        self._replan_listeners: List[object] = []
        # ZeRO-1 sharded-update knob (ISSUE 11): resolved once per epoch
        # like the strategy/wire/async modes; consulted by the frontends
        # (ShardedUpdateSession, torch ZeroSGDOptimizer, api helpers) to
        # pick sharded vs replicated updates. Cluster-agreed — it decides
        # the step's whole rendezvous dataflow (zrs/zag names vs fused
        # allreduce names), so it rides the knob consensus.
        self.zero_mode = knobs.get("KF_CONFIG_ZERO")
        self._scheduler: Optional["CollectiveScheduler"] = None
        self._scheduler_lock = threading.Lock()
        self._epoch_closed = False
        # adaptive control (parity: session/adaptiveStrategies.go): a
        # deterministic candidate order — identical on every peer — so a
        # majority vote can advance everyone in lockstep. Candidates are
        # (strategy, wire-mode) pairs: the first alternate toggles the
        # CODEC on the same graphs (the cheapest lever against a
        # congested/interfered link — half or restore the wire bytes
        # without re-pairing anyone), then the strategy alternates walk
        # under the configured codec, RING_SEGMENTED first so votes can
        # switch ONTO the bandwidth-optimal member (and off it, by
        # advancing again). Candidate graph lists are built lazily:
        # sessions are rebuilt every elastic epoch and most never adapt.
        wire_toggled = "off" if self.wire_mode != "off" else "bf16"
        self._candidates: List[Tuple[Strategy, str]] = (
            [(strategy, self.wire_mode), (strategy, wire_toggled)]
            + [
                (s, self.wire_mode) for s in (
                    Strategy.RING_SEGMENTED, Strategy.RING,
                    Strategy.BINARY_TREE_STAR, Strategy.STAR, Strategy.CLIQUE,
                ) if s != strategy
            ]
        )
        self._candidates_built: dict = {0: self.global_strategies, 1: self.global_strategies}
        self.adaptive = AdaptiveState(
            len(self._candidates),
            names=[f"{s.name}/{wm}" for s, wm in self._candidates],
        )
        self._tree_override = False
        # per-collective latency histogram (telemetry): one observe per
        # COLLECTIVE call (not per message), gated off with the rest of
        # the metrics so the steady-state walk stays untouched
        self._coll_hist = (
            tmetrics.histogram(
                "kungfu_collective_latency_seconds",
                "Host-plane collective latency by kind",
                ("collective",),
            )
            if tconfig.metrics_enabled()
            else None
        )
        # wire-byte accounting: bytes this peer SENDS into collective
        # walks, by (public collective, executing strategy, wire codec).
        # This is the counter the segmented engine's bandwidth-optimality
        # claim is asserted against (tests) and the A/B bench reports;
        # the codec dimension separates compressed from raw traffic.
        self._wire_ctr = (
            tmetrics.counter(
                "kungfu_collective_wire_bytes_total",
                "Host-plane collective payload bytes sent by this peer",
                ("collective", "strategy", "codec"),
            )
            if tconfig.metrics_enabled()
            else None
        )
        # bytes the codec kept OFF the wire: raw payload minus encoded
        # payload, summed over every compressed send
        self._wire_saved_ctr = (
            tmetrics.counter(
                "kungfu_collective_wire_saved_bytes_total",
                "Wire bytes saved by the collective codec on this peer",
                ("collective", "codec"),
            )
            if tconfig.metrics_enabled()
            else None
        )
        self._wire_kind = "raw"
        # audit dedup for codec bypasses: one event per (reason, dtype)
        # per session epoch, so consensus lanes don't flood the audit log
        self._codec_bypass_seen: set = set()
        # error-feedback residual store of the quantized wire codec
        # (ISSUE 20): per-workspace f32 remainders, flushed on wire-mode
        # changes and re-plan adoption (see WireCodec._flush_residuals);
        # dies with the session on elastic resize — deterministically
        # zero on every peer of the new epoch
        self._ef_store: Dict[str, np.ndarray] = {}
        self._ef_mode: Optional[str] = None
        self._ef_flush_listeners: List[object] = []
        self._unknown_wire_warned: set = set()
        # monotone count of adopted precision flips: names the vote
        # workspaces and stamps the consensus digest of each switch
        self._precision_flips = 0
        # link plane + walk profiler (ISSUE 6): the local link table
        # supplies per-destination bandwidth estimates the profiler
        # scores walks against; the sampler thins per-step spans
        self._links = tlink.get_table() if tlink.enabled() else None
        self._span_sampler = SpanSampler(tconfig.span_sample())
        # graph-fallback audit dedup (ISSUE 14 satellite): while
        # RING_SEGMENTED is active, non-allreduce graph consumers and
        # sub-threshold payloads run the rank-0 binary-tree pair — by
        # design, but previously silent. One audit event per session
        # epoch names the fallback the first time it executes.
        self._segmented_fallback_noted = False
        self._in_fixed_walk = False
        # active-ring observability (ISSUE 14): this peer's position in
        # the current ring order and its successor, exported so the
        # cluster aggregator can reconstruct (and `info links` render)
        # the ACTIVE ring next to the measured matrix
        if tconfig.metrics_enabled():
            self._ring_pos_g = tmetrics.gauge(
                "kungfu_topology_ring_position",
                "This peer's position in the active segmented-ring order "
                "(0-based; equals rank until a measured re-plan lands)",
            )
            self._ring_next_g = tmetrics.gauge(
                "kungfu_topology_ring_next",
                "The active ring successor of this peer (child per dst, "
                "value 1) — the edge every segmented send crosses",
                ("dst",),
            )
            self._replans_ctr = tmetrics.counter(
                "kungfu_topology_replans_total",
                "Measured-topology re-plans adopted by this peer's "
                "session epochs",
            )
            # two-level plan role (ISSUE 19): (level, role) of this peer
            # in the active hierarchy — level `flat` (no hierarchy) or
            # `intra`/`inter` (member vs elected head of the inter-host
            # ring), role `member`/`head`/`demoted`; the VALUE is the
            # peer's host-group index, so the aggregator can reconstruct
            # the full hierarchy like it does the flat ring
            self._ring_role_g = tmetrics.gauge(
                "kungfu_topology_ring_role",
                "Active two-level plan role of this peer (child per "
                "(level, role), value = host-group index)",
                ("level", "role"),
            )
            # active wire precision (ISSUE 20): the RUNNING codec mode
            # (config + lockstep precision/interference votes), exported
            # so `info links` can render what payloads actually cross
            # the transport as
            self._wire_mode_g = tmetrics.gauge(
                "kungfu_collective_wire_mode",
                "Active wire-codec mode of this peer's collective "
                "session (child per mode, value 1 on the running one)",
                ("mode",),
            )
        else:
            self._ring_pos_g = self._ring_next_g = self._replans_ctr = None
            self._ring_role_g = None
            self._wire_mode_g = None
        self._publish_ring_metrics()
        # collective-order sentinel (ISSUE 12): with the debug knob set,
        # protowatch wraps this instance's public entry points at bind
        # time. Unset = the module is never imported and the methods stay
        # the plain class functions — zero hot-path cost (asserted by
        # tests/test_protowatch.py, like lockwatch)
        self._protowatch = None
        if knobs.get("KF_DEBUG_PROTOCOL"):
            from kungfu_tpu.devtools import protowatch

            protowatch.attach(self)

    def _candidate(self, idx: int) -> List[st.StrategyPair]:
        if idx not in self._candidates_built:
            self._candidates_built[idx] = st.gen_global_strategies(
                self.peers, self._candidates[idx][0]
            )
        return self._candidates_built[idx]

    @property
    def size(self) -> int:
        return len(self.peers)

    # ------------------------------------------------------------------
    # async scheduler (ISSUE 10 tentpole)
    # ------------------------------------------------------------------

    def async_enabled(self) -> bool:
        """Whether this epoch runs asynchronous group collectives.
        `auto` resolves to on for multi-peer sessions (a cluster of one
        has nothing to overlap). Cluster-agreed — the mode decides the
        fused rendezvous names, so it rides the knob consensus."""
        if self.async_mode == "on":
            return True
        if self.async_mode == "auto":
            return self.size >= 2
        return False

    def zero_enabled(self) -> bool:
        """Whether this epoch runs the ZeRO-1 sharded weight update
        (ISSUE 11). `auto` resolves to on for multi-peer sessions (a
        cluster of one has nothing to shard). Cluster-agreed — the mode
        decides the step's rendezvous dataflow, so it rides the knob
        consensus like KF_CONFIG_ASYNC.

        The memory plane (ISSUE 17) is CONSULTED here but deliberately
        cannot flip the resolution: `engine_knobs()` carries the mode
        string, not the resolved boolean, so two peers resolving
        `auto` differently from their own live RSS would sail through
        the consensus check and deadlock on mismatched rendezvous
        dataflow. The consult is therefore advisory — when `auto`
        resolves OFF (single peer) while this worker's measured
        headroom sits at/below the pressure line, it logs that sharding
        would have relieved the replicated optimizer state — and the
        BEHAVIOURAL consumer of measured headroom is the rank-0-local
        elastic grow gate (elastic/schedule.py), where a single
        decision maker is safe."""
        if self.zero_mode == "on":
            return True
        if self.zero_mode == "auto":
            on = self.size >= 2
            if not on and not getattr(self, "_zero_mem_advised", False):
                self._zero_mem_advised = True  # one advisory per session
                try:
                    from kungfu_tpu.telemetry import log
                    from kungfu_tpu.telemetry import memory as tmem

                    sig = tmem.get_plane().signals()
                    if sig.get("memory/pressure"):
                        log.warn(
                            "zero=auto resolved off (single peer) under "
                            "measured memory pressure (headroom %.0f%%): "
                            "replicated optimizer state is a candidate — "
                            "grow the cluster or set KF_CONFIG_ZERO=on "
                            "fleet-wide",
                            100.0 * float(sig.get("memory/headroom_frac", 0)),
                        )
                # kfcheck: disable=KF400 — advisory log only; a failed
                # headroom read must never block auto resolution
                except Exception:  # noqa: BLE001
                    pass
            return on
        return False

    def scheduler(self) -> "CollectiveScheduler":
        """The session's async collective scheduler, created on first
        use. Lives exactly as long as the session epoch: Peer._update_to
        calls :meth:`close` (drain) before replacing the session."""
        with self._scheduler_lock:
            if self._scheduler is None:
                from kungfu_tpu.collective.scheduler import (
                    CollectiveScheduler,
                    SchedulerClosed,
                )

                if self._epoch_closed:
                    # a resize already ended this epoch: a fresh
                    # scheduler here would walk against a fenced
                    # transport token — the caller must re-fetch the
                    # CURRENT session
                    raise SchedulerClosed(
                        "session epoch closed — fetch the current "
                        "session's scheduler"
                    )
                self._scheduler = CollectiveScheduler(self)
                if self._protowatch is not None:
                    from kungfu_tpu.devtools import protowatch

                    protowatch.attach_scheduler(self._scheduler)
            return self._scheduler

    def close(self, timeout: Optional[float] = None) -> None:
        """End-of-epoch teardown: drain or cancel the async scheduler's
        in-flight buckets so nothing from this epoch keeps walking (or
        writing caller buffers) once the next session exists."""
        with self._scheduler_lock:
            sched = self._scheduler
            self._scheduler = None
            self._epoch_closed = True
        if sched is not None:
            sched.close(timeout=self.timeout if timeout is None else timeout)

    def _collected(self, kind: str, nbytes: int):
        """Telemetry wrapper for one public collective: a named span
        (feeding /trace) plus a latency-histogram observation when
        metrics are on. Returns a context manager."""
        return _CollectiveScope(self, kind, nbytes)

    # ------------------------------------------------------------------
    # public collectives
    # ------------------------------------------------------------------

    def all_reduce(self, w: Workspace) -> None:
        with self._collected("all_reduce", w.recv.nbytes):
            with stall_detect(f"all_reduce({w.name})"):
                self._allreduce_ws(w)

    def reduce_scatter(
        self, w: Workspace, cancel: Optional[threading.Event] = None
    ) -> Tuple[int, int]:
        """First-class reduce-scatter half of the segmented ring walk
        (ISSUE 11): after it, ``w.recv`` holds the fully reduced OWNED
        segment — whose (begin, end) element bounds are returned — and
        partially reduced garbage elsewhere. The layout is
        :meth:`owned_bounds`: contiguous ``segment_bounds`` slices under
        the CURRENT ring plan (equal, or throughput-weighted after a
        measured re-plan — ISSUE 14), identical on every peer without
        negotiation. Always raw f32-exact ((k-1)/k·N bytes per peer);
        k == 1 (and empty payloads) degrade to ``forward()`` with the
        whole array owned. Runs the ring regardless of payload size —
        an explicit RS is a deliberate choice, not a heuristic."""
        with self._collected("reduce_scatter", w.recv.nbytes):
            with stall_detect(f"reduce_scatter({w.name})"):
                self._run_segmented(w, cancel=cancel, phase="rs")
        return self.owned_bounds(w.recv.size)

    def all_gather_shards(
        self,
        full: np.ndarray,
        name: str,
        cancel: Optional[threading.Event] = None,
        allow_wire: bool = True,
        ef: Optional[np.ndarray] = None,
    ) -> None:
        """Standalone segment all-gather (ISSUE 11): the caller placed
        this rank's shard into ``full``'s owned segment
        (``topo.owned_segment_bounds``); the walk relays every segment
        around the ring until ``full`` is complete and identical on all
        peers. The inverse of :meth:`reduce_scatter` — rs + this ==
        all_reduce, bit for bit.

        With the wire codec active (and ``allow_wire``) eligible f32
        payloads cross the transport in the wire dtype — (k-1)/k·N/2
        bytes per peer — with each segment quantized exactly once by its
        owner and decoded once per peer at walk end, so every peer
        (owner included) lands on bit-identical values; see
        docs/collectives.md for the error model.

        ``ef`` (quantized modes only): a caller-owned f32 error-feedback
        residual sized to THIS RANK's owned segment — the send quantizes
        shard+residual and the new residual is written back in place.
        Callers whose shards outlive the walk name (ZeRO's round-stamped
        gathers) pass their per-shard buffer here instead of relying on
        the session's name-keyed store."""
        ws = Workspace(send=full, recv=full, op=ReduceOp.SUM, name=name)
        wire = self._wire_codec_for(ws) if allow_wire else None
        with self._collected("all_gather", full.nbytes):
            with stall_detect(f"all_gather({name})"):
                self._run_segmented(ws, cancel=cancel, wire=wire, phase="ag",
                                    ef_owned=ef)

    def monitored_all_reduce(self, w: Workspace) -> None:
        """AllReduce + throughput accounting for the ACTIVE strategy
        (parity: KungfuMonitoredAllReduce, ops/cpu/collective.cpp:149-196 +
        runMonitoredStrategies, session/monitoring.go:15-35).

        Runs the active candidate's wire format like all_reduce — this
        is the ONLY site feeding adaptive.current, so it MUST measure
        what the candidate actually does or codec candidates would
        accumulate raw-walk stats and interference votes could never
        observe compression. Probe-style traffic keeps exact semantics
        through the codec's own gates: non-f32 lanes and payloads under
        WIRE_MIN_BYTES always bypass (audited), and the gradient-
        variance/noise-scale monitors are on-device psums that never
        touch the host plane at all."""
        nbytes = w.recv.size * w.recv.itemsize
        t0 = time.perf_counter()
        with self._collected("monitored_all_reduce", nbytes):
            with stall_detect(f"monitored_all_reduce({w.name})"):
                self._allreduce_ws(w)
        self.adaptive.current.update(nbytes, time.perf_counter() - t0)

    def check_interference(self, vote_tag: str = "") -> bool:
        """Majority vote on local interference suspicion; on a cluster-wide
        majority every peer advances to the next candidate strategy in the
        same deterministic order. Returns True if the strategy switched.
        Parity: CheckInterference + MonitoredAllReduce consensus switch
        (session/adaptiveStrategies.go:61-121).

        Call this at a step boundary. With the async scheduler active
        the switch lands at a bucket boundary by construction: walks are
        launched one at a time from the scheduler thread and re-read the
        active candidate per workspace, and the flush() barrier that
        ends every round means no bucket of the PREVIOUS round is still
        in flight when the vote's allreduce runs."""
        if self._tree_override or len(self._candidates) < 2:
            return False
        suspect = self.adaptive.current.suspect_interference()
        votes_in = np.array([1 if suspect else 0], np.int32)
        votes_out = np.zeros(1, np.int32)
        self.all_reduce(
            Workspace(votes_in, votes_out, ReduceOp.SUM,
                      f"kungfu::interference:{self.adaptive.switch_count}{vote_tag}")
        )
        if int(votes_out[0]) * 2 <= self.size:
            return False
        old_strategy, old_wire = self._candidates[self.adaptive.active]
        idx = self.adaptive.advance()
        self.global_strategies = self._candidate(idx)
        new_strategy, new_wire = self._candidates[idx]
        # safety: all peers must now run the same graphs AND wire format
        # (a codec split would desync every message size in the walk)
        if not self.bytes_consensus(
            st.digest(self.global_strategies) + new_wire.encode(),
            f":switch:{self.adaptive.switch_count}",
        ):
            raise RuntimeError("strategy switch diverged across peers")
        self._publish_wire_mode()
        from kungfu_tpu.telemetry import audit as _audit

        _audit.record_event(
            "strategy_switch",
            peer=str(self.self_id),
            trigger="interference_vote",
            old_strategy=old_strategy.name,
            new_strategy=new_strategy.name,
            old_wire=old_wire,
            new_wire=new_wire,
            switch_count=self.adaptive.switch_count,
        )
        # decision ledger (ISSUE 15): open the causal record the moment
        # the switch lands — the paired step windows around this point
        # close it with a realized gain and verdict
        from kungfu_tpu.telemetry import decisions as _decisions

        _decisions.open_decision(
            "strategy_switch",
            peer=str(self.self_id),
            epoch=self.cluster_version,
            trigger="interference_vote",
            signals={"votes": int(votes_out[0]), "size": self.size},
            old=f"{old_strategy.name}/{old_wire}",
            new=f"{new_strategy.name}/{new_wire}",
        )
        return True

    def check_precision(
        self,
        proposal: Optional[str] = None,
        trigger: str = "noise_scale",
        signals: Optional[dict] = None,
        vote_tag: str = "",
    ) -> Optional[str]:
        """Majority vote on the wire PRECISION of the active candidate
        (ISSUE 20): every peer calls in lockstep at a step boundary with
        its locally preferred mode (``proposal``; None votes to keep the
        current one), ballots are one-hot over :data:`WIRE_MODES`, and a
        strict cluster majority for a different mode flips the active
        candidate's wire member on EVERY peer — same graphs, new codec.
        Returns the new mode, or None when nothing changed.

        The flip is digest-checked like a strategy switch (a codec split
        would desync every message size in the walk), flushes the
        error-feedback residual store (residuals measure the OLD codec's
        rounding), and opens a ``precision_switch`` decision-ledger
        record so a throughput- or accuracy-hostile downshift closes
        ``regressed`` and the precision policy votes itself back."""
        if proposal is not None and proposal not in WIRE_MODES:
            raise ValueError(
                f"check_precision: unknown wire mode {proposal!r}; "
                f"expected one of {', '.join(WIRE_MODES)}"
            )
        old_mode = self._active_wire_mode()
        want = proposal if proposal is not None else old_mode
        votes_in = np.zeros(len(WIRE_MODES), np.int32)
        votes_in[WIRE_MODES.index(want)] = 1
        votes_out = np.zeros(len(WIRE_MODES), np.int32)
        self.all_reduce(
            Workspace(votes_in, votes_out, ReduceOp.SUM,
                      f"kungfu::precision:{self._precision_flips}{vote_tag}")
        )
        winner = None
        for i, mode in enumerate(WIRE_MODES):
            if mode != old_mode and int(votes_out[i]) * 2 > self.size:
                winner = mode
                break
        if winner is None:
            return None
        self._precision_flips += 1
        if self._tree_override:
            self.wire_mode = winner
        else:
            strategy = self._candidates[self.adaptive.active][0]
            self._candidates[self.adaptive.active] = (strategy, winner)
        # safety: every peer must now frame messages in the same codec
        if not self.bytes_consensus(
            winner.encode(),
            f":precision:{self._precision_flips}",
        ):
            raise RuntimeError("precision switch diverged across peers")
        self._flush_residuals(f"precision vote {old_mode!r} -> {winner!r}")
        self._publish_wire_mode()
        from kungfu_tpu.telemetry import audit as _audit

        _audit.record_event(
            "precision_switch",
            peer=str(self.self_id),
            trigger=trigger,
            old_wire=old_mode,
            new_wire=winner,
            flip_count=self._precision_flips,
        )
        from kungfu_tpu.telemetry import decisions as _decisions

        _decisions.open_decision(
            "precision_switch",
            peer=str(self.self_id),
            epoch=self.cluster_version,
            trigger=trigger,
            signals=dict(signals or {},
                         votes=int(votes_out[WIRE_MODES.index(winner)]),
                         size=self.size),
            old=old_mode,
            new=winner,
        )
        return winner

    def active_strategy(self) -> Optional[Strategy]:
        """The running candidate strategy, or None when an explicit
        set_tree forest overrides the candidates. This is the Strategy-
        typed accessor; the operator-facing codec-qualified name lives
        in :meth:`active_candidate_name` (ISSUE 10 satellite — the two
        contracts used to be conflated at the api layer)."""
        if self._tree_override:
            return None
        return self._candidates[self.adaptive.active][0]

    def active_candidate_name(self) -> str:
        """Display name of the running adaptive candidate: the strategy,
        suffixed with "/<codec>" when a wire codec is active (an
        interference vote may have toggled compression rather than the
        graphs); "SET_TREE" under a set_tree override."""
        s = self.active_strategy()
        if s is None:
            return "SET_TREE"
        wire = self._active_wire_mode()
        return s.name if wire == "off" else f"{s.name}/{wire}"

    def set_tree(self, fathers: Sequence[int]) -> None:
        """Install a runtime forest (e.g. an MST over probed latencies) as
        the active global strategy (parity: SetTree / SetGlobalStrategy,
        adaptation.cpp:5-33). Disables vote-driven switching — an explicit
        tree wins until the next session epoch.

        The installed forest must be a single tree rooted at rank 0:
        gather/reduce/broadcast walk global_strategies[0] assuming its root
        is rank 0, so a forest rooted elsewhere (or with several roots)
        would silently produce wrong data. Per-component forests are still
        available via subset_all_reduce/all_reduce_with."""
        if len(fathers) != self.size:
            raise ValueError(f"forest size {len(fathers)} != cluster {self.size}")
        roots = [r for r, f in enumerate(fathers) if int(f) == r]
        if roots != [0]:
            raise ValueError(
                f"set_tree forest must be one tree rooted at rank 0, got roots {roots}"
            )
        self.global_strategies = st.from_forest_array(list(fathers))
        self._tree_override = True

    def calc_stats(self) -> dict:
        """Per-strategy throughput summary (parity: CalcStats/LogStats)."""
        return self.adaptive.summary()

    # ------------------------------------------------------------------
    # measured-topology re-planning (ISSUE 14)
    # ------------------------------------------------------------------

    def ring_plan(self) -> Optional[rp.RingPlan]:
        """The adopted measured-topology plan, or None for the naive
        rank-order ring with equal segments. Under a two-level plan
        this is its FLAT projection (``HierPlan.as_ring_plan``) — the
        single layout every flat consumer (ZeRO shard bounds, ring
        gauges, the segmented RS/AG legs) keeps reading unchanged."""
        return self._ring_plan

    def hier_plan(self) -> Optional[rp.HierPlan]:
        """The adopted two-level plan (ISSUE 19), or None when the
        session runs a flat ring."""
        return self._hier_plan

    def demoted_peers(self) -> Tuple[int, ...]:
        """Ranks currently voted into the demoted (backup) role."""
        return self._demoted

    def _static_hosts(self) -> List[List[int]]:
        """The static host partition as rank groups — the clustering
        fallback when the measured matrix is not bimodal enough to
        derive host boundaries."""
        _, master_of = self.peers.partition_by_host()
        groups: Dict[int, List[int]] = {}
        for r in range(self.size):
            groups.setdefault(master_of[r], []).append(r)
        return [sorted(g) for _, g in sorted(groups.items())]

    def owned_bounds(self, count: int) -> Tuple[int, int]:
        """(begin, end) bounds of the segment THIS rank owns fully
        reduced after a reduce-scatter of ``count`` elements, under the
        CURRENT ring plan — the single layout source the walk engine,
        the ZeRO-1 shard views and the api helpers all read, so a plan
        change re-shards every consumer through one function."""
        plan = self._ring_plan
        if plan is None:
            return topo.owned_segment_bounds(count, self.size, self.rank)
        return topo.owned_segment_bounds(
            count, self.size, self.rank,
            order=plan.order, weights=plan.weights,
        )

    def add_replan_listener(self, listener) -> None:
        """Register an object with ``pre_replan() -> token`` /
        ``post_replan(token)`` hooks, invoked around every plan adoption
        (the ZeRO-1 session registers itself: pre exports exact state
        under the OLD shard layout, post re-shards under the new)."""
        self._replan_listeners.append(listener)

    def _replan_name(self, kind: str) -> str:
        """Round-stamped rendezvous name for the lockstep re-plan
        rounds (KF700 discipline: version + per-epoch sequence — every
        member runs these rounds in lockstep, so the stamp agrees
        cluster-wide and repeats can never cross-consume lanes)."""
        return f"kungfu::replan:{kind}:v{self.cluster_version}:{self._replan_seq}"

    def measured_matrix(self) -> "np.ndarray":
        """Exchange every peer's outgoing link-table row and return the
        merged k×k bandwidth matrix (bytes/sec; 0 = no estimate),
        identical bytes on every peer BY CONSTRUCTION: one gather to
        rank 0 + one broadcast of the concatenation (``all_gather``),
        so the plan derivation downstream is a pure function of shared
        input — the version-skew a scraped /cluster/links snapshot
        would reintroduce cannot exist here. Collective: call in
        lockstep on every peer."""
        k = self.size
        row = np.zeros(k, np.float32)
        if self._links is not None:
            for j, pid in enumerate(self.peers):
                if j == self.rank:
                    continue
                bw = self._links.bandwidth(pid)
                if bw is not None and bw > 0:
                    row[j] = np.float32(bw)
        out = np.zeros(k * k, np.float32)
        self.all_gather(Workspace(
            send=row, recv=out, op=ReduceOp.SUM,
            name=self._replan_name("mx"),
        ))
        return out.reshape(k, k).astype(np.float64)

    def measured_compute_frac(self) -> float:
        """All-gather each peer's measured window CPU fraction (the
        resource plane's compute floor, ISSUE 16) and return the
        cluster MAX — identical bytes on every peer by construction,
        like :meth:`measured_matrix`, so ``derive_plan``'s Amdahl clamp
        stays a pure function of shared input. 0.0 when nobody has a
        measurement (no clamp: missing data must never fabricate
        pessimism). Collective: call in lockstep on every peer."""
        k = self.size
        mine = 0.0
        try:
            from kungfu_tpu.telemetry import resource as _tres

            mine = max(0.0, min(1.0, _tres.get_plane().compute_frac()))
        # kfcheck: disable=KF400 — an unmeasurable local floor must
        # degrade to 0.0 (no clamp), never kill the re-plan round; every
        # peer still runs the same all_gather below so the protocol
        # stays lockstep
        except Exception:  # noqa: BLE001
            pass
        send = np.array([np.float32(mine)], np.float32)
        out = np.zeros(k, np.float32)
        self.all_gather(Workspace(
            send=send, recv=out, op=ReduceOp.SUM,
            name=self._replan_name("cf"),
        ))
        return round(float(out.max()), 6)

    def check_replan(
        self, want: bool = True, min_gain: float = 1.05, tag: str = ""
    ) -> Optional[rp.RingPlan]:
        """One lockstep re-plan round (ISSUE 14): call on EVERY peer at
        the same step boundary (the :class:`~kungfu_tpu.policy
        .ReplanPolicy` gates on the step counter). Mirrors the
        interference vote's shape:

        1. majority vote over each peer's local ``want`` (its signal
           window: a persistent ``links/slowest_edge`` or
           ``step/critical_edge``);
        2. on a majority, exchange the measured link rows
           (:meth:`measured_matrix`) — every peer now holds identical
           matrix bytes;
        3. derive the plan (``plan.replan.derive_plan`` — pure function
           of the matrix, so every peer derives the identical plan) and
           adopt it via :meth:`adopt_replan` when the predicted gain
           clears ``min_gain``.

        Returns the adopted plan, or None (no majority / no measurable
        win / mode off). ``KF_CONFIG_REPLAN`` is consensus-checked at
        session start, so either every peer runs these rounds or none
        does — a half-configured fleet fails fast at epoch start, not
        here."""
        if (
            self.replan_mode == "off"
            or self.size < 2
            or self._tree_override
        ):
            return None
        votes_in = np.array([1 if want else 0], np.int32)
        votes_out = np.zeros(1, np.int32)
        self._fixed_allreduce(Workspace(
            votes_in, votes_out, ReduceOp.SUM,
            self._replan_name("vote") + tag,
        ))
        if int(votes_out[0]) * 2 <= self.size:
            self._replan_seq += 1
            return None
        matrix = self.measured_matrix()
        # the measured compute floor (ISSUE 16): a ring re-order only
        # shrinks the network share of the step, so the predicted gain
        # is clamped by the busiest peer's CPU fraction — gathered like
        # the matrix so every peer clamps by the identical scalar
        compute_frac = self.measured_compute_frac()
        if self.replan_mode == "hier":
            # two-level mode (ISSUE 19): derive the hierarchy from the
            # shared matrix; on a single host group (nothing to nest)
            # fall back to the flat measured ring — same pure-function
            # contract, every peer takes the same branch
            hier = rp.derive_hier_plan(
                matrix, hosts=self._static_hosts(), mode=self.replan_mode,
                current=self._hier_plan, compute_frac=compute_frac,
                demoted=self._demoted,
            )
            if hier is not None:
                if not self._hier_worthwhile(hier, min_gain):
                    self._replan_seq += 1
                    return None
                self.adopt_replan(hier)
                return self._ring_plan
            if self._hier_plan is not None:
                # current hierarchy still the best derivation: keep it
                # (a flat fallback here would silently tear it down)
                self._replan_seq += 1
                return None
            plan = rp.derive_plan(
                matrix, mode="auto", current=self._ring_plan,
                compute_frac=compute_frac,
            )
        else:
            plan = rp.derive_plan(
                matrix, mode=self.replan_mode, current=self._ring_plan,
                compute_frac=compute_frac,
            )
        if plan is None or not self._replan_worthwhile(plan, min_gain):
            # nothing derivable, or the predicted win doesn't clear the
            # bar — seq still advances (every peer took the same branch:
            # the decision is a pure function of the shared matrix)
            self._replan_seq += 1
            return None
        self.adopt_replan(plan)
        return plan

    def _hier_worthwhile(self, plan: rp.HierPlan, min_gain: float) -> bool:
        """Churn gate for two-level derivations, pure like
        `_replan_worthwhile`: the FIRST hierarchy (or any change to the
        demoted set) is structural and always adopted — demotions are
        voted deliberately and their win is graded by the ledger, not
        predicted — while a re-derivation that merely reshuffles groups
        or heads must clear ``min_gain``."""
        cur = self._hier_plan
        if cur is None or plan.demoted != cur.demoted:
            return True
        return plan.gain >= min_gain

    def check_demote(
        self,
        demote: Optional[int] = None,
        promote: Optional[int] = None,
        tag: str = "",
    ) -> Optional[rp.RingPlan]:
        """One lockstep demote/promote round (ISSUE 19): call on EVERY
        peer at the same step boundary, like :meth:`check_replan`. Each
        peer proposes at most one rank to demote into the backup role
        and one to promote back; a one-hot per-candidate SUM on the
        knob-independent star walk counts the proposals, candidates
        carried by a strict majority flip, and the changed demoted set
        re-derives the two-level plan from freshly exchanged matrix
        rows, adopted through the ordinary :meth:`adopt_replan` digest +
        listener bracket (the ledger opens a `peer_demoted` /
        `peer_promoted` record per flipped rank there).

        Returns the adopted flat projection, or None when no candidate
        carried, the set didn't change, or no hierarchy is derivable
        (demotion only acts under an active two-level mode — a flat
        ring routes around stragglers instead). A vote that would
        demote the last contributing member of a host is rejected by
        the derivation (no head candidate), never half-applied."""
        if (
            self.replan_mode != "hier"
            or self.size < 2
            or self._tree_override
        ):
            return None
        k = self.size
        ballot = np.zeros(2 * k, np.int32)
        if demote is not None and 0 <= int(demote) < k:
            ballot[int(demote)] = 1
        if promote is not None and 0 <= int(promote) < k:
            ballot[k + int(promote)] = 1
        counts = np.zeros(2 * k, np.int32)
        self._fixed_allreduce(Workspace(
            ballot, counts, ReduceOp.SUM,
            self._replan_name("demote") + tag,
        ))
        demotes = {r for r in range(k) if int(counts[r]) * 2 > k}
        promotes = {r for r in range(k) if int(counts[k + r]) * 2 > k}
        new_demoted = tuple(sorted(
            (set(self._demoted) | demotes) - promotes
        ))
        if new_demoted == self._demoted:
            self._replan_seq += 1
            return None
        matrix = self.measured_matrix()
        compute_frac = self.measured_compute_frac()
        hier = rp.derive_hier_plan(
            matrix, hosts=self._static_hosts(), mode=self.replan_mode,
            current=self._hier_plan, compute_frac=compute_frac,
            demoted=new_demoted,
        )
        if hier is None:
            # not derivable with the new set (single host group, or a
            # host would lose its last head) — same branch on every
            # peer: the inputs are all shared
            self._replan_seq += 1
            return None
        self.adopt_replan(hier)
        return self._ring_plan

    def _replan_worthwhile(self, plan: rp.RingPlan, min_gain: float) -> bool:
        """Churn gate, a pure function of (current plan, derived plan):
        a REORDER must clear ``min_gain`` (estimates drift every round —
        re-pairing the ring on noise costs a ZeRO re-shard each time,
        live-drive finding); an order-preserving weight refinement must
        move some segment weight by ≥10% relative."""
        cur = self._ring_plan
        if cur is None or plan.order != cur.order:
            return plan.gain >= min_gain
        if plan.weights is None or cur.weights is None:
            return True  # weights appearing/disappearing is material
        return any(
            abs(n - o) > 0.1 * max(o, 1e-12)
            for n, o in zip(plan.weights, cur.weights)
        )

    def adopt_replan(self, plan) -> None:
        """Install ``plan`` (a :class:`RingPlan`, a :class:`HierPlan`,
        or None = back to the naive ring) as the active topology,
        cluster-safely; call in lockstep on every peer at a step
        boundary (no walk in flight).

        The plan digest is asserted on the knob-INDEPENDENT star walk
        first (KF700/701 discipline): a peer whose matrix-fed derivation
        diverged gets a named RuntimeError here — never a rendezvous
        hang inside a later walk whose segment bounds silently differ.
        Registered listeners bracket the swap (``pre_replan`` runs under
        the OLD plan — the ZeRO-1 session exports exact state there —
        and ``post_replan`` re-shards under the new). A HierPlan
        installs BOTH itself (driving the two-level walk) and its flat
        projection (``as_ring_plan``), so every flat consumer —
        owned_bounds, the ring gauges, the ZeRO RS/AG legs — re-shards
        through the same one listener bracket, flat→hier flips
        included."""
        seq = self._replan_seq
        self._replan_seq += 1
        if not self._bytes_agree(
            rp.plan_digest(plan),
            f":replan:adopt:v{self.cluster_version}:{seq}",
            self._fixed_allreduce,
        ):
            raise RuntimeError(
                "measured-topology re-plan diverged across peers: the "
                "ring plan must be a pure function of the exchanged "
                "link matrix, but this peer derived "
                f"{plan.describe() if plan is not None else 'naive'} "
                f"(digest {rp.plan_digest(plan).hex()}) and at least one "
                "peer derived something else — refusing to install "
                "mismatched segment bounds (walks would deadlock or "
                "corrupt); this is a determinism bug, not a transient"
            )
        if isinstance(plan, rp.HierPlan):
            hier: Optional[rp.HierPlan] = plan
            flat: Optional[rp.RingPlan] = plan.as_ring_plan()
        else:
            hier = None
            flat = plan
        tokens = [
            (listener, listener.pre_replan())
            for listener in self._replan_listeners
        ]
        old = self._ring_plan
        old_demoted = self._demoted
        self._ring_plan = flat
        self._hier_plan = hier
        self._demoted = hier.demoted if hier is not None else ()
        for listener, token in tokens:
            listener.post_replan(token)
        # error-feedback residuals index the OLD plan's segment bounds;
        # under the new ownership they would correct the wrong slices
        self._flush_residuals("replan adopted: segment ownership moved")
        self._publish_ring_metrics()
        if self._replans_ctr is not None:
            self._replans_ctr.inc()
        from kungfu_tpu.telemetry import audit as _audit

        _audit.record_event(
            "topology_replanned",
            peer=str(self.self_id),
            trigger="replan_vote",
            old_order=list(old.order) if old is not None else list(range(self.size)),
            new_order=(
                list(flat.order) if flat is not None
                else list(range(self.size))
            ),
            weighted=bool(flat is not None and flat.weights is not None),
            hier=hier is not None,
            demoted=list(self._demoted),
            predicted_gain=flat.gain if flat is not None else 1.0,
        )
        # decision ledger (ISSUE 15): the re-plan predicted a throughput
        # ratio — this record is what finally measures the realized one.
        # Demote/promote flips get their OWN named records (ISSUE 19) so
        # `info decisions` can grade each straggler demotion separately.
        from kungfu_tpu.telemetry import decisions as _decisions

        _decisions.open_decision(
            "topology_replanned",
            peer=str(self.self_id),
            epoch=self.cluster_version,
            trigger="replan_vote",
            predicted_gain=flat.gain if flat is not None else 1.0,
            old_order=",".join(
                str(r) for r in (old.order if old is not None
                                 else range(self.size))
            ),
            new_order=",".join(
                str(r) for r in (flat.order if flat is not None
                                 else range(self.size))
            ),
            weighted=bool(flat is not None and flat.weights is not None),
            hier=hier is not None,
        )
        for r in sorted(set(self._demoted) - set(old_demoted)):
            _decisions.open_decision(
                "peer_demoted",
                peer=str(self.self_id),
                epoch=self.cluster_version,
                trigger="straggler_patience",
                predicted_gain=flat.gain if flat is not None else 1.0,
                demoted_rank=str(r),
            )
        for r in sorted(set(old_demoted) - set(self._demoted)):
            _decisions.open_decision(
                "peer_promoted",
                peer=str(self.self_id),
                epoch=self.cluster_version,
                trigger="straggler_recovered",
                predicted_gain=1.0,
                promoted_rank=str(r),
            )

    def _publish_ring_metrics(self) -> None:
        """Refresh the active-ring gauges (position + successor edge)
        from the current plan; children are rebuilt so a re-plan never
        leaves the OLD successor edge frozen in the exposition."""
        if self._ring_pos_g is None:
            return
        order = (
            self._ring_plan.order if self._ring_plan is not None
            else tuple(range(self.size))
        )
        pos = order.index(self.rank)
        succ = self.peers[order[(pos + 1) % self.size]] if self.size > 1 else None
        self._ring_pos_g.set(pos)
        self._ring_next_g.clear_children()
        if succ is not None:
            self._ring_next_g.labels(str(succ)).set(1)
        if self._ring_role_g is not None:
            self._ring_role_g.clear_children()
            hier = self._hier_plan
            if hier is None:
                self._ring_role_g.labels("flat", "member").set(0)
            else:
                gi = hier.group_of(self.rank)
                if self.rank in hier.demoted:
                    level, role = "intra", "demoted"
                elif self.rank == hier.heads[gi]:
                    level, role = "inter", "head"
                else:
                    level, role = "intra", "member"
                self._ring_role_g.labels(level, role).set(gi)
        self._publish_wire_mode()

    def _publish_wire_mode(self) -> None:
        """Refresh the active-precision gauge; children are rebuilt so a
        precision flip never leaves the OLD mode frozen at 1."""
        if self._wire_mode_g is None:
            return
        self._wire_mode_g.clear_children()
        self._wire_mode_g.labels(self._active_wire_mode()).set(1)

    def cross_all_reduce(self, w: Workspace) -> None:
        """AllReduce across host masters only (hierarchical path). While
        RING_SEGMENTED is the ACTIVE strategy, masters run the segmented
        walk over the master ring (the subset/cross variant); non-masters
        forward. Gated on _segmented_active — not the static configured
        strategy — so set_tree overrides and adaptive switches govern the
        cross path exactly like the global one (votes advance in lockstep
        on every peer, so the gate stays cluster-consistent).

        The wire codec applies here like the global allreduce — the
        cross-host hop crosses the DCN, exactly where halving wire
        bytes pays most; the intra-host reduce/broadcast phases around
        it stay raw (loopback/shm, nothing to save)."""
        wire = self._wire_codec_for(w)
        with stall_detect(f"cross_all_reduce({w.name})"):
            if (
                self._segmented_active()
                and len(self._masters) >= 2
                and w.recv.nbytes >= self.SEGMENT_MIN_BYTES
            ):
                self._run_segmented(w, ranks=self._masters, wire=wire)
            else:
                self._run_strategies(w, self.cross_strategies, wire=wire)

    def local_reduce(self, w: Workspace) -> None:
        self._run_graphs(w, [self.local_strategies[0].reduce_graph])

    def local_broadcast(self, w: Workspace) -> None:
        self._run_graphs(w, [self.local_strategies[0].bcast_graph])

    def _root_star_graphs(self, root: int) -> Tuple[Graph, Graph]:
        """(bcast, reduce) star graphs rooted at `root`, cached on the
        session — reduce/broadcast/broadcast_bytes used to regenerate
        them on every call (a Graph build is O(size) allocations, paid
        per elastic state-sync message). Benign to race: both writers
        compute identical graphs."""
        pair = self._root_graphs.get(root)
        if pair is None:
            bcast = topo.gen_star_bcast_graph(self.size, root)
            pair = (bcast, topo.gen_default_reduce_graph(bcast))
            self._root_graphs[root] = pair
        return pair

    def reduce(self, w: Workspace, root: int = 0) -> None:
        """Reduce to `root` (parity: runGraphs with a reduce graph; the
        reference's Reduce takes arbitrary roots). Root 0 walks the
        configured strategy; other roots use a root-specific star."""
        if root == 0:
            self._run_graphs(w, [self.global_strategies[0].reduce_graph])
        else:
            self._check_root(root)
            self._run_graphs(w, [self._root_star_graphs(root)[1]])

    def broadcast(self, w: Workspace, root: int = 0) -> None:
        with self._collected("broadcast", w.recv.nbytes):
            if root == 0:
                self._run_graphs(w, [self.global_strategies[0].bcast_graph])
            else:
                self._check_root(root)
                self._run_graphs(w, [self._root_star_graphs(root)[0]])

    def _check_root(self, root: int) -> None:
        if not 0 <= root < self.size:
            raise ValueError(f"root {root} outside cluster of {self.size}")

    def subset_all_reduce(self, fathers: Sequence[int], w: Workspace) -> None:
        sl = st.from_forest_array(list(fathers))
        self._run_strategies(w, sl)

    def all_reduce_with(self, fathers: Sequence[int], w: Workspace) -> None:
        """AllReduce on a runtime-supplied tree (parity: AllReduceWith)."""
        if fathers:
            sl = st.from_forest_array(list(fathers))
        else:
            sl = self.global_strategies
        self._run_strategies(w, sl)

    def barrier(self, tag: str = "") -> None:
        """Parity: session.go:98-113 (an allreduce of size bytes)."""
        k = len(self.peers)
        w = Workspace(
            send=np.zeros(k, np.uint8),
            recv=np.zeros(k, np.uint8),
            op=ReduceOp.SUM,
            name=f"kungfu::barrier{tag}",
        )
        self.all_reduce(w)

    def bytes_consensus(self, bs: bytes, name: str) -> bool:
        """True iff every peer supplied identical bytes (parity:
        session.go:126-157, which runs 4 allreduce rounds). 2 rounds
        here: a MIN-allreduce of the packed (len, -len) int64 workspace
        yields the cluster's (min-len, -max-len) in one walk, and a
        MIN-allreduce of the two-lane (payload, 255-payload) bytes yields
        (elementwise-min, 255-elementwise-max) in another — consensus iff
        min == max in both. Every elastic resize and strategy switch pays
        this path, so halving the rounds halves its serialized latency.

        Runs int64/uint8 lanes through the regular engine — the wire
        codec is f32-only, so consensus payloads are never quantized
        (docs/collectives.md: consensus MUST stay exact)."""
        return self._bytes_agree(bs, name, self.all_reduce)

    def _bytes_agree(
        self, bs: bytes, name: str, run: Callable[[Workspace], None]
    ) -> bool:
        """The 2-round consensus algebra, parameterized over the
        allreduce runner so the knob-consensus check can use graphs that
        do not depend on the very knobs being checked."""
        n = len(bs)
        lens = np.array([n, -n], np.int64)
        out_len = np.zeros(2, np.int64)
        run(Workspace(lens, out_len, ReduceOp.MIN, f":consensus:len:{name}"))
        if out_len[0] != -out_len[1]:
            return False
        if n == 0:
            return True
        x = np.frombuffer(bs, np.uint8)
        lanes = np.empty(2 * n, np.uint8)
        lanes[:n] = x
        np.subtract(255, x, out=lanes[n:])
        out = np.zeros(2 * n, np.uint8)
        run(Workspace(lanes, out, ReduceOp.MIN, f":consensus:data:{name}"))
        return bool(np.array_equal(out[:n], 255 - out[n:]))

    # ------------------------------------------------------------------
    # engine-knob consensus (fail fast instead of deadlocking)
    # ------------------------------------------------------------------

    def engine_knobs(self) -> List[Tuple[str, str]]:
        """The cluster-agreed engine knobs, as resolved BY THIS SESSION.

        Every entry decides rendezvous names, message sizes or peer
        pairings, so peers that resolved different values would wait on
        each other's names (or mis-frame messages) forever. Local-only
        tuning (KF_CONFIG_GROUP_WINDOW — pure intra-host concurrency —
        and KF_CONFIG_ASYNC_QUEUE, the scheduler's local in-flight
        depth) is deliberately excluded: it may legitimately differ per
        host."""
        return [
            ("KF_CONFIG_ALGO", knobs.get("KF_CONFIG_ALGO")),
            ("KF_CONFIG_CHUNK_BYTES", str(CHUNK_BYTES)),
            ("KF_CONFIG_SEGMENT_MIN_BYTES", str(self.SEGMENT_MIN_BYTES)),
            ("KF_CONFIG_GROUP_BUCKET_BYTES", str(self.GROUP_BUCKET_BYTES)),
            ("KF_CONFIG_GROUP_FUSE_MIN", str(self.FUSE_MIN_TENSORS)),
            ("KF_CONFIG_WIRE", self.wire_mode),
            ("KF_CONFIG_WIRE_MIN_BYTES", str(self.WIRE_MIN_BYTES)),
            ("KF_WIRE_BLOCK", str(self.WIRE_BLOCK)),
            ("KF_CONFIG_ASYNC", self.async_mode),
            ("KF_CONFIG_ZERO", self.zero_mode),
            ("KF_CONFIG_REPLAN", self.replan_mode),
            ("KF_REPLAN_DEMOTE_PATIENCE", str(self.demote_patience)),
        ]

    def _fixed_allreduce(self, w: Workspace) -> None:
        """Allreduce over a rank-0 star, unchunked and uncompressed — a
        walk whose rendezvous names and message sizes depend on NOTHING
        the knobs control, so it completes even across knob-divergent
        peers (tiny payloads; latency is 2 serialized hops).

        Marked as a DELIBERATE graph walk: the knob-consensus and
        re-plan rounds choose the star by design, so they must not
        trip the `segmented_fallback` audit meant for payloads that
        FELL BACK from the segmented engine (review finding: every
        segmented session fired the event on its startup consensus
        walk, before any user collective could)."""
        self._in_fixed_walk = True
        try:
            bcast, red = self._root_star_graphs(0)
            self._run_graphs(w, [red, bcast])
        finally:
            self._in_fixed_walk = False

    def check_knob_consensus(self) -> None:
        """Fail fast on engine-knob divergence (satellite of ISSUE 5).

        Without this, peers that resolved different KF_CONFIG_ALGO /
        CHUNK_BYTES / GROUP_BUCKET_BYTES / WIRE / ASYNC values wait on
        each other's rendezvous names forever — the first collective of
        the epoch just hangs. One consensus over the resolved knob tuple
        at session start turns that into an immediate, named error. Runs
        on the knob-independent star walk, so the check itself cannot
        deadlock on the very disagreement it detects; on mismatch a
        per-knob round pins down WHICH knob diverged."""
        if self.size < 2:
            return
        resolved = self.engine_knobs()
        blob = ";".join(f"{k}={v}" for k, v in resolved).encode()
        if self._bytes_agree(blob, ":knobs", self._fixed_allreduce):
            return
        bad = [
            k for k, v in resolved
            if not self._bytes_agree(
                v.encode(), f":knob:{k}", self._fixed_allreduce
            )
        ]
        mine = dict(resolved)
        names = ", ".join(bad) if bad else "engine knob tuple"
        raise RuntimeError(
            f"engine knob mismatch across peers: {names} — these KF_CONFIG_* "
            f"values decide rendezvous names and message sizes, so they MUST "
            f"be set identically fleet-wide (collectives would deadlock); "
            f"this peer ({self.self_id}) resolved "
            + ", ".join(f"{k}={mine[k]!r}" for k in (bad or mine))
        )

    def broadcast_bytes(self, bs: bytes, name: str, root: int = 0) -> bytes:
        """Broadcast variable-length bytes from `root` (two graph walks:
        length, then payload). Used to bootstrap the device plane — the
        TPU analog of broadcasting the NCCL unique id over the CPU
        collective (gpu_collective.cpp:190-212) — and for elastic state
        re-sync, where the root must be a SURVIVING peer (not necessarily
        rank 0 of the new cluster)."""
        # a fixed star keeps the walk root-correct regardless of the active
        # strategy (set_tree/adaptive switches may re-root global_strategies)
        graph = self._root_star_graphs(root)[0]
        n_send = np.array([len(bs) if self.rank == root else 0], np.int64)
        n_recv = np.zeros(1, np.int64)
        self._run_graphs(
            Workspace(n_send, n_recv, ReduceOp.SUM, f"{name}:len"), [graph]
        )
        n = int(n_recv[0])
        if n == 0:
            return b""
        if self.rank == root:
            send = np.frombuffer(bs, np.uint8)
        else:
            send = np.zeros(n, np.uint8)
        recv = np.zeros(n, np.uint8)
        self._run_graphs(
            Workspace(send, recv, ReduceOp.SUM, f"{name}:data"), [graph]
        )
        return recv.tobytes()

    def gather(self, w: Workspace, root: int = 0) -> None:
        """`root` receives everyone's send buffer into recv (rank-major);
        parity: runGather (session.go:195-221), arbitrary roots like the
        reference's Gather. Handles unequal per-peer counts: the wire
        framing carries each message's true length, so the root lays
        contributions out by their actual sizes (the reference relies on
        the same message framing)."""
        self._check_root(root)
        if self.rank != root:
            with self._collected("gather", w.send.nbytes):
                self.client.send(
                    self.peers[root], w.name, _buf(w.send), ConnType.COLLECTIVE
                )
                self._count_wire(w.send.nbytes, "STAR")
            return
        scope = self._collected("gather", w.recv.nbytes)
        scope.__enter__()
        cancel = threading.Event()
        parts: List[Optional[np.ndarray]] = [None] * len(self.peers)
        releases: List = [None] * len(self.peers)

        def recv_part(r: int, peer: PeerID) -> None:
            msg = self.endpoint.recv(peer, w.name, self.timeout)
            if cancel.is_set():
                if msg.release is not None:
                    msg.release()
                return
            parts[r] = np.frombuffer(msg.data, w.send.dtype)
            releases[r] = msg.release

        jobs = []
        for r, peer in enumerate(self.peers):
            if r == self.rank:
                parts[r] = w.send.reshape(-1)
            else:
                jobs.append(lambda r=r, p=peer: recv_part(r, p))
        try:
            _par(jobs, self.timeout, cancel)
            off = 0
            for part in parts:
                assert part is not None
                n = part.size
                if off + n > w.recv.size:
                    raise ValueError(
                        f"gather overflow: recv buffer {w.recv.size} < {off + n}"
                    )
                np.copyto(w.recv[off:off + n], part)
                off += n
            if off != w.recv.size:
                # a short contribution would silently shift later ranks' data
                raise ValueError(
                    f"gather underflow: contributions fill {off} of {w.recv.size}"
                )
        finally:
            parts.clear()
            for rel in releases:
                if rel is not None:
                    rel()
            scope.__exit__(None, None, None)

    def all_gather(self, w: Workspace) -> None:
        """Gather to root then broadcast the concatenation (parity:
        AllGatherTransform, session.cpp:201-220)."""
        self.gather(w)
        bw = Workspace(send=w.recv, recv=w.recv, op=w.op, name=w.name + ":bcast")
        self.broadcast(bw)
