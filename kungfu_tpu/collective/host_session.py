"""Host-side collective engine: graph-walk collectives over the transport.

Capability parity: srcs/go/kungfu/session/session.go — an immutable
peer-list epoch running Barrier / Consensus / Reduce / Broadcast / Gather /
AllReduce by walking (reduce, bcast) graph pairs, with 1 MiB chunking
striped across multi-root strategies (runStrategies, session.go:301-330)
and SIMD reduction on receive (base.Transform2).

Role in the TPU build: this engine runs on HOSTS over DCN for control
collectives (consensus on cluster configs, barriers, progress sync) and for
CPU-only test clusters — the device data plane is XLA over ICI
(kungfu_tpu.ops). It is the direct replacement for the reference's
rchannel data plane.
"""

from __future__ import annotations

import os
import queue
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from kungfu_tpu.base.ops import (
    ReduceOp,
    copy_segment,
    reduce_inplace,
    reduce_segment,
    transform_n,
)
from kungfu_tpu.telemetry import config as tconfig
from kungfu_tpu.telemetry import metrics as tmetrics
from kungfu_tpu.utils import trace
from kungfu_tpu.base.strategy import Strategy
from kungfu_tpu.collective.adaptive import AdaptiveState
from kungfu_tpu.base.workspace import Workspace, even_partition
from kungfu_tpu.collective import strategies as st
from kungfu_tpu.collective.strategies import effective_cpu_count
from kungfu_tpu.plan import topology as topo
from kungfu_tpu.plan.graph import Graph
from kungfu_tpu.plan.peer import PeerID, PeerList
from kungfu_tpu.transport.client import Client
from kungfu_tpu.transport.handlers import CollectiveEndpoint
from kungfu_tpu.transport.message import ConnType, Flags
from kungfu_tpu.utils.pool import get_buffer_pool, get_pool
from kungfu_tpu.utils.stall import stall_detect

# Chunking (parity: session.go chunkSize, but self-tuned): the optimal
# trades chunk-walk overhead (fewer, bigger chunks) against striping/
# pipelining (more, smaller chunks) and depends on host core count —
# concurrent chunk walks only pay when cores exist to run them; on a
# 1-core host every extra in-flight chunk is pure context-switch cost.
# KF_CONFIG_CHUNK_BYTES overrides the heuristic.
CHUNK_BYTES = int(os.environ.get("KF_CONFIG_CHUNK_BYTES", "0"))
_CHUNK_MIN = 1 << 20
_CHUNK_MAX = 32 << 20
DEFAULT_TIMEOUT = 120.0

# A/B algorithm override (benchmarks, operators): forces the engine onto
# one family regardless of the configured/AUTO strategy. Like every other
# engine knob it MUST agree cluster-wide (peers that resolved different
# algorithms would wait on each other's rendezvous names forever).
_ALGO_STRATEGY = {
    "": None,
    "auto": Strategy.AUTO,
    "tree": Strategy.BINARY_TREE,
    "segmented": Strategy.RING_SEGMENTED,
}


def algo_override() -> Optional[Strategy]:
    """Parse KF_CONFIG_ALGO (read per session epoch, not import time)."""
    raw = os.environ.get("KF_CONFIG_ALGO", "").strip().lower()
    try:
        return _ALGO_STRATEGY[raw]
    except KeyError:
        raise ValueError(
            f"KF_CONFIG_ALGO must be one of "
            f"{sorted(k for k in _ALGO_STRATEGY if k)}, got {raw!r}"
        ) from None


def choose_chunk_bytes(total: int) -> int:
    """Chunk size for a `total`-byte collective: honour the env override,
    else ~8 chunks per collective, clamped to [1 MiB, 32 MiB].

    MUST depend only on cluster-agreed inputs (the workspace size): chunk
    workspaces are named '<name>[i/k]', so peers that computed different
    k would wait forever on each other's chunk names. That rules out
    os.cpu_count() here (heterogeneous hosts); measured on the 1-core
    box, 8 in-flight walks of >=1 MiB is within noise of the per-core
    optimum anyway."""
    if CHUNK_BYTES > 0:
        return CHUNK_BYTES
    c = total // 8
    return max(_CHUNK_MIN, min(_CHUNK_MAX, c))


def _par(
    fns: List[Callable[[], None]],
    timeout: float,
    cancel: Optional[threading.Event] = None,
) -> None:
    """Run callables on the shared cached-thread pool, wait for all,
    re-raise the first error (goroutine-style fan-out; an unbounded cached
    pool avoids both thread-spawn cost per call and pool-exhaustion
    deadlocks on nested parallelism).

    All waits share ONE deadline (worst case = timeout, not
    len(fns)*timeout). On timeout `cancel` is set before raising so
    abandoned workers that later complete a recv can observe it and must
    NOT mutate the caller's workspace (a reused recv buffer would be
    corrupted by a late write)."""
    if not fns:
        return
    if len(fns) == 1:
        fns[0]()
        return
    cond = threading.Condition()
    state = {"done": 0}
    errs: List[BaseException] = []

    def run(fn):
        err: Optional[BaseException] = None
        try:
            fn()
        except BaseException as e:  # noqa: BLE001 - propagated below
            err = e
        with cond:
            state["done"] += 1
            if err is not None:
                errs.append(err)
            cond.notify_all()

    pool = get_pool()
    for fn in fns:
        pool.submit(lambda f=fn: run(f))
    with cond:
        if not cond.wait_for(lambda: state["done"] >= len(fns), timeout):
            if cancel is not None:
                cancel.set()
            raise TimeoutError("collective thread timed out")
        if errs:
            raise errs[0]


def _buf(arr: np.ndarray):
    """Zero-copy byte view of a contiguous array (tobytes() fallback)."""
    try:
        return arr.data.cast("B")
    except (ValueError, TypeError, AttributeError):
        return arr.tobytes()


class _CollectiveScope:
    """Span + latency-histogram wrapper around one public collective
    (plain classes end to end — tracing._Span underneath is also
    class-based — so the per-call telemetry cost stays at two clock
    reads, a deque append and an optional histogram observe)."""

    __slots__ = ("_sess", "_kind", "_span", "_t0", "_prev_kind")

    def __init__(self, sess: "HostSession", kind: str, nbytes: int):
        self._sess = sess
        self._kind = kind
        self._span = trace.span(
            f"collective.{kind}", bytes=int(nbytes), size=sess.size
        )

    def __enter__(self):
        self._t0 = time.perf_counter()
        # label wire-byte counts with the public collective that caused
        # them (walks run on pool threads, so this lives on the session;
        # rare concurrent collectives of different kinds may cross-label
        # a few bytes, which accounting tolerates)
        self._prev_kind = self._sess._wire_kind
        self._sess._wire_kind = self._kind
        self._span.__enter__()
        return self

    def __exit__(self, *exc):
        self._span.__exit__(*exc)
        self._sess._wire_kind = self._prev_kind
        hist = self._sess._coll_hist
        if hist is not None:
            hist.labels(self._kind).observe(time.perf_counter() - self._t0)
        return False



class HostSession:
    """One collective epoch over a fixed PeerList."""

    def __init__(
        self,
        strategy: Strategy,
        self_id: PeerID,
        peers: PeerList,
        client: Client,
        endpoint: CollectiveEndpoint,
        timeout: float = DEFAULT_TIMEOUT,
    ):
        rank = peers.rank(self_id)
        if rank is None:
            raise ValueError(f"{self_id} not in peer list {peers}")
        self.self_id = self_id
        self.peers = peers
        self.rank = rank
        self.local_rank = peers.local_rank(self_id)
        self.local_size = peers.local_size(self_id)
        self.host_count = peers.host_count()
        self.client = client
        self.endpoint = endpoint
        self.timeout = timeout
        forced = algo_override()
        if forced is not None:
            strategy = forced
        if strategy == Strategy.AUTO:
            strategy = st.auto_select(peers)
        self.strategy = strategy
        self.global_strategies = st.gen_global_strategies(peers, strategy)
        self.local_strategies = st.gen_local_strategies(peers)
        self.cross_strategies = st.gen_cross_strategies(peers, strategy)
        # ring order for the cross-host segmented walk (hierarchical mode)
        self._masters, _ = peers.partition_by_host()
        # per-root star graph cache (satellite: reduce/broadcast with
        # root != 0 regenerated star + default-reduce on every call);
        # sessions are rebuilt each epoch, so invalidation is automatic
        self._root_graphs: Dict[int, Tuple[Graph, Graph]] = {}
        # adaptive control (parity: session/adaptiveStrategies.go): a
        # deterministic candidate order — identical on every peer — so a
        # majority vote can advance everyone in lockstep. Candidate graph
        # lists are built lazily: sessions are rebuilt every elastic epoch
        # and most never adapt. RING_SEGMENTED sits first among the
        # alternates so interference votes can switch ONTO the
        # bandwidth-optimal member (and off it, by advancing again).
        self._candidate_names = [strategy] + [
            s for s in (
                Strategy.RING_SEGMENTED, Strategy.RING,
                Strategy.BINARY_TREE_STAR, Strategy.STAR, Strategy.CLIQUE,
            ) if s != strategy
        ]
        self._candidates_built: dict = {0: self.global_strategies}
        self.adaptive = AdaptiveState(len(self._candidate_names))
        self._tree_override = False
        # per-collective latency histogram (telemetry): one observe per
        # COLLECTIVE call (not per message), gated off with the rest of
        # the metrics so the steady-state walk stays untouched
        self._coll_hist = (
            tmetrics.histogram(
                "kungfu_collective_latency_seconds",
                "Host-plane collective latency by kind",
                ("collective",),
            )
            if tconfig.metrics_enabled()
            else None
        )
        # wire-byte accounting: bytes this peer SENDS into collective
        # walks, by (public collective, executing strategy). This is the
        # counter the segmented engine's bandwidth-optimality claim is
        # asserted against (tests) and the A/B bench reports.
        self._wire_ctr = (
            tmetrics.counter(
                "kungfu_collective_wire_bytes_total",
                "Host-plane collective payload bytes sent by this peer",
                ("collective", "strategy"),
            )
            if tconfig.metrics_enabled()
            else None
        )
        self._wire_kind = "raw"

    def _candidate(self, idx: int) -> List[st.StrategyPair]:
        if idx not in self._candidates_built:
            self._candidates_built[idx] = st.gen_global_strategies(
                self.peers, self._candidate_names[idx]
            )
        return self._candidates_built[idx]

    @property
    def size(self) -> int:
        return len(self.peers)

    def close(self) -> None:
        pass

    def _collected(self, kind: str, nbytes: int):
        """Telemetry wrapper for one public collective: a named span
        (feeding /trace) plus a latency-histogram observation when
        metrics are on. Returns a context manager."""
        return _CollectiveScope(self, kind, nbytes)

    def _count_wire(self, nbytes: int, strategy_label: str) -> None:
        if self._wire_ctr is not None and nbytes:
            self._wire_ctr.labels(self._wire_kind, strategy_label).inc(nbytes)

    def _walk_label(self) -> str:
        """Strategy label for graph-walk wire accounting. Labels the
        graphs that actually EXECUTED: when RING_SEGMENTED is active but
        a payload fell below SEGMENT_MIN_BYTES, the walk ran the binary-
        tree fallback graphs and must not pollute the RING_SEGMENTED
        series (it is the one the optimality assertion reads)."""
        if self._tree_override:
            return "SET_TREE"
        active = self._candidate_names[self.adaptive.active]
        if active == Strategy.RING_SEGMENTED:
            return Strategy.BINARY_TREE.name
        return active.name

    def _recv_collective(
        self, peer: PeerID, name: str, nbytes: int, dtype, count: int,
        timeout: float,
    ):
        """Receive (peer, name) into a pooled scratch buffer — delivered
        straight off the socket when we're parked first (sink path), else
        from the buffered Message (possibly a zero-copy shm borrow).
        Returns (ndarray view, scratch-or-None to return to the pool,
        release-or-None to call once the view has been consumed). Shared
        by the graph walk and the segmented walk so the borrow/release/
        leak-on-timeout contract lives in ONE place. On error the scratch
        is deliberately NOT returned to the pool: a timed-out sink may
        still be mid-fill by the transport thread."""
        bufpool = get_buffer_pool()
        scratch = bufpool.get(nbytes)
        msg, filled = self.endpoint.recv_into(
            peer, name, memoryview(scratch), timeout
        )
        if filled:
            return np.frombuffer(scratch, dtype, count), scratch, None
        bufpool.put(scratch)  # unused: sender raced us or size mismatch
        return np.frombuffer(msg.data, dtype, count), None, msg.release

    # ------------------------------------------------------------------
    # public collectives
    # ------------------------------------------------------------------

    # Segmentation pays only when the per-step segment amortizes the
    # 2*(k-1) serialized message latencies; below this the rank-0 binary
    # tree fallback graphs win. MUST be cluster-agreed (it decides which
    # rendezvous names a peer waits on) — like CHUNK_BYTES, the default
    # is a constant and the env override must be set fleet-wide.
    SEGMENT_MIN_BYTES = int(
        os.environ.get("KF_CONFIG_SEGMENT_MIN_BYTES", "") or (64 << 10)
    )

    def _segmented_active(self) -> bool:
        return (
            not self._tree_override
            and self.size >= 2
            and self._candidate_names[self.adaptive.active]
            == Strategy.RING_SEGMENTED
        )

    def _allreduce_ws(
        self, w: Workspace, cancel: Optional[threading.Event] = None
    ) -> None:
        """Engine dispatch for one allreduce workspace: the segmented
        ring walk when RING_SEGMENTED is active and the payload is worth
        segmenting, else chunked graph walks. `cancel` (group/window
        scope) propagates so an abandoned walk observes the caller's
        timeout before mutating recv buffers."""
        if self._segmented_active() and w.recv.nbytes >= self.SEGMENT_MIN_BYTES:
            self._run_segmented(w, cancel=cancel)
        else:
            self._run_strategies(w, self.global_strategies, cancel)

    def all_reduce(self, w: Workspace) -> None:
        with self._collected("all_reduce", w.recv.nbytes):
            with stall_detect(f"all_reduce({w.name})"):
                self._allreduce_ws(w)

    # concurrent workspaces per batch in group ops: concurrency only pays
    # when cores exist to run the walks (on a 1-core host it just adds
    # context switches), so the default scales with the cgroup-aware
    # core count — os.cpu_count() reports the HOST's cores inside a
    # CPU-quota'd container, the phantom-parallelism trap auto_select
    # already avoids; KF_CONFIG_GROUP_WINDOW overrides
    GROUP_WINDOW = int(
        os.environ.get("KF_CONFIG_GROUP_WINDOW", "")
        or max(1, min(8, effective_cpu_count()))
    )

    # Gradient bucketing: fuse same-(dtype, op) workspaces into ONE
    # contiguous walk. A 160-tensor gradient set otherwise pays the fixed
    # per-walk cost (rendezvous conditions, pool dispatch, ~6 framed
    # messages) 160 times — on a host-plane reduce that overhead rivals
    # the byte-copy time itself. Two extra memcpy passes (pack + unpack)
    # buy a ~160x cut in message count. The reference runs one collective
    # per tensor and leans on cheap goroutines instead; bucketing is the
    # standard DDP/Horovod answer and is strictly better here.
    FUSE_MIN_TENSORS = int(os.environ.get("KF_CONFIG_GROUP_FUSE_MIN", "4"))

    # Fused-bucket size cap: fused groups split into buckets that pack /
    # walk / unpack as a 3-stage pipeline, so the cap trades per-walk
    # fixed cost (bigger buckets) against pack/unpack overlap (smaller
    # buckets start their walk sooner and unpack while the next bucket is
    # on the wire). Measured on the 2-core bench box: 8 MiB buckets pay
    # 12 walks' fixed cost for resnet50 and run 2x SLOWER than one big
    # bucket; 64 MiB is within noise of a single bucket while still
    # pipelining multi-hundred-MB sets (bert ~700 MB -> 11 buckets).
    # Part of the fused workspace name, so it MUST be cluster-agreed
    # like CHUNK_BYTES (which also rules out core-count scaling here).
    GROUP_BUCKET_BYTES = int(
        os.environ.get("KF_CONFIG_GROUP_BUCKET_BYTES", "") or (64 << 20)
    )

    def group_all_reduce(self, ws: Sequence[Workspace]) -> None:
        """Allreduce of many workspaces as one windowed group op (parity:
        the reference reduces a whole gradient set per session.run —
        srcs/python/kungfu/tensorflow/v1/benchmarks). Fused buckets run
        through the 3-stage pipeline while the singles windows walk
        concurrently — neither waits for the other to finish."""
        if not ws:
            return
        with self._collected(
            "group_all_reduce", sum(w.recv.nbytes for w in ws)
        ), stall_detect(f"group_all_reduce[{len(ws)}]"):
            singles: List[Workspace] = []
            groups: Dict[tuple, List[Workspace]] = {}
            for w in ws:
                if w.is_empty:
                    continue
                groups.setdefault((w.send.dtype.str, int(w.op)), []).append(w)
            buckets: List[List[Workspace]] = []
            for members in groups.values():
                if len(members) < self.FUSE_MIN_TENSORS:
                    singles.extend(members)
                else:
                    buckets.extend(self._make_buckets(members))
            jobs: List[Callable[[], None]] = []
            # the group deadline scales with the number of walks it
            # covers — the serial predecessor allowed one self.timeout
            # PER fused walk / singles window, and a large healthy group
            # on a slow link must not trip a single flat budget
            windows = -(-len(singles) // self.GROUP_WINDOW)
            group_timeout = self.timeout * max(1, len(buckets) + windows)
            # shared cancel: a group-level timeout must also abort the
            # pipeline stages, or a lingering unpacker would keep writing
            # caller recv buffers after this call already raised (the
            # late-write hazard _par's contract exists to prevent)
            cancel = threading.Event()
            if buckets:
                jobs.append(
                    lambda: self._fused_pipeline(buckets, group_timeout, cancel)
                )
            if singles:
                jobs.append(lambda: self._singles_windows(singles, cancel))
            _par(jobs, group_timeout, cancel)

    def _make_buckets(
        self, members: List[Workspace]
    ) -> List[List[Workspace]]:
        """Greedy, order-preserving packing of same-(dtype, op)
        workspaces into <= GROUP_BUCKET_BYTES buckets. Derived only from
        the caller's tensor order and the byte cap, so every peer computes
        the same layout (the fused name encodes it); an oversized single
        tensor gets a bucket of its own."""
        buckets: List[List[Workspace]] = []
        cur: List[Workspace] = []
        cur_bytes = 0
        for w in members:
            if cur and cur_bytes + w.send.nbytes > self.GROUP_BUCKET_BYTES:
                buckets.append(cur)
                cur, cur_bytes = [], 0
            cur.append(w)
            cur_bytes += w.send.nbytes
        if cur:
            buckets.append(cur)
        return buckets

    def _singles_windows(
        self,
        singles: List[Workspace],
        cancel: Optional[threading.Event] = None,
    ) -> None:
        for i in range(0, len(singles), self.GROUP_WINDOW):
            if cancel is not None and cancel.is_set():
                # the group already raised (timeout, or a pipeline-stage
                # error that set the shared cancel): stop launching
                # windows, but return QUIETLY — raising here would race
                # the real error to _par's errs[0] and misreport a
                # deterministic failure as 'cancelled'
                return
            batch = singles[i : i + self.GROUP_WINDOW]
            _par(
                [lambda w=w: self._allreduce_ws(w, cancel) for w in batch],
                self.timeout,
                cancel,
            )

    def _pack_bucket(self, bi: int, members: List[Workspace]):
        """Pack one bucket into pooled contiguous buffers. Workspace
        order is the caller's tensor order, identical on every peer, so
        the fused name and layout agree cluster-wide."""
        dtype = members[0].send.dtype
        op = members[0].op
        total = sum(w.send.size for w in members)
        nbytes = total * dtype.itemsize
        pool = get_buffer_pool()
        send_b = pool.get(nbytes)
        recv_b = pool.get(nbytes)
        with trace.span("host.fuse.pack"):
            send = np.frombuffer(send_b, dtype, total)
            recv = np.frombuffer(recv_b, dtype, total)
            off = 0
            for w in members:
                send[off : off + w.send.size] = w.send
                off += w.send.size
        fused = Workspace(
            send=send,
            recv=recv,
            op=op,
            name=f"{members[0].name}::fused:b{bi}:{len(members)}x{total}",
        )
        return (fused, send_b, recv_b, members)

    def _unpack_bucket(self, item) -> None:
        fused, send_b, recv_b, members = item
        pool = get_buffer_pool()
        try:
            with trace.span("host.fuse.unpack"):
                off = 0
                for w in members:
                    np.copyto(w.recv, fused.recv[off : off + w.recv.size])
                    off += w.recv.size
        finally:
            pool.put(send_b)
            pool.put(recv_b)

    def _fused_pipeline(
        self,
        buckets: List[List[Workspace]],
        timeout: float,
        cancel: Optional[threading.Event] = None,
    ) -> None:
        """3-stage software pipeline over fused buckets: pack bucket i+1
        and unpack bucket i-1 while bucket i is on the wire. The serial
        predecessor (all packs, then all walks, then all unpacks per
        bucket) left the wire idle during every memcpy phase. Depth-1
        handoff queues bound live pooled buffers at 5 buckets (one per
        stage + one per queue) — x2 buffers x GROUP_BUCKET_BYTES, well
        under the serial path's single whole-group buffer pair for big
        sets. Every queue get/put is abort-aware, so any stage's failure
        (or a dropped sentinel after one) unblocks the other two and the
        REAL error propagates out of _par; aborted in-flight buffers are
        dropped to GC (the pool's documented policy for buffers a worker
        may still touch)."""
        packed: "queue.Queue" = queue.Queue(maxsize=1)
        unpackq: "queue.Queue" = queue.Queue(maxsize=1)
        # the caller's cancel event doubles as the abort flag: _par sets
        # it on timeout, so every stage (unpacker included) stops before
        # touching caller buffers again
        abort = cancel if cancel is not None else threading.Event()

        def put(q: "queue.Queue", item) -> bool:
            """Bounded put that gives up once the pipeline aborts."""
            while True:
                try:
                    q.put(item, timeout=0.2)
                    return True
                except queue.Full:
                    if abort.is_set():
                        return False

        def get(q: "queue.Queue"):
            """Blocking get that turns into the sentinel on abort, so a
            consumer can never be stranded by a lost sentinel."""
            while True:
                try:
                    return q.get(timeout=0.2)
                except queue.Empty:
                    if abort.is_set():
                        return None

        def packer():
            try:
                for bi, members in enumerate(buckets):
                    if abort.is_set():
                        return
                    if not put(packed, self._pack_bucket(bi, members)):
                        return
            except BaseException:
                abort.set()
                raise
            finally:
                put(packed, None)

        def walker():
            try:
                while True:
                    item = get(packed)
                    if item is None:
                        return
                    if abort.is_set():
                        continue  # drain to the sentinel
                    with trace.span("host.fuse.walk"):
                        self._allreduce_ws(item[0])
                    if not put(unpackq, item):
                        return
            except BaseException:
                abort.set()
                raise
            finally:
                put(unpackq, None)

        def unpacker():
            try:
                while True:
                    item = get(unpackq)
                    if item is None:
                        return
                    if abort.is_set():
                        continue  # aborted: must not touch caller buffers
                    self._unpack_bucket(item)
            except BaseException:
                abort.set()
                raise

        _par([packer, walker, unpacker], timeout, abort)

    def monitored_all_reduce(self, w: Workspace) -> None:
        """AllReduce + throughput accounting for the ACTIVE strategy
        (parity: KungfuMonitoredAllReduce, ops/cpu/collective.cpp:149-196 +
        runMonitoredStrategies, session/monitoring.go:15-35)."""
        nbytes = w.recv.size * w.recv.itemsize
        t0 = time.perf_counter()
        with self._collected("monitored_all_reduce", nbytes):
            with stall_detect(f"monitored_all_reduce({w.name})"):
                self._allreduce_ws(w)
        self.adaptive.current.update(nbytes, time.perf_counter() - t0)

    def check_interference(self, vote_tag: str = "") -> bool:
        """Majority vote on local interference suspicion; on a cluster-wide
        majority every peer advances to the next candidate strategy in the
        same deterministic order. Returns True if the strategy switched.
        Parity: CheckInterference + MonitoredAllReduce consensus switch
        (session/adaptiveStrategies.go:61-121)."""
        if self._tree_override or len(self._candidate_names) < 2:
            return False
        suspect = self.adaptive.current.suspect_interference()
        votes_in = np.array([1 if suspect else 0], np.int32)
        votes_out = np.zeros(1, np.int32)
        self.all_reduce(
            Workspace(votes_in, votes_out, ReduceOp.SUM,
                      f"kungfu::interference:{self.adaptive.switch_count}{vote_tag}")
        )
        if int(votes_out[0]) * 2 <= self.size:
            return False
        old_name = self._candidate_names[self.adaptive.active].name
        idx = self.adaptive.advance()
        self.global_strategies = self._candidate(idx)
        # safety: all peers must now run the same graphs
        if not self.bytes_consensus(
            st.digest(self.global_strategies), f":switch:{self.adaptive.switch_count}"
        ):
            raise RuntimeError("strategy switch diverged across peers")
        from kungfu_tpu.telemetry import audit as _audit

        _audit.record_event(
            "strategy_switch",
            peer=str(self.self_id),
            trigger="interference_vote",
            old_strategy=old_name,
            new_strategy=self._candidate_names[idx].name,
            switch_count=self.adaptive.switch_count,
        )
        return True

    def active_strategy(self) -> Optional[Strategy]:
        """The running candidate strategy, or None when an explicit
        set_tree forest overrides the candidates."""
        if self._tree_override:
            return None
        return self._candidate_names[self.adaptive.active]

    def set_tree(self, fathers: Sequence[int]) -> None:
        """Install a runtime forest (e.g. an MST over probed latencies) as
        the active global strategy (parity: SetTree / SetGlobalStrategy,
        adaptation.cpp:5-33). Disables vote-driven switching — an explicit
        tree wins until the next session epoch.

        The installed forest must be a single tree rooted at rank 0:
        gather/reduce/broadcast walk global_strategies[0] assuming its root
        is rank 0, so a forest rooted elsewhere (or with several roots)
        would silently produce wrong data. Per-component forests are still
        available via subset_all_reduce/all_reduce_with."""
        if len(fathers) != self.size:
            raise ValueError(f"forest size {len(fathers)} != cluster {self.size}")
        roots = [r for r, f in enumerate(fathers) if int(f) == r]
        if roots != [0]:
            raise ValueError(
                f"set_tree forest must be one tree rooted at rank 0, got roots {roots}"
            )
        self.global_strategies = st.from_forest_array(list(fathers))
        self._tree_override = True

    def calc_stats(self) -> dict:
        """Per-strategy throughput summary (parity: CalcStats/LogStats)."""
        return self.adaptive.summary()

    def cross_all_reduce(self, w: Workspace) -> None:
        """AllReduce across host masters only (hierarchical path). While
        RING_SEGMENTED is the ACTIVE strategy, masters run the segmented
        walk over the master ring (the subset/cross variant); non-masters
        forward. Gated on _segmented_active — not the static configured
        strategy — so set_tree overrides and adaptive switches govern the
        cross path exactly like the global one (votes advance in lockstep
        on every peer, so the gate stays cluster-consistent)."""
        with stall_detect(f"cross_all_reduce({w.name})"):
            if (
                self._segmented_active()
                and len(self._masters) >= 2
                and w.recv.nbytes >= self.SEGMENT_MIN_BYTES
            ):
                self._run_segmented(w, ranks=self._masters)
            else:
                self._run_strategies(w, self.cross_strategies)

    def local_reduce(self, w: Workspace) -> None:
        self._run_graphs(w, [self.local_strategies[0].reduce_graph])

    def local_broadcast(self, w: Workspace) -> None:
        self._run_graphs(w, [self.local_strategies[0].bcast_graph])

    def _root_star_graphs(self, root: int) -> Tuple[Graph, Graph]:
        """(bcast, reduce) star graphs rooted at `root`, cached on the
        session — reduce/broadcast/broadcast_bytes used to regenerate
        them on every call (a Graph build is O(size) allocations, paid
        per elastic state-sync message). Benign to race: both writers
        compute identical graphs."""
        pair = self._root_graphs.get(root)
        if pair is None:
            bcast = topo.gen_star_bcast_graph(self.size, root)
            pair = (bcast, topo.gen_default_reduce_graph(bcast))
            self._root_graphs[root] = pair
        return pair

    def reduce(self, w: Workspace, root: int = 0) -> None:
        """Reduce to `root` (parity: runGraphs with a reduce graph; the
        reference's Reduce takes arbitrary roots). Root 0 walks the
        configured strategy; other roots use a root-specific star."""
        if root == 0:
            self._run_graphs(w, [self.global_strategies[0].reduce_graph])
        else:
            self._check_root(root)
            self._run_graphs(w, [self._root_star_graphs(root)[1]])

    def broadcast(self, w: Workspace, root: int = 0) -> None:
        with self._collected("broadcast", w.recv.nbytes):
            if root == 0:
                self._run_graphs(w, [self.global_strategies[0].bcast_graph])
            else:
                self._check_root(root)
                self._run_graphs(w, [self._root_star_graphs(root)[0]])

    def _check_root(self, root: int) -> None:
        if not 0 <= root < self.size:
            raise ValueError(f"root {root} outside cluster of {self.size}")

    def subset_all_reduce(self, fathers: Sequence[int], w: Workspace) -> None:
        sl = st.from_forest_array(list(fathers))
        self._run_strategies(w, sl)

    def all_reduce_with(self, fathers: Sequence[int], w: Workspace) -> None:
        """AllReduce on a runtime-supplied tree (parity: AllReduceWith)."""
        if fathers:
            sl = st.from_forest_array(list(fathers))
        else:
            sl = self.global_strategies
        self._run_strategies(w, sl)

    def barrier(self, tag: str = "") -> None:
        """Parity: session.go:98-113 (an allreduce of size bytes)."""
        k = len(self.peers)
        w = Workspace(
            send=np.zeros(k, np.uint8),
            recv=np.zeros(k, np.uint8),
            op=ReduceOp.SUM,
            name=f"kungfu::barrier{tag}",
        )
        self.all_reduce(w)

    def bytes_consensus(self, bs: bytes, name: str) -> bool:
        """True iff every peer supplied identical bytes (parity:
        session.go:126-157, which runs 4 allreduce rounds). 2 rounds
        here: a MIN-allreduce of the packed (len, -len) int64 workspace
        yields the cluster's (min-len, -max-len) in one walk, and a
        MIN-allreduce of the two-lane (payload, 255-payload) bytes yields
        (elementwise-min, 255-elementwise-max) in another — consensus iff
        min == max in both. Every elastic resize and strategy switch pays
        this path, so halving the rounds halves its serialized latency."""
        n = len(bs)
        lens = np.array([n, -n], np.int64)
        out_len = np.zeros(2, np.int64)
        self.all_reduce(
            Workspace(lens, out_len, ReduceOp.MIN, f":consensus:len:{name}")
        )
        if out_len[0] != -out_len[1]:
            return False
        if n == 0:
            return True
        x = np.frombuffer(bs, np.uint8)
        lanes = np.empty(2 * n, np.uint8)
        lanes[:n] = x
        np.subtract(255, x, out=lanes[n:])
        out = np.zeros(2 * n, np.uint8)
        self.all_reduce(
            Workspace(lanes, out, ReduceOp.MIN, f":consensus:data:{name}")
        )
        return bool(np.array_equal(out[:n], 255 - out[n:]))

    def broadcast_bytes(self, bs: bytes, name: str, root: int = 0) -> bytes:
        """Broadcast variable-length bytes from `root` (two graph walks:
        length, then payload). Used to bootstrap the device plane — the
        TPU analog of broadcasting the NCCL unique id over the CPU
        collective (gpu_collective.cpp:190-212) — and for elastic state
        re-sync, where the root must be a SURVIVING peer (not necessarily
        rank 0 of the new cluster)."""
        # a fixed star keeps the walk root-correct regardless of the active
        # strategy (set_tree/adaptive switches may re-root global_strategies)
        graph = self._root_star_graphs(root)[0]
        n_send = np.array([len(bs) if self.rank == root else 0], np.int64)
        n_recv = np.zeros(1, np.int64)
        self._run_graphs(
            Workspace(n_send, n_recv, ReduceOp.SUM, f"{name}:len"), [graph]
        )
        n = int(n_recv[0])
        if n == 0:
            return b""
        if self.rank == root:
            send = np.frombuffer(bs, np.uint8)
        else:
            send = np.zeros(n, np.uint8)
        recv = np.zeros(n, np.uint8)
        self._run_graphs(
            Workspace(send, recv, ReduceOp.SUM, f"{name}:data"), [graph]
        )
        return recv.tobytes()

    def gather(self, w: Workspace, root: int = 0) -> None:
        """`root` receives everyone's send buffer into recv (rank-major);
        parity: runGather (session.go:195-221), arbitrary roots like the
        reference's Gather. Handles unequal per-peer counts: the wire
        framing carries each message's true length, so the root lays
        contributions out by their actual sizes (the reference relies on
        the same message framing)."""
        self._check_root(root)
        if self.rank != root:
            with self._collected("gather", w.send.nbytes):
                self.client.send(
                    self.peers[root], w.name, _buf(w.send), ConnType.COLLECTIVE
                )
                self._count_wire(w.send.nbytes, "STAR")
            return
        scope = self._collected("gather", w.recv.nbytes)
        scope.__enter__()
        cancel = threading.Event()
        parts: List[Optional[np.ndarray]] = [None] * len(self.peers)
        releases: List = [None] * len(self.peers)

        def recv_part(r: int, peer: PeerID) -> None:
            msg = self.endpoint.recv(peer, w.name, self.timeout)
            if cancel.is_set():
                if msg.release is not None:
                    msg.release()
                return
            parts[r] = np.frombuffer(msg.data, w.send.dtype)
            releases[r] = msg.release

        jobs = []
        for r, peer in enumerate(self.peers):
            if r == self.rank:
                parts[r] = w.send.reshape(-1)
            else:
                jobs.append(lambda r=r, p=peer: recv_part(r, p))
        try:
            _par(jobs, self.timeout, cancel)
            off = 0
            for part in parts:
                assert part is not None
                n = part.size
                if off + n > w.recv.size:
                    raise ValueError(
                        f"gather overflow: recv buffer {w.recv.size} < {off + n}"
                    )
                np.copyto(w.recv[off:off + n], part)
                off += n
            if off != w.recv.size:
                # a short contribution would silently shift later ranks' data
                raise ValueError(
                    f"gather underflow: contributions fill {off} of {w.recv.size}"
                )
        finally:
            parts.clear()
            for rel in releases:
                if rel is not None:
                    rel()
            scope.__exit__(None, None, None)

    def all_gather(self, w: Workspace) -> None:
        """Gather to root then broadcast the concatenation (parity:
        AllGatherTransform, session.cpp:201-220)."""
        self.gather(w)
        bw = Workspace(send=w.recv, recv=w.recv, op=w.op, name=w.name + ":bcast")
        self.broadcast(bw)

    # ------------------------------------------------------------------
    # engine
    # ------------------------------------------------------------------

    def _run_segmented(
        self,
        w: Workspace,
        ranks: Optional[Sequence[int]] = None,
        cancel: Optional[threading.Event] = None,
    ) -> None:
        """Bandwidth-optimal segmented walk: a (k-1)-step reduce-scatter
        over contiguous segments followed by a (k-1)-step all-gather
        around a ring (arXiv:1810.11112 §3; the TPU-pod MLPerf stack
        leans on the same segmented summation, arXiv:1909.09756). Each
        step sends ONE ~N/k segment to the ring successor and reduces
        (or, in the gather phase, copies) the segment arriving from the
        predecessor in place — zero-copy views into the recv buffer, no
        full-payload relays, ~2*(k-1)/k*N bytes moved per peer total.

        Contracts shared with the graph walk: receives prefer the
        zero-copy sink/shm-borrow path (`recv_into`) and release borrows
        after the in-place reduce; one deadline bounds the WHOLE walk (not
        per step); a timed-out scratch buffer is never returned to the
        pool (the transport thread may still be mid-fill); empty segments
        (payload < k elements) are skipped identically on both ends of
        every edge, so no peer waits on a message that never departs.

        `ranks` restricts the ring to a subset (hierarchical cross-host
        mode); non-members just forward send into recv."""
        if w.is_empty:
            w.forward()
            return
        members = list(range(self.size)) if ranks is None else list(ranks)
        k = len(members)
        if self.rank not in members or k == 1:
            w.forward()
            return
        sched = topo.gen_segmented_schedule(members, members.index(self.rank))
        bounds = even_partition(w.recv.size, k)
        w.forward()  # seed the accumulator with own contribution
        acc = w.recv
        send_peer = self.peers[sched.send_peer]
        recv_peer = self.peers[sched.recv_peer]
        itemsize = acc.itemsize
        bufpool = get_buffer_pool()
        deadline = time.monotonic() + self.timeout
        wire = 0

        def do_send(name: str, sb: int, se: int) -> None:
            """Deadline-bounded send: a frozen successor (full shm ring
            -> socket fallback -> full TCP buffer) would otherwise block
            sendall forever and the walk-wide deadline — checked only in
            do_recv — would never fire. Dispatch + event-wait costs tens
            of µs per step, noise against the segment memcpy. A timed-out
            send thread is abandoned exactly like the graph walk's _par
            send threads; the zero-copy view stays valid because the
            caller raises out of the walk without touching acc again."""
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                raise TimeoutError(f"segmented walk timed out: {name}")
            done = threading.Event()
            errs: List[BaseException] = []

            def run() -> None:
                try:
                    # zero-copy: segments are disjoint and steps
                    # sequential per workspace, so this view cannot be
                    # mutated mid-sendall
                    self.client.send(
                        send_peer, name, _buf(acc[sb:se]), ConnType.COLLECTIVE
                    )
                except BaseException as e:  # noqa: BLE001 - re-raised below
                    errs.append(e)
                finally:
                    done.set()

            get_pool().submit(run)
            if not done.wait(remaining):
                raise TimeoutError(f"segmented send timed out: {name}")
            if errs:
                raise errs[0]

        def do_recv(name: str, rb: int, re_: int, reducing: bool) -> None:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                raise TimeoutError(f"segmented walk timed out: {name}")
            incoming, scratch, release = self._recv_collective(
                recv_peer, name, (re_ - rb) * itemsize, acc.dtype,
                re_ - rb, remaining,
            )
            try:
                if cancel is not None and cancel.is_set():
                    # caller-scope timeout fired while we were blocked:
                    # the recv buffer may already be reused — a late
                    # arrival must not be reduced into it
                    raise TimeoutError(f"collective cancelled: {name}")
                if reducing:
                    reduce_segment(acc, rb, re_, incoming, w.op)
                else:
                    copy_segment(acc, rb, re_, incoming)
            finally:
                del incoming
                if release is not None:
                    release()
            if scratch is not None:
                bufpool.put(scratch)

        def step(phase: str, s: int, send_seg: int, recv_seg: int, reducing: bool) -> None:
            nonlocal wire
            sb, se = bounds[send_seg]
            rb, re_ = bounds[recv_seg]
            name = f"{w.name}:{phase}{s}"
            if cancel is not None and cancel.is_set():
                raise TimeoutError(f"collective cancelled: {name}")
            # empty segments (payload < k elements) are skipped on BOTH
            # ends: sender and receiver compute identical bounds.
            # send-then-recv is deliberately SEQUENTIAL: the send returns
            # once the payload is in the shm ring / kernel buffer, so the
            # wire is already busy while we block on the predecessor —
            # and a _par pair per step measured 15% slower on the 2-core
            # bench box (thread dispatch + GIL beat the overlap).
            if se > sb:
                do_send(name, sb, se)
                wire += (se - sb) * itemsize
            if re_ > rb:
                do_recv(name, rb, re_, reducing)

        _t0 = time.perf_counter()
        for s, (snd, rcv) in enumerate(sched.rs_steps):
            with trace.span("host.rs.step", step=s, k=k):
                step("rs", s, snd, rcv, True)
        for s, (snd, rcv) in enumerate(sched.ag_steps):
            with trace.span("host.ag.step", step=s, k=k):
                step("ag", s, snd, rcv, False)
        self._count_wire(wire, Strategy.RING_SEGMENTED.name)
        trace.record(
            f"host.segmented[{w.recv.nbytes >> 20}MiB]",
            time.perf_counter() - _t0,
        )

    def _run_strategies(
        self,
        w: Workspace,
        strategies: List[st.StrategyPair],
        cancel: Optional[threading.Event] = None,
    ) -> None:
        total = w.recv.size * w.recv.itemsize
        k = max(1, -(-total // choose_chunk_bytes(total)))
        chunks = w.split(even_partition, k) if k > 1 else [w]
        if cancel is None:
            cancel = threading.Event()
        if k == 1:
            pair = strategies[0]
            self._run_graphs(chunks[0], [pair.reduce_graph, pair.bcast_graph], cancel)
            return
        jobs = []
        for i, chunk in enumerate(chunks):
            pair = st.choose(strategies, i)
            jobs.append(
                lambda c=chunk, p=pair: self._run_graphs(
                    c, [p.reduce_graph, p.bcast_graph], cancel
                )
            )
        _par(jobs, self.timeout, cancel)

    def _run_graphs(
        self,
        w: Workspace,
        graphs: List[Graph],
        cancel: Optional[threading.Event] = None,
    ) -> None:
        """The hot walk; parity: runGraphs (session.go:231-299).

        `cancel` is shared across every thread touching this workspace: once
        any part of the collective times out, late-arriving receives must not
        write into (possibly reused) caller buffers."""
        if w.is_empty:
            return
        if all(g.is_isolated(self.rank) for g in graphs):
            w.forward()
            return
        if cancel is None:
            cancel = threading.Event()
        _t_walk = time.perf_counter()

        state = {"recv_count": 0}
        lock = threading.Lock()

        def effective() -> np.ndarray:
            if state["recv_count"] > 0 or w.is_inplace:
                return w.recv
            return w.send

        wire_label = self._walk_label()

        def send_to(peer: PeerID, flags: Flags = Flags.NONE) -> None:
            # zero-copy: the walk's phases are sequential per chunk, so the
            # buffer cannot be mutated while sendall drains it
            self.client.send(
                peer, w.name, _buf(effective()), ConnType.COLLECTIVE, flags
            )
            self._count_wire(nbytes, wire_label)

        bufpool = get_buffer_pool()
        nbytes = w.recv.size * w.recv.itemsize

        def recv_payload(peer: PeerID):
            """See _recv_collective (shared with the segmented walk)."""
            return self._recv_collective(
                peer, w.name, nbytes, w.send.dtype, w.recv.size, self.timeout
            )

        def recv_onto(peer: PeerID) -> None:
            incoming, scratch, release = recv_payload(peer)
            try:
                with lock:
                    if cancel.is_set():
                        # abort the whole walk: a late arrival must neither
                        # write the workspace nor let the send phase relay
                        # stale data
                        raise TimeoutError(f"collective cancelled: {w.name}")
                    if state["recv_count"] == 0 and not w.is_inplace:
                        # first arrival: recv = send (op) incoming
                        from kungfu_tpu.base.ops import transform2

                        transform2(w.recv, w.send, incoming, w.op)
                    else:
                        reduce_inplace(w.recv, incoming, w.op)
                    state["recv_count"] += 1
            finally:
                del incoming
                if release is not None:
                    release()
            if scratch is not None:
                bufpool.put(scratch)

        def recv_all_onto(peers: List[PeerID]) -> None:
            """Accumulate phase: receive every prev, then reduce them all
            in ONE n-ary pass (kf_transform_n). Pairwise-on-arrival
            overlaps receive with reduce, which pays when cores are free;
            the n-ary pass minimizes memory traffic, which wins outright
            on busy/low-core hosts — and the receives themselves still
            overlap each other."""
            got: List = [None] * len(peers)

            def grab(i: int, p: PeerID) -> None:
                res = recv_payload(p)
                if cancel.is_set():
                    # the walk already timed out and its finally block may
                    # have run: release the borrow here or nobody will
                    if res[2] is not None:
                        res[2]()
                    return
                got[i] = res

            try:
                _par(
                    [lambda i=i, p=p: grab(i, p) for i, p in enumerate(peers)],
                    self.timeout,
                    cancel,
                )
                with lock:
                    if cancel.is_set():
                        raise TimeoutError(f"collective cancelled: {w.name}")
                    if w.is_inplace:
                        for incoming, _, _ in got:
                            reduce_inplace(w.recv, incoming, w.op)
                    else:
                        transform_n(
                            w.recv,
                            [w.send] + [inc for inc, _, _ in got],
                            w.op,
                        )
                    state["recv_count"] += len(peers)
            finally:
                for item in got:
                    if item is not None and item[2] is not None:
                        item[2]()
            for item in got:
                if item is not None and item[1] is not None:
                    bufpool.put(item[1])

        def recv_into(peer: PeerID) -> None:
            incoming, scratch, release = recv_payload(peer)
            try:
                with lock:
                    if cancel.is_set():
                        raise TimeoutError(f"collective cancelled: {w.name}")
                    np.copyto(w.recv, incoming)
                    state["recv_count"] += 1
            finally:
                del incoming
                if release is not None:
                    release()
            if scratch is not None:
                bufpool.put(scratch)

        for g in graphs:
            prevs = [self.peers[r] for r in g.prevs(self.rank)]
            nexts = [self.peers[r] for r in g.nexts(self.rank)]
            if g.is_self_loop(self.rank):
                # accumulate: receive from all prevs, n-ary reduce, send on
                if prevs and state["recv_count"] == 0:
                    recv_all_onto(prevs)
                else:
                    _par([lambda p=p: recv_onto(p) for p in prevs], self.timeout, cancel)
                _par([lambda p=p: send_to(p) for p in nexts], self.timeout, cancel)
            else:
                # pass-through node: take value from single prev (or forward
                # own), relay to nexts
                if not prevs and state["recv_count"] == 0:
                    w.forward()
                else:
                    for p in prevs:
                        recv_into(p)
                _par(
                    [lambda p=p: send_to(p, Flags.WAIT_RECV_BUF) for p in nexts],
                    self.timeout,
                    cancel,
                )
        trace.record(f"host.walk[{w.recv.nbytes >> 20}MiB]",
                     time.perf_counter() - _t_walk)
