"""Host-side collective engine: graph-walk collectives over the transport.

Capability parity: srcs/go/kungfu/session/session.go — an immutable
peer-list epoch running Barrier / Consensus / Reduce / Broadcast / Gather /
AllReduce by walking (reduce, bcast) graph pairs, with 1 MiB chunking
striped across multi-root strategies (runStrategies, session.go:301-330)
and SIMD reduction on receive (base.Transform2).

Role in the TPU build: this engine runs on HOSTS over DCN for control
collectives (consensus on cluster configs, barriers, progress sync) and for
CPU-only test clusters — the device data plane is XLA over ICI
(kungfu_tpu.ops). It is the direct replacement for the reference's
rchannel data plane.
"""

from __future__ import annotations

import os
import queue
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from kungfu_tpu.base.dtype import DType
from kungfu_tpu.base.ops import (
    ReduceOp,
    copy_segment,
    decode_accumulate,
    decode_wire,
    encode_wire,
    reduce_inplace,
    reduce_segment,
    transform_n,
)
from kungfu_tpu.telemetry import config as tconfig
from kungfu_tpu.telemetry import link as tlink
from kungfu_tpu.telemetry import metrics as tmetrics
from kungfu_tpu import knobs
from kungfu_tpu.utils import trace
from kungfu_tpu.base.strategy import Strategy
from kungfu_tpu.collective.adaptive import AdaptiveState
from kungfu_tpu.base.workspace import Workspace, even_partition
from kungfu_tpu.collective import strategies as st
from kungfu_tpu.collective.strategies import effective_cpu_count
from kungfu_tpu.plan import topology as topo
from kungfu_tpu.plan.graph import Graph
from kungfu_tpu.plan.peer import PeerID, PeerList
from kungfu_tpu.transport.client import Client
from kungfu_tpu.transport.handlers import CollectiveEndpoint
from kungfu_tpu.transport.message import ConnType, Flags
from kungfu_tpu.utils.pool import get_buffer_pool, get_pool
from kungfu_tpu.utils.stall import stall_detect

# Chunking (parity: session.go chunkSize, but self-tuned): the optimal
# trades chunk-walk overhead (fewer, bigger chunks) against striping/
# pipelining (more, smaller chunks) and depends on host core count —
# concurrent chunk walks only pay when cores exist to run them; on a
# 1-core host every extra in-flight chunk is pure context-switch cost.
# KF_CONFIG_CHUNK_BYTES overrides the heuristic.
CHUNK_BYTES = int(knobs.get("KF_CONFIG_CHUNK_BYTES"))
_CHUNK_MIN = 1 << 20
_CHUNK_MAX = 32 << 20
DEFAULT_TIMEOUT = 120.0

# A/B algorithm override (benchmarks, operators): forces the engine onto
# one family regardless of the configured/AUTO strategy. Like every other
# engine knob it MUST agree cluster-wide (peers that resolved different
# algorithms would wait on each other's rendezvous names forever).
_ALGO_STRATEGY = {
    "": None,
    "auto": Strategy.AUTO,
    "tree": Strategy.BINARY_TREE,
    "segmented": Strategy.RING_SEGMENTED,
}


def algo_override() -> Optional[Strategy]:
    """Parse KF_CONFIG_ALGO (read per session epoch, not import time).
    The registry's strict choice parser raises on a typo — fail fast,
    not silently diverge the cluster."""
    return _ALGO_STRATEGY[knobs.get("KF_CONFIG_ALGO")]


# Wire codec (ISSUE 5 tentpole): f32 allreduce payloads travel the
# transport as bf16/f16 while every reduce step accumulates into the f32
# buffer. Like KF_CONFIG_ALGO this is a cluster-agreed runtime knob (it
# decides message SIZES, so a disagreeing peer would read short/long
# frames) — fail-fast enforced by check_knob_consensus at session start.
# `auto` currently resolves to bf16 for eligible payloads (the TPU-native
# format: f32-identical exponent range, so no overflow surprises); it is
# a distinct mode so later heuristics (payload- or link-aware) can slot
# in without an env change.
_WIRE_MODES = ("off", "bf16", "f16", "auto")

_WIRE_DTYPE = {"bf16": DType.BF16, "f16": DType.F16, "auto": DType.BF16}


def wire_override() -> str:
    """Parse KF_CONFIG_WIRE (read per session epoch, not import time).
    The registry's strict choice parser raises on a typo and resolves
    unset/empty to "off"."""
    return knobs.get("KF_CONFIG_WIRE")


def choose_chunk_bytes(total: int) -> int:
    """Chunk size for a `total`-byte collective: honour the env override,
    else ~8 chunks per collective, clamped to [1 MiB, 32 MiB].

    MUST depend only on cluster-agreed inputs (the workspace size): chunk
    workspaces are named '<name>[i/k]', so peers that computed different
    k would wait forever on each other's chunk names. That rules out
    os.cpu_count() here (heterogeneous hosts); measured on the 1-core
    box, 8 in-flight walks of >=1 MiB is within noise of the per-core
    optimum anyway."""
    if CHUNK_BYTES > 0:
        return CHUNK_BYTES
    c = total // 8
    return max(_CHUNK_MIN, min(_CHUNK_MAX, c))


def _par(
    fns: List[Callable[[], None]],
    timeout: float,
    cancel: Optional[threading.Event] = None,
) -> None:
    """Run callables on the shared cached-thread pool, wait for all,
    re-raise the first error (goroutine-style fan-out; an unbounded cached
    pool avoids both thread-spawn cost per call and pool-exhaustion
    deadlocks on nested parallelism).

    All waits share ONE deadline (worst case = timeout, not
    len(fns)*timeout). On timeout `cancel` is set before raising so
    abandoned workers that later complete a recv can observe it and must
    NOT mutate the caller's workspace (a reused recv buffer would be
    corrupted by a late write)."""
    if not fns:
        return
    if len(fns) == 1:
        fns[0]()
        return
    cond = threading.Condition()
    state = {"done": 0}
    errs: List[BaseException] = []

    def run(fn):
        err: Optional[BaseException] = None
        try:
            fn()
        except BaseException as e:  # noqa: BLE001 - propagated below
            err = e
        with cond:
            state["done"] += 1
            if err is not None:
                errs.append(err)
            cond.notify_all()

    pool = get_pool()
    for fn in fns:
        pool.submit(lambda f=fn: run(f))
    with cond:
        if not cond.wait_for(lambda: state["done"] >= len(fns), timeout):
            if cancel is not None:
                cancel.set()
            raise TimeoutError("collective thread timed out")
        if errs:
            raise errs[0]


def _buf(arr: np.ndarray):
    """Zero-copy byte view of a contiguous array (tobytes() fallback)."""
    try:
        return arr.data.cast("B")
    except (ValueError, TypeError, AttributeError):
        return arr.tobytes()


class _DeferredDecode:
    """Handle to a compressed segmented walk's all-gather wire buffer,
    returned instead of the walk-end f32 decode when the caller asked to
    defer it (`_allreduce_ws(defer_decode=True)`). The fused pipeline's
    unpacker decodes straight from this buffer into each member's recv —
    fusing decode with unpack saves one full f32 pass over the bucket on
    the hot path. Call `decode_into(dst, begin, end)` per member, then
    `close()` exactly once to return the buffer to the pool."""

    __slots__ = ("wire", "_buf", "_arr")

    def __init__(self, wire: DType, buf, arr: np.ndarray):
        self.wire = wire
        self._buf = buf
        self._arr = arr

    def decode_into(self, dst: np.ndarray, begin: int, end: int) -> None:
        seg = self._arr[begin:end]
        if dst.flags["C_CONTIGUOUS"]:
            decode_wire(dst, seg, self.wire)
        else:
            tmp = np.empty(end - begin, np.float32)
            decode_wire(tmp, seg, self.wire)
            np.copyto(dst, tmp)

    def close(self) -> None:
        if self._buf is not None:
            get_buffer_pool().put(self._buf)
            self._buf = None


class _WalkProfile:
    """Per-walk critical-path accumulator (one walk = one thread running
    one segmented ring or one chunk's graph pair): seconds the walk
    thread spent blocked on receives and blocked on sends. Everything
    else — reduce/codec kernels, pack/unpack memcpys, Python overhead —
    is compute by construction (wall − wait − send), so the three
    fractions always sum to 1."""

    __slots__ = ("wait", "send")

    def __init__(self):
        self.wait = 0.0
        self.send = 0.0


class _SpanSampler:
    """Deterministic walk sampler for per-step spans
    (KF_TELEMETRY_SPAN_SAMPLE): emits per-step spans for walk n iff the
    integer part of n*rate advances — exactly rate*N of any N walks,
    evenly spaced, identical across reruns (no RNG)."""

    __slots__ = ("rate", "_n", "_lock")

    def __init__(self, rate: float):
        self.rate = rate
        self._n = 0
        self._lock = threading.Lock()

    def sample(self) -> bool:
        if self.rate >= 1.0:
            return True
        if self.rate <= 0.0:
            return False
        with self._lock:
            self._n += 1
            n = self._n
        return int(n * self.rate) != int((n - 1) * self.rate)


class WalkProfiler:
    """Collective critical-path profiler (ISSUE 6 tentpole, part b).

    Aggregates every allreduce walk's wall-time attribution per
    (public collective, executing strategy): fractions of walk time
    spent wait-on-recv vs reduce/codec compute vs send-blocked, the
    achieved throughput against the 2·(k−1)/k·N bandwidth-optimal
    bound, and — when the link plane has a bandwidth estimate for the
    links the walk used — an **efficiency ratio**:

        efficiency = (2·(k−1)/k·N / link_bw) / wall
                   = optimal transfer time / achieved wall time

    1.0 means the walk moved its optimal byte volume at full measured
    link speed; the gap to 1.0 is the overhead the async scheduler and
    topology re-planner (ROADMAP items 2/5) have to harvest. Exported
    as ``kungfu_collective_efficiency_ratio`` gauges and
    ``kungfu_collective_walk_seconds_total{phase}`` counters; process-
    global (sessions are rebuilt every elastic epoch, the attribution
    series must survive them).

    Attribution caveats (documented, not bugs): on graph walks the
    pairwise receive path folds its in-place reduce into the timed
    receive block (the n-ary fan-in path separates them), and wire-mode
    fan-out encodes land in compute while the transport part of the
    fan-out lands in send. The fractions describe the walk *thread*;
    pool-thread work overlapped with a timed block is deliberately not
    double-counted.
    """

    _ALPHA = 0.2  # EWMA for the efficiency series, matches the link plane

    def __init__(self):
        self._lock = threading.Lock()
        self._acc: Dict[Tuple[str, str], dict] = {}

    def record(
        self,
        collective: str,
        strategy: str,
        k: int,
        payload_bytes: int,
        wall: float,
        wait: float,
        send: float,
        link_bw: Optional[float] = None,
    ) -> None:
        if wall <= 0.0 or k < 2 or payload_bytes <= 0:
            return
        # clamp measurement jitter so per-walk phases never exceed wall
        # (fractions must sum to 1 by construction)
        blocked = wait + send
        if blocked > wall:
            scale = wall / blocked
            wait *= scale
            send *= scale
        opt_bytes = 2.0 * (k - 1) / k * payload_bytes
        eff = None
        if link_bw is not None and link_bw > 0:
            eff = (opt_bytes / link_bw) / wall
        key = (collective, strategy)
        with self._lock:
            a = self._acc.get(key)
            if a is None:
                a = self._acc[key] = {
                    "walks": 0, "wall": 0.0, "wait": 0.0, "send": 0.0,
                    "payload_bytes": 0.0, "opt_bytes": 0.0,
                    "eff": None, "eff_samples": 0,
                    # EWMAs of RECENT walks, for signals(): the cumulative
                    # sums above describe the whole run (snapshot/bench),
                    # but an adaptation signal weighted by all-time sums
                    # goes inert after hours — a link that degrades at
                    # walk 50,000 must move the signal within ~10 walks,
                    # like the link plane's own bandwidth EWMA does
                    "wait_frac_ewma": None, "wall_ewma": None,
                }
            a["walks"] += 1
            a["wall"] += wall
            a["wait"] += wait
            a["send"] += send
            a["payload_bytes"] += payload_bytes
            a["opt_bytes"] += opt_bytes
            wf = wait / wall
            a["wait_frac_ewma"] = (
                wf if a["wait_frac_ewma"] is None
                else self._ALPHA * wf + (1.0 - self._ALPHA) * a["wait_frac_ewma"]
            )
            a["wall_ewma"] = (
                wall if a["wall_ewma"] is None
                else self._ALPHA * wall + (1.0 - self._ALPHA) * a["wall_ewma"]
            )
            if eff is not None:
                a["eff"] = (
                    eff if a["eff"] is None
                    else self._ALPHA * eff + (1.0 - self._ALPHA) * a["eff"]
                )
                a["eff_samples"] += 1
                ewma = a["eff"]
            else:
                ewma = None
        self._publish(collective, strategy, wall, wait, send, ewma)

    def _publish(self, collective, strategy, wall, wait, send, eff) -> None:
        # re-read the gate every walk (once per walk, not per step):
        # the profiler is process-global and outlives session epochs,
        # so a one-shot cache would freeze a pre-enable() answer forever
        if not tconfig.metrics_enabled():
            return
        phases = tmetrics.counter(
            "kungfu_collective_walk_seconds_total",
            "Walk wall time attributed to wait-on-recv / reduce+codec "
            "compute / send-blocked, per collective and strategy",
            ("collective", "strategy", "phase"),
        )
        phases.labels(collective, strategy, "wait").inc(wait)
        phases.labels(collective, strategy, "send").inc(send)
        phases.labels(collective, strategy, "compute").inc(
            max(wall - wait - send, 0.0)
        )
        if eff is not None:
            tmetrics.gauge(
                "kungfu_collective_efficiency_ratio",
                "EWMA of achieved walk time vs the 2(k-1)/k*N bandwidth-"
                "optimal bound at measured link speed (1.0 = optimal)",
                ("collective", "strategy"),
            ).labels(collective, strategy).set(eff)

    def snapshot(self) -> Dict[str, dict]:
        """Per-'collective/strategy' attribution summary; fractions sum
        to ~1.0 (compute is the residual)."""
        with self._lock:
            items = {k: dict(v) for k, v in self._acc.items()}
        out: Dict[str, dict] = {}
        for (collective, strategy), a in sorted(items.items()):
            wall = a["wall"]
            if wall <= 0:
                continue
            wait_f = a["wait"] / wall
            send_f = a["send"] / wall
            out[f"{collective}/{strategy}"] = {
                "walks": a["walks"],
                "wall_s": wall,
                "payload_bytes": a["payload_bytes"],
                "wait_frac": wait_f,
                "send_frac": send_f,
                "compute_frac": max(1.0 - wait_f - send_f, 0.0),
                "achieved_gib_s": a["opt_bytes"] / wall / (1 << 30),
                "efficiency": a["eff"],
                "efficiency_samples": a["eff_samples"],
            }
        return out

    def signals(self) -> Dict[str, float]:
        """Adaptation-facing summary for PolicyContext.metrics: the
        EWMA wait fraction and efficiency of RECENT walks, weighted
        across walk families by each family's recent wall time (a family
        that stopped running stops steering the signal; one that turned
        slow dominates it — all-time sums would go inert on long runs)."""
        with self._lock:
            # copy under the lock (like snapshot): the per-key dicts are
            # mutated by record() on walk threads, and the sums below
            # must read one consistent state
            items = [dict(v) for v in self._acc.values()]
        items = [a for a in items if a["wall_ewma"]]
        wall = sum(a["wall_ewma"] for a in items)
        if wall <= 0:
            return {}
        out: Dict[str, float] = {
            "collective/wait_frac": (
                sum(a["wall_ewma"] * a["wait_frac_ewma"] for a in items) / wall
            ),
        }
        eff_wall = sum(a["wall_ewma"] for a in items if a["eff"] is not None)
        if eff_wall > 0:
            out["collective/efficiency"] = (
                sum(
                    a["wall_ewma"] * a["eff"]
                    for a in items
                    if a["eff"] is not None
                )
                / eff_wall
            )
        return out

    def reset(self) -> None:
        with self._lock:
            self._acc.clear()


_walk_profiler = WalkProfiler()


def get_walk_profiler() -> WalkProfiler:
    return _walk_profiler


class _CollectiveScope:
    """Span + latency-histogram wrapper around one public collective
    (plain classes end to end — tracing._Span underneath is also
    class-based — so the per-call telemetry cost stays at two clock
    reads, a deque append and an optional histogram observe)."""

    __slots__ = ("_sess", "_kind", "_span", "_t0", "_prev_kind")

    def __init__(self, sess: "HostSession", kind: str, nbytes: int):
        self._sess = sess
        self._kind = kind
        self._span = trace.span(
            f"collective.{kind}", bytes=int(nbytes), size=sess.size
        )

    def __enter__(self):
        self._t0 = time.perf_counter()
        # label wire-byte counts with the public collective that caused
        # them (walks run on pool threads, so this lives on the session;
        # rare concurrent collectives of different kinds may cross-label
        # a few bytes, which accounting tolerates)
        self._prev_kind = self._sess._wire_kind
        self._sess._wire_kind = self._kind
        self._span.__enter__()
        return self

    def __exit__(self, *exc):
        self._span.__exit__(*exc)
        self._sess._wire_kind = self._prev_kind
        hist = self._sess._coll_hist
        if hist is not None:
            hist.labels(self._kind).observe(time.perf_counter() - self._t0)
        return False



class HostSession:
    """One collective epoch over a fixed PeerList."""

    def __init__(
        self,
        strategy: Strategy,
        self_id: PeerID,
        peers: PeerList,
        client: Client,
        endpoint: CollectiveEndpoint,
        timeout: float = DEFAULT_TIMEOUT,
    ):
        rank = peers.rank(self_id)
        if rank is None:
            raise ValueError(f"{self_id} not in peer list {peers}")
        self.self_id = self_id
        self.peers = peers
        self.rank = rank
        self.local_rank = peers.local_rank(self_id)
        self.local_size = peers.local_size(self_id)
        self.host_count = peers.host_count()
        self.client = client
        self.endpoint = endpoint
        self.timeout = timeout
        forced = algo_override()
        if forced is not None:
            strategy = forced
        if strategy == Strategy.AUTO:
            strategy = st.auto_select(peers)
        self.strategy = strategy
        self.global_strategies = st.gen_global_strategies(peers, strategy)
        self.local_strategies = st.gen_local_strategies(peers)
        self.cross_strategies = st.gen_cross_strategies(peers, strategy)
        # ring order for the cross-host segmented walk (hierarchical mode)
        self._masters, _ = peers.partition_by_host()
        # per-root star graph cache (satellite: reduce/broadcast with
        # root != 0 regenerated star + default-reduce on every call);
        # sessions are rebuilt each epoch, so invalidation is automatic
        self._root_graphs: Dict[int, Tuple[Graph, Graph]] = {}
        # wire codec knob: resolved once per session epoch like the
        # strategy; the ACTIVE codec can differ when adaptation toggles it
        self.wire_mode = wire_override()
        # adaptive control (parity: session/adaptiveStrategies.go): a
        # deterministic candidate order — identical on every peer — so a
        # majority vote can advance everyone in lockstep. Candidates are
        # (strategy, wire-mode) pairs: the first alternate toggles the
        # CODEC on the same graphs (the cheapest lever against a
        # congested/interfered link — half or restore the wire bytes
        # without re-pairing anyone), then the strategy alternates walk
        # under the configured codec, RING_SEGMENTED first so votes can
        # switch ONTO the bandwidth-optimal member (and off it, by
        # advancing again). Candidate graph lists are built lazily:
        # sessions are rebuilt every elastic epoch and most never adapt.
        wire_toggled = "off" if self.wire_mode != "off" else "bf16"
        self._candidates: List[Tuple[Strategy, str]] = (
            [(strategy, self.wire_mode), (strategy, wire_toggled)]
            + [
                (s, self.wire_mode) for s in (
                    Strategy.RING_SEGMENTED, Strategy.RING,
                    Strategy.BINARY_TREE_STAR, Strategy.STAR, Strategy.CLIQUE,
                ) if s != strategy
            ]
        )
        self._candidates_built: dict = {0: self.global_strategies, 1: self.global_strategies}
        self.adaptive = AdaptiveState(
            len(self._candidates),
            names=[f"{s.name}/{wm}" for s, wm in self._candidates],
        )
        self._tree_override = False
        # per-collective latency histogram (telemetry): one observe per
        # COLLECTIVE call (not per message), gated off with the rest of
        # the metrics so the steady-state walk stays untouched
        self._coll_hist = (
            tmetrics.histogram(
                "kungfu_collective_latency_seconds",
                "Host-plane collective latency by kind",
                ("collective",),
            )
            if tconfig.metrics_enabled()
            else None
        )
        # wire-byte accounting: bytes this peer SENDS into collective
        # walks, by (public collective, executing strategy, wire codec).
        # This is the counter the segmented engine's bandwidth-optimality
        # claim is asserted against (tests) and the A/B bench reports;
        # the codec dimension separates compressed from raw traffic.
        self._wire_ctr = (
            tmetrics.counter(
                "kungfu_collective_wire_bytes_total",
                "Host-plane collective payload bytes sent by this peer",
                ("collective", "strategy", "codec"),
            )
            if tconfig.metrics_enabled()
            else None
        )
        # bytes the codec kept OFF the wire: raw payload minus encoded
        # payload, summed over every compressed send
        self._wire_saved_ctr = (
            tmetrics.counter(
                "kungfu_collective_wire_saved_bytes_total",
                "Wire bytes saved by the collective codec on this peer",
                ("collective", "codec"),
            )
            if tconfig.metrics_enabled()
            else None
        )
        self._wire_kind = "raw"
        # audit dedup for codec bypasses: one event per (reason, dtype)
        # per session epoch, so consensus lanes don't flood the audit log
        self._codec_bypass_seen: set = set()
        # link plane + walk profiler (ISSUE 6): the local link table
        # supplies per-destination bandwidth estimates the profiler
        # scores walks against; the sampler thins per-step spans
        self._links = tlink.get_table() if tlink.enabled() else None
        self._span_sampler = _SpanSampler(tconfig.span_sample())

    def _candidate(self, idx: int) -> List[st.StrategyPair]:
        if idx not in self._candidates_built:
            self._candidates_built[idx] = st.gen_global_strategies(
                self.peers, self._candidates[idx][0]
            )
        return self._candidates_built[idx]

    @property
    def size(self) -> int:
        return len(self.peers)

    def close(self) -> None:
        pass

    def _collected(self, kind: str, nbytes: int):
        """Telemetry wrapper for one public collective: a named span
        (feeding /trace) plus a latency-histogram observation when
        metrics are on. Returns a context manager."""
        return _CollectiveScope(self, kind, nbytes)

    def _count_wire(
        self, nbytes: int, strategy_label: str, codec: str = "off",
        raw_bytes: int = 0,
    ) -> None:
        if self._wire_ctr is not None and nbytes:
            self._wire_ctr.labels(self._wire_kind, strategy_label, codec).inc(nbytes)
        if (
            self._wire_saved_ctr is not None
            and codec != "off"
            and raw_bytes > nbytes
        ):
            self._wire_saved_ctr.labels(self._wire_kind, codec).inc(
                raw_bytes - nbytes
            )

    def _record_walk(
        self,
        strategy_label: str,
        k: int,
        payload_bytes: int,
        wall: float,
        prof: "_WalkProfile",
        dsts=None,
    ) -> None:
        """Feed one finished allreduce walk to the process profiler,
        scored against the slowest link the walk used (all estimated
        links when `dsts` is None — graph walks fan out over many)."""
        link_bw = None
        if self._links is not None:
            _, link_bw = self._links.min_bandwidth(dsts)
        _walk_profiler.record(
            self._wire_kind, strategy_label, k, payload_bytes,
            wall, prof.wait, prof.send, link_bw,
        )

    def _walk_label(self) -> str:
        """Strategy label for graph-walk wire accounting. Labels the
        graphs that actually EXECUTED: when RING_SEGMENTED is active but
        a payload fell below SEGMENT_MIN_BYTES, the walk ran the binary-
        tree fallback graphs and must not pollute the RING_SEGMENTED
        series (it is the one the optimality assertion reads)."""
        if self._tree_override:
            return "SET_TREE"
        active = self._candidates[self.adaptive.active][0]
        if active == Strategy.RING_SEGMENTED:
            return Strategy.BINARY_TREE.name
        return active.name

    def _active_wire_mode(self) -> str:
        """The RUNNING codec mode: the active adaptive candidate's wire
        member, or the configured mode under a set_tree override (an
        explicit forest replaces the graphs, not the codec)."""
        if self._tree_override:
            return self.wire_mode
        return self._candidates[self.adaptive.active][1]

    def _codec_bypass(self, reason: str, w: Workspace) -> None:
        """Audit (once per (reason, dtype) per session epoch) that a
        workspace bypassed an enabled codec — exact semantics preserved
        for consensus lanes, variance probes and tiny residuals."""
        key = (reason, w.send.dtype.str)
        if key in self._codec_bypass_seen:
            return
        self._codec_bypass_seen.add(key)
        from kungfu_tpu.telemetry import audit as _audit

        _audit.record_event(
            "wire_codec_bypass",
            peer=str(self.self_id),
            reason=reason,
            dtype=w.send.dtype.str,
            name=w.name,
            nbytes=int(w.recv.nbytes),
        )

    def _wire_codec_for(self, w: Workspace) -> Optional[DType]:
        """Codec decision for one allreduce workspace, or None (raw).

        MUST depend only on cluster-agreed inputs — the resolved wire
        mode (env + lockstep adaptive votes) and workspace properties
        identical on every peer — because it decides the byte count of
        every message in the walk. Non-f32 payloads (consensus lanes,
        int gradients) and sub-WIRE_MIN_BYTES residuals bypass with an
        audit event, never an error."""
        mode = self._active_wire_mode()
        if mode == "off":
            return None
        if w.send.dtype != np.float32:
            self._codec_bypass("non_f32", w)
            return None
        if w.recv.nbytes < self.WIRE_MIN_BYTES:
            self._codec_bypass("below_min_bytes", w)
            return None
        return _WIRE_DTYPE[mode]

    def _recv_collective(
        self, peer: PeerID, name: str, nbytes: int, dtype, count: int,
        timeout: float,
    ):
        """Receive (peer, name) into a pooled scratch buffer — delivered
        straight off the socket when we're parked first (sink path), else
        from the buffered Message (possibly a zero-copy shm borrow).
        Returns (ndarray view, scratch-or-None to return to the pool,
        release-or-None to call once the view has been consumed). Shared
        by the graph walk and the segmented walk so the borrow/release/
        leak-on-timeout contract lives in ONE place. On error the scratch
        is deliberately NOT returned to the pool: a timed-out sink may
        still be mid-fill by the transport thread."""
        bufpool = get_buffer_pool()
        scratch = bufpool.get(nbytes)
        msg, filled = self.endpoint.recv_into(
            peer, name, memoryview(scratch), timeout
        )
        if filled:
            return np.frombuffer(scratch, dtype, count), scratch, None
        bufpool.put(scratch)  # unused: sender raced us or size mismatch
        return np.frombuffer(msg.data, dtype, count), None, msg.release

    # ------------------------------------------------------------------
    # public collectives
    # ------------------------------------------------------------------

    # Segmentation pays only when the per-step segment amortizes the
    # 2*(k-1) serialized message latencies; below this the rank-0 binary
    # tree fallback graphs win. MUST be cluster-agreed (it decides which
    # rendezvous names a peer waits on) — like CHUNK_BYTES, the default
    # is a constant and the env override must be set fleet-wide.
    SEGMENT_MIN_BYTES = int(knobs.get("KF_CONFIG_SEGMENT_MIN_BYTES"))

    # Codec floor: encoding pays two passes (encode + decode) to halve
    # the wire bytes, which only wins once the payload dwarfs the fixed
    # per-walk costs; tiny control collectives also stay exact this way.
    # Cluster-agreed like SEGMENT_MIN_BYTES (it decides message sizes).
    WIRE_MIN_BYTES = int(knobs.get("KF_CONFIG_WIRE_MIN_BYTES"))

    def _segmented_active(self) -> bool:
        return (
            not self._tree_override
            and self.size >= 2
            and self._candidates[self.adaptive.active][0]
            == Strategy.RING_SEGMENTED
        )

    def _allreduce_ws(
        self,
        w: Workspace,
        cancel: Optional[threading.Event] = None,
        defer_decode: bool = False,
    ) -> Optional[_DeferredDecode]:
        """Engine dispatch for one allreduce workspace: the segmented
        ring walk when RING_SEGMENTED is active and the payload is worth
        segmenting, else chunked graph walks. `cancel` (group/window
        scope) propagates so an abandoned walk observes the caller's
        timeout before mutating recv buffers.

        With `defer_decode=True` a compressed segmented walk skips its
        walk-end decode and returns the wire buffer as a
        _DeferredDecode (w.recv is then NOT fully written!); every
        other path returns None and w.recv holds the result."""
        wire = self._wire_codec_for(w)
        if self._segmented_active() and w.recv.nbytes >= self.SEGMENT_MIN_BYTES:
            return self._run_segmented(
                w, cancel=cancel, wire=wire, defer_decode=defer_decode
            )
        self._run_strategies(w, self.global_strategies, cancel, wire=wire)
        return None

    def all_reduce(self, w: Workspace) -> None:
        with self._collected("all_reduce", w.recv.nbytes):
            with stall_detect(f"all_reduce({w.name})"):
                self._allreduce_ws(w)

    # concurrent workspaces per batch in group ops: concurrency only pays
    # when cores exist to run the walks (on a 1-core host it just adds
    # context switches), so the default scales with the cgroup-aware
    # core count — os.cpu_count() reports the HOST's cores inside a
    # CPU-quota'd container, the phantom-parallelism trap auto_select
    # already avoids; KF_CONFIG_GROUP_WINDOW overrides
    GROUP_WINDOW = int(
        knobs.get("KF_CONFIG_GROUP_WINDOW")
        or max(1, min(8, effective_cpu_count()))
    )

    # Gradient bucketing: fuse same-(dtype, op) workspaces into ONE
    # contiguous walk. A 160-tensor gradient set otherwise pays the fixed
    # per-walk cost (rendezvous conditions, pool dispatch, ~6 framed
    # messages) 160 times — on a host-plane reduce that overhead rivals
    # the byte-copy time itself. Two extra memcpy passes (pack + unpack)
    # buy a ~160x cut in message count. The reference runs one collective
    # per tensor and leans on cheap goroutines instead; bucketing is the
    # standard DDP/Horovod answer and is strictly better here.
    FUSE_MIN_TENSORS = int(knobs.get("KF_CONFIG_GROUP_FUSE_MIN"))

    # Fused-bucket size cap: fused groups split into buckets that pack /
    # walk / unpack as a 3-stage pipeline, so the cap trades per-walk
    # fixed cost (bigger buckets) against pack/unpack overlap (smaller
    # buckets start their walk sooner and unpack while the next bucket is
    # on the wire). Measured on the 2-core bench box: 8 MiB buckets pay
    # 12 walks' fixed cost for resnet50 and run 2x SLOWER than one big
    # bucket; 64 MiB is within noise of a single bucket while still
    # pipelining multi-hundred-MB sets (bert ~700 MB -> 11 buckets).
    # Part of the fused workspace name, so it MUST be cluster-agreed
    # like CHUNK_BYTES (which also rules out core-count scaling here).
    GROUP_BUCKET_BYTES = int(knobs.get("KF_CONFIG_GROUP_BUCKET_BYTES"))

    def group_all_reduce(self, ws: Sequence[Workspace]) -> None:
        """Allreduce of many workspaces as one windowed group op (parity:
        the reference reduces a whole gradient set per session.run —
        srcs/python/kungfu/tensorflow/v1/benchmarks). Fused buckets run
        through the 3-stage pipeline while the singles windows walk
        concurrently — neither waits for the other to finish."""
        if not ws:
            return
        with self._collected(
            "group_all_reduce", sum(w.recv.nbytes for w in ws)
        ), stall_detect(f"group_all_reduce[{len(ws)}]"):
            singles: List[Workspace] = []
            groups: Dict[tuple, List[Workspace]] = {}
            for w in ws:
                if w.is_empty:
                    continue
                groups.setdefault((w.send.dtype.str, int(w.op)), []).append(w)
            buckets: List[List[Workspace]] = []
            for members in groups.values():
                if len(members) < self.FUSE_MIN_TENSORS:
                    singles.extend(members)
                else:
                    buckets.extend(self._make_buckets(members))
            jobs: List[Callable[[], None]] = []
            # the group deadline scales with the number of walks it
            # covers — the serial predecessor allowed one self.timeout
            # PER fused walk / singles window, and a large healthy group
            # on a slow link must not trip a single flat budget
            windows = -(-len(singles) // self.GROUP_WINDOW)
            group_timeout = self.timeout * max(1, len(buckets) + windows)
            # shared cancel: a group-level timeout must also abort the
            # pipeline stages, or a lingering unpacker would keep writing
            # caller recv buffers after this call already raised (the
            # late-write hazard _par's contract exists to prevent)
            cancel = threading.Event()
            if buckets:
                jobs.append(
                    lambda: self._fused_pipeline(buckets, group_timeout, cancel)
                )
            if singles:
                jobs.append(lambda: self._singles_windows(singles, cancel))
            _par(jobs, group_timeout, cancel)

    def _make_buckets(
        self, members: List[Workspace]
    ) -> List[List[Workspace]]:
        """Greedy, order-preserving packing of same-(dtype, op)
        workspaces into <= GROUP_BUCKET_BYTES buckets. Derived only from
        the caller's tensor order and the byte cap, so every peer computes
        the same layout (the fused name encodes it); an oversized single
        tensor gets a bucket of its own."""
        buckets: List[List[Workspace]] = []
        cur: List[Workspace] = []
        cur_bytes = 0
        for w in members:
            if cur and cur_bytes + w.send.nbytes > self.GROUP_BUCKET_BYTES:
                buckets.append(cur)
                cur, cur_bytes = [], 0
            cur.append(w)
            cur_bytes += w.send.nbytes
        if cur:
            buckets.append(cur)
        return buckets

    def _singles_windows(
        self,
        singles: List[Workspace],
        cancel: Optional[threading.Event] = None,
    ) -> None:
        for i in range(0, len(singles), self.GROUP_WINDOW):
            if cancel is not None and cancel.is_set():
                # the group already raised (timeout, or a pipeline-stage
                # error that set the shared cancel): stop launching
                # windows, but return QUIETLY — raising here would race
                # the real error to _par's errs[0] and misreport a
                # deterministic failure as 'cancelled'
                return
            batch = singles[i : i + self.GROUP_WINDOW]
            _par(
                [lambda w=w: self._allreduce_ws(w, cancel) for w in batch],
                self.timeout,
                cancel,
            )

    def _pack_bucket(self, bi: int, members: List[Workspace]):
        """Pack one bucket into pooled contiguous buffers. Workspace
        order is the caller's tensor order, identical on every peer, so
        the fused name and layout agree cluster-wide.

        When the wire codec will compress this bucket, members are
        packed straight into ONE buffer that doubles as the walk's f32
        accumulator (an inplace workspace): all wire staging already
        happens in pooled 2-byte scratches inside the walk, so the
        second full-size f32 buffer (and its memcpy) of the raw path
        buys nothing. Inplace fused workspaces are valid on every walk
        path, so a mid-flight adaptive codec toggle stays correct."""
        dtype = members[0].send.dtype
        op = members[0].op
        total = sum(w.send.size for w in members)
        nbytes = total * dtype.itemsize
        pool = get_buffer_pool()
        single = (
            self._active_wire_mode() != "off"
            and dtype == np.float32
            and nbytes >= self.WIRE_MIN_BYTES
        )
        send_b = pool.get(nbytes)
        recv_b = None if single else pool.get(nbytes)
        with trace.span("host.fuse.pack"):
            send = np.frombuffer(send_b, dtype, total)
            recv = send if single else np.frombuffer(recv_b, dtype, total)
            off = 0
            for w in members:
                send[off : off + w.send.size] = w.send
                off += w.send.size
        fused = Workspace(
            send=send,
            recv=recv,
            op=op,
            name=f"{members[0].name}::fused:b{bi}:{len(members)}x{total}",
        )
        return (fused, send_b, recv_b, members)

    def _unpack_bucket(self, item) -> None:
        fused, send_b, recv_b, members, deferred = item
        pool = get_buffer_pool()
        try:
            with trace.span("host.fuse.unpack"):
                off = 0
                if deferred is not None:
                    # fused decode+unpack: the compressed walk handed us
                    # its wire buffer instead of decoding into the fused
                    # recv first — one full f32 pass saved per bucket
                    for w in members:
                        deferred.decode_into(w.recv, off, off + w.recv.size)
                        off += w.recv.size
                else:
                    for w in members:
                        np.copyto(w.recv, fused.recv[off : off + w.recv.size])
                        off += w.recv.size
        finally:
            if deferred is not None:
                deferred.close()
            pool.put(send_b)
            if recv_b is not None:
                pool.put(recv_b)

    def _fused_pipeline(
        self,
        buckets: List[List[Workspace]],
        timeout: float,
        cancel: Optional[threading.Event] = None,
    ) -> None:
        """3-stage software pipeline over fused buckets: pack bucket i+1
        and unpack bucket i-1 while bucket i is on the wire. The serial
        predecessor (all packs, then all walks, then all unpacks per
        bucket) left the wire idle during every memcpy phase. Depth-1
        handoff queues bound live pooled buffers at 5 buckets (one per
        stage + one per queue) — x2 buffers x GROUP_BUCKET_BYTES, well
        under the serial path's single whole-group buffer pair for big
        sets. Every queue get/put is abort-aware, so any stage's failure
        (or a dropped sentinel after one) unblocks the other two and the
        REAL error propagates out of _par; aborted in-flight buffers are
        dropped to GC (the pool's documented policy for buffers a worker
        may still touch)."""
        packed: "queue.Queue" = queue.Queue(maxsize=1)
        unpackq: "queue.Queue" = queue.Queue(maxsize=1)
        # the caller's cancel event doubles as the abort flag: _par sets
        # it on timeout, so every stage (unpacker included) stops before
        # touching caller buffers again
        abort = cancel if cancel is not None else threading.Event()

        def put(q: "queue.Queue", item) -> bool:
            """Bounded put that gives up once the pipeline aborts."""
            while True:
                try:
                    q.put(item, timeout=0.2)
                    return True
                except queue.Full:
                    if abort.is_set():
                        return False

        def get(q: "queue.Queue"):
            """Blocking get that turns into the sentinel on abort, so a
            consumer can never be stranded by a lost sentinel."""
            while True:
                try:
                    return q.get(timeout=0.2)
                except queue.Empty:
                    if abort.is_set():
                        return None

        def packer():
            try:
                for bi, members in enumerate(buckets):
                    if abort.is_set():
                        return
                    if not put(packed, self._pack_bucket(bi, members)):
                        return
            except BaseException:
                abort.set()
                raise
            finally:
                put(packed, None)

        def walker():
            try:
                while True:
                    item = get(packed)
                    if item is None:
                        return
                    if abort.is_set():
                        continue  # drain to the sentinel
                    with trace.span("host.fuse.walk"):
                        # defer the codec's walk-end decode to the
                        # unpacker, which fuses it with the member
                        # scatter (an aborted in-flight wire buffer is
                        # dropped to GC like every other staging buffer)
                        deferred = self._allreduce_ws(
                            item[0], defer_decode=True
                        )
                    if not put(unpackq, item + (deferred,)):
                        return
            except BaseException:
                abort.set()
                raise
            finally:
                put(unpackq, None)

        def unpacker():
            try:
                while True:
                    item = get(unpackq)
                    if item is None:
                        return
                    if abort.is_set():
                        continue  # aborted: must not touch caller buffers
                    self._unpack_bucket(item)
            except BaseException:
                abort.set()
                raise

        _par([packer, walker, unpacker], timeout, abort)

    def monitored_all_reduce(self, w: Workspace) -> None:
        """AllReduce + throughput accounting for the ACTIVE strategy
        (parity: KungfuMonitoredAllReduce, ops/cpu/collective.cpp:149-196 +
        runMonitoredStrategies, session/monitoring.go:15-35).

        Runs the active candidate's wire format like all_reduce — this
        is the ONLY site feeding adaptive.current, so it MUST measure
        what the candidate actually does or codec candidates would
        accumulate raw-walk stats and interference votes could never
        observe compression. Probe-style traffic keeps exact semantics
        through the codec's own gates: non-f32 lanes and payloads under
        WIRE_MIN_BYTES always bypass (audited), and the gradient-
        variance/noise-scale monitors are on-device psums that never
        touch the host plane at all."""
        nbytes = w.recv.size * w.recv.itemsize
        t0 = time.perf_counter()
        with self._collected("monitored_all_reduce", nbytes):
            with stall_detect(f"monitored_all_reduce({w.name})"):
                self._allreduce_ws(w)
        self.adaptive.current.update(nbytes, time.perf_counter() - t0)

    def check_interference(self, vote_tag: str = "") -> bool:
        """Majority vote on local interference suspicion; on a cluster-wide
        majority every peer advances to the next candidate strategy in the
        same deterministic order. Returns True if the strategy switched.
        Parity: CheckInterference + MonitoredAllReduce consensus switch
        (session/adaptiveStrategies.go:61-121)."""
        if self._tree_override or len(self._candidates) < 2:
            return False
        suspect = self.adaptive.current.suspect_interference()
        votes_in = np.array([1 if suspect else 0], np.int32)
        votes_out = np.zeros(1, np.int32)
        self.all_reduce(
            Workspace(votes_in, votes_out, ReduceOp.SUM,
                      f"kungfu::interference:{self.adaptive.switch_count}{vote_tag}")
        )
        if int(votes_out[0]) * 2 <= self.size:
            return False
        old_strategy, old_wire = self._candidates[self.adaptive.active]
        idx = self.adaptive.advance()
        self.global_strategies = self._candidate(idx)
        new_strategy, new_wire = self._candidates[idx]
        # safety: all peers must now run the same graphs AND wire format
        # (a codec split would desync every message size in the walk)
        if not self.bytes_consensus(
            st.digest(self.global_strategies) + new_wire.encode(),
            f":switch:{self.adaptive.switch_count}",
        ):
            raise RuntimeError("strategy switch diverged across peers")
        from kungfu_tpu.telemetry import audit as _audit

        _audit.record_event(
            "strategy_switch",
            peer=str(self.self_id),
            trigger="interference_vote",
            old_strategy=old_strategy.name,
            new_strategy=new_strategy.name,
            old_wire=old_wire,
            new_wire=new_wire,
            switch_count=self.adaptive.switch_count,
        )
        return True

    def active_strategy(self) -> Optional[Strategy]:
        """The running candidate strategy, or None when an explicit
        set_tree forest overrides the candidates."""
        if self._tree_override:
            return None
        return self._candidates[self.adaptive.active][0]

    def set_tree(self, fathers: Sequence[int]) -> None:
        """Install a runtime forest (e.g. an MST over probed latencies) as
        the active global strategy (parity: SetTree / SetGlobalStrategy,
        adaptation.cpp:5-33). Disables vote-driven switching — an explicit
        tree wins until the next session epoch.

        The installed forest must be a single tree rooted at rank 0:
        gather/reduce/broadcast walk global_strategies[0] assuming its root
        is rank 0, so a forest rooted elsewhere (or with several roots)
        would silently produce wrong data. Per-component forests are still
        available via subset_all_reduce/all_reduce_with."""
        if len(fathers) != self.size:
            raise ValueError(f"forest size {len(fathers)} != cluster {self.size}")
        roots = [r for r, f in enumerate(fathers) if int(f) == r]
        if roots != [0]:
            raise ValueError(
                f"set_tree forest must be one tree rooted at rank 0, got roots {roots}"
            )
        self.global_strategies = st.from_forest_array(list(fathers))
        self._tree_override = True

    def calc_stats(self) -> dict:
        """Per-strategy throughput summary (parity: CalcStats/LogStats)."""
        return self.adaptive.summary()

    def cross_all_reduce(self, w: Workspace) -> None:
        """AllReduce across host masters only (hierarchical path). While
        RING_SEGMENTED is the ACTIVE strategy, masters run the segmented
        walk over the master ring (the subset/cross variant); non-masters
        forward. Gated on _segmented_active — not the static configured
        strategy — so set_tree overrides and adaptive switches govern the
        cross path exactly like the global one (votes advance in lockstep
        on every peer, so the gate stays cluster-consistent).

        The wire codec applies here like the global allreduce — the
        cross-host hop crosses the DCN, exactly where halving wire
        bytes pays most; the intra-host reduce/broadcast phases around
        it stay raw (loopback/shm, nothing to save)."""
        wire = self._wire_codec_for(w)
        with stall_detect(f"cross_all_reduce({w.name})"):
            if (
                self._segmented_active()
                and len(self._masters) >= 2
                and w.recv.nbytes >= self.SEGMENT_MIN_BYTES
            ):
                self._run_segmented(w, ranks=self._masters, wire=wire)
            else:
                self._run_strategies(w, self.cross_strategies, wire=wire)

    def local_reduce(self, w: Workspace) -> None:
        self._run_graphs(w, [self.local_strategies[0].reduce_graph])

    def local_broadcast(self, w: Workspace) -> None:
        self._run_graphs(w, [self.local_strategies[0].bcast_graph])

    def _root_star_graphs(self, root: int) -> Tuple[Graph, Graph]:
        """(bcast, reduce) star graphs rooted at `root`, cached on the
        session — reduce/broadcast/broadcast_bytes used to regenerate
        them on every call (a Graph build is O(size) allocations, paid
        per elastic state-sync message). Benign to race: both writers
        compute identical graphs."""
        pair = self._root_graphs.get(root)
        if pair is None:
            bcast = topo.gen_star_bcast_graph(self.size, root)
            pair = (bcast, topo.gen_default_reduce_graph(bcast))
            self._root_graphs[root] = pair
        return pair

    def reduce(self, w: Workspace, root: int = 0) -> None:
        """Reduce to `root` (parity: runGraphs with a reduce graph; the
        reference's Reduce takes arbitrary roots). Root 0 walks the
        configured strategy; other roots use a root-specific star."""
        if root == 0:
            self._run_graphs(w, [self.global_strategies[0].reduce_graph])
        else:
            self._check_root(root)
            self._run_graphs(w, [self._root_star_graphs(root)[1]])

    def broadcast(self, w: Workspace, root: int = 0) -> None:
        with self._collected("broadcast", w.recv.nbytes):
            if root == 0:
                self._run_graphs(w, [self.global_strategies[0].bcast_graph])
            else:
                self._check_root(root)
                self._run_graphs(w, [self._root_star_graphs(root)[0]])

    def _check_root(self, root: int) -> None:
        if not 0 <= root < self.size:
            raise ValueError(f"root {root} outside cluster of {self.size}")

    def subset_all_reduce(self, fathers: Sequence[int], w: Workspace) -> None:
        sl = st.from_forest_array(list(fathers))
        self._run_strategies(w, sl)

    def all_reduce_with(self, fathers: Sequence[int], w: Workspace) -> None:
        """AllReduce on a runtime-supplied tree (parity: AllReduceWith)."""
        if fathers:
            sl = st.from_forest_array(list(fathers))
        else:
            sl = self.global_strategies
        self._run_strategies(w, sl)

    def barrier(self, tag: str = "") -> None:
        """Parity: session.go:98-113 (an allreduce of size bytes)."""
        k = len(self.peers)
        w = Workspace(
            send=np.zeros(k, np.uint8),
            recv=np.zeros(k, np.uint8),
            op=ReduceOp.SUM,
            name=f"kungfu::barrier{tag}",
        )
        self.all_reduce(w)

    def bytes_consensus(self, bs: bytes, name: str) -> bool:
        """True iff every peer supplied identical bytes (parity:
        session.go:126-157, which runs 4 allreduce rounds). 2 rounds
        here: a MIN-allreduce of the packed (len, -len) int64 workspace
        yields the cluster's (min-len, -max-len) in one walk, and a
        MIN-allreduce of the two-lane (payload, 255-payload) bytes yields
        (elementwise-min, 255-elementwise-max) in another — consensus iff
        min == max in both. Every elastic resize and strategy switch pays
        this path, so halving the rounds halves its serialized latency.

        Runs int64/uint8 lanes through the regular engine — the wire
        codec is f32-only, so consensus payloads are never quantized
        (docs/collectives.md: consensus MUST stay exact)."""
        return self._bytes_agree(bs, name, self.all_reduce)

    def _bytes_agree(
        self, bs: bytes, name: str, run: Callable[[Workspace], None]
    ) -> bool:
        """The 2-round consensus algebra, parameterized over the
        allreduce runner so the knob-consensus check can use graphs that
        do not depend on the very knobs being checked."""
        n = len(bs)
        lens = np.array([n, -n], np.int64)
        out_len = np.zeros(2, np.int64)
        run(Workspace(lens, out_len, ReduceOp.MIN, f":consensus:len:{name}"))
        if out_len[0] != -out_len[1]:
            return False
        if n == 0:
            return True
        x = np.frombuffer(bs, np.uint8)
        lanes = np.empty(2 * n, np.uint8)
        lanes[:n] = x
        np.subtract(255, x, out=lanes[n:])
        out = np.zeros(2 * n, np.uint8)
        run(Workspace(lanes, out, ReduceOp.MIN, f":consensus:data:{name}"))
        return bool(np.array_equal(out[:n], 255 - out[n:]))

    # ------------------------------------------------------------------
    # engine-knob consensus (fail fast instead of deadlocking)
    # ------------------------------------------------------------------

    def engine_knobs(self) -> List[Tuple[str, str]]:
        """The cluster-agreed engine knobs, as resolved BY THIS SESSION.

        Every entry decides rendezvous names, message sizes or peer
        pairings, so peers that resolved different values would wait on
        each other's names (or mis-frame messages) forever. Local-only
        tuning (KF_CONFIG_GROUP_WINDOW — pure intra-host concurrency) is
        deliberately excluded: it may legitimately differ per host."""
        return [
            ("KF_CONFIG_ALGO", knobs.get("KF_CONFIG_ALGO")),
            ("KF_CONFIG_CHUNK_BYTES", str(CHUNK_BYTES)),
            ("KF_CONFIG_SEGMENT_MIN_BYTES", str(self.SEGMENT_MIN_BYTES)),
            ("KF_CONFIG_GROUP_BUCKET_BYTES", str(self.GROUP_BUCKET_BYTES)),
            ("KF_CONFIG_GROUP_FUSE_MIN", str(self.FUSE_MIN_TENSORS)),
            ("KF_CONFIG_WIRE", self.wire_mode),
            ("KF_CONFIG_WIRE_MIN_BYTES", str(self.WIRE_MIN_BYTES)),
        ]

    def _fixed_allreduce(self, w: Workspace) -> None:
        """Allreduce over a rank-0 star, unchunked and uncompressed — a
        walk whose rendezvous names and message sizes depend on NOTHING
        the knobs control, so it completes even across knob-divergent
        peers (tiny payloads; latency is 2 serialized hops)."""
        bcast, red = self._root_star_graphs(0)
        self._run_graphs(w, [red, bcast])

    def check_knob_consensus(self) -> None:
        """Fail fast on engine-knob divergence (satellite of ISSUE 5).

        Without this, peers that resolved different KF_CONFIG_ALGO /
        CHUNK_BYTES / GROUP_BUCKET_BYTES / WIRE values wait on each
        other's rendezvous names forever — the first collective of the
        epoch just hangs. One consensus over the resolved knob tuple at
        session start turns that into an immediate, named error. Runs on
        the knob-independent star walk, so the check itself cannot
        deadlock on the very disagreement it detects; on mismatch a
        per-knob round pins down WHICH knob diverged."""
        if self.size < 2:
            return
        knobs = self.engine_knobs()
        blob = ";".join(f"{k}={v}" for k, v in knobs).encode()
        if self._bytes_agree(blob, ":knobs", self._fixed_allreduce):
            return
        bad = [
            k for k, v in knobs
            if not self._bytes_agree(
                v.encode(), f":knob:{k}", self._fixed_allreduce
            )
        ]
        mine = dict(knobs)
        names = ", ".join(bad) if bad else "engine knob tuple"
        raise RuntimeError(
            f"engine knob mismatch across peers: {names} — these KF_CONFIG_* "
            f"values decide rendezvous names and message sizes, so they MUST "
            f"be set identically fleet-wide (collectives would deadlock); "
            f"this peer ({self.self_id}) resolved "
            + ", ".join(f"{k}={mine[k]!r}" for k in (bad or mine))
        )

    def broadcast_bytes(self, bs: bytes, name: str, root: int = 0) -> bytes:
        """Broadcast variable-length bytes from `root` (two graph walks:
        length, then payload). Used to bootstrap the device plane — the
        TPU analog of broadcasting the NCCL unique id over the CPU
        collective (gpu_collective.cpp:190-212) — and for elastic state
        re-sync, where the root must be a SURVIVING peer (not necessarily
        rank 0 of the new cluster)."""
        # a fixed star keeps the walk root-correct regardless of the active
        # strategy (set_tree/adaptive switches may re-root global_strategies)
        graph = self._root_star_graphs(root)[0]
        n_send = np.array([len(bs) if self.rank == root else 0], np.int64)
        n_recv = np.zeros(1, np.int64)
        self._run_graphs(
            Workspace(n_send, n_recv, ReduceOp.SUM, f"{name}:len"), [graph]
        )
        n = int(n_recv[0])
        if n == 0:
            return b""
        if self.rank == root:
            send = np.frombuffer(bs, np.uint8)
        else:
            send = np.zeros(n, np.uint8)
        recv = np.zeros(n, np.uint8)
        self._run_graphs(
            Workspace(send, recv, ReduceOp.SUM, f"{name}:data"), [graph]
        )
        return recv.tobytes()

    def gather(self, w: Workspace, root: int = 0) -> None:
        """`root` receives everyone's send buffer into recv (rank-major);
        parity: runGather (session.go:195-221), arbitrary roots like the
        reference's Gather. Handles unequal per-peer counts: the wire
        framing carries each message's true length, so the root lays
        contributions out by their actual sizes (the reference relies on
        the same message framing)."""
        self._check_root(root)
        if self.rank != root:
            with self._collected("gather", w.send.nbytes):
                self.client.send(
                    self.peers[root], w.name, _buf(w.send), ConnType.COLLECTIVE
                )
                self._count_wire(w.send.nbytes, "STAR")
            return
        scope = self._collected("gather", w.recv.nbytes)
        scope.__enter__()
        cancel = threading.Event()
        parts: List[Optional[np.ndarray]] = [None] * len(self.peers)
        releases: List = [None] * len(self.peers)

        def recv_part(r: int, peer: PeerID) -> None:
            msg = self.endpoint.recv(peer, w.name, self.timeout)
            if cancel.is_set():
                if msg.release is not None:
                    msg.release()
                return
            parts[r] = np.frombuffer(msg.data, w.send.dtype)
            releases[r] = msg.release

        jobs = []
        for r, peer in enumerate(self.peers):
            if r == self.rank:
                parts[r] = w.send.reshape(-1)
            else:
                jobs.append(lambda r=r, p=peer: recv_part(r, p))
        try:
            _par(jobs, self.timeout, cancel)
            off = 0
            for part in parts:
                assert part is not None
                n = part.size
                if off + n > w.recv.size:
                    raise ValueError(
                        f"gather overflow: recv buffer {w.recv.size} < {off + n}"
                    )
                np.copyto(w.recv[off:off + n], part)
                off += n
            if off != w.recv.size:
                # a short contribution would silently shift later ranks' data
                raise ValueError(
                    f"gather underflow: contributions fill {off} of {w.recv.size}"
                )
        finally:
            parts.clear()
            for rel in releases:
                if rel is not None:
                    rel()
            scope.__exit__(None, None, None)

    def all_gather(self, w: Workspace) -> None:
        """Gather to root then broadcast the concatenation (parity:
        AllGatherTransform, session.cpp:201-220)."""
        self.gather(w)
        bw = Workspace(send=w.recv, recv=w.recv, op=w.op, name=w.name + ":bcast")
        self.broadcast(bw)

    # ------------------------------------------------------------------
    # engine
    # ------------------------------------------------------------------

    def _run_segmented(
        self,
        w: Workspace,
        ranks: Optional[Sequence[int]] = None,
        cancel: Optional[threading.Event] = None,
        wire: Optional[DType] = None,
        defer_decode: bool = False,
    ) -> Optional[_DeferredDecode]:
        """Bandwidth-optimal segmented walk: a (k-1)-step reduce-scatter
        over contiguous segments followed by a (k-1)-step all-gather
        around a ring (arXiv:1810.11112 §3; the TPU-pod MLPerf stack
        leans on the same segmented summation, arXiv:1909.09756). Each
        step sends ONE ~N/k segment to the ring successor and reduces
        (or, in the gather phase, copies) the segment arriving from the
        predecessor in place — zero-copy views into the recv buffer, no
        full-payload relays, ~2*(k-1)/k*N bytes moved per peer total.

        With `wire` set (the codec, ISSUE 5) each segment crosses the
        transport as bf16/f16 — half the bytes, 2*(k-1)/k*N/2 per peer:

        * reduce-scatter: the sender encodes its f32 partial into a
          pooled wire scratch; the receiver decode-accumulates into the
          f32 buffer in one fused pass, so every transmitted value is
          quantized exactly once and no rounding compounds in 16-bit
          storage across the (k-1) steps;
        * all-gather: segments STAY in wire dtype in a walk-local wire
          buffer — each already-reduced segment is quantized once by its
          owner, relayed untouched, and decoded exactly once per peer at
          walk end (the owner decodes its own encoding too, so every
          peer lands on bit-identical results).

        Contracts shared with the graph walk: receives prefer the
        zero-copy sink/shm-borrow path (`recv_into`) and release borrows
        after the in-place reduce; one deadline bounds the WHOLE walk (not
        per step); a timed-out scratch buffer is never returned to the
        pool (the transport thread may still be mid-fill); empty segments
        (payload < k elements) are skipped identically on both ends of
        every edge, so no peer waits on a message that never departs.

        `ranks` restricts the ring to a subset (hierarchical cross-host
        mode); non-members just forward send into recv. With
        `defer_decode` (compressed walks only) the walk-end decode is
        skipped and the wire buffer returned — see _DeferredDecode."""
        if w.is_empty:
            w.forward()
            return None
        members = list(range(self.size)) if ranks is None else list(ranks)
        k = len(members)
        if self.rank not in members or k == 1:
            w.forward()
            return None
        sched = topo.gen_segmented_schedule(members, members.index(self.rank))
        bounds = even_partition(w.recv.size, k)
        w.forward()  # seed the accumulator with own contribution
        acc = w.recv
        send_peer = self.peers[sched.send_peer]
        recv_peer = self.peers[sched.recv_peer]
        itemsize = acc.itemsize
        wire_itemsize = 2 if wire is not None else itemsize
        codec_label = wire.name.lower() if wire is not None else "off"
        bufpool = get_buffer_pool()
        deadline = time.monotonic() + self.timeout
        wire_bytes = 0
        raw_bytes = 0
        # critical-path attribution for this walk (profiler, ISSUE 6):
        # wait-on-recv and send-blocked seconds of THIS thread; the
        # reduce/codec compute is the residual against walk wall time
        prof = _WalkProfile()
        emit_steps = self._span_sampler.sample()
        # all-gather wire buffer: segments stay encoded here from the
        # owner's single quantization until the walk-end decode. Leaked
        # (not pool-returned) on any error — the transport may still be
        # mid-fill into a timed-out sink slice.
        wirebuf: Optional[bytearray] = None
        wirearr: Optional[np.ndarray] = None
        if wire is not None:
            wirebuf = bufpool.get(acc.size * 2)
            wirearr = np.frombuffer(wirebuf, np.uint16, acc.size)

        def do_send(name: str, sb: int, se: int, buf) -> None:
            """Deadline-bounded send: a frozen successor (full shm ring
            -> socket fallback -> full TCP buffer) would otherwise block
            sendall forever and the walk-wide deadline — checked only in
            do_recv — would never fire. Dispatch + event-wait costs tens
            of µs per step, noise against the segment memcpy. A timed-out
            send thread is abandoned exactly like the graph walk's _par
            send threads; the buffer stays valid because the caller
            raises out of the walk without touching acc again."""
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                raise TimeoutError(f"segmented walk timed out: {name}")
            done = threading.Event()
            errs: List[BaseException] = []

            def run() -> None:
                try:
                    # zero-copy: segments are disjoint and steps
                    # sequential per workspace, so this view cannot be
                    # mutated mid-sendall
                    self.client.send(
                        send_peer, name, _buf(buf), ConnType.COLLECTIVE
                    )
                except BaseException as e:  # noqa: BLE001 - re-raised below
                    errs.append(e)
                finally:
                    done.set()

            _t_send = time.perf_counter()
            get_pool().submit(run)
            ok = done.wait(remaining)
            prof.send += time.perf_counter() - _t_send
            if not ok:
                raise TimeoutError(f"segmented send timed out: {name}")
            if errs:
                raise errs[0]

        def start_send_wire(name: str, sb: int, se: int, buf):
            """Async wire-mode send: encode (when `buf` is an f32 view)
            and transport copy run on the pool thread so they OVERLAP
            the blocking predecessor recv — the codec's encode would
            otherwise sit on the ring's serialized critical path, which
            a time-sliced multi-worker host punishes step after step.
            Safe because a step's send and recv segments are disjoint by
            schedule construction, so the thread reads acc[sb:se] (or a
            wirearr slice) while the main thread fills a different
            segment. Returns (done, errs) for finish_send; the encode
            scratch is pool-returned by the thread itself (never while
            anything can still read it)."""
            done = threading.Event()
            errs: List[BaseException] = []

            def run() -> None:
                try:
                    if buf.dtype == np.uint16:
                        payload = buf  # all-gather: already wire dtype
                        scratch = None
                    else:
                        scratch = bufpool.get((se - sb) * 2)
                        payload = np.frombuffer(scratch, np.uint16, se - sb)
                        encode_wire(payload, buf, wire)
                    self.client.send(
                        send_peer, name, _buf(payload), ConnType.COLLECTIVE
                    )
                    if scratch is not None:
                        bufpool.put(scratch)
                except BaseException as e:  # noqa: BLE001 - re-raised below
                    errs.append(e)
                finally:
                    done.set()

            get_pool().submit(run)
            return done, errs

        def finish_send(pending, name: str) -> None:
            done, errs = pending
            remaining = deadline - time.monotonic()
            _t_send = time.perf_counter()
            ok = remaining > 0 and done.wait(remaining)
            prof.send += time.perf_counter() - _t_send
            if not ok:
                raise TimeoutError(f"segmented send timed out: {name}")
            if errs:
                raise errs[0]

        def recv_rs(name: str, rb: int, re_: int) -> None:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                raise TimeoutError(f"segmented walk timed out: {name}")
            recv_dtype = np.dtype(np.uint16) if wire is not None else acc.dtype
            _t_recv = time.perf_counter()
            incoming, scratch, release = self._recv_collective(
                recv_peer, name, (re_ - rb) * wire_itemsize, recv_dtype,
                re_ - rb, remaining,
            )
            prof.wait += time.perf_counter() - _t_recv
            try:
                if cancel is not None and cancel.is_set():
                    # caller-scope timeout fired while we were blocked:
                    # the recv buffer may already be reused — a late
                    # arrival must not be reduced into it
                    raise TimeoutError(f"collective cancelled: {name}")
                if wire is not None:
                    # fused decode + f32 accumulate: one pass, one
                    # quantization deep (the sender's encode)
                    decode_accumulate(acc, rb, re_, incoming, wire, w.op)
                else:
                    reduce_segment(acc, rb, re_, incoming, w.op)
            finally:
                del incoming
                if release is not None:
                    release()
            if scratch is not None:
                bufpool.put(scratch)

        def recv_ag(name: str, rb: int, re_: int) -> None:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                raise TimeoutError(f"segmented walk timed out: {name}")
            if wire is None:
                _t_recv = time.perf_counter()
                incoming, scratch, release = self._recv_collective(
                    recv_peer, name, (re_ - rb) * itemsize, acc.dtype,
                    re_ - rb, remaining,
                )
                prof.wait += time.perf_counter() - _t_recv
                try:
                    if cancel is not None and cancel.is_set():
                        raise TimeoutError(f"collective cancelled: {name}")
                    copy_segment(acc, rb, re_, incoming)
                finally:
                    del incoming
                    if release is not None:
                        release()
                if scratch is not None:
                    bufpool.put(scratch)
                return
            # wire mode: deliver straight into the wire buffer slice —
            # no scratch, no decode (the segment is relayed as-is and
            # decoded once at walk end)
            _t_recv = time.perf_counter()
            msg, filled = self.endpoint.recv_into(
                recv_peer, name, memoryview(wirebuf)[rb * 2 : re_ * 2],
                remaining,
            )
            prof.wait += time.perf_counter() - _t_recv
            if cancel is not None and cancel.is_set():
                if msg is not None and msg.release is not None:
                    msg.release()
                raise TimeoutError(f"collective cancelled: {name}")
            if not filled:
                try:
                    np.copyto(
                        wirearr[rb:re_],
                        np.frombuffer(msg.data, np.uint16, re_ - rb),
                    )
                finally:
                    if msg.release is not None:
                        msg.release()

        def step(phase: str, s: int, send_seg: int, recv_seg: int) -> None:
            nonlocal wire_bytes, raw_bytes
            sb, se = bounds[send_seg]
            rb, re_ = bounds[recv_seg]
            name = f"{w.name}:{phase}{s}"
            if cancel is not None and cancel.is_set():
                raise TimeoutError(f"collective cancelled: {name}")
            # empty segments (payload < k elements) are skipped on BOTH
            # ends: sender and receiver compute identical bounds.
            # RAW mode: send-then-recv is deliberately SEQUENTIAL — the
            # send returns once the payload is in the shm ring / kernel
            # buffer, so the wire is already busy while we block on the
            # predecessor, and a _par pair per step measured 15% slower
            # on the 2-core bench box (thread dispatch + GIL beat the
            # overlap). WIRE mode: the encode pass makes the send phase
            # heavy enough to flip that trade — encode+send run async on
            # the pool thread and overlap the predecessor wait, awaited
            # at step end (disjoint segments make this safe).
            if se > sb:
                wire_bytes += (se - sb) * wire_itemsize
                raw_bytes += (se - sb) * itemsize
            if wire is not None:
                pending = None
                if se > sb:
                    pending = start_send_wire(
                        name, sb, se,
                        acc[sb:se] if phase == "rs" else wirearr[sb:se],
                    )
                if re_ > rb:
                    if phase == "rs":
                        recv_rs(name, rb, re_)
                    else:
                        recv_ag(name, rb, re_)
                if pending is not None:
                    finish_send(pending, name)
                return
            if se > sb:
                do_send(name, sb, se, acc[sb:se])
            if re_ > rb:
                if phase == "rs":
                    recv_rs(name, rb, re_)
                else:
                    recv_ag(name, rb, re_)

        def timed_step(span_name: str, phase: str, s: int, snd: int, rcv: int) -> None:
            """One ring step, with a per-step span (subject to
            KF_TELEMETRY_SPAN_SAMPLE) annotated with how long the step
            was blocked waiting on its predecessor vs its successor."""
            if not emit_steps:
                step(phase, s, snd, rcv)
                return
            w0, s0 = prof.wait, prof.send
            with trace.span(span_name, step=s, k=k) as sp:
                step(phase, s, snd, rcv)
                sp.args["wait_us"] = round((prof.wait - w0) * 1e6)
                sp.args["send_us"] = round((prof.send - s0) * 1e6)

        _t0 = time.perf_counter()
        for s, (snd, rcv) in enumerate(sched.rs_steps):
            timed_step("host.rs.step", "rs", s, snd, rcv)
        if wire is not None:
            # seed the all-gather: quantize the owned (fully reduced)
            # segment ONCE; every peer — self included — will decode
            # this same encoding, so results stay bit-identical ringwide
            ob, oe = bounds[sched.owned_segment]
            if oe > ob:
                encode_wire(wirearr[ob:oe], acc[ob:oe], wire)
        for s, (snd, rcv) in enumerate(sched.ag_steps):
            timed_step("host.ag.step", "ag", s, snd, rcv)
        deferred: Optional[_DeferredDecode] = None
        if wire is not None:
            if defer_decode:
                deferred = _DeferredDecode(wire, wirebuf, wirearr)
            else:
                with trace.span("host.wire.decode", bytes=int(acc.size * 2)):
                    decode_wire(acc, wirearr, wire)
                bufpool.put(wirebuf)
        self._count_wire(
            wire_bytes, Strategy.RING_SEGMENTED.name, codec_label, raw_bytes
        )
        wall = time.perf_counter() - _t0
        trace.record(f"host.segmented[{w.recv.nbytes >> 20}MiB]", wall)
        # the ring's only outgoing edge is the successor: score this walk
        # against that link's measured bandwidth
        self._record_walk(
            Strategy.RING_SEGMENTED.name, k, w.recv.nbytes, wall, prof,
            dsts=[send_peer],
        )
        return deferred

    def _run_strategies(
        self,
        w: Workspace,
        strategies: List[st.StrategyPair],
        cancel: Optional[threading.Event] = None,
        wire: Optional[DType] = None,
    ) -> None:
        """`wire` is decided ONCE on the whole workspace (in
        _allreduce_ws) and inherited by every chunk — a per-chunk
        decision would let a residual chunk fall below WIRE_MIN_BYTES
        and mix wire formats inside one collective (still cluster-
        consistent, but pointlessly branchy on the hot path)."""
        total = w.recv.size * w.recv.itemsize
        k = max(1, -(-total // choose_chunk_bytes(total)))
        chunks = w.split(even_partition, k) if k > 1 else [w]
        if cancel is None:
            cancel = threading.Event()
        if k == 1:
            pair = strategies[0]
            self._run_graphs(
                chunks[0], [pair.reduce_graph, pair.bcast_graph], cancel,
                wire, profile=True,
            )
            return
        jobs = []
        for i, chunk in enumerate(chunks):
            pair = st.choose(strategies, i)
            jobs.append(
                lambda c=chunk, p=pair: self._run_graphs(
                    c, [p.reduce_graph, p.bcast_graph], cancel, wire,
                    profile=True,
                )
            )
        _par(jobs, self.timeout, cancel)

    def _run_graphs(
        self,
        w: Workspace,
        graphs: List[Graph],
        cancel: Optional[threading.Event] = None,
        wire: Optional[DType] = None,
        profile: bool = False,
    ) -> None:
        """The hot walk; parity: runGraphs (session.go:231-299).

        `profile=True` (the allreduce paths, via _run_strategies) feeds
        this walk's wait/send/compute attribution to the process
        WalkProfiler; direct reduce/broadcast/gather walks skip it (the
        2(k-1)/k*N allreduce bound doesn't describe them).

        `cancel` is shared across every thread touching this workspace: once
        any part of the collective times out, late-arriving receives must not
        write into (possibly reused) caller buffers.

        With `wire` set, every send encodes the f32 buffer into a pooled
        bf16/f16 scratch and every receive decode-accumulates (reduce
        phase) or decodes (bcast phase) back into f32 — accumulation
        never happens in 16-bit storage. Relays re-encode values that
        are already wire-quantized, which is exact (encode of an
        exactly-representable value is the identity), so the quantized
        result every peer converges on is bit-identical."""
        if w.is_empty:
            return
        if all(g.is_isolated(self.rank) for g in graphs):
            w.forward()
            return
        if cancel is None:
            cancel = threading.Event()
        _t_walk = time.perf_counter()
        prof = _WalkProfile() if profile else None

        state = {"recv_count": 0}
        lock = threading.Lock()

        def effective() -> np.ndarray:
            if state["recv_count"] > 0 or w.is_inplace:
                return w.recv
            return w.send

        wire_label = self._walk_label()
        codec_label = wire.name.lower() if wire is not None else "off"

        def send_to(peer: PeerID, flags: Flags = Flags.NONE) -> None:
            # zero-copy: the walk's phases are sequential per chunk, so the
            # buffer cannot be mutated while sendall drains it
            self.client.send(
                peer, w.name, _buf(effective()), ConnType.COLLECTIVE, flags
            )
            self._count_wire(wire_nbytes, wire_label, codec_label, nbytes)

        def send_all(peers: List[PeerID], flags: Flags = Flags.NONE) -> None:
            """Fan-out send of the current effective() buffer. Wire mode
            encodes ONCE into a shared scratch for the whole fan-out —
            every edge carries identical bytes, so per-peer encodes (a
            full payload pass each) would be pure waste at STAR/CLIQUE
            fan-outs. The scratch returns to the pool only on success:
            after a timeout an abandoned send thread may still be
            draining it."""
            if not peers:
                return
            if wire is None:
                _t_send = time.perf_counter()
                _par([lambda p=p: send_to(p, flags) for p in peers],
                     self.timeout, cancel)
                if prof is not None:
                    prof.send += time.perf_counter() - _t_send
                return
            scratch = bufpool.get(wire_nbytes)
            enc = np.frombuffer(scratch, np.uint16, w.recv.size)
            # the fan-out encode is codec COMPUTE (the residual bucket),
            # so only the transport fan-out below is timed as send
            encode_wire(enc, effective(), wire)

            def send_enc(peer: PeerID) -> None:
                self.client.send(
                    peer, w.name, _buf(enc), ConnType.COLLECTIVE, flags
                )
                self._count_wire(wire_nbytes, wire_label, codec_label, nbytes)

            _t_send = time.perf_counter()
            _par([lambda p=p: send_enc(p) for p in peers], self.timeout, cancel)
            if prof is not None:
                prof.send += time.perf_counter() - _t_send
            bufpool.put(scratch)

        bufpool = get_buffer_pool()
        nbytes = w.recv.size * w.recv.itemsize
        wire_nbytes = w.recv.size * 2 if wire is not None else nbytes
        recv_dtype = np.dtype(np.uint16) if wire is not None else w.send.dtype

        def recv_payload(peer: PeerID):
            """See _recv_collective (shared with the segmented walk)."""
            return self._recv_collective(
                peer, w.name, wire_nbytes, recv_dtype, w.recv.size, self.timeout
            )

        def recv_onto(peer: PeerID) -> None:
            incoming, scratch, release = recv_payload(peer)
            try:
                with lock:
                    if cancel.is_set():
                        # abort the whole walk: a late arrival must neither
                        # write the workspace nor let the send phase relay
                        # stale data
                        raise TimeoutError(f"collective cancelled: {w.name}")
                    if wire is not None:
                        if state["recv_count"] == 0 and not w.is_inplace:
                            # first arrival: recv = decode(incoming), then
                            # fold own send in f32 (ops are commutative)
                            decode_wire(w.recv, incoming, wire)
                            reduce_inplace(w.recv, w.send, w.op)
                        else:
                            decode_accumulate(
                                w.recv, 0, w.recv.size, incoming, wire, w.op
                            )
                    elif state["recv_count"] == 0 and not w.is_inplace:
                        # first arrival: recv = send (op) incoming
                        from kungfu_tpu.base.ops import transform2

                        transform2(w.recv, w.send, incoming, w.op)
                    else:
                        reduce_inplace(w.recv, incoming, w.op)
                    state["recv_count"] += 1
            finally:
                del incoming
                if release is not None:
                    release()
            if scratch is not None:
                bufpool.put(scratch)

        def recv_all_onto(peers: List[PeerID]) -> None:
            """Accumulate phase: receive every prev, then reduce them all
            in ONE n-ary pass (kf_transform_n). Pairwise-on-arrival
            overlaps receive with reduce, which pays when cores are free;
            the n-ary pass minimizes memory traffic, which wins outright
            on busy/low-core hosts — and the receives themselves still
            overlap each other."""
            got: List = [None] * len(peers)

            def grab(i: int, p: PeerID) -> None:
                res = recv_payload(p)
                if cancel.is_set():
                    # the walk already timed out and its finally block may
                    # have run: release the borrow here or nobody will
                    if res[2] is not None:
                        res[2]()
                    return
                got[i] = res

            try:
                _t_recv = time.perf_counter()
                _par(
                    [lambda i=i, p=p: grab(i, p) for i, p in enumerate(peers)],
                    self.timeout,
                    cancel,
                )
                if prof is not None:
                    prof.wait += time.perf_counter() - _t_recv
                with lock:
                    if cancel.is_set():
                        raise TimeoutError(f"collective cancelled: {w.name}")
                    if wire is not None:
                        # decode-accumulate each arrival into f32 (the
                        # fused kernel; no n-ary variant exists for mixed
                        # wire/f32 sources and the tree fan-in is small)
                        if not w.is_inplace:
                            w.forward()
                        for incoming, _, _ in got:
                            decode_accumulate(
                                w.recv, 0, w.recv.size, incoming, wire, w.op
                            )
                    elif w.is_inplace:
                        for incoming, _, _ in got:
                            reduce_inplace(w.recv, incoming, w.op)
                    else:
                        transform_n(
                            w.recv,
                            [w.send] + [inc for inc, _, _ in got],
                            w.op,
                        )
                    state["recv_count"] += len(peers)
            finally:
                for item in got:
                    if item is not None and item[2] is not None:
                        item[2]()
            for item in got:
                if item is not None and item[1] is not None:
                    bufpool.put(item[1])

        def recv_into(peer: PeerID) -> None:
            incoming, scratch, release = recv_payload(peer)
            try:
                with lock:
                    if cancel.is_set():
                        raise TimeoutError(f"collective cancelled: {w.name}")
                    if wire is not None:
                        decode_wire(w.recv, incoming, wire)
                    else:
                        np.copyto(w.recv, incoming)
                    state["recv_count"] += 1
            finally:
                del incoming
                if release is not None:
                    release()
            if scratch is not None:
                bufpool.put(scratch)

        for g in graphs:
            prevs = [self.peers[r] for r in g.prevs(self.rank)]
            nexts = [self.peers[r] for r in g.nexts(self.rank)]
            if g.is_self_loop(self.rank):
                # accumulate: receive from all prevs, n-ary reduce, send on
                if prevs and state["recv_count"] == 0:
                    recv_all_onto(prevs)
                elif prevs:
                    # pairwise path: the pool threads fold their reduce
                    # into this timed block (profiler caveat, see
                    # WalkProfiler) — receives dominate it
                    _t_recv = time.perf_counter()
                    _par([lambda p=p: recv_onto(p) for p in prevs], self.timeout, cancel)
                    if prof is not None:
                        prof.wait += time.perf_counter() - _t_recv
                send_all(nexts)
            else:
                # pass-through node: take value from single prev (or forward
                # own), relay to nexts
                if not prevs and state["recv_count"] == 0:
                    w.forward()
                else:
                    _t_recv = time.perf_counter()
                    for p in prevs:
                        recv_into(p)
                    if prof is not None:
                        prof.wait += time.perf_counter() - _t_recv
                send_all(nexts, Flags.WAIT_RECV_BUF)
        if wire is not None and not graphs[-1].prevs(self.rank):
            # the bcast root never receives a wire message, so it would
            # keep its full-precision f32 result while every other peer
            # decodes the quantized broadcast: roundtrip the root's recv
            # through the codec so all peers land on bit-identical values
            scratch = bufpool.get(wire_nbytes)
            enc = np.frombuffer(scratch, np.uint16, w.recv.size)
            encode_wire(enc, w.recv, wire)
            decode_wire(w.recv, enc, wire)
            bufpool.put(scratch)
        wall = time.perf_counter() - _t_walk
        trace.record(f"host.walk[{w.recv.nbytes >> 20}MiB]", wall)
        if prof is not None:
            # graph walks fan out over many edges: score against the
            # slowest estimated link overall (dsts=None)
            self._record_walk(wire_label, self.size, w.recv.nbytes, wall, prof)
