"""Host-side collective engine: graph-walk collectives over the transport.

Capability parity: srcs/go/kungfu/session/session.go — an immutable
peer-list epoch running Barrier / Consensus / Reduce / Broadcast / Gather /
AllReduce by walking (reduce, bcast) graph pairs, with 1 MiB chunking
striped across multi-root strategies (runStrategies, session.go:301-330)
and SIMD reduction on receive (base.Transform2).

Role in the TPU build: this engine runs on HOSTS over DCN for control
collectives (consensus on cluster configs, barriers, progress sync) and for
CPU-only test clusters — the device data plane is XLA over ICI
(kungfu_tpu.ops). It is the direct replacement for the reference's
rchannel data plane.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence

import numpy as np

from kungfu_tpu.base.ops import ReduceOp, reduce_inplace, transform_n
from kungfu_tpu.telemetry import config as tconfig
from kungfu_tpu.telemetry import metrics as tmetrics
from kungfu_tpu.utils import trace
from kungfu_tpu.base.strategy import Strategy
from kungfu_tpu.collective.adaptive import AdaptiveState
from kungfu_tpu.base.workspace import Workspace, even_partition
from kungfu_tpu.collective import strategies as st
from kungfu_tpu.collective.strategies import effective_cpu_count
from kungfu_tpu.plan.graph import Graph
from kungfu_tpu.plan.peer import PeerID, PeerList
from kungfu_tpu.transport.client import Client
from kungfu_tpu.transport.handlers import CollectiveEndpoint
from kungfu_tpu.transport.message import ConnType, Flags
from kungfu_tpu.utils.pool import get_buffer_pool, get_pool
from kungfu_tpu.utils.stall import stall_detect

# Chunking (parity: session.go chunkSize, but self-tuned): the optimal
# trades chunk-walk overhead (fewer, bigger chunks) against striping/
# pipelining (more, smaller chunks) and depends on host core count —
# concurrent chunk walks only pay when cores exist to run them; on a
# 1-core host every extra in-flight chunk is pure context-switch cost.
# KF_CONFIG_CHUNK_BYTES overrides the heuristic.
CHUNK_BYTES = int(os.environ.get("KF_CONFIG_CHUNK_BYTES", "0"))
_CHUNK_MIN = 1 << 20
_CHUNK_MAX = 32 << 20
DEFAULT_TIMEOUT = 120.0


def choose_chunk_bytes(total: int) -> int:
    """Chunk size for a `total`-byte collective: honour the env override,
    else ~8 chunks per collective, clamped to [1 MiB, 32 MiB].

    MUST depend only on cluster-agreed inputs (the workspace size): chunk
    workspaces are named '<name>[i/k]', so peers that computed different
    k would wait forever on each other's chunk names. That rules out
    os.cpu_count() here (heterogeneous hosts); measured on the 1-core
    box, 8 in-flight walks of >=1 MiB is within noise of the per-core
    optimum anyway."""
    if CHUNK_BYTES > 0:
        return CHUNK_BYTES
    c = total // 8
    return max(_CHUNK_MIN, min(_CHUNK_MAX, c))


def _par(
    fns: List[Callable[[], None]],
    timeout: float,
    cancel: Optional[threading.Event] = None,
) -> None:
    """Run callables on the shared cached-thread pool, wait for all,
    re-raise the first error (goroutine-style fan-out; an unbounded cached
    pool avoids both thread-spawn cost per call and pool-exhaustion
    deadlocks on nested parallelism).

    All waits share ONE deadline (worst case = timeout, not
    len(fns)*timeout). On timeout `cancel` is set before raising so
    abandoned workers that later complete a recv can observe it and must
    NOT mutate the caller's workspace (a reused recv buffer would be
    corrupted by a late write)."""
    if not fns:
        return
    if len(fns) == 1:
        fns[0]()
        return
    cond = threading.Condition()
    state = {"done": 0}
    errs: List[BaseException] = []

    def run(fn):
        err: Optional[BaseException] = None
        try:
            fn()
        except BaseException as e:  # noqa: BLE001 - propagated below
            err = e
        with cond:
            state["done"] += 1
            if err is not None:
                errs.append(err)
            cond.notify_all()

    pool = get_pool()
    for fn in fns:
        pool.submit(lambda f=fn: run(f))
    with cond:
        if not cond.wait_for(lambda: state["done"] >= len(fns), timeout):
            if cancel is not None:
                cancel.set()
            raise TimeoutError("collective thread timed out")
        if errs:
            raise errs[0]


def _buf(arr: np.ndarray):
    """Zero-copy byte view of a contiguous array (tobytes() fallback)."""
    try:
        return arr.data.cast("B")
    except (ValueError, TypeError, AttributeError):
        return arr.tobytes()


class _CollectiveScope:
    """Span + latency-histogram wrapper around one public collective
    (plain classes end to end — tracing._Span underneath is also
    class-based — so the per-call telemetry cost stays at two clock
    reads, a deque append and an optional histogram observe)."""

    __slots__ = ("_sess", "_kind", "_span", "_t0")

    def __init__(self, sess: "HostSession", kind: str, nbytes: int):
        self._sess = sess
        self._kind = kind
        self._span = trace.span(
            f"collective.{kind}", bytes=int(nbytes), size=sess.size
        )

    def __enter__(self):
        self._t0 = time.perf_counter()
        self._span.__enter__()
        return self

    def __exit__(self, *exc):
        self._span.__exit__(*exc)
        hist = self._sess._coll_hist
        if hist is not None:
            hist.labels(self._kind).observe(time.perf_counter() - self._t0)
        return False



class HostSession:
    """One collective epoch over a fixed PeerList."""

    def __init__(
        self,
        strategy: Strategy,
        self_id: PeerID,
        peers: PeerList,
        client: Client,
        endpoint: CollectiveEndpoint,
        timeout: float = DEFAULT_TIMEOUT,
    ):
        rank = peers.rank(self_id)
        if rank is None:
            raise ValueError(f"{self_id} not in peer list {peers}")
        self.self_id = self_id
        self.peers = peers
        self.rank = rank
        self.local_rank = peers.local_rank(self_id)
        self.local_size = peers.local_size(self_id)
        self.host_count = peers.host_count()
        self.client = client
        self.endpoint = endpoint
        self.timeout = timeout
        if strategy == Strategy.AUTO:
            strategy = st.auto_select(peers)
        self.strategy = strategy
        self.global_strategies = st.gen_global_strategies(peers, strategy)
        self.local_strategies = st.gen_local_strategies(peers)
        self.cross_strategies = st.gen_cross_strategies(peers, strategy)
        # adaptive control (parity: session/adaptiveStrategies.go): a
        # deterministic candidate order — identical on every peer — so a
        # majority vote can advance everyone in lockstep. Candidate graph
        # lists are built lazily: sessions are rebuilt every elastic epoch
        # and most never adapt.
        self._candidate_names = [strategy] + [
            s for s in (
                Strategy.RING, Strategy.BINARY_TREE_STAR, Strategy.STAR,
                Strategy.CLIQUE,
            ) if s != strategy
        ]
        self._candidates_built: dict = {0: self.global_strategies}
        self.adaptive = AdaptiveState(len(self._candidate_names))
        self._tree_override = False
        # per-collective latency histogram (telemetry): one observe per
        # COLLECTIVE call (not per message), gated off with the rest of
        # the metrics so the steady-state walk stays untouched
        self._coll_hist = (
            tmetrics.histogram(
                "kungfu_collective_latency_seconds",
                "Host-plane collective latency by kind",
                ("collective",),
            )
            if tconfig.metrics_enabled()
            else None
        )

    def _candidate(self, idx: int) -> List[st.StrategyPair]:
        if idx not in self._candidates_built:
            self._candidates_built[idx] = st.gen_global_strategies(
                self.peers, self._candidate_names[idx]
            )
        return self._candidates_built[idx]

    @property
    def size(self) -> int:
        return len(self.peers)

    def close(self) -> None:
        pass

    def _collected(self, kind: str, nbytes: int):
        """Telemetry wrapper for one public collective: a named span
        (feeding /trace) plus a latency-histogram observation when
        metrics are on. Returns a context manager."""
        return _CollectiveScope(self, kind, nbytes)

    # ------------------------------------------------------------------
    # public collectives
    # ------------------------------------------------------------------

    def all_reduce(self, w: Workspace) -> None:
        with self._collected("all_reduce", w.recv.nbytes):
            with stall_detect(f"all_reduce({w.name})"):
                self._run_strategies(w, self.global_strategies)

    # concurrent workspaces per batch in group ops: concurrency only pays
    # when cores exist to run the walks (on a 1-core host it just adds
    # context switches), so the default scales with the cgroup-aware
    # core count — os.cpu_count() reports the HOST's cores inside a
    # CPU-quota'd container, the phantom-parallelism trap auto_select
    # already avoids; KF_CONFIG_GROUP_WINDOW overrides
    GROUP_WINDOW = int(
        os.environ.get("KF_CONFIG_GROUP_WINDOW", "")
        or max(1, min(8, effective_cpu_count()))
    )

    # Gradient bucketing: fuse same-(dtype, op) workspaces into ONE
    # contiguous walk. A 160-tensor gradient set otherwise pays the fixed
    # per-walk cost (rendezvous conditions, pool dispatch, ~6 framed
    # messages) 160 times — on a host-plane reduce that overhead rivals
    # the byte-copy time itself. Two extra memcpy passes (pack + unpack)
    # buy a ~160x cut in message count. The reference runs one collective
    # per tensor and leans on cheap goroutines instead; bucketing is the
    # standard DDP/Horovod answer and is strictly better here.
    FUSE_MIN_TENSORS = int(os.environ.get("KF_CONFIG_GROUP_FUSE_MIN", "4"))

    def group_all_reduce(self, ws: Sequence[Workspace]) -> None:
        """Allreduce of many workspaces as one windowed group op (parity:
        the reference reduces a whole gradient set per session.run —
        srcs/python/kungfu/tensorflow/v1/benchmarks)."""
        if not ws:
            return
        with self._collected(
            "group_all_reduce", sum(w.recv.nbytes for w in ws)
        ), stall_detect(f"group_all_reduce[{len(ws)}]"):
            singles: List[Workspace] = []
            groups: Dict[tuple, List[Workspace]] = {}
            for w in ws:
                if w.is_empty:
                    continue
                groups.setdefault((w.send.dtype.str, int(w.op)), []).append(w)
            fused_jobs: List[Callable[[], None]] = []
            for members in groups.values():
                if len(members) < self.FUSE_MIN_TENSORS:
                    singles.extend(members)
                else:
                    fused_jobs.append(
                        lambda ms=members: self._fused_all_reduce(ms)
                    )
            for job in fused_jobs:
                job()
            for i in range(0, len(singles), self.GROUP_WINDOW):
                batch = singles[i : i + self.GROUP_WINDOW]
                _par(
                    [
                        lambda w=w: self._run_strategies(w, self.global_strategies)
                        for w in batch
                    ],
                    self.timeout,
                )

    def _fused_all_reduce(self, members: List[Workspace]) -> None:
        """Pack same-(dtype, op) workspaces into one contiguous buffer,
        allreduce once, unpack. Workspace order is the caller's tensor
        order, which is identical on every peer, so the fused name and
        layout agree cluster-wide."""
        dtype = members[0].send.dtype
        op = members[0].op
        total = sum(w.send.size for w in members)
        nbytes = total * dtype.itemsize
        pool = get_buffer_pool()
        send_b = pool.get(nbytes)
        recv_b = pool.get(nbytes)
        try:
            with trace.span("host.fuse.pack"):
                send = np.frombuffer(send_b, dtype, total)
                recv = np.frombuffer(recv_b, dtype, total)
                off = 0
                for w in members:
                    send[off : off + w.send.size] = w.send
                    off += w.send.size
            fused = Workspace(
                send=send,
                recv=recv,
                op=op,
                name=f"{members[0].name}::fused{len(members)}x{total}",
            )
            with trace.span("host.fuse.walk"):
                self._run_strategies(fused, self.global_strategies)
            with trace.span("host.fuse.unpack"):
                off = 0
                for w in members:
                    np.copyto(w.recv, recv[off : off + w.recv.size])
                    off += w.recv.size
        finally:
            pool.put(send_b)
            pool.put(recv_b)

    def monitored_all_reduce(self, w: Workspace) -> None:
        """AllReduce + throughput accounting for the ACTIVE strategy
        (parity: KungfuMonitoredAllReduce, ops/cpu/collective.cpp:149-196 +
        runMonitoredStrategies, session/monitoring.go:15-35)."""
        nbytes = w.recv.size * w.recv.itemsize
        t0 = time.perf_counter()
        with self._collected("monitored_all_reduce", nbytes):
            with stall_detect(f"monitored_all_reduce({w.name})"):
                self._run_strategies(w, self.global_strategies)
        self.adaptive.current.update(nbytes, time.perf_counter() - t0)

    def check_interference(self, vote_tag: str = "") -> bool:
        """Majority vote on local interference suspicion; on a cluster-wide
        majority every peer advances to the next candidate strategy in the
        same deterministic order. Returns True if the strategy switched.
        Parity: CheckInterference + MonitoredAllReduce consensus switch
        (session/adaptiveStrategies.go:61-121)."""
        if self._tree_override or len(self._candidate_names) < 2:
            return False
        suspect = self.adaptive.current.suspect_interference()
        votes_in = np.array([1 if suspect else 0], np.int32)
        votes_out = np.zeros(1, np.int32)
        self.all_reduce(
            Workspace(votes_in, votes_out, ReduceOp.SUM,
                      f"kungfu::interference:{self.adaptive.switch_count}{vote_tag}")
        )
        if int(votes_out[0]) * 2 <= self.size:
            return False
        old_name = self._candidate_names[self.adaptive.active].name
        idx = self.adaptive.advance()
        self.global_strategies = self._candidate(idx)
        # safety: all peers must now run the same graphs
        if not self.bytes_consensus(
            st.digest(self.global_strategies), f":switch:{self.adaptive.switch_count}"
        ):
            raise RuntimeError("strategy switch diverged across peers")
        from kungfu_tpu.telemetry import audit as _audit

        _audit.record_event(
            "strategy_switch",
            peer=str(self.self_id),
            trigger="interference_vote",
            old_strategy=old_name,
            new_strategy=self._candidate_names[idx].name,
            switch_count=self.adaptive.switch_count,
        )
        return True

    def active_strategy(self) -> Optional[Strategy]:
        """The running candidate strategy, or None when an explicit
        set_tree forest overrides the candidates."""
        if self._tree_override:
            return None
        return self._candidate_names[self.adaptive.active]

    def set_tree(self, fathers: Sequence[int]) -> None:
        """Install a runtime forest (e.g. an MST over probed latencies) as
        the active global strategy (parity: SetTree / SetGlobalStrategy,
        adaptation.cpp:5-33). Disables vote-driven switching — an explicit
        tree wins until the next session epoch.

        The installed forest must be a single tree rooted at rank 0:
        gather/reduce/broadcast walk global_strategies[0] assuming its root
        is rank 0, so a forest rooted elsewhere (or with several roots)
        would silently produce wrong data. Per-component forests are still
        available via subset_all_reduce/all_reduce_with."""
        if len(fathers) != self.size:
            raise ValueError(f"forest size {len(fathers)} != cluster {self.size}")
        roots = [r for r, f in enumerate(fathers) if int(f) == r]
        if roots != [0]:
            raise ValueError(
                f"set_tree forest must be one tree rooted at rank 0, got roots {roots}"
            )
        self.global_strategies = st.from_forest_array(list(fathers))
        self._tree_override = True

    def calc_stats(self) -> dict:
        """Per-strategy throughput summary (parity: CalcStats/LogStats)."""
        return self.adaptive.summary()

    def cross_all_reduce(self, w: Workspace) -> None:
        """AllReduce across host masters only (hierarchical path)."""
        with stall_detect(f"cross_all_reduce({w.name})"):
            self._run_strategies(w, self.cross_strategies)

    def local_reduce(self, w: Workspace) -> None:
        self._run_graphs(w, [self.local_strategies[0].reduce_graph])

    def local_broadcast(self, w: Workspace) -> None:
        self._run_graphs(w, [self.local_strategies[0].bcast_graph])

    def reduce(self, w: Workspace, root: int = 0) -> None:
        """Reduce to `root` (parity: runGraphs with a reduce graph; the
        reference's Reduce takes arbitrary roots). Root 0 walks the
        configured strategy; other roots use a root-specific star."""
        if root == 0:
            self._run_graphs(w, [self.global_strategies[0].reduce_graph])
        else:
            self._check_root(root)
            from kungfu_tpu.plan import topology as _topo

            g = _topo.gen_default_reduce_graph(
                _topo.gen_star_bcast_graph(self.size, root)
            )
            self._run_graphs(w, [g])

    def broadcast(self, w: Workspace, root: int = 0) -> None:
        with self._collected("broadcast", w.recv.nbytes):
            if root == 0:
                self._run_graphs(w, [self.global_strategies[0].bcast_graph])
            else:
                self._check_root(root)
                from kungfu_tpu.plan import topology as _topo

                self._run_graphs(
                    w, [_topo.gen_star_bcast_graph(self.size, root)]
                )

    def _check_root(self, root: int) -> None:
        if not 0 <= root < self.size:
            raise ValueError(f"root {root} outside cluster of {self.size}")

    def subset_all_reduce(self, fathers: Sequence[int], w: Workspace) -> None:
        sl = st.from_forest_array(list(fathers))
        self._run_strategies(w, sl)

    def all_reduce_with(self, fathers: Sequence[int], w: Workspace) -> None:
        """AllReduce on a runtime-supplied tree (parity: AllReduceWith)."""
        if fathers:
            sl = st.from_forest_array(list(fathers))
        else:
            sl = self.global_strategies
        self._run_strategies(w, sl)

    def barrier(self, tag: str = "") -> None:
        """Parity: session.go:98-113 (an allreduce of size bytes)."""
        k = len(self.peers)
        w = Workspace(
            send=np.zeros(k, np.uint8),
            recv=np.zeros(k, np.uint8),
            op=ReduceOp.SUM,
            name=f"kungfu::barrier{tag}",
        )
        self.all_reduce(w)

    def bytes_consensus(self, bs: bytes, name: str) -> bool:
        """True iff every peer supplied identical bytes (session.go:126-157):
        min/max allreduce of the length, then of the padded bytes."""
        n = len(bs)
        lo = np.array([n], np.int32)
        hi = np.array([n], np.int32)
        out_lo = np.zeros(1, np.int32)
        out_hi = np.zeros(1, np.int32)
        self.all_reduce(Workspace(lo, out_lo, ReduceOp.MIN, f":consensus:len:min:{name}"))
        self.all_reduce(Workspace(hi, out_hi, ReduceOp.MAX, f":consensus:len:max:{name}"))
        if out_lo[0] != out_hi[0]:
            return False
        if n == 0:
            return True
        x = np.frombuffer(bs, np.uint8)
        out1 = np.zeros(n, np.uint8)
        out2 = np.zeros(n, np.uint8)
        self.all_reduce(Workspace(x, out1, ReduceOp.MIN, f":consensus:min:{name}"))
        self.all_reduce(Workspace(x, out2, ReduceOp.MAX, f":consensus:max:{name}"))
        return bool(np.array_equal(out1, out2))

    def broadcast_bytes(self, bs: bytes, name: str, root: int = 0) -> bytes:
        """Broadcast variable-length bytes from `root` (two graph walks:
        length, then payload). Used to bootstrap the device plane — the
        TPU analog of broadcasting the NCCL unique id over the CPU
        collective (gpu_collective.cpp:190-212) — and for elastic state
        re-sync, where the root must be a SURVIVING peer (not necessarily
        rank 0 of the new cluster)."""
        from kungfu_tpu.plan import topology as _topo

        # a fixed star keeps the walk root-correct regardless of the active
        # strategy (set_tree/adaptive switches may re-root global_strategies)
        graph = _topo.gen_star_bcast_graph(self.size, root)
        n_send = np.array([len(bs) if self.rank == root else 0], np.int64)
        n_recv = np.zeros(1, np.int64)
        self._run_graphs(
            Workspace(n_send, n_recv, ReduceOp.SUM, f"{name}:len"), [graph]
        )
        n = int(n_recv[0])
        if n == 0:
            return b""
        if self.rank == root:
            send = np.frombuffer(bs, np.uint8)
        else:
            send = np.zeros(n, np.uint8)
        recv = np.zeros(n, np.uint8)
        self._run_graphs(
            Workspace(send, recv, ReduceOp.SUM, f"{name}:data"), [graph]
        )
        return recv.tobytes()

    def gather(self, w: Workspace, root: int = 0) -> None:
        """`root` receives everyone's send buffer into recv (rank-major);
        parity: runGather (session.go:195-221), arbitrary roots like the
        reference's Gather. Handles unequal per-peer counts: the wire
        framing carries each message's true length, so the root lays
        contributions out by their actual sizes (the reference relies on
        the same message framing)."""
        self._check_root(root)
        if self.rank != root:
            with self._collected("gather", w.send.nbytes):
                self.client.send(
                    self.peers[root], w.name, _buf(w.send), ConnType.COLLECTIVE
                )
            return
        scope = self._collected("gather", w.recv.nbytes)
        scope.__enter__()
        cancel = threading.Event()
        parts: List[Optional[np.ndarray]] = [None] * len(self.peers)
        releases: List = [None] * len(self.peers)

        def recv_part(r: int, peer: PeerID) -> None:
            msg = self.endpoint.recv(peer, w.name, self.timeout)
            if cancel.is_set():
                if msg.release is not None:
                    msg.release()
                return
            parts[r] = np.frombuffer(msg.data, w.send.dtype)
            releases[r] = msg.release

        jobs = []
        for r, peer in enumerate(self.peers):
            if r == self.rank:
                parts[r] = w.send.reshape(-1)
            else:
                jobs.append(lambda r=r, p=peer: recv_part(r, p))
        try:
            _par(jobs, self.timeout, cancel)
            off = 0
            for part in parts:
                assert part is not None
                n = part.size
                if off + n > w.recv.size:
                    raise ValueError(
                        f"gather overflow: recv buffer {w.recv.size} < {off + n}"
                    )
                np.copyto(w.recv[off:off + n], part)
                off += n
            if off != w.recv.size:
                # a short contribution would silently shift later ranks' data
                raise ValueError(
                    f"gather underflow: contributions fill {off} of {w.recv.size}"
                )
        finally:
            parts.clear()
            for rel in releases:
                if rel is not None:
                    rel()
            scope.__exit__(None, None, None)

    def all_gather(self, w: Workspace) -> None:
        """Gather to root then broadcast the concatenation (parity:
        AllGatherTransform, session.cpp:201-220)."""
        self.gather(w)
        bw = Workspace(send=w.recv, recv=w.recv, op=w.op, name=w.name + ":bcast")
        self.broadcast(bw)

    # ------------------------------------------------------------------
    # engine
    # ------------------------------------------------------------------

    def _run_strategies(self, w: Workspace, strategies: List[st.StrategyPair]) -> None:
        total = w.recv.size * w.recv.itemsize
        k = max(1, -(-total // choose_chunk_bytes(total)))
        chunks = w.split(even_partition, k) if k > 1 else [w]
        cancel = threading.Event()
        if k == 1:
            pair = strategies[0]
            self._run_graphs(chunks[0], [pair.reduce_graph, pair.bcast_graph], cancel)
            return
        jobs = []
        for i, chunk in enumerate(chunks):
            pair = st.choose(strategies, i)
            jobs.append(
                lambda c=chunk, p=pair: self._run_graphs(
                    c, [p.reduce_graph, p.bcast_graph], cancel
                )
            )
        _par(jobs, self.timeout, cancel)

    def _run_graphs(
        self,
        w: Workspace,
        graphs: List[Graph],
        cancel: Optional[threading.Event] = None,
    ) -> None:
        """The hot walk; parity: runGraphs (session.go:231-299).

        `cancel` is shared across every thread touching this workspace: once
        any part of the collective times out, late-arriving receives must not
        write into (possibly reused) caller buffers."""
        if w.is_empty:
            return
        if all(g.is_isolated(self.rank) for g in graphs):
            w.forward()
            return
        if cancel is None:
            cancel = threading.Event()
        _t_walk = time.perf_counter()

        state = {"recv_count": 0}
        lock = threading.Lock()

        def effective() -> np.ndarray:
            if state["recv_count"] > 0 or w.is_inplace:
                return w.recv
            return w.send

        def send_to(peer: PeerID, flags: Flags = Flags.NONE) -> None:
            # zero-copy: the walk's phases are sequential per chunk, so the
            # buffer cannot be mutated while sendall drains it
            self.client.send(
                peer, w.name, _buf(effective()), ConnType.COLLECTIVE, flags
            )

        bufpool = get_buffer_pool()
        nbytes = w.recv.size * w.recv.itemsize

        def recv_payload(peer: PeerID):
            """Receive (peer, w.name) into a pooled scratch buffer —
            delivered straight off the socket when we're parked first
            (sink path), else from the buffered Message (possibly a
            zero-copy shm borrow). Returns (ndarray view, scratch-or-None
            to return to the pool, release-or-None to call once the view
            has been consumed)."""
            scratch = bufpool.get(nbytes)
            # on error the scratch is deliberately NOT returned to the pool:
            # a timed-out sink may still be mid-fill by the transport thread
            msg, filled = self.endpoint.recv_into(
                peer, w.name, memoryview(scratch), self.timeout
            )
            if filled:
                return np.frombuffer(scratch, w.send.dtype), scratch, None
            bufpool.put(scratch)  # unused: sender raced us or size mismatch
            return (
                np.frombuffer(msg.data, w.send.dtype),
                None,
                msg.release,
            )

        def recv_onto(peer: PeerID) -> None:
            incoming, scratch, release = recv_payload(peer)
            try:
                with lock:
                    if cancel.is_set():
                        # abort the whole walk: a late arrival must neither
                        # write the workspace nor let the send phase relay
                        # stale data
                        raise TimeoutError(f"collective cancelled: {w.name}")
                    if state["recv_count"] == 0 and not w.is_inplace:
                        # first arrival: recv = send (op) incoming
                        from kungfu_tpu.base.ops import transform2

                        transform2(w.recv, w.send, incoming, w.op)
                    else:
                        reduce_inplace(w.recv, incoming, w.op)
                    state["recv_count"] += 1
            finally:
                del incoming
                if release is not None:
                    release()
            if scratch is not None:
                bufpool.put(scratch)

        def recv_all_onto(peers: List[PeerID]) -> None:
            """Accumulate phase: receive every prev, then reduce them all
            in ONE n-ary pass (kf_transform_n). Pairwise-on-arrival
            overlaps receive with reduce, which pays when cores are free;
            the n-ary pass minimizes memory traffic, which wins outright
            on busy/low-core hosts — and the receives themselves still
            overlap each other."""
            got: List = [None] * len(peers)

            def grab(i: int, p: PeerID) -> None:
                res = recv_payload(p)
                if cancel.is_set():
                    # the walk already timed out and its finally block may
                    # have run: release the borrow here or nobody will
                    if res[2] is not None:
                        res[2]()
                    return
                got[i] = res

            try:
                _par(
                    [lambda i=i, p=p: grab(i, p) for i, p in enumerate(peers)],
                    self.timeout,
                    cancel,
                )
                with lock:
                    if cancel.is_set():
                        raise TimeoutError(f"collective cancelled: {w.name}")
                    if w.is_inplace:
                        for incoming, _, _ in got:
                            reduce_inplace(w.recv, incoming, w.op)
                    else:
                        transform_n(
                            w.recv,
                            [w.send] + [inc for inc, _, _ in got],
                            w.op,
                        )
                    state["recv_count"] += len(peers)
            finally:
                for item in got:
                    if item is not None and item[2] is not None:
                        item[2]()
            for item in got:
                if item is not None and item[1] is not None:
                    bufpool.put(item[1])

        def recv_into(peer: PeerID) -> None:
            incoming, scratch, release = recv_payload(peer)
            try:
                with lock:
                    if cancel.is_set():
                        raise TimeoutError(f"collective cancelled: {w.name}")
                    np.copyto(w.recv, incoming)
                    state["recv_count"] += 1
            finally:
                del incoming
                if release is not None:
                    release()
            if scratch is not None:
                bufpool.put(scratch)

        for g in graphs:
            prevs = [self.peers[r] for r in g.prevs(self.rank)]
            nexts = [self.peers[r] for r in g.nexts(self.rank)]
            if g.is_self_loop(self.rank):
                # accumulate: receive from all prevs, n-ary reduce, send on
                if prevs and state["recv_count"] == 0:
                    recv_all_onto(prevs)
                else:
                    _par([lambda p=p: recv_onto(p) for p in prevs], self.timeout, cancel)
                _par([lambda p=p: send_to(p) for p in nexts], self.timeout, cancel)
            else:
                # pass-through node: take value from single prev (or forward
                # own), relay to nexts
                if not prevs and state["recv_count"] == 0:
                    w.forward()
                else:
                    for p in prevs:
                        recv_into(p)
                _par(
                    [lambda p=p: send_to(p, Flags.WAIT_RECV_BUF) for p in nexts],
                    self.timeout,
                    cancel,
                )
        trace.record(f"host.walk[{w.recv.nbytes >> 20}MiB]",
                     time.perf_counter() - _t_walk)
