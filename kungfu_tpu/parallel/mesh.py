"""Device mesh session: the TPU-native Session epoch.

Capability parity: srcs/go/kungfu/session/session.go — an immutable
peer-list epoch exposing rank/size/local metadata, barrier, and collectives.
On TPU the "peer list" is a `jax.sharding.Mesh` over the slice's chips: the
membership of a compiled program is fixed at compile time exactly like a
Session is fixed per cluster version. An elastic resize creates a NEW
DeviceSession over a new mesh (and retriggers compilation), mirroring
`Peer.updateTo` building a new Session per cluster version.

Rank vocabulary (multi-host TPU pod):
- process == host (jax.process_index) — the unit the control plane manages;
- device == chip — the unit the data plane (ICI collectives) runs over.
The reference's rank/local-rank/host-count map to device index / index on
host / process count.
"""

from __future__ import annotations

import functools
from typing import Dict, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from kungfu_tpu.parallel._compat import shard_map


def make_mesh(shape: Optional[Dict[str, int]] = None, *, devices=None) -> Mesh:
    """Build a Mesh. shape maps axis name -> size; one size may be -1
    (inferred). Default: all devices on a single 'dp' axis.

    Axis order convention follows the scaling-book recipe: put the
    most-communication-hungry axis last ('tp' innermost over ICI
    neighbours), 'dp' outermost (crosses DCN on multi-slice).
    """
    devices = np.asarray(devices if devices is not None else jax.devices())
    n = devices.size
    if shape is None:
        shape = {"dp": n}
    names = tuple(shape)
    sizes = list(shape.values())
    if sizes.count(-1) > 1:
        raise ValueError("at most one axis size may be -1")
    if -1 in sizes:
        known = int(np.prod([s for s in sizes if s != -1]))
        if n % known:
            raise ValueError(f"cannot infer axis: {n} devices over {shape}")
        sizes[sizes.index(-1)] = n // known
    total = int(np.prod(sizes))
    if total != n:
        raise ValueError(f"mesh {dict(zip(names, sizes))} needs {total} devices, have {n}")
    return Mesh(devices.reshape(sizes), names)


class DeviceSession:
    """An immutable epoch over a device mesh, with KungFu-parity metadata
    and host-callable collectives."""

    def __init__(self, mesh: Optional[Mesh] = None, version: int = 0):
        self.mesh = mesh if mesh is not None else make_mesh()
        self.version = version

    # -- metadata (parity: session.go Rank/Size/LocalRank/LocalSize/HostCount)
    @property
    def size(self) -> int:
        return self.mesh.devices.size

    @property
    def axis_names(self) -> Tuple[str, ...]:
        return tuple(self.mesh.axis_names)

    @property
    def rank(self) -> int:
        return jax.process_index()

    @property
    def host_count(self) -> int:
        return jax.process_count()

    @property
    def local_size(self) -> int:
        return jax.local_device_count()

    def sharding(self, *spec) -> NamedSharding:
        return NamedSharding(self.mesh, P(*spec))

    @property
    def replicated(self) -> NamedSharding:
        return self.sharding()

    # -- collectives -------------------------------------------------------
    def spmd(self, fn, in_specs, out_specs, check_vma: bool = False):
        """shard_map+jit over this mesh (one compiled SPMD program)."""
        return jax.jit(
            shard_map(fn, mesh=self.mesh, in_specs=in_specs, out_specs=out_specs,
                      check_vma=check_vma)
        )

    @functools.cached_property
    def _barrier_fn(self):
        axes = self.axis_names

        def fence(x):
            for a in axes:
                x = jax.lax.psum(x, a)
            return x

        return self.spmd(fence, in_specs=P(), out_specs=P())

    def barrier(self) -> None:
        """Device-fence barrier: a tiny AllReduce over every mesh axis,
        blocked on. Parity: Session.Barrier (session.go:98-113). In
        multi-process mode this also synchronizes processes (all hosts must
        dispatch the same program)."""
        self._barrier_fn(jnp.zeros((), jnp.int32)).block_until_ready()

    def all_reduce(self, tree, axis_name: Optional[str] = None):
        """AllReduce device-sharded data: each leaf's leading axis is sharded
        over `axis_name` (default: first mesh axis); returns the reduction
        over shards, replicated."""
        from kungfu_tpu.ops.collective import group_all_reduce

        axis = axis_name or self.axis_names[0]
        fn = self.spmd(
            lambda t: group_all_reduce(t, axis),
            in_specs=P(axis),
            out_specs=P(),
        )
        return fn(tree)

    def describe(self) -> str:
        shape = dict(zip(self.axis_names, self.mesh.devices.shape))
        return (
            f"DeviceSession(v{self.version}, {self.size} devices, mesh={shape}, "
            f"process {self.rank}/{self.host_count})"
        )
