"""JAX version compatibility for shard_map.

The API moved twice across the jax versions this repo meets in the
wild: `jax.experimental.shard_map.shard_map` -> `jax.shard_map`, and
its replication-check kwarg renamed `check_rep` -> `check_vma`. Every
call site imports this wrapper (newer-jax calling convention) so the
package works on both.
"""

from __future__ import annotations

import inspect

try:
    from jax import shard_map as _shard_map  # jax >= 0.6
except ImportError:  # older jax: experimental namespace
    from jax.experimental.shard_map import shard_map as _shard_map

_params = set(inspect.signature(_shard_map).parameters)
if "check_vma" in _params:
    _CHECK_KW = "check_vma"
elif "check_rep" in _params:
    _CHECK_KW = "check_rep"
else:
    _CHECK_KW = None


def shard_map(f, *, mesh, in_specs, out_specs, check_vma: bool = False):
    kw = {_CHECK_KW: check_vma} if _CHECK_KW else {}
    return _shard_map(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kw
    )
