"""Data-parallel training-step factory.

The TPU-native replacement for "wrap your optimizer and run sess.run":
given a loss and a (possibly communication-injecting) optax optimizer,
build ONE jitted SPMD program that
  - shards the batch over the mesh's data axis,
  - computes local grads,
  - lets the optimizer's traced collectives (pmean etc.) synchronize,
  - applies updates.
Params/optimizer state are replicated across the dp axis. XLA overlaps the
grad AllReduce with backprop automatically (no hand scheduling — contrast
with the reference's NCCL scheduler + fuse-ordering workarounds,
sync_sgd.py:81-94).
"""

from __future__ import annotations

from typing import Callable, Optional, Tuple

import jax
import optax
from kungfu_tpu.parallel._compat import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def make_train_step(
    loss_fn: Callable,
    optimizer: optax.GradientTransformation,
    mesh: Mesh,
    axis_name: str = "dp",
    batch_spec: Optional[P] = None,
    donate: bool = True,
):
    """Build a jitted SPMD train step.

    loss_fn(params, batch) -> scalar loss (per local shard).
    Returns step(params, opt_state, batch) -> (params, opt_state, loss)
    where loss is the mean over the axis.
    """
    if batch_spec is None:
        batch_spec = P(axis_name)

    def local_step(params, opt_state, batch):
        loss, grads = jax.value_and_grad(loss_fn)(params, batch)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        loss = jax.lax.pmean(loss, axis_name)
        return params, opt_state, loss

    spmd = shard_map(
        local_step,
        mesh=mesh,
        in_specs=(P(), P(), batch_spec),
        out_specs=(P(), P(), P()),
        check_vma=False,
    )
    return jax.jit(spmd, donate_argnums=(0, 1) if donate else ())


def replicate(tree, mesh: Mesh):
    """Place a pytree fully replicated on the mesh."""
    return jax.device_put(tree, NamedSharding(mesh, P()))


def shard_batch(batch, mesh: Mesh, axis_name: str = "dp"):
    """Place a batch sharded over the data axis (leading dim)."""
    return jax.device_put(batch, NamedSharding(mesh, P(axis_name)))
