"""Pipeline parallelism: GPipe-style microbatch pipelining over a mesh
axis, with ppermute stage handoffs.

Beyond-reference capability (the reference is data-parallel only,
SURVEY §2.4): the transformer's layer-stacked parameter layout (leading
layer axis) shards directly over a ``pp`` mesh axis — each stage holds
n_layers/pp contiguous blocks — and the classic GPipe schedule runs as a
`lax.scan` over M + P - 1 ticks: at every tick each stage transforms the
activation it holds and hands it to the next stage via `ppermute` (ICI
neighbor traffic), stage 0 injects a fresh microbatch, and the last stage
accumulates the LM loss. Backward differentiates straight through the
scan + ppermute (the transpose of a shift is the reverse shift), giving
1F1B-equivalent math with GPipe scheduling.

Composes with data parallelism: batch over ``dp``, layers over ``pp``.
Bubble fraction is (P-1)/(M+P-1); pick n_micro >= ~4x the stage count.
Each stage also computes the (cheap) LM head every tick — dead compute on
non-final stages that XLA cannot skip under SPMD; acceptable because the
head is O(D*V) vs the stages' O(L/P * D^2 * S) blocks.

Negative results (round 5, measured at pp=4 on the 8-device CPU mesh,
vocab-heavy config where the dead head compute is LARGEST): gating the
per-tick head (and the stage-0 embed gather) behind `lax.cond` so only
the owning stage executes it ran 2x SLOWER end-to-end — AD through a
conditional inside the tick scan costs far more than the skipped flops;
hoisting the head out of the scan over stacked per-tick outputs (one
large matmul, single mask site) was 13% slower (extra stacked-activation
traffic, and the off-stage copies remain dead under where()). The
where()-masked schedule stands as the measured-fastest formulation; a
hand-scheduled 1F1B (manual backward interleave) is the remaining
approach and is out of scope while its main win (activation memory)
is already bounded by the scan's per-tick residuals.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P


def make_pp_transformer_loss(cfg, mesh, n_micro: int, pp_axis: str = "pp",
                             dp_axis: str = None):
    """Pipelined causal-LM loss for kungfu_tpu.models.transformer params.

    batch = (tokens, targets), both (B, S); B divisible by n_micro (and by
    the dp axis when given). Returns loss_fn(params, batch) -> replicated
    scalar, jit/grad-compatible."""
    from kungfu_tpu.models.transformer import _block, lm_head_loss

    n_stages = mesh.shape[pp_axis]
    if cfg.n_layers % n_stages:
        raise ValueError(
            f"n_layers {cfg.n_layers} not divisible by pp={n_stages}"
        )

    def shard_fn(params, batch):
        tokens, targets = batch
        stage = lax.axis_index(pp_axis)
        B, S = tokens.shape
        if B % n_micro:
            raise ValueError(f"batch {B} not divisible by n_micro {n_micro}")
        b = B // n_micro
        dt = cfg.dtype
        embed = params["embed"].astype(dt)
        pos = params["pos_embed"].astype(dt)[:S]
        micro_tok = tokens.reshape(n_micro, b, S)
        micro_tgt = targets.reshape(n_micro, b, S)

        is_first = stage == 0
        is_last = stage == n_stages - 1
        shift = [(i, i + 1) for i in range(n_stages - 1)]

        def tick(carry, t):
            act_in, loss_acc = carry
            # stage 0 injects microbatch t (while t < n_micro); the value
            # is ignored on other stages / out-of-range ticks
            m_in = jnp.clip(t, 0, n_micro - 1)
            x0 = embed[micro_tok[m_in]] + pos
            x = jnp.where(is_first, x0, act_in)
            x, _ = lax.scan(
                lambda h, layer: (_block(h, layer, cfg), None),
                x,
                params["layers"],  # THIS stage's layer slice
            )
            # the microbatch leaving the last stage at tick t entered at
            # t - (n_stages - 1)
            m_out = t - (n_stages - 1)
            valid = (m_out >= 0) & (m_out < n_micro)
            tgt = micro_tgt[jnp.clip(m_out, 0, n_micro - 1)]
            l = lm_head_loss(params, x, tgt, cfg)
            loss_acc = loss_acc + jnp.where(is_last & valid, l, 0.0)
            act_out = (
                lax.ppermute(x, pp_axis, shift) if n_stages > 1 else x
            )
            return (act_out, loss_acc), None

        act0 = jnp.zeros((b, S, cfg.d_model), dt)
        ticks = jnp.arange(n_micro + n_stages - 1)
        (_, loss_acc), _ = lax.scan(tick, (act0, jnp.float32(0.0)), ticks)
        # only the last stage accumulated anything; share it with everyone
        loss = lax.psum(jnp.where(is_last, loss_acc, 0.0), pp_axis) / n_micro
        if dp_axis is not None:
            loss = lax.pmean(loss, dp_axis)
        return loss

    from kungfu_tpu.parallel._compat import shard_map

    batch_spec = P(dp_axis) if dp_axis is not None else P()
    param_specs = {
        "embed": P(),
        "pos_embed": P(),
        "ln_f_scale": P(),
        # layer-stacked leaves shard their leading (layer) axis over pp
        "layers": jax.tree.map(lambda _: P(pp_axis), {
            "ln1_scale": 0, "ln2_scale": 0, "wqkv": 0, "wo": 0,
            "w_in": 0, "w_out": 0,
        }),
    }
    return shard_map(
        shard_fn,
        mesh=mesh,
        in_specs=(param_specs, (batch_spec, batch_spec)),
        out_specs=P(),
        check_vma=False,
    )
