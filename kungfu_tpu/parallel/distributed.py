"""Multi-host device-plane bootstrap over the host control plane.

The reference bootstraps its GPU data plane by having rank 0 create an
NCCL unique id and broadcasting it over the CPU collective
(srcs/cpp/src/nccl/gpu_collective.cpp:190-243). The TPU-native analog:
rank 0 picks a JAX coordination-service address, broadcasts it over the
HOST plane (kfrun's TCP collectives), and every worker calls
`jax.distributed.initialize` with its host-plane rank — after which
`jax.devices()` spans ALL workers' chips and one `jax.sharding.Mesh` /
compiled program covers the whole cluster (SURVEY §7 stages 4+6).

Elastic semantics:
- reload mode (PRIMARY on TPU — the ICI mesh shape is fixed per slice):
  workers exit on resize, runners respawn them, and the fresh processes
  bootstrap a fresh device plane here. Nothing to tear down.
- delta mode: `reinitialize_device_plane()` tears the XLA backend down
  in-process (distributed shutdown + backend clear) and bootstraps again
  over the NEW host session. Works on CPU clusters; on real TPU pods
  prefer reload mode — the TPU runtime does not always release chips
  cleanly for in-process re-init.
"""

from __future__ import annotations

import socket
import threading
from typing import Optional

from kungfu_tpu.utils import log
from kungfu_tpu.utils.stall import stall_detect

_state = {"initialized": False, "local_only": False, "version": -1}
_lock = threading.Lock()


def _free_port(host: str) -> int:
    s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    try:
        s.bind((host if host not in ("localhost",) else "127.0.0.1", 0))
        return s.getsockname()[1]
    finally:
        s.close()


def device_plane_initialized() -> bool:
    return _state["initialized"]


def initialize_device_plane(platform: Optional[str] = None) -> None:
    """Stand up ONE JAX world across all workers of the current cluster.

    Must run before any other JAX API touches the backend (jax.devices()
    etc.) — the same constraint the reference's NCCL init has. Single
    process (no kfrun): no-op, local devices only.
    """
    import jax

    from kungfu_tpu.peer import get_default_peer

    with _lock:
        if _state["initialized"]:
            return
        peer = get_default_peer()
        if platform:
            jax.config.update("jax_platforms", platform)
        sess = peer.current_session()
        if peer.config.single_process or sess.size == 1:
            _state["local_only"] = True
            _state["initialized"] = True
            log.debug("device plane: single-process, local devices only")
            return
        if sess.rank == 0:
            host = peer.self_id.host
            addr = f"{host}:{_free_port(host)}".encode()
        else:
            addr = b""
        with stall_detect("device_plane_bootstrap"):
            addr = sess.broadcast_bytes(addr, f"kungfu::devplane:v{peer.cluster_version}")
            coordinator = addr.decode()
            log.info(
                "device plane: initializing process %d/%d, coordinator %s",
                sess.rank, sess.size, coordinator,
            )
            jax.distributed.initialize(
                coordinator_address=coordinator,
                num_processes=sess.size,
                process_id=sess.rank,
            )
        _state["initialized"] = True
        _state["local_only"] = False
        _state["version"] = peer.cluster_version


def shutdown_device_plane() -> None:
    """Tear down the distributed JAX backend so a new world can form."""
    import jax

    with _lock:
        if not _state["initialized"]:
            return
        if not _state["local_only"]:
            jax.distributed.shutdown()
        # Drop live backends + compiled programs so the next JAX call (after
        # re-initialize) builds a client for the NEW process set. JAX has no
        # public backend-reset API; feature-detect the internal one and fail
        # with guidance (use reload mode) if a future JAX moves it.
        try:
            from jax._src import xla_bridge

            xla_bridge._clear_backends()
        except (ImportError, AttributeError) as e:
            _state["initialized"] = False
            _state["local_only"] = False
            raise RuntimeError(
                "cannot reset the XLA backend in-process with this JAX "
                "version; use elastic reload mode (process restart) instead"
            ) from e
        jax.clear_caches()
        _state["initialized"] = False
        _state["local_only"] = False


def reinitialize_device_plane(platform: Optional[str] = None) -> None:
    """Delta-mode elastic rebuild: new host session -> new JAX world.

    The caller must drop references to arrays/compiled functions from the
    old world first (they hold the old backend alive). Parity: NCCL
    ReInit per new cluster version (nccl/controller.hpp:14-44).
    """
    shutdown_device_plane()
    initialize_device_plane(platform)


def current_device_plane_version() -> int:
    return _state["version"]
