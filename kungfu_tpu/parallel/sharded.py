"""Sharded (pjit-style) train steps: DP x TP over a mesh.

The jit-with-shardings path: params carry PartitionSpecs (tensor
parallelism), the batch shards over 'dp', and XLA's SPMD partitioner
derives every collective (grad AllReduce over dp, activation collectives
over tp) from the annotations. This is the TPU-idiomatic generalization of
the reference's data-parallel-only engine — the "strategy" is a mesh-axis
layout instead of a communication graph (SURVEY.md §7 stage 4).
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def named(mesh: Mesh, spec_tree):
    """Map a PartitionSpec tree to NamedShardings."""
    return jax.tree.map(
        lambda s: NamedSharding(mesh, s),
        spec_tree,
        is_leaf=lambda s: isinstance(s, P),
    )


def make_sharded_train_step(
    loss_fn: Callable,
    optimizer: optax.GradientTransformation,
    mesh: Mesh,
    param_specs,
    batch_spec: P = P("dp"),
    donate: bool = True,
):
    """Build a jitted SPMD train step with sharded params.

    loss_fn(params, batch) -> scalar. Optimizer state inherits the param
    shardings leaf-wise where shapes match (optax state mirrors params).
    Returns step(params, opt_state, batch) -> (params, opt_state, loss).
    """

    def train_step(params, opt_state, batch):
        loss, grads = jax.value_and_grad(loss_fn)(params, batch)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    param_sh = named(mesh, param_specs)
    step = jax.jit(
        train_step,
        in_shardings=(param_sh, None, named(mesh, batch_spec)),
        donate_argnums=(0, 1) if donate else (),
    )
    return step


def shard_params(params, mesh: Mesh, param_specs):
    return jax.device_put(params, named(mesh, param_specs))
