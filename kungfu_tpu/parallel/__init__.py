from kungfu_tpu.parallel.mesh import DeviceSession, make_mesh
from kungfu_tpu.parallel.dp import make_train_step

__all__ = ["DeviceSession", "make_mesh", "make_train_step"]
