from kungfu_tpu.parallel.mesh import DeviceSession, make_mesh
from kungfu_tpu.parallel.dp import make_train_step
from kungfu_tpu.parallel.pipeline import make_pp_transformer_loss
from kungfu_tpu.parallel.distributed import (
    device_plane_initialized,
    initialize_device_plane,
    reinitialize_device_plane,
    shutdown_device_plane,
)

__all__ = [
    "DeviceSession",
    "make_mesh",
    "make_pp_transformer_loss",
    "make_train_step",
    "initialize_device_plane",
    "reinitialize_device_plane",
    "shutdown_device_plane",
    "device_plane_initialized",
]
