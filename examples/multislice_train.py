"""Multi-slice data parallelism: ICI psum within each slice, host-plane
allreduce across slices — one jitted step per world.

Each kfrun worker owns one jax world (one TPU slice / ICI domain); the
cross-slice gradient average rides the DCN host plane from INSIDE the
compiled step (parity: the reference's hierarchical NCCL+CPU allreduce,
gpu/collective.cpp:108-162). Run it:

  kfrun -np 2 -H 127.0.0.1:2 python3 examples/multislice_train.py

On real hardware each worker would see its own slice's chips; here each
worker self-provisions a 4-device virtual CPU world so the full dp-within
x dp-across composition runs anywhere.
"""

import argparse


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--steps", type=int, default=10)
    p.add_argument("--devices", type=int, default=4,
                   help="virtual devices per worker (0 = real backend)")
    args = p.parse_args()

    import jax

    if args.devices:
        jax.config.update("jax_platforms", "cpu")
        jax.config.update("jax_num_cpu_devices", args.devices)

    import jax.numpy as jnp
    import numpy as np
    import optax

    from kungfu_tpu import api
    from kungfu_tpu.models.mlp import init_mlp, mlp_loss
    from kungfu_tpu.ops.hierarchical import make_hier_train_step
    from kungfu_tpu.parallel import make_mesh

    rank, size = api.current_rank(), api.cluster_size()
    mesh = make_mesh()  # all this world's devices on "dp"
    ndev = mesh.devices.size

    params = init_mlp(jax.random.PRNGKey(42))  # same seed in every world
    opt = optax.sgd(0.1)
    step = make_hier_train_step(mlp_loss, opt, mesh)
    opt_state = opt.init(params)

    # each world takes a disjoint shard of the global batch
    per_world = 64 * ndev
    key = jax.random.PRNGKey(1000 + rank)
    for i in range(args.steps):
        key, k1, k2 = jax.random.split(key, 3)
        x = jax.random.normal(k1, (per_world, 784))
        y = jax.random.randint(k2, (per_world,), 0, 10)
        params, opt_state, loss = step(params, opt_state, (x, y))
        if rank == 0:
            print(f"step {i}: loss {float(loss):.4f} "
                  f"({size} worlds x {ndev} devices)", flush=True)

    # worlds must agree bitwise: the cross-slice sync keeps them lockstep.
    # MIN and MAX allreduce both equal to the local value is an exact
    # cross-world equality check (a summed allclose could hide drift)
    from kungfu_tpu.base.ops import ReduceOp

    flat = np.concatenate([np.ravel(l) for l in jax.tree.leaves(
        jax.device_get(params))])
    lo = api.all_reduce_array(flat, ReduceOp.MIN, name="check-min")
    hi = api.all_reduce_array(flat, ReduceOp.MAX, name="check-max")
    assert np.array_equal(lo, flat) and np.array_equal(hi, flat), "worlds diverged"
    print(f"rank {rank}: worlds in sync after {args.steps} steps", flush=True)


if __name__ == "__main__":
    main()
