"""BERT-base masked-LM pretraining under SynchronousAveraging (SMA).

BASELINE.md tracked config 3: the reference's third headline workload is
BERT pretraining with the SynchronousAveragingOptimizer
(srcs/python/kungfu/tensorflow/optimizers/sma_sgd.py) over its host
allreduce. Here the same training scheme runs TPU-native:

- model: the flagship decoder transformer at BERT-base scale
  (`TransformerConfig.bert_base()`: 768 d_model, 12 layers, 12 heads,
  30522 vocab) with a masked-LM objective;
- optimizer: local AdamW steps, then the SMA blend
  ``p <- p + alpha * (mean_cluster(p) - p)`` with the cluster mean taken
  over the HOST collective plane (the DCN-path SMA, exactly the
  reference's placement — gradients never cross the host plane, params
  do, once per step);
- elastic: the cluster average adapts to membership automatically since
  it is just a host allreduce over the current session.

Run (small, CPU mesh, np=2 — loss must decrease):

  kfrun -np 2 -H 127.0.0.1:2 python examples/bert_sma.py --steps 30

Full-size single chip:

  python examples/bert_sma.py --config bert-base --steps 10 --batch 8

Single-process runs (no kfrun) train without the SMA blend (cluster of
one), so the same script doubles as a plain masked-LM trainer.
"""

from __future__ import annotations

import argparse
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax

from kungfu_tpu.models.transformer import (
    TransformerConfig,
    init_transformer,
    transformer_apply,
)

MASK_FRAC = 0.15


def synthetic_batch(rng, cfg, batch, seq):
    """Synthetic masked-LM batch: structured token streams (skip-gram-ish
    correlations) so the loss has real signal to fit."""
    base = rng.integers(4, cfg.vocab_size, size=(batch, 1))
    drift = rng.integers(0, 17, size=(batch, seq))
    tokens = (base + np.cumsum(drift, axis=1)) % (cfg.vocab_size - 4) + 4
    mask = rng.random((batch, seq)) < MASK_FRAC
    inputs = np.where(mask, 3, tokens)  # 3 = [MASK]
    return inputs.astype(np.int32), tokens.astype(np.int32), mask


def mlm_loss(params, inputs, targets, mask, cfg):
    logits = transformer_apply(params, inputs, cfg)
    logp = jax.nn.log_softmax(logits.astype(jnp.float32))
    tok_logp = jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    maskf = mask.astype(jnp.float32)
    return -jnp.sum(tok_logp * maskf) / jnp.maximum(jnp.sum(maskf), 1.0)


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--config", choices=["tiny", "bert-base"], default="tiny")
    p.add_argument("--steps", type=int, default=30)
    p.add_argument("--batch", type=int, default=16)
    p.add_argument("--seq", type=int, default=0, help="0 = config max_seq")
    p.add_argument("--lr", type=float, default=3e-4)
    p.add_argument("--alpha", type=float, default=0.1,
                   help="SMA blend weight toward the cluster average")
    args = p.parse_args()

    cfg = (TransformerConfig.bert_base() if args.config == "bert-base"
           else TransformerConfig.tiny())
    seq = args.seq or min(cfg.max_seq, 128 if args.config == "tiny" else 512)

    from kungfu_tpu import api

    rank = api.current_rank()
    n = api.cluster_size()
    # distinct data per worker, like the reference's sharded input pipeline
    rng = np.random.default_rng(1234 + rank)

    params = init_transformer(jax.random.PRNGKey(0), cfg)
    opt = optax.adamw(args.lr, weight_decay=0.01)
    opt_state = opt.init(params)

    @jax.jit
    def local_step(params, opt_state, inputs, targets, mask):
        loss, grads = jax.value_and_grad(mlm_loss)(
            params, inputs, targets, mask, cfg
        )
        updates, opt_state = opt.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    leaves, treedef = jax.tree.flatten(params)
    outs = [np.empty(np.shape(l), np.result_type(l)) for l in leaves]

    def sma_blend(params):
        """p <- p + alpha * (cluster_mean(p) - p) over the host plane."""
        if n == 1:
            return params
        leaves = [np.asarray(l) for l in jax.tree.leaves(params)]
        summed = api.group_all_reduce_arrays(leaves, name="sma", outs=outs)
        blended = [
            l + args.alpha * (s / n - l) for l, s in zip(leaves, summed)
        ]
        return jax.tree.unflatten(treedef, blended)

    first = last = None
    for step in range(args.steps):
        inputs, targets, mask = synthetic_batch(rng, cfg, args.batch, seq)
        t0 = time.perf_counter()
        params, opt_state, loss = local_step(
            params, opt_state, inputs, targets, mask
        )
        loss = float(jax.device_get(loss))
        params = sma_blend(params)
        if first is None:
            first = loss
        last = loss
        if rank == 0:
            print(
                f"step {step} loss {loss:.4f} "
                f"({(time.perf_counter() - t0) * 1e3:.0f} ms, np={n})",
                flush=True,
            )
    if rank == 0:
        print(f"loss {first:.4f} -> {last:.4f} "
              f"({'DECREASED' if last < first else 'NOT DECREASED'})",
              flush=True)


if __name__ == "__main__":
    main()
