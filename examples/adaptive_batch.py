"""GNS-driven adaptive batch size: gradient-noise-scale monitoring picks
the cluster size (BASELINE config 5 — "elastic with resize_cluster +
gradient-noise-scale adaptive batch on preemptible TPU VMs").

The McCandlish critical batch size B_crit ~= GNS: while the measured GNS
is well above the current GLOBAL batch (workers x per-worker batch),
adding workers still buys near-linear speedup, so rank 0 proposes a
bigger cluster; when GNS falls toward the global batch, growth stops.
Run it:

  kfrun -np 1 -H 127.0.0.1:4 -w -builtin-config-port 0 \\
      python3 examples/adaptive_batch.py

and watch the cluster grow as the noise estimate warms up.

Host-plane variant for portability (the same wiring with the on-device
`monitor_gradient_noise_scale` optimizer applies on a TPU mesh): per-step
the gradient noise scale is estimated from the per-worker vs averaged
gradient norms, exactly the McCandlish small/big-batch pair the
reference's NoiseScale op consumes (srcs/cpp/src/op/noise_scale —
capability parity: P9/MonitorGradientNoiseScaleOptimizer + policy-driven
resize)."""

import argparse

import numpy as np

from kungfu_tpu import api
from kungfu_tpu.elastic import ElasticState


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--steps", type=int, default=120)
    p.add_argument("--batch", type=int, default=32, help="per-worker batch")
    p.add_argument("--max-workers", type=int, default=4)
    p.add_argument("--alpha", type=float, default=0.7, help="GNS EMA")
    args = p.parse_args()

    rng = np.random.default_rng(1234 + api.current_rank())
    dim = 256
    w_true = np.random.default_rng(7).normal(size=(dim,))

    # live state (weights + GNS EMAs): joiners inherit it from a survivor
    # via the ElasticState re-sync broadcast — a fresh-zeros joiner would
    # break the S-SGD identical-params invariant AND poison the GNS
    # estimate that drives resizing
    state = {"w": np.zeros(dim), "emas": np.zeros(2)}
    es = ElasticState(max_progress=args.steps)
    es.register_state(lambda: state, lambda t: state.update(t))
    lr = 0.05

    while not es.stopped():
        with es.scope():
            size = api.cluster_size()
            rank = api.current_rank()
            w = state["w"]
            # noisy linear-regression gradient on this worker's batch
            x = rng.normal(size=(args.batch, dim))
            noise = rng.normal(size=args.batch) * 3.0
            err = x @ w - (x @ w_true + noise)
            g_local = x.T @ err / args.batch

            g_avg = api.all_reduce_array(g_local, name="grad") / size
            # McCandlish pair from within-worker HALF batches (works even
            # at cluster size 1, where per-worker vs average degenerates):
            # |g_small|^2 over half-batch grads, |g_big|^2 of the cluster
            # average
            h = args.batch // 2
            g_h1 = x[:h].T @ err[:h] / h
            g_h2 = x[h:].T @ err[h:] / (args.batch - h)
            local_gs = 0.5 * (g_h1 @ g_h1 + g_h2 @ g_h2)
            gs = float(api.all_reduce_array(
                np.array([local_gs]), name="gs")[0]) / size
            gb = float(g_avg @ g_avg)
            b_small, b_big = h, args.batch * size
            g2_ema, s_ema = state["emas"]
            if b_big > b_small:
                s = (gs - gb) * b_small * b_big / (b_big - b_small)
                g2 = (b_big * gb - b_small * gs) / (b_big - b_small)
                g2_ema = args.alpha * g2_ema + (1 - args.alpha) * max(g2, 1e-12)
                s_ema = args.alpha * s_ema + (1 - args.alpha) * max(s, 0.0)
                state["emas"] = np.array([g2_ema, s_ema])
            gns = s_ema / g2_ema if g2_ema > 0 else 0.0

            state["w"] = w - lr * g_avg
            step = es.progress
            if rank == 0 and step % 10 == 9:
                global_batch = args.batch * size
                print(f"step {step}: size={size} gns={gns:.0f} "
                      f"global_batch={global_batch}", flush=True)
                # grow while the critical batch exceeds what we have
                if gns > 2 * global_batch and size < args.max_workers:
                    print(f"step {step}: proposing size {size + 1}", flush=True)
                    api.propose_new_size(size + 1)
            es.end(1)

    print(f"done rank={api.current_rank()} size={api.cluster_size()} "
          f"reason={es.stop_reason}", flush=True)


if __name__ == "__main__":
    main()
