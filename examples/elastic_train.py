"""Elastic training example: resizable MLP training with ElasticState.

Parity: /root/reference/examples (elastic estimator examples) — run:

  kfrun -np 2 -H 127.0.0.1:4 -w -builtin-config-port 9100 \\
      python examples/elastic_train.py

then grow/shrink the cluster from another terminal:

  curl -X PUT -d '{"Runners": ["127.0.0.1:38080"], "Workers": \\
      ["127.0.0.1:38000","127.0.0.1:38001","127.0.0.1:38002"]}' \\
      http://127.0.0.1:9100/config

Workers re-sync progress via int-max allreduce and keep training; removed
workers detach and exit. (Host/DCN plane only — single-chip compute per
worker. On a TPU pod, pair this with reload-mode restarts so each epoch
gets a fresh ICI mesh.)
"""

import argparse

import jax
import jax.numpy as jnp
import numpy as np
import optax

from kungfu_tpu import api
from kungfu_tpu.elastic.state import ElasticState
from kungfu_tpu.models.mlp import init_mlp, mlp_loss


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--steps", type=int, default=100)
    p.add_argument("--batch", type=int, default=64)
    args = p.parse_args()

    rank = api.current_rank()
    params = init_mlp(jax.random.PRNGKey(0))
    opt = optax.sgd(0.1)
    state = opt.init(params)

    @jax.jit
    def local_step(params, state, batch):
        loss, grads = jax.value_and_grad(mlp_loss)(params, batch)
        updates, state = opt.update(grads, state, params)
        return optax.apply_updates(params, updates), state, loss

    rng = np.random.default_rng(rank)
    es = ElasticState(max_progress=args.steps)
    while not es.stopped():
        with es.scope():
            x = jnp.asarray(rng.normal(size=(args.batch, 784)), jnp.float32)
            y = jnp.asarray(rng.integers(0, 10, args.batch))
            params, state, loss = local_step(params, state, (x, y))
            # average the models across the (possibly just-resized) cluster
            flat = np.concatenate(
                [np.ravel(np.asarray(l, np.float32)) for l in jax.tree.leaves(params)]
            )
            avg = api.all_reduce_array(flat, name="model-avg") / api.cluster_size()
            leaves = jax.tree.leaves(params)
            out, off = [], 0
            for l in leaves:
                out.append(jnp.asarray(avg[off:off + l.size].reshape(l.shape)))
                off += l.size
            params = jax.tree.unflatten(jax.tree.structure(params), out)
            if rank == 0 and es.progress % 20 == 0:
                print(f"step {es.progress}: loss {float(loss):.4f} np={api.cluster_size()}")
            es.end(1)
    print(f"rank {rank}: {es.stop_reason} at progress {es.progress}")


if __name__ == "__main__":
    main()
