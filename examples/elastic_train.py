"""Elastic training example: resizable MLP training with ElasticState.

Parity: /root/reference/examples (elastic estimator examples) — run:

  kfrun -np 2 -H 127.0.0.1:4 -w -builtin-config-port 9100 \\
      python examples/elastic_train.py

then grow/shrink the cluster from another terminal:

  curl -X PUT -d '{"Runners": ["127.0.0.1:38080"], "Workers": \\
      ["127.0.0.1:38000","127.0.0.1:38001","127.0.0.1:38002"]}' \\
      http://127.0.0.1:9100/config

Synchronous data parallelism on the HOST plane: gradients are averaged
across the (possibly just-resized) cluster every step; joining workers
inherit rank-0's live params + optimizer state via the ElasticState
re-sync broadcast (no per-step model averaging, no fresh-init
contamination). The elastic dataset resumes from the synced progress so
no sample is skipped or double-trained across resizes.

On a TPU pod, run with -elastic-mode reload and initialize_device_plane()
so each membership epoch gets a fresh ICI mesh; the ElasticState /
dataset logic is identical (see tests/integration/reload_agent.py).
"""

import argparse

import jax
import jax.numpy as jnp
import numpy as np
import optax

from kungfu_tpu import api
from kungfu_tpu.elastic import ElasticDataset, ElasticState
from kungfu_tpu.models.mlp import init_mlp, mlp_loss
from kungfu_tpu.ops.collective import fuse_pytree


def synthetic_mnist(n=4096, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, 784)).astype(np.float32)
    w = np.random.default_rng(seed + 1).normal(size=(784, 10)).astype(np.float32)
    y = np.argmax(x @ w, axis=1)
    return x, y


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--samples", type=int, default=20_000)
    p.add_argument("--batch", type=int, default=64)
    p.add_argument("--lr", type=float, default=0.5)
    args = p.parse_args()

    x, y = synthetic_mnist()
    ds = ElasticDataset([x, y], args.batch, seed=1)
    params = init_mlp(jax.random.PRNGKey(0))
    opt = optax.sgd(args.lr)
    opt_state = opt.init(params)

    @jax.jit
    def grads_fn(params, batch):
        return jax.value_and_grad(mlp_loss)(params, batch)

    @jax.jit
    def apply_fn(params, opt_state, grads):
        updates, opt_state = opt.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state

    state = {"params": params, "opt": opt_state}
    es = ElasticState(max_progress=args.samples)
    es.register_state(lambda: state, lambda t: state.update(t))

    while not es.stopped():
        with es.scope():
            rank, size = api.current_rank(), api.cluster_size()
            xb, yb = ds.batch_at(es.progress, rank, size)
            loss, grads = grads_fn(state["params"], (jnp.asarray(xb), jnp.asarray(yb)))
            # S-SGD: average GRADIENTS across the cluster (host/DCN plane)
            fused, unflatten = fuse_pytree(grads)
            flat = np.asarray(fused, np.float32)
            avg = api.all_reduce_array(flat, name=f"g{es.progress}") / size
            state["params"], state["opt"] = apply_fn(
                state["params"], state["opt"], unflatten(avg)
            )
            if rank == 0 and (es.progress // ds.cluster_delta(size)) % 20 == 0:
                print(f"progress {es.progress}: loss {float(loss):.4f} np={size}")
            es.end(ds.cluster_delta(size))
    print(f"rank {api.current_rank()}: {es.stop_reason} at progress {es.progress}")


if __name__ == "__main__":
    main()
