"""MNIST SLP with SynchronousSGD — the minimum end-to-end example.

Parity: /root/reference/examples/tf2_mnist_gradient_tape.py — wrap the
optimizer, broadcast initial weights, train data-parallel. Run it:

  python examples/mnist_slp.py                       # single process, all local devices
  kfrun -np 4 python examples/mnist_slp.py           # 4-process host cluster (CPU)

Uses synthetic MNIST-shaped data by default (this environment has no
dataset egress); pass ``--data <dir>`` with the standard idx[.gz] files to
train on real MNIST (kungfu_tpu.datasets.load_mnist).
"""

import argparse

import jax
import jax.numpy as jnp
import numpy as np
import optax

from kungfu_tpu.initializer import broadcast_variables
from kungfu_tpu.models.mlp import init_mlp, mlp_apply, mlp_loss
from kungfu_tpu.optimizers import synchronous_sgd
from kungfu_tpu.parallel import make_mesh, make_train_step
from kungfu_tpu.parallel.dp import replicate, shard_batch


def synthetic_mnist(n=8192, seed=0):
    key = jax.random.PRNGKey(seed)
    x = jax.random.normal(key, (n, 784)) * 0.5
    w = jax.random.normal(jax.random.PRNGKey(seed + 1), (784, 10))
    y = jnp.argmax(x @ w, axis=1)
    return np.asarray(x), np.asarray(y)


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--epochs", type=int, default=3)
    p.add_argument("--batch", type=int, default=512)
    p.add_argument("--lr", type=float, default=0.5)
    p.add_argument("--data", default="",
                   help="directory with the 4 MNIST idx[.gz] files; "
                        "synthetic data when omitted")
    args = p.parse_args()

    mesh = make_mesh()  # all local devices on 'dp'
    ndev = mesh.devices.size
    batch = (args.batch // ndev) * ndev or ndev

    if args.data:
        from kungfu_tpu.datasets import load_mnist

        d = load_mnist(args.data)
        x, y = d["train_images"], d["train_labels"]
    else:
        x, y = synthetic_mnist()
    params = broadcast_variables(init_mlp(jax.random.PRNGKey(42)), mesh)
    opt = synchronous_sgd(optax.sgd(args.lr), "dp")
    state = replicate(opt.init(jax.device_get(params)), mesh)
    step = make_train_step(mlp_loss, opt, mesh, "dp", donate=False)

    for epoch in range(args.epochs):
        perm = np.random.default_rng(epoch).permutation(len(x))
        losses = []
        for i in range(0, len(x) - batch + 1, batch):
            idx = perm[i:i + batch]
            b = shard_batch((jnp.asarray(x[idx]), jnp.asarray(y[idx])), mesh)
            params, state, loss = step(params, state, b)
            losses.append(float(loss))
        logits = mlp_apply(jax.device_get(params), jnp.asarray(x))
        acc = float(jnp.mean(jnp.argmax(logits, axis=1) == jnp.asarray(y)))
        print(f"epoch {epoch}: loss {np.mean(losses):.4f} acc {acc:.2%} ({ndev} devices)")


if __name__ == "__main__":
    main()
