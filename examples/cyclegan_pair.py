"""CycleGAN with PairAveraging: asynchronous decentralized GAN training
(BASELINE config 4 — "CycleGAN PairAveragingOptimizer async peer-to-peer
request_model").

A miniature cycle-consistency GAN on synthetic 2-D point clouds (domain X
= a Gaussian blob, domain Y = the blob rotated and shifted): generators
G: X->Y and F: Y->X plus least-squares discriminators, trained with
simultaneous gradients under the AD-PSGD PairAveraging driver — every
step each worker averages its whole parameter set 0.5/0.5 with a random
peer's published model (versioned p2p store, background prefetch) and
applies its local gradients. No global barrier: workers run at their own
pace, exactly the reference's CycleGAN setup. Run:

  kfrun -np 2 -H 127.0.0.1:2 python3 examples/cyclegan_pair.py
"""

import argparse

import jax
import jax.numpy as jnp
import numpy as np
import optax


def mlp_init(key, sizes):
    ks = jax.random.split(key, len(sizes) - 1)
    return [
        {
            "w": jax.random.normal(k, (a, b)) * (1.0 / np.sqrt(a)),
            "b": jnp.zeros((b,)),
        }
        for k, a, b in zip(ks, sizes[:-1], sizes[1:])
    ]


def mlp_apply(layers, x):
    for i, l in enumerate(layers):
        x = x @ l["w"] + l["b"]
        if i + 1 < len(layers):
            x = jax.nn.tanh(x)
    return x


def sample_x(rng, n):
    return jnp.asarray(rng.normal(size=(n, 2)), jnp.float32)


def sample_y(rng, n):
    x = rng.normal(size=(n, 2))
    rot = np.array([[0.0, -1.0], [1.0, 0.0]])
    return jnp.asarray(x @ rot + np.array([2.0, 1.0]), jnp.float32)


def losses(params, xb, yb):
    g, f, dx, dy = params["g"], params["f"], params["dx"], params["dy"]
    fake_y = mlp_apply(g, xb)
    fake_x = mlp_apply(f, yb)
    cyc_x = mlp_apply(f, fake_y)
    cyc_y = mlp_apply(g, fake_x)
    # least-squares GAN objectives
    d_loss = (
        jnp.mean((mlp_apply(dy, yb) - 1) ** 2)
        + jnp.mean(mlp_apply(dy, jax.lax.stop_gradient(fake_y)) ** 2)
        + jnp.mean((mlp_apply(dx, xb) - 1) ** 2)
        + jnp.mean(mlp_apply(dx, jax.lax.stop_gradient(fake_x)) ** 2)
    )
    g_loss = (
        jnp.mean((mlp_apply(dy, fake_y) - 1) ** 2)
        + jnp.mean((mlp_apply(dx, fake_x) - 1) ** 2)
        + 10.0 * (jnp.mean((cyc_x - xb) ** 2) + jnp.mean((cyc_y - yb) ** 2))
    )
    return g_loss, d_loss


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--steps", type=int, default=900)
    p.add_argument("--batch", type=int, default=128)
    p.add_argument("--platform", default="cpu",
                   help="jax platform per worker; colocated workers must "
                        "not fight over one chip ('' = backend default)")
    args = p.parse_args()
    if args.platform:
        jax.config.update("jax_platforms", args.platform)

    from kungfu_tpu import api
    from kungfu_tpu.optimizers.pair_averaging import PairAveraging

    rank = api.current_rank()
    key = jax.random.PRNGKey(0)  # same init everywhere
    kg, kf, kdx, kdy = jax.random.split(key, 4)
    params = {
        "g": mlp_init(kg, [2, 32, 2]),
        "f": mlp_init(kf, [2, 32, 2]),
        "dx": mlp_init(kdx, [2, 32, 1]),
        "dy": mlp_init(kdy, [2, 32, 1]),
    }

    @jax.jit
    def grads_fn(params, xb, yb):
        gl, g_gen = jax.value_and_grad(
            lambda p: losses(p, xb, yb)[0]
        )(params)
        dl, g_disc = jax.value_and_grad(
            lambda p: losses(p, xb, yb)[1]
        )(params)
        # simultaneous gradients: generator groups from the gen loss,
        # discriminator groups from the disc loss
        grads = {
            "g": g_gen["g"], "f": g_gen["f"],
            "dx": g_disc["dx"], "dy": g_disc["dy"],
        }
        return grads, gl, dl

    pa = PairAveraging(optax.adam(2e-3), name="cyclegan")
    opt_state = pa.init(params)
    rng = np.random.default_rng(100 + rank)  # different data per worker

    for step in range(args.steps):
        xb, yb = sample_x(rng, args.batch), sample_y(rng, args.batch)
        grads, gl, dl = grads_fn(params, xb, yb)
        params, opt_state = pa.step(params, opt_state, grads)
        if rank == 0 and step % 50 == 49:
            print(f"step {step}: g_loss {float(gl):.3f} d_loss {float(dl):.3f}",
                  flush=True)

    # quality probe: G should map the X blob near the Y blob's center.
    # Barrier BEFORE the assert: a rank failing the probe must not leave
    # peers wedged inside the barrier
    api.run_barrier()
    probe = sample_x(np.random.default_rng(9), 512)
    center = np.asarray(jnp.mean(mlp_apply(params["g"], probe), axis=0))
    err = float(np.linalg.norm(center - np.array([2.0, 1.0])))
    print(f"rank {rank}: G(X) center {center.round(2)} err {err:.2f}", flush=True)
    assert err < 1.0, f"generator failed to reach domain Y: {err}"
    print(f"rank {rank}: cyclegan pair-averaging OK", flush=True)


if __name__ == "__main__":
    main()
