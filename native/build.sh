#!/bin/sh
# Build the native host-side kernels into kungfu_tpu/base/.
# reduce.cpp carries the SIMD reduce (kf_transform2/_n) AND the wire
# codec (kf_encode_wire/kf_decode_wire/kf_decode_accumulate); a stale
# .so missing the newer symbols degrades gracefully to the numpy paths
# via the guarded ctypes loader (base/_native_reduce.py — asserted by
# tests/test_wire_codec.py).
#
# Usage: native/build.sh [CXX]           release .so + pdeathsig shim
#        native/build.sh --tsan [CXX]    ThreadSanitizer concurrency
#                                        smoke (sanitizer_smoke.cpp +
#                                        reduce.cpp), built into
#                                        native/build/ and RUN
#        native/build.sh --ubsan [CXX]   same under UBSan
#
# The sanitizer targets are the correctness gate ISSUE 7 added for the
# codec kernels (the engine calls them concurrently from pool threads on
# disjoint segments); tests/test_native_sanitizers.py invokes them
# behind a compiler-capability skip. See docs/devtools.md.
set -e
cd "$(dirname "$0")"

MODE=build
case "${1:-}" in
  --tsan) MODE=tsan; shift ;;
  --ubsan) MODE=ubsan; shift ;;
esac
CXX=${1:-g++}

if [ "$MODE" = tsan ] || [ "$MODE" = ubsan ]; then
  mkdir -p build
  if [ "$MODE" = tsan ]; then
    SAN="-fsanitize=thread"
    BIN=build/kf_tsan_smoke
  else
    SAN="-fsanitize=undefined -fno-sanitize-recover=undefined"
    BIN=build/kf_ubsan_smoke
  fi
  # -O1 keeps the sanitizer's shadow instrumentation honest (-O3 can
  # elide the very accesses under test); -march=native so the F16C bulk
  # paths are the ones exercised when the host has them
  $CXX $SAN -O1 -g -march=native -std=c++17 \
      -o "$BIN" sanitizer_smoke.cpp reduce.cpp -lpthread
  echo "built $BIN"
  # any reported race/UB exits nonzero (the harness itself exits 0)
  TSAN_OPTIONS="exitcode=66 halt_on_error=1" \
  UBSAN_OPTIONS="halt_on_error=1" "./$BIN"
  exit 0
fi

OUT=../kungfu_tpu/base/libkfnative.so
$CXX -O3 -march=native -shared -fPIC -std=c++17 -o "$OUT" reduce.cpp mst.cpp io_pump.cpp
echo "built $OUT"
# exec shim arming PR_SET_PDEATHSIG for spawned workers (Linux only)
if [ "$(uname -s)" = "Linux" ]; then
    SHIM=../kungfu_tpu/runner/kf-pdeathsig
    $CXX -O2 -o "$SHIM" pdeathsig.c
    echo "built $SHIM"
fi
