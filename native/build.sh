#!/bin/sh
# Build the native host-side kernels into kungfu_tpu/base/.
# Usage: native/build.sh [CXX]
set -e
cd "$(dirname "$0")"
CXX=${1:-g++}
OUT=../kungfu_tpu/base/libkfnative.so
$CXX -O3 -march=native -shared -fPIC -std=c++17 -o "$OUT" reduce.cpp mst.cpp io_pump.cpp
echo "built $OUT"
# exec shim arming PR_SET_PDEATHSIG for spawned workers (Linux only)
if [ "$(uname -s)" = "Linux" ]; then
    SHIM=../kungfu_tpu/runner/kf-pdeathsig
    $CXX -O2 -o "$SHIM" pdeathsig.c
    echo "built $SHIM"
fi
