#!/bin/sh
# Build the native host-side kernels into kungfu_tpu/base/.
# reduce.cpp carries the SIMD reduce (kf_transform2/_n) AND the wire
# codec (kf_encode_wire/kf_decode_wire/kf_decode_accumulate); a stale
# .so missing the newer symbols degrades gracefully to the numpy paths
# via the guarded ctypes loader (base/_native_reduce.py — asserted by
# tests/test_wire_codec.py).
# Usage: native/build.sh [CXX]
set -e
cd "$(dirname "$0")"
CXX=${1:-g++}
OUT=../kungfu_tpu/base/libkfnative.so
$CXX -O3 -march=native -shared -fPIC -std=c++17 -o "$OUT" reduce.cpp mst.cpp io_pump.cpp
echo "built $OUT"
# exec shim arming PR_SET_PDEATHSIG for spawned workers (Linux only)
if [ "$(uname -s)" = "Linux" ]; then
    SHIM=../kungfu_tpu/runner/kf-pdeathsig
    $CXX -O2 -o "$SHIM" pdeathsig.c
    echo "built $SHIM"
fi
