/* kf-pdeathsig: exec shim arming PR_SET_PDEATHSIG before the worker runs.
 *
 * Orphan protection for spawned workers: a hard-killed runner (SIGKILL,
 * OOM) never reaches its cleanup, and calling prctl from a Python
 * preexec_fn is unsafe in a threaded runner (the forked child can
 * deadlock on locks held by threads that no longer exist). A fresh
 * single-threaded C process has no such hazard: arm the death signal,
 * re-check the parent is still alive (the arm is useless if the runner
 * died during our exec), then become the worker via execvp. The setting
 * survives execvp.
 *
 * Usage: kf-pdeathsig <cmd> [args...]
 */
#include <signal.h>
#include <stdio.h>
#include <stdlib.h>
#include <sys/prctl.h>
#include <unistd.h>

int main(int argc, char **argv) {
    if (argc < 2) {
        fprintf(stderr, "usage: kf-pdeathsig <cmd> [args...]\n");
        return 2;
    }
    prctl(PR_SET_PDEATHSIG, SIGTERM);
    /* Died-before-arm race: compare against the EXPLICIT runner pid
     * (KF_RUNNER_PID, set by WorkerProc). A getppid()==1 heuristic would
     * misfire when the runner itself is PID 1 (container entrypoint) or
     * under a subreaper. No env -> skip the check; the arm alone still
     * protects every later death. */
    const char *rp = getenv("KF_RUNNER_PID");
    if (rp && atoi(rp) > 0 && getppid() != atoi(rp)) {
        return 0; /* runner died before the arm: don't start an orphan */
    }
    execvp(argv[1], &argv[1]);
    perror("kf-pdeathsig: execvp");
    return 127;
}
