// Host-side SIMD reduction kernel for the DCN collective engine.
//
// Capability parity: the reference's std_transform_2 dispatch
// (srcs/go/kungfu/base/op.cpp) with F16C-accelerated float16
// (srcs/go/kungfu/base/f16.c). Used by kungfu_tpu.base.ops.transform2 via
// ctypes; auto-vectorized by -O3 -march=native (bf16 handled as widened
// float ops — no AVX512-BF16 assumption).
//
// ABI: kf_transform2(dst, x, y, count, dtype, op) -> 0 ok / -1 unsupported.
// dtype codes match kungfu_tpu.base.dtype.DType; op codes ReduceOp.

#include <cstdint>
#include <cstddef>

namespace {

enum DTypeCode : int32_t {
  U8 = 1, I8 = 2, I16 = 3, I32 = 4, I64 = 5,
  U16 = 6, U32 = 7, U64 = 8,
  F16 = 9, BF16 = 10, F32 = 11, F64 = 12,
};

enum OpCode : int32_t { SUM = 0, MIN = 1, MAX = 2, PROD = 3 };

template <typename T, typename Op>
void apply(T *dst, const T *x, const T *y, size_t n, Op op) {
  for (size_t i = 0; i < n; ++i) dst[i] = op(x[i], y[i]);
}

template <typename T>
int run(T *dst, const T *x, const T *y, size_t n, int32_t op) {
  switch (op) {
    case SUM:  apply(dst, x, y, n, [](T a, T b) { return static_cast<T>(a + b); }); return 0;
    case MIN:  apply(dst, x, y, n, [](T a, T b) { return a < b ? a : b; }); return 0;
    case MAX:  apply(dst, x, y, n, [](T a, T b) { return a > b ? a : b; }); return 0;
    case PROD: apply(dst, x, y, n, [](T a, T b) { return static_cast<T>(a * b); }); return 0;
  }
  return -1;
}

// --- 16-bit float formats, widened to f32 lane-wise --------------------

inline float half_to_float(uint16_t h) {
  uint32_t sign = (uint32_t)(h >> 15) << 31;
  uint32_t exp = (h >> 10) & 0x1f;
  uint32_t man = h & 0x3ff;
  uint32_t bits;
  if (exp == 0) {
    if (man == 0) {
      bits = sign;
    } else {  // subnormal
      exp = 127 - 15 + 1;
      while (!(man & 0x400)) { man <<= 1; --exp; }
      man &= 0x3ff;
      bits = sign | (exp << 23) | (man << 13);
    }
  } else if (exp == 0x1f) {
    bits = sign | 0x7f800000u | (man << 13);
  } else {
    bits = sign | ((exp - 15 + 127) << 23) | (man << 13);
  }
  float out;
  __builtin_memcpy(&out, &bits, 4);
  return out;
}

inline uint16_t float_to_half(float f) {
  uint32_t bits;
  __builtin_memcpy(&bits, &f, 4);
  uint16_t sign = (uint16_t)((bits >> 16) & 0x8000u);
  int32_t exp = (int32_t)((bits >> 23) & 0xff) - 127 + 15;
  uint32_t man = bits & 0x7fffffu;
  if (exp >= 0x1f) return (uint16_t)(sign | 0x7c00u | ((((bits >> 23) & 0xff) == 0xff && man) ? 0x200 : 0));
  if (exp <= 0) {
    if (exp < -10) return sign;
    man |= 0x800000u;
    uint32_t shift = (uint32_t)(14 - exp);
    uint16_t h = (uint16_t)(sign | (man >> shift));
    if ((man >> (shift - 1)) & 1) h = (uint16_t)(h + 1);  // round-to-nearest
    return h;
  }
  uint16_t h = (uint16_t)(sign | ((uint32_t)exp << 10) | (man >> 13));
  if (man & 0x1000u) h = (uint16_t)(h + 1);
  return h;
}

inline float bf16_to_float(uint16_t b) {
  uint32_t bits = (uint32_t)b << 16;
  float out;
  __builtin_memcpy(&out, &bits, 4);
  return out;
}

inline uint16_t float_to_bf16(float f) {
  uint32_t bits;
  __builtin_memcpy(&bits, &f, 4);
  // round-to-nearest-even
  uint32_t rounding = 0x7fffu + ((bits >> 16) & 1);
  return (uint16_t)((bits + rounding) >> 16);
}

template <float (*Load)(uint16_t), uint16_t (*Store)(float)>
int run16(uint16_t *dst, const uint16_t *x, const uint16_t *y, size_t n, int32_t op) {
  switch (op) {
    case SUM:
      for (size_t i = 0; i < n; ++i) dst[i] = Store(Load(x[i]) + Load(y[i]));
      return 0;
    case MIN:
      for (size_t i = 0; i < n; ++i) {
        float a = Load(x[i]), b = Load(y[i]);
        dst[i] = Store(a < b ? a : b);
      }
      return 0;
    case MAX:
      for (size_t i = 0; i < n; ++i) {
        float a = Load(x[i]), b = Load(y[i]);
        dst[i] = Store(a > b ? a : b);
      }
      return 0;
    case PROD:
      for (size_t i = 0; i < n; ++i) dst[i] = Store(Load(x[i]) * Load(y[i]));
      return 0;
  }
  return -1;
}

}  // namespace

extern "C" int kf_transform2(void *dst, const void *x, const void *y,
                             int64_t count, int32_t dtype, int32_t op) {
  size_t n = (size_t)count;
  switch (dtype) {
    case U8:  return run((uint8_t *)dst, (const uint8_t *)x, (const uint8_t *)y, n, op);
    case I8:  return run((int8_t *)dst, (const int8_t *)x, (const int8_t *)y, n, op);
    case I16: return run((int16_t *)dst, (const int16_t *)x, (const int16_t *)y, n, op);
    case I32: return run((int32_t *)dst, (const int32_t *)x, (const int32_t *)y, n, op);
    case I64: return run((int64_t *)dst, (const int64_t *)x, (const int64_t *)y, n, op);
    case U16: return run((uint16_t *)dst, (const uint16_t *)x, (const uint16_t *)y, n, op);
    case U32: return run((uint32_t *)dst, (const uint32_t *)x, (const uint32_t *)y, n, op);
    case U64: return run((uint64_t *)dst, (const uint64_t *)x, (const uint64_t *)y, n, op);
    case F16: return run16<half_to_float, float_to_half>(
        (uint16_t *)dst, (const uint16_t *)x, (const uint16_t *)y, n, op);
    case BF16: return run16<bf16_to_float, float_to_bf16>(
        (uint16_t *)dst, (const uint16_t *)x, (const uint16_t *)y, n, op);
    case F32: return run((float *)dst, (const float *)x, (const float *)y, n, op);
    case F64: return run((double *)dst, (const double *)x, (const double *)y, n, op);
  }
  return -1;
}

// N-ary single-pass reduce: dst = srcs[0] op srcs[1] op ... op srcs[k-1].
// A STAR root receiving k-1 peers otherwise runs k-1 pairwise passes over
// dst (3x the memory traffic at np=4); one fused pass keeps the
// accumulator in registers. dst must not alias any src.
namespace {

template <typename T, typename Op>
int run_n(T *dst, const T *const *srcs, int32_t k, size_t n, Op op) {
  for (size_t i = 0; i < n; ++i) {
    T acc = srcs[0][i];
    for (int32_t j = 1; j < k; ++j) acc = op(acc, srcs[j][i]);
    dst[i] = acc;
  }
  return 0;
}

template <typename T>
int dispatch_n(T *dst, const T *const *srcs, int32_t k, size_t n, int32_t op) {
  switch (op) {
    case SUM:  return run_n(dst, srcs, k, n, [](T a, T b) { return static_cast<T>(a + b); });
    case MIN:  return run_n(dst, srcs, k, n, [](T a, T b) { return a < b ? a : b; });
    case MAX:  return run_n(dst, srcs, k, n, [](T a, T b) { return a > b ? a : b; });
    case PROD: return run_n(dst, srcs, k, n, [](T a, T b) { return static_cast<T>(a * b); });
  }
  return -1;
}

template <float (*Load)(uint16_t), uint16_t (*Store)(float)>
int dispatch_n16(uint16_t *dst, const uint16_t *const *srcs, int32_t k,
                 size_t n, int32_t op) {
  switch (op) {
    case SUM:
      for (size_t i = 0; i < n; ++i) {
        float acc = Load(srcs[0][i]);
        for (int32_t j = 1; j < k; ++j) acc += Load(srcs[j][i]);
        dst[i] = Store(acc);
      }
      return 0;
    case MIN:
      for (size_t i = 0; i < n; ++i) {
        float acc = Load(srcs[0][i]);
        for (int32_t j = 1; j < k; ++j) {
          float b = Load(srcs[j][i]);
          acc = acc < b ? acc : b;
        }
        dst[i] = Store(acc);
      }
      return 0;
    case MAX:
      for (size_t i = 0; i < n; ++i) {
        float acc = Load(srcs[0][i]);
        for (int32_t j = 1; j < k; ++j) {
          float b = Load(srcs[j][i]);
          acc = acc > b ? acc : b;
        }
        dst[i] = Store(acc);
      }
      return 0;
    case PROD:
      for (size_t i = 0; i < n; ++i) {
        float acc = Load(srcs[0][i]);
        for (int32_t j = 1; j < k; ++j) acc *= Load(srcs[j][i]);
        dst[i] = Store(acc);
      }
      return 0;
  }
  return -1;
}

}  // namespace

extern "C" int kf_transform_n(void *dst, const void *const *srcs, int32_t k,
                              int64_t count, int32_t dtype, int32_t op) {
  if (k < 1) return -1;
  size_t n = (size_t)count;
  switch (dtype) {
    case U8:  return dispatch_n((uint8_t *)dst, (const uint8_t *const *)srcs, k, n, op);
    case I8:  return dispatch_n((int8_t *)dst, (const int8_t *const *)srcs, k, n, op);
    case I16: return dispatch_n((int16_t *)dst, (const int16_t *const *)srcs, k, n, op);
    case I32: return dispatch_n((int32_t *)dst, (const int32_t *const *)srcs, k, n, op);
    case I64: return dispatch_n((int64_t *)dst, (const int64_t *const *)srcs, k, n, op);
    case U16: return dispatch_n((uint16_t *)dst, (const uint16_t *const *)srcs, k, n, op);
    case U32: return dispatch_n((uint32_t *)dst, (const uint32_t *const *)srcs, k, n, op);
    case U64: return dispatch_n((uint64_t *)dst, (const uint64_t *const *)srcs, k, n, op);
    case F16: return dispatch_n16<half_to_float, float_to_half>(
        (uint16_t *)dst, (const uint16_t *const *)srcs, k, n, op);
    case BF16: return dispatch_n16<bf16_to_float, float_to_bf16>(
        (uint16_t *)dst, (const uint16_t *const *)srcs, k, n, op);
    case F32: return dispatch_n((float *)dst, (const float *const *)srcs, k, n, op);
    case F64: return dispatch_n((double *)dst, (const double *const *)srcs, k, n, op);
  }
  return -1;
}

// --- wire codec (compressed host-plane collectives) --------------------
//
// f32 workspaces travel the wire as bf16/f16 while every reduce step
// accumulates into an f32 buffer, so rounding stays one quantization
// deep per transmitted value instead of compounding in 16-bit storage.
// kf_encode_wire / kf_decode_wire are the bulk converters; the fused
// kf_decode_accumulate does decode + reduce in one pass over the
// segment (the per-step hot path of the segmented ring walk).
//
// Rounding contract: both encoders round to nearest-even, bit-matching
// numpy's f32->f16 astype and the (bits + 0x7fff + lsb) >> 16 bf16
// fold, so the numpy fallback in base/ops.py is a drop-in replacement
// (asserted by the codec parity tests).

namespace {

// f32 -> f16 with round-to-nearest-even across normals, subnormals and
// overflow (the existing float_to_half rounds half-up; the codec must
// match numpy astype exactly). Subnormal rounding rides an exponent-
// aligning float add: adding 2^-14 forces the result's ulp to the f16
// subnormal spacing, so the hardware's RNE does the rounding for us.
inline uint16_t f32_to_f16_rne(float ff) {
  uint32_t f;
  __builtin_memcpy(&f, &ff, 4);
  const uint32_t sign = f & 0x80000000u;
  f ^= sign;
  uint16_t out;
  if (f >= 0x7f800000u) {  // inf / nan
    out = (f > 0x7f800000u) ? (uint16_t)(0x7e00u | ((f >> 13) & 0x3ffu))
                            : (uint16_t)0x7c00u;
  } else if (f >= ((127u + 16u) << 23)) {  // >= 2^16: overflow to inf
    out = 0x7c00u;
  } else if (f < (113u << 23)) {  // < 2^-14: f16 subnormal or zero
    // align-to-ulp trick: 0.5f's f32 ulp (2^-24) IS the f16 subnormal
    // spacing, so adding it makes the hardware's RNE round the mantissa
    // to subnormal precision; the bits of (sum - 0.5f) are the mantissa
    const uint32_t magic = 126u << 23;  // 0.5f
    float tmp, magicf;
    __builtin_memcpy(&tmp, &f, 4);
    __builtin_memcpy(&magicf, &magic, 4);
    tmp += magicf;
    uint32_t t;
    __builtin_memcpy(&t, &tmp, 4);
    out = (uint16_t)(t - magic);
  } else {  // normal range: rebias exponent, RNE on bit 13
    const uint32_t mant_odd = (f >> 13) & 1u;
    f += ((uint32_t)(15 - 127) << 23) + 0xfffu + mant_odd;
    out = (uint16_t)(f >> 13);
  }
  return (uint16_t)(out | (sign >> 16));
}

template <float (*Load)(uint16_t)>
int decode_acc(float *acc, const uint16_t *src, size_t n, int32_t op) {
  switch (op) {
    case SUM:
      for (size_t i = 0; i < n; ++i) acc[i] += Load(src[i]);
      return 0;
    case MIN:
      for (size_t i = 0; i < n; ++i) {
        float b = Load(src[i]);
        acc[i] = acc[i] < b ? acc[i] : b;
      }
      return 0;
    case MAX:
      for (size_t i = 0; i < n; ++i) {
        float b = Load(src[i]);
        acc[i] = acc[i] > b ? acc[i] : b;
      }
      return 0;
    case PROD:
      for (size_t i = 0; i < n; ++i) acc[i] *= Load(src[i]);
      return 0;
  }
  return -1;
}

}  // namespace

// F16C fast paths: the scalar f16 converters are branchy (subnormal
// normalization loops) and defeat auto-vectorization — measured 2x
// SLOWER end-to-end than uncompressed on the bench box, where bf16's
// branchless integer fold vectorizes fine. vcvtps2ph/vcvtph2ps do the
// full IEEE round-to-nearest-even conversion (subnormals, overflow) in
// hardware, bit-matching numpy's astype — the same lever the reference
// pulls in srcs/go/kungfu/base/f16.c. Scalar tails + non-F16C builds
// keep the exact-RNE scalar fallbacks.
#if defined(__F16C__)
#include <immintrin.h>
#endif

namespace {

#if defined(__F16C__)
inline void encode_f16_bulk(uint16_t *d, const float *s, size_t n) {
  size_t i = 0;
  for (; i + 8 <= n; i += 8) {
    __m128i h = _mm256_cvtps_ph(_mm256_loadu_ps(s + i),
                                _MM_FROUND_TO_NEAREST_INT | _MM_FROUND_NO_EXC);
    _mm_storeu_si128((__m128i *)(d + i), h);
  }
  for (; i < n; ++i) d[i] = f32_to_f16_rne(s[i]);
}

inline void decode_f16_bulk(float *d, const uint16_t *s, size_t n) {
  size_t i = 0;
  for (; i + 8 <= n; i += 8) {
    _mm256_storeu_ps(d + i,
                     _mm256_cvtph_ps(_mm_loadu_si128((const __m128i *)(s + i))));
  }
  for (; i < n; ++i) d[i] = half_to_float(s[i]);
}

template <typename VOp, typename SOp>
int decode_acc_f16(float *a, const uint16_t *s, size_t n, VOp vop, SOp sop) {
  size_t i = 0;
  for (; i + 8 <= n; i += 8) {
    __m256 inc = _mm256_cvtph_ps(_mm_loadu_si128((const __m128i *)(s + i)));
    _mm256_storeu_ps(a + i, vop(_mm256_loadu_ps(a + i), inc));
  }
  for (; i < n; ++i) a[i] = sop(a[i], half_to_float(s[i]));
  return 0;
}
#endif

}  // namespace

extern "C" int kf_encode_wire(void *dst, const void *src, int64_t count,
                              int32_t wire_dtype) {
  uint16_t *d = (uint16_t *)dst;
  const float *s = (const float *)src;
  size_t n = (size_t)count;
  switch (wire_dtype) {
    case BF16:
      for (size_t i = 0; i < n; ++i) d[i] = float_to_bf16(s[i]);
      return 0;
    case F16:
#if defined(__F16C__)
      encode_f16_bulk(d, s, n);
#else
      for (size_t i = 0; i < n; ++i) d[i] = f32_to_f16_rne(s[i]);
#endif
      return 0;
  }
  return -1;
}

extern "C" int kf_decode_wire(void *dst, const void *src, int64_t count,
                              int32_t wire_dtype) {
  float *d = (float *)dst;
  const uint16_t *s = (const uint16_t *)src;
  size_t n = (size_t)count;
  switch (wire_dtype) {
    case BF16:
      for (size_t i = 0; i < n; ++i) d[i] = bf16_to_float(s[i]);
      return 0;
    case F16:
#if defined(__F16C__)
      decode_f16_bulk(d, s, n);
#else
      for (size_t i = 0; i < n; ++i) d[i] = half_to_float(s[i]);
#endif
      return 0;
  }
  return -1;
}

extern "C" int kf_decode_accumulate(void *acc, const void *src, int64_t count,
                                    int32_t wire_dtype, int32_t op) {
  float *a = (float *)acc;
  const uint16_t *s = (const uint16_t *)src;
  size_t n = (size_t)count;
  switch (wire_dtype) {
    case BF16: return decode_acc<bf16_to_float>(a, s, n, op);
    case F16:
#if defined(__F16C__)
      // NaN caveat: _mm256_min/max_ps pick the SECOND operand on NaN,
      // like the scalar a<b?a:b with NaN on either side picking b via
      // the false branch — gradients are NaN-free by contract anyway
      switch (op) {
        case SUM:
          return decode_acc_f16(a, s, n,
              [](__m256 x, __m256 y) { return _mm256_add_ps(x, y); },
              [](float x, float y) { return x + y; });
        case MIN:
          return decode_acc_f16(a, s, n,
              [](__m256 x, __m256 y) { return _mm256_min_ps(x, y); },
              [](float x, float y) { return x < y ? x : y; });
        case MAX:
          return decode_acc_f16(a, s, n,
              [](__m256 x, __m256 y) { return _mm256_max_ps(x, y); },
              [](float x, float y) { return x > y ? x : y; });
        case PROD:
          return decode_acc_f16(a, s, n,
              [](__m256 x, __m256 y) { return _mm256_mul_ps(x, y); },
              [](float x, float y) { return x * y; });
      }
      return -1;
#else
      return decode_acc<half_to_float>(a, s, n, op);
#endif
  }
  return -1;
}

// --- block-scaled int8/int4 wire codec ---------------------------------
//
// Per-block power-of-two absmax scaling: each `block`-element run of the
// f32 segment gets one f32 scale s = 2^ceil(log2(absmax / Qmax)) (Qmax =
// 127 for int8, 7 for int4), then q = clamp(rint(x * (1/s)), -Qmax, Qmax)
// packed as signed bytes (int8) or two's-complement low-nibble-first
// pairs (int4). The pow2 scale is the idempotency lever: decode s*q is
// EXACT in f32 (power of two times a small integer), and re-encoding a
// decoded block re-derives the identical s and identical q — so graph-
// walk relays and the bcast-root roundtrip stay bit-identical, the same
// contract the 16-bit codec gets for free from dtype narrowing.
//
// Layout of an encoded segment of `count` elements:
//   [ceil(count/block) f32 little-endian scales][payload]
// payload = count bytes (int8) or ceil(count/2) bytes (int4, odd count
// leaves the last high nibble zero). Scales are memcpy'd at arbitrary
// byte offsets — no alignment requirement (segments start anywhere).
//
// Rounding contract: scale derivation is fl(absmax/Qmax) -> frexp ->
// ldexp and quantization is rint (round-to-nearest-even), bit-matching
// the numpy fallback's np.frexp/np.ldexp/np.rint path in base/ops.py.

#include <cmath>

namespace {

inline float q_block_scale(const float *s, size_t n, float qmax) {
  float amax = 0.0f;
  for (size_t i = 0; i < n; ++i) {
    float a = s[i] < 0.0f ? -s[i] : s[i];
    if (a > amax) amax = a;
  }
  if (amax == 0.0f) return 0.0f;
  float t = amax / qmax;
  int e;
  float m = frexpf(t, &e);  // t = m * 2^e, m in [0.5, 1)
  return ldexpf(1.0f, m == 0.5f ? e - 1 : e);  // 2^ceil(log2(t))
}

inline int8_t q_unpack4(const uint8_t *payload, size_t i) {
  uint8_t nib = (uint8_t)((payload[i >> 1] >> ((i & 1) ? 4 : 0)) & 0xFu);
  return (int8_t)(nib >= 8u ? (int)nib - 16 : (int)nib);
}

}  // namespace

extern "C" int kf_encode_wire_q(void *dst, const void *src, int64_t count,
                                int32_t bits, int32_t block) {
  if (count < 0 || block < 1 || (bits != 8 && bits != 4)) return -1;
  const float *s = (const float *)src;
  size_t n = (size_t)count;
  size_t nb = (n + (size_t)block - 1) / (size_t)block;
  uint8_t *scales = (uint8_t *)dst;
  uint8_t *payload = scales + 4 * nb;
  const float qmax = bits == 8 ? 127.0f : 7.0f;
  for (size_t b = 0; b < nb; ++b) {
    size_t lo = b * (size_t)block;
    size_t hi = lo + (size_t)block;
    if (hi > n) hi = n;
    float scale = q_block_scale(s + lo, hi - lo, qmax);
    __builtin_memcpy(scales + 4 * b, &scale, 4);
    float inv = scale == 0.0f ? 0.0f : 1.0f / scale;  // pow2: exact
    for (size_t i = lo; i < hi; ++i) {
      float q = rintf(s[i] * inv);
      if (q > qmax) q = qmax;
      if (q < -qmax) q = -qmax;
      int8_t qi = (int8_t)q;
      if (bits == 8) {
        payload[i] = (uint8_t)qi;
      } else if (i & 1) {
        payload[i >> 1] = (uint8_t)(payload[i >> 1] | (((uint8_t)qi & 0xFu) << 4));
      } else {
        payload[i >> 1] = (uint8_t)((uint8_t)qi & 0xFu);
      }
    }
  }
  return 0;
}

extern "C" int kf_decode_wire_q(void *dst, const void *src, int64_t count,
                                int32_t bits, int32_t block) {
  if (count < 0 || block < 1 || (bits != 8 && bits != 4)) return -1;
  float *d = (float *)dst;
  size_t n = (size_t)count;
  size_t nb = (n + (size_t)block - 1) / (size_t)block;
  const uint8_t *scales = (const uint8_t *)src;
  const uint8_t *payload = scales + 4 * nb;
  for (size_t b = 0; b < nb; ++b) {
    size_t lo = b * (size_t)block;
    size_t hi = lo + (size_t)block;
    if (hi > n) hi = n;
    float scale;
    __builtin_memcpy(&scale, scales + 4 * b, 4);
    if (bits == 8) {
      for (size_t i = lo; i < hi; ++i) d[i] = scale * (float)(int8_t)payload[i];
    } else {
      for (size_t i = lo; i < hi; ++i) d[i] = scale * (float)q_unpack4(payload, i);
    }
  }
  return 0;
}

extern "C" int kf_decode_accumulate_q(void *acc, const void *src, int64_t count,
                                      int32_t bits, int32_t block, int32_t op) {
  if (count < 0 || block < 1 || (bits != 8 && bits != 4)) return -1;
  float *a = (float *)acc;
  size_t n = (size_t)count;
  size_t nb = (n + (size_t)block - 1) / (size_t)block;
  const uint8_t *scales = (const uint8_t *)src;
  const uint8_t *payload = scales + 4 * nb;
  for (size_t b = 0; b < nb; ++b) {
    size_t lo = b * (size_t)block;
    size_t hi = lo + (size_t)block;
    if (hi > n) hi = n;
    float scale;
    __builtin_memcpy(&scale, scales + 4 * b, 4);
    for (size_t i = lo; i < hi; ++i) {
      float v = scale * (float)(bits == 8 ? (int8_t)payload[i]
                                          : q_unpack4(payload, i));
      switch (op) {
        case SUM:  a[i] += v; break;
        case MIN:  a[i] = a[i] < v ? a[i] : v; break;
        case MAX:  a[i] = a[i] > v ? a[i] : v; break;
        case PROD: a[i] *= v; break;
        default: return -1;
      }
    }
  }
  return 0;
}
