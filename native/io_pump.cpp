// Native socket pump for the host (DCN) data plane.
//
// Capability parity: the reference drives its rchannel byte loops from Go
// (srcs/go/rchannel/connection/connection.go:90-146) where send/recv run
// on goroutines with no interpreter lock. Python cannot match that from
// bytecode: a framed recv of an 8 MiB chunk is ~100 recv_into() loop
// iterations, each re-acquiring the GIL under contention from every other
// transport thread on the host. These entry points run the entire framed
// send/recv in one GIL-released ctypes call.
//
// ABI (all return 0 on success, -1 on EOF, -2 on timeout, -errno on error):
//   kf_send2(fd, hdr, hdr_len, payload, payload_len, timeout_ms)
//     writev-loop the [frame header+name | payload] pair until drained.
//   kf_recv_exact(fd, buf, n, timeout_ms)
//     recv-loop exactly n bytes into buf.
//
// Sockets may be blocking or non-blocking (Python socket timeouts put the
// fd in O_NONBLOCK): EAGAIN parks in poll() honouring timeout_ms
// (timeout_ms < 0 means block forever).

#include <cerrno>
#include <cstdint>
#include <cstring>
#include <ctime>

#include <poll.h>
#include <sys/socket.h>
#include <sys/uio.h>
#include <unistd.h>

namespace {

int64_t now_ms() {
  struct timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  return (int64_t)ts.tv_sec * 1000 + ts.tv_nsec / 1000000;
}

// Wait for readiness; returns 0 ready, -2 timeout, -errno error.
int wait_fd(int fd, short events, int timeout_ms, int64_t deadline_ms) {
  struct pollfd p;
  p.fd = fd;
  p.events = events;
  for (;;) {
    int t = timeout_ms;
    if (timeout_ms >= 0) {
      int64_t left = deadline_ms - now_ms();
      if (left <= 0) return -2;
      t = (int)left;
    }
    int r = poll(&p, 1, t);
    if (r > 0) return 0;
    if (r == 0) return -2;
    if (errno == EINTR) continue;
    return -errno;
  }
}

}  // namespace

extern "C" {

int kf_send2(int fd, const void *hdr, int64_t hdr_len, const void *payload,
             int64_t payload_len, int timeout_ms) {
  int64_t deadline = timeout_ms >= 0 ? now_ms() + timeout_ms : 0;
  struct iovec iov[2];
  iov[0].iov_base = const_cast<void *>(hdr);
  iov[0].iov_len = (size_t)hdr_len;
  iov[1].iov_base = const_cast<void *>(payload);
  iov[1].iov_len = (size_t)payload_len;
  int iovcnt = payload_len > 0 ? 2 : 1;
  struct iovec *cur = iov;
  while (iovcnt > 0) {
    ssize_t n = writev(fd, cur, iovcnt);
    if (n < 0) {
      if (errno == EINTR) continue;
      if (errno == EAGAIN || errno == EWOULDBLOCK) {
        int w = wait_fd(fd, POLLOUT, timeout_ms, deadline);
        if (w != 0) return w;
        continue;
      }
      return -errno;
    }
    size_t left = (size_t)n;
    while (left > 0 && iovcnt > 0) {
      if (left >= cur->iov_len) {
        left -= cur->iov_len;
        ++cur;
        --iovcnt;
      } else {
        cur->iov_base = (char *)cur->iov_base + left;
        cur->iov_len -= left;
        left = 0;
      }
    }
  }
  return 0;
}

int kf_recv_exact(int fd, void *buf, int64_t n, int timeout_ms) {
  int64_t deadline = timeout_ms >= 0 ? now_ms() + timeout_ms : 0;
  char *p = (char *)buf;
  int64_t got = 0;
  while (got < n) {
    ssize_t r = recv(fd, p + got, (size_t)(n - got), MSG_WAITALL);
    if (r == 0) return -1;  // peer closed
    if (r < 0) {
      if (errno == EINTR) continue;
      if (errno == EAGAIN || errno == EWOULDBLOCK) {
        int w = wait_fd(fd, POLLIN, timeout_ms, deadline);
        if (w != 0) return w;
        continue;
      }
      return -errno;
    }
    got += r;
  }
  return 0;
}

}  // extern "C"
