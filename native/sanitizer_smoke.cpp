// Concurrency smoke for the wire-codec kernels, built and run under
// ThreadSanitizer / UBSan by `native/build.sh --tsan|--ubsan` (gated
// test: tests/test_native_sanitizers.py).
//
// Mirrors how the engine actually drives the kernels: the segmented
// walk's pool threads encode DISJOINT segments of one shared f32
// buffer concurrently (RS sends overlap the predecessor recv), while
// receive paths decode-accumulate into disjoint regions of a shared
// accumulator. Any data race the codec introduces on that pattern —
// a stray write outside [sb, se), hidden shared scratch state — is
// exactly what TSan exists to catch and a Python test cannot.
//
// Exit 0 = ran to completion with correct sums; the sanitizer runtime
// turns any race/UB into a nonzero exit (TSAN_OPTIONS=exitcode).

#include <cmath>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <thread>
#include <vector>

extern "C" int kf_transform2(void *dst, const void *x, const void *y,
                             int64_t count, int32_t dtype, int32_t op);
extern "C" int kf_encode_wire(void *dst, const void *src, int64_t count,
                              int32_t wire_dtype);
extern "C" int kf_decode_wire(void *dst, const void *src, int64_t count,
                              int32_t wire_dtype);
extern "C" int kf_decode_accumulate(void *acc, const void *src, int64_t count,
                                    int32_t wire_dtype, int32_t op);
extern "C" int kf_encode_wire_q(void *dst, const void *src, int64_t count,
                                int32_t bits, int32_t block);
extern "C" int kf_decode_wire_q(void *dst, const void *src, int64_t count,
                                int32_t bits, int32_t block);
extern "C" int kf_decode_accumulate_q(void *acc, const void *src, int64_t count,
                                      int32_t bits, int32_t block, int32_t op);

namespace {
constexpr int32_t F32 = 11, F16 = 9, BF16 = 10, SUM = 0;
constexpr int64_t N = 1 << 18;     // one "bucket"
constexpr int THREADS = 8;         // pool threads sharing it
constexpr int ROUNDS = 16;
constexpr int32_t QBLOCK = 16;     // KF_WIRE_BLOCK default

// encoded byte length of one n-element segment ([scales][payload],
// mirroring base/ops.py wire_nbytes_q) — each thread's segment lands in
// a DISJOINT byte window of the shared wire buffer, like the segmented
// walk's qoff prefix sums
int64_t q_nbytes(int64_t n, int32_t bits) {
  const int64_t nb = (n + QBLOCK - 1) / QBLOCK;
  return 4 * nb + (bits == 8 ? n : (n + 1) / 2);
}

int fail(const char *what) {
  std::fprintf(stderr, "sanitizer_smoke: FAILED at %s\n", what);
  return 1;
}
}  // namespace

int main() {
  const int32_t wires[] = {BF16, F16};
  std::vector<float> src(N), dec(N), acc(N), red(N);
  std::vector<uint16_t> wire(N);
  for (int64_t i = 0; i < N; ++i) src[i] = (float)(i % 128) - 64.0f;

  for (int round = 0; round < ROUNDS; ++round) {
    const int32_t wd = wires[round % 2];
    std::fill(acc.begin(), acc.end(), 1.0f);
    std::vector<std::thread> ts;
    ts.reserve(THREADS);
    for (int t = 0; t < THREADS; ++t) {
      ts.emplace_back([&, t, wd] {
        // disjoint segment of the shared buffers, like a ring step
        const int64_t sb = t * (N / THREADS);
        const int64_t se = (t + 1) * (N / THREADS);
        const int64_t n = se - sb;
        if (kf_encode_wire(wire.data() + sb, src.data() + sb, n, wd))
          std::exit(2);
        if (kf_decode_wire(dec.data() + sb, wire.data() + sb, n, wd))
          std::exit(2);
        if (kf_decode_accumulate(acc.data() + sb, wire.data() + sb, n, wd,
                                 SUM))
          std::exit(2);
        if (kf_transform2(red.data() + sb, dec.data() + sb, acc.data() + sb,
                          n, F32, SUM))
          std::exit(2);
      });
    }
    for (auto &t : ts) t.join();
    // every value in src is a small integer in [-64, 63], exactly
    // representable in bf16 AND f16, so the codec must round-trip
    // bit-exactly and the sums are exact
    for (int64_t i = 0; i < N; i += 997) {
      if (dec[i] != src[i]) return fail("decode round-trip");
      if (acc[i] != src[i] + 1.0f) return fail("decode-accumulate");
      if (red[i] != dec[i] + acc[i]) return fail("transform2");
    }
  }

  // block-scaled int8/int4 kernels (ISSUE 20), same discipline: pool
  // threads encode disjoint segments of the shared f32 buffer into
  // DISJOINT byte windows of one shared wire buffer (the walk's qoff
  // layout), then decode / decode-accumulate back into disjoint slices
  // of shared outputs. Values are chosen so the pow2 block scale is 1
  // (absmax 64 -> int8, absmax 7 -> int4) and the round-trip is exact.
  const int64_t seg = N / THREADS;
  for (int round = 0; round < ROUNDS; ++round) {
    const int32_t bits = (round % 2) ? 4 : 8;
    const int mod = bits == 8 ? 128 : 15;        // absmax 64 / 7
    const float base = bits == 8 ? 64.0f : 7.0f;
    for (int64_t i = 0; i < N; ++i) src[i] = (float)(i % mod) - base;
    std::fill(acc.begin(), acc.end(), 1.0f);
    const int64_t segb = q_nbytes(seg, bits);
    std::vector<uint8_t> qwire(THREADS * segb);
    std::vector<std::thread> ts;
    ts.reserve(THREADS);
    for (int t = 0; t < THREADS; ++t) {
      ts.emplace_back([&, t, bits, segb] {
        const int64_t sb = t * seg;
        uint8_t *w = qwire.data() + t * segb;
        if (kf_encode_wire_q(w, src.data() + sb, seg, bits, QBLOCK))
          std::exit(2);
        if (kf_decode_wire_q(dec.data() + sb, w, seg, bits, QBLOCK))
          std::exit(2);
        if (kf_decode_accumulate_q(acc.data() + sb, w, seg, bits, QBLOCK,
                                   SUM))
          std::exit(2);
      });
    }
    for (auto &t : ts) t.join();
    for (int64_t i = 0; i < N; i += 997) {
      if (dec[i] != src[i]) return fail("quantized decode round-trip");
      if (acc[i] != src[i] + 1.0f) return fail("quantized decode-accumulate");
    }
  }
  std::puts("sanitizer_smoke: ok");
  return 0;
}
