// Concurrency smoke for the wire-codec kernels, built and run under
// ThreadSanitizer / UBSan by `native/build.sh --tsan|--ubsan` (gated
// test: tests/test_native_sanitizers.py).
//
// Mirrors how the engine actually drives the kernels: the segmented
// walk's pool threads encode DISJOINT segments of one shared f32
// buffer concurrently (RS sends overlap the predecessor recv), while
// receive paths decode-accumulate into disjoint regions of a shared
// accumulator. Any data race the codec introduces on that pattern —
// a stray write outside [sb, se), hidden shared scratch state — is
// exactly what TSan exists to catch and a Python test cannot.
//
// Exit 0 = ran to completion with correct sums; the sanitizer runtime
// turns any race/UB into a nonzero exit (TSAN_OPTIONS=exitcode).

#include <cmath>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <thread>
#include <vector>

extern "C" int kf_transform2(void *dst, const void *x, const void *y,
                             int64_t count, int32_t dtype, int32_t op);
extern "C" int kf_encode_wire(void *dst, const void *src, int64_t count,
                              int32_t wire_dtype);
extern "C" int kf_decode_wire(void *dst, const void *src, int64_t count,
                              int32_t wire_dtype);
extern "C" int kf_decode_accumulate(void *acc, const void *src, int64_t count,
                                    int32_t wire_dtype, int32_t op);

namespace {
constexpr int32_t F32 = 11, F16 = 9, BF16 = 10, SUM = 0;
constexpr int64_t N = 1 << 18;     // one "bucket"
constexpr int THREADS = 8;         // pool threads sharing it
constexpr int ROUNDS = 16;

int fail(const char *what) {
  std::fprintf(stderr, "sanitizer_smoke: FAILED at %s\n", what);
  return 1;
}
}  // namespace

int main() {
  const int32_t wires[] = {BF16, F16};
  std::vector<float> src(N), dec(N), acc(N), red(N);
  std::vector<uint16_t> wire(N);
  for (int64_t i = 0; i < N; ++i) src[i] = (float)(i % 128) - 64.0f;

  for (int round = 0; round < ROUNDS; ++round) {
    const int32_t wd = wires[round % 2];
    std::fill(acc.begin(), acc.end(), 1.0f);
    std::vector<std::thread> ts;
    ts.reserve(THREADS);
    for (int t = 0; t < THREADS; ++t) {
      ts.emplace_back([&, t, wd] {
        // disjoint segment of the shared buffers, like a ring step
        const int64_t sb = t * (N / THREADS);
        const int64_t se = (t + 1) * (N / THREADS);
        const int64_t n = se - sb;
        if (kf_encode_wire(wire.data() + sb, src.data() + sb, n, wd))
          std::exit(2);
        if (kf_decode_wire(dec.data() + sb, wire.data() + sb, n, wd))
          std::exit(2);
        if (kf_decode_accumulate(acc.data() + sb, wire.data() + sb, n, wd,
                                 SUM))
          std::exit(2);
        if (kf_transform2(red.data() + sb, dec.data() + sb, acc.data() + sb,
                          n, F32, SUM))
          std::exit(2);
      });
    }
    for (auto &t : ts) t.join();
    // every value in src is a small integer in [-64, 63], exactly
    // representable in bf16 AND f16, so the codec must round-trip
    // bit-exactly and the sums are exact
    for (int64_t i = 0; i < N; i += 997) {
      if (dec[i] != src[i]) return fail("decode round-trip");
      if (acc[i] != src[i] + 1.0f) return fail("decode-accumulate");
      if (red[i] != dec[i] + acc[i]) return fail("transform2");
    }
  }
  std::puts("sanitizer_smoke: ok");
  return 0;
}
