// Prim's minimum-spanning-tree over a dense symmetric weight matrix,
// emitting a father array (forest form used by the collective engine).
//
// TPU-native role: the host control plane probes per-peer RTTs over DCN,
// allgathers them into an n x n latency matrix, and this kernel turns the
// matrix into a low-latency reduce/broadcast tree for the HOST-plane
// collectives (capability parity: the reference's MST topology
// optimization, srcs/cpp/include/kungfu/mst.hpp + the
// MinimumSpanningTree TF op). The ICI data plane needs no such tree —
// XLA's collectives already know the torus.

#include <cstddef>
#include <cstdint>
#include <limits>
#include <vector>

extern "C" {

// weights: n*n row-major, w[i*n+j] = cost(i<->j); father: out, length n.
// Node 0 is the root (father[0] == 0). Returns 0 on success.
int kf_mst(int64_t n, const double* weights, int32_t* father) {
    if (n <= 0 || weights == nullptr || father == nullptr) return 1;
    const double inf = std::numeric_limits<double>::infinity();
    std::vector<char> done(static_cast<size_t>(n), 0);
    std::vector<double> best_cost(static_cast<size_t>(n), inf);
    std::vector<int32_t> best_from(static_cast<size_t>(n), 0);

    father[0] = 0;
    done[0] = 1;
    for (int64_t j = 1; j < n; ++j) {
        best_cost[j] = weights[j];  // row 0
        best_from[j] = 0;
    }
    for (int64_t added = 1; added < n; ++added) {
        int64_t pick = -1;
        for (int64_t j = 0; j < n; ++j) {
            if (!done[j] && (pick < 0 || best_cost[j] < best_cost[pick])) pick = j;
        }
        if (pick < 0 || !(best_cost[pick] < inf)) return 2;  // disconnected
        done[pick] = 1;
        father[pick] = best_from[pick];
        const double* row = weights + pick * n;
        for (int64_t j = 0; j < n; ++j) {
            if (!done[j] && row[j] < best_cost[j]) {
                best_cost[j] = row[j];
                best_from[j] = static_cast<int32_t>(pick);
            }
        }
    }
    return 0;
}

}  // extern "C"
