"""GNS estimator tests (on-device, 8-dev CPU mesh)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from kungfu_tpu.monitor.noise_scale import (
    gns_init,
    gns_update,
    monitor_gradient_noise_scale,
    noise_scale,
)
from kungfu_tpu.parallel import make_mesh, make_train_step
from kungfu_tpu.parallel.dp import replicate, shard_batch


def test_gns_math():
    """Hand-checked estimator: b_small=1, b_big=4, |g_small|^2=5, |g_big|^2=2."""
    state = gns_init()
    local = {"g": jnp.array([jnp.sqrt(5.0), 0.0])}
    avg = {"g": jnp.array([jnp.sqrt(2.0), 0.0])}
    state = gns_update(state, local, avg, 1, 4)
    # g2 = (4*2 - 1*5)/3 = 1; s = (5-2)/(1 - 1/4) = 4
    np.testing.assert_allclose(float(state.g2_ema), 1.0, rtol=1e-6)
    np.testing.assert_allclose(float(state.s_ema), 4.0, rtol=1e-6)
    np.testing.assert_allclose(float(noise_scale(state)), 4.0, rtol=1e-6)


def test_gns_ema_progression():
    state = gns_init()
    local = {"g": jnp.array([2.0])}
    avg = {"g": jnp.array([1.0])}
    s1 = gns_update(state, local, avg, 1, 4)
    s2 = gns_update(s1, local, avg, 1, 4)
    # same inputs: EMA stays fixed after seeding
    np.testing.assert_allclose(float(s1.g2_ema), float(s2.g2_ema), rtol=1e-6)
    assert int(s2.count) == 2


def test_gns_interval_thinning():
    """interval>1: count advances every step, EMAs update every Nth."""
    mesh = make_mesh({"dp": 8})

    def loss_fn(params, batch):
        x, y = batch
        return jnp.mean((x @ params["w"] - y) ** 2)

    opt = monitor_gradient_noise_scale(optax.sgd(0.0), 4, "dp", interval=3)
    params = {"w": jax.random.normal(jax.random.PRNGKey(0), (4, 1))}
    step = make_train_step(loss_fn, opt, mesh, "dp", donate=False)
    p = replicate(params, mesh)
    s = replicate(opt.init(params), mesh)
    emas = []
    for i in range(7):
        x = jax.random.normal(jax.random.PRNGKey(100 + i), (32, 4))
        y = jax.random.normal(jax.random.PRNGKey(200 + i), (32, 1))
        p, s, _ = step(p, s, shard_batch((x, y), mesh))
        st = jax.device_get(s).gns
        emas.append(float(st.s_ema))
    assert int(jax.device_get(s).gns.count) == 7
    # updates at steps 0, 3, 6 (count % 3 == 0); frozen in between
    assert emas[0] == emas[1] == emas[2]
    assert emas[3] == emas[4] == emas[5]
    assert emas[2] != emas[3] and emas[5] != emas[6]


def test_gns_in_training_step():
    """GNS computed inside the jitted DP step; noisy per-shard grads give a
    positive finite noise scale."""
    mesh = make_mesh({"dp": 8})
    batch_small = 4

    def loss_fn(params, batch):
        x, y = batch
        pred = x @ params["w"]
        return jnp.mean((pred - y) ** 2)

    opt = monitor_gradient_noise_scale(optax.sgd(0.01), batch_small, "dp")
    key = jax.random.PRNGKey(0)
    params = {"w": jax.random.normal(key, (4, 1))}
    x = jax.random.normal(jax.random.PRNGKey(1), (32, 4))
    y = jax.random.normal(jax.random.PRNGKey(2), (32, 1))  # pure noise labels

    step = make_train_step(loss_fn, opt, mesh, "dp", donate=False)
    p = replicate(params, mesh)
    s = replicate(opt.init(params), mesh)
    for i in range(5):
        x = jax.random.normal(jax.random.PRNGKey(10 + i), (32, 4))
        y = jax.random.normal(jax.random.PRNGKey(50 + i), (32, 1))
        batch = shard_batch((x, y), mesh)
        p, s, loss = step(p, s, batch)
    host_state = jax.device_get(s)
    gns = float(noise_scale(host_state.gns))
    assert np.isfinite(gns)
    assert host_state.gns.count == 5
    # noise-dominated problem: tr(S) estimate must be positive
    assert float(host_state.gns.s_ema) > 0


def test_gns_overhead_bench_runs(capsys):
    """The GNS-overhead harness (BASELINE.md 'GNS monitoring overhead'
    row) runs on the CPU mesh and prints a RESULT line."""
    from kungfu_tpu.benchmarks.__main__ import bench_gns

    bench_gns(iters=3)
    out = capsys.readouterr().out
    assert "RESULT:" in out and "+GNS" in out
