"""Flight-recorder e2e (ISSUE 3 acceptance): SIGKILL a worker mid-run
under `kfrun -w -auto-recover` and assert the black box exists at every
surface — a `worker_postmortem` audit event on the runner, a non-empty
live /cluster/postmortem entry for the dead peer, the durable
postmortems.jsonl in the run dir, and an `info postmortem` timeline
rendered from both the URL and the directory."""

import json
import os
import subprocess
import sys
import time
import urllib.request

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
AGENT = os.path.join(REPO, "tests", "integration", "dying_elastic_agent.py")
DEBUG_PORT = 38497


def _poll_postmortem(base_url, proc, timeout_s=240.0):
    deadline = time.time() + timeout_s
    last_err = None
    while time.time() < deadline:
        if proc.poll() is not None:
            return None, f"runner exited early (rc={proc.returncode})"
        try:
            with urllib.request.urlopen(
                base_url + "/cluster/postmortem", timeout=2
            ) as r:
                doc = json.loads(r.read().decode())
            if doc.get("deaths", 0) >= 1:
                return doc, None
        except (OSError, ValueError) as e:
            last_err = e
        time.sleep(0.3)
    return None, f"timed out; last error: {last_err}"


def test_sigkilled_worker_leaves_a_black_box(tmp_path):
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["KF_TELEMETRY_DIR"] = str(tmp_path)
    env["KF_FLIGHT_INTERVAL"] = "0.2"  # snapshot faster than the agent dies
    proc = subprocess.Popen(
        [
            sys.executable, "-m", "kungfu_tpu.runner.cli",
            "-np", "3", "-H", "127.0.0.1:4",
            "-w", "-auto-recover", "30s",
            "-warm-spares", "0",
            "-builtin-config-port", "0",
            "-debug-port", str(DEBUG_PORT),
            sys.executable, AGENT,
        ],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
        text=True, cwd=REPO,
    )
    base_url = f"http://127.0.0.1:{DEBUG_PORT}"
    try:
        # -- live surface: /cluster/postmortem fills in while running --
        doc, err = _poll_postmortem(base_url, proc)
        if doc is None and proc.poll() is None:
            proc.kill()
        if doc is None:
            out, errout = proc.communicate(timeout=30)
            pytest.fail(
                f"no postmortem appeared: {err}\nstdout:\n{out}\nstderr:\n{errout}"
            )
        dead_peer = "127.0.0.1:38002"  # rank 2 of 3 on the 38000+ range
        assert dead_peer in doc["peers"], doc
        pm = doc["peers"][dead_peer][-1]
        assert pm["death"] == "signal SIGKILL (-9)"
        assert pm["clean_exit"] is False
        # the runner-captured output ring carries the agent's last words
        assert any("dying (SIGKILL)" in l for l in pm.get("output_tail", [])), pm

        # -- info postmortem straight off the live endpoint --
        r = subprocess.run(
            [sys.executable, "-m", "kungfu_tpu.info", "postmortem", base_url],
            env=env, capture_output=True, text=True, timeout=60, cwd=REPO,
        )
        assert r.returncode == 0, r.stderr
        assert f"== postmortem: {dead_peer} ==" in r.stdout
        assert "SIGKILL" in r.stdout

        out, errout = proc.communicate(timeout=240)
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.communicate(timeout=30)
    # the run itself still recovers and completes (size 2, progress carried)
    assert proc.returncode == 0, f"stdout:\n{out}\nstderr:\n{errout}"
    # the worker_postmortem audit event was recorded on the runner
    assert "worker_postmortem recorded for 127.0.0.1:38002" in errout, errout

    # -- durable surface: the run dir outlives the runner --
    pm_file = tmp_path / "postmortems.jsonl"
    assert pm_file.exists()
    records = [
        json.loads(l) for l in pm_file.read_text().splitlines() if l.strip()
    ]
    dead = [r for r in records if r["peer"] == dead_peer]
    assert dead and dead[-1]["death"] == "signal SIGKILL (-9)"
    # the dead worker's journal is on disk and readable (snapshots made
    # it out before the SIGKILL thanks to the fast flight interval)
    from kungfu_tpu.telemetry import flight

    recs, _ = flight.read_journal(flight.peer_dir(str(tmp_path), dead_peer))
    assert any(r.get("kind") in ("snapshot", "start") for r in recs)

    r = subprocess.run(
        [sys.executable, "-m", "kungfu_tpu.info", "postmortem", str(tmp_path)],
        env=env, capture_output=True, text=True, timeout=60, cwd=REPO,
    )
    assert r.returncode == 0, r.stderr
    assert f"== postmortem: {dead_peer} ==" in r.stdout
    assert "SIGKILL" in r.stdout
    assert "output tail" in r.stdout
