"""PyTorch frontend over the host plane (parity: kungfu/torch/__init__.py
+ module_cpu.cpp — the reference's second-framework contract)."""

import os
import subprocess
import sys

import numpy as np
import pytest

torch = pytest.importorskip("torch")

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
AGENT = os.path.join(REPO, "tests", "integration", "torch_agent.py")


def test_single_process_noops():
    """Cluster of one: sync/broadcast are no-ops, wrapper still steps."""
    from kungfu_tpu import torch as kf_torch

    model = torch.nn.Linear(2, 1, bias=False)
    with torch.no_grad():
        model.weight.fill_(1.0)
    kf_torch.broadcast_parameters(model)
    opt = kf_torch.SynchronousSGDOptimizer(
        torch.optim.SGD(model.parameters(), lr=0.5)
    )
    opt.zero_grad()
    loss = model(torch.ones(1, 2)).sum()
    loss.backward()
    opt.step()
    np.testing.assert_allclose(
        model.weight.detach().numpy(), [[0.5, 0.5]], rtol=1e-6
    )


def test_all_reduce_tensor_single():
    from kungfu_tpu import torch as kf_torch

    t = torch.arange(6, dtype=torch.float32).view(2, 3)
    out = kf_torch.all_reduce(t)
    assert torch.equal(out, t)


@pytest.mark.parametrize("async_mode", ["", "on"])
def test_torch_e2e_two_workers(async_mode):
    """kfrun np=2: broadcast equalizes params, S-SGD keeps them
    bit-identical across ranks with rank-dependent data, PairAveraging
    contracts divergent models. Parametrized over KF_CONFIG_ASYNC: the
    "on" leg drives the async scheduler's optimizer step path (ISSUE
    10) — post-accumulate-grad hooks submit during backward from step 1
    on — and must land on the same cross-rank-identical params."""
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    if async_mode:
        env["KF_CONFIG_ASYNC"] = async_mode
    r = subprocess.run(
        [
            sys.executable, "-m", "kungfu_tpu.runner.cli",
            "-np", "2", "-H", "127.0.0.1:2",
            sys.executable, AGENT,
        ],
        env=env, capture_output=True, text=True, timeout=300, cwd=REPO,
    )
    assert r.returncode == 0, f"stdout:\n{r.stdout}\nstderr:\n{r.stderr}"
    oks = [l for l in r.stdout.splitlines() if "OK" in l]
    assert len(oks) == 2, r.stdout
    digests = {
        l.split("ssgd=")[1].strip()
        for l in r.stdout.splitlines() if "ssgd=" in l
    }
    assert len(digests) == 1, "S-SGD params differ across ranks"


def test_bf16_numpy_bridge_roundtrip():
    """torch bf16 crosses the numpy bridge by bit-reinterpretation (torch
    refuses .numpy() on bf16); _to_torch inverts it exactly."""
    from kungfu_tpu.torch import _flat_view, _to_torch

    t = torch.tensor([0.5, -1.25, 3.0, 65280.0], dtype=torch.bfloat16)
    v = _flat_view(t)
    assert v.dtype.itemsize == 2 and str(v.dtype) == "bfloat16"
    back = _to_torch(v)
    assert back.dtype == torch.bfloat16
    assert torch.equal(back, t)


def test_bf16_sync_and_allreduce_single():
    """bf16 params/grads work through sync_gradients and all_reduce
    (cluster of one: identity, but the whole bridge executes)."""
    from kungfu_tpu import torch as kf_torch

    model = torch.nn.Linear(3, 1, bias=False).to(torch.bfloat16)
    kf_torch.broadcast_parameters(model)
    loss = model(torch.ones(1, 3, dtype=torch.bfloat16)).sum()
    loss.backward()
    g0 = model.weight.grad.detach().clone()
    kf_torch.sync_gradients(model)
    assert torch.equal(model.weight.grad, g0)
    out = kf_torch.all_reduce(model.weight.detach())
    assert out.dtype == torch.bfloat16
    assert torch.equal(out, model.weight.detach())
