"""Unit tests for the unified telemetry subsystem (ISSUE 1):

- metrics registry: concurrent increments, histogram quantiles,
  Prometheus text exposition;
- tracing: span nesting, Chrome-trace JSON export round-trip;
- config: shared truthy parsing + KF_TELEMETRY feature selection;
- log: structured fields, level filtering, echo;
- http: /metrics + /trace + /audit endpoint.
"""

import json
import math
import threading
import urllib.request

import pytest

from kungfu_tpu.telemetry import audit, config, log, metrics, tracing


# ---------------------------------------------------------------------------
# metrics registry
# ---------------------------------------------------------------------------

class TestCounters:
    def test_concurrent_increments(self):
        reg = metrics.Registry()
        c = reg.counter("t_total", "test", ("worker",))
        n_threads, n_incs = 8, 2000

        def run(i):
            child = c.labels(str(i % 2))
            for _ in range(n_incs):
                child.inc()

        ts = [threading.Thread(target=run, args=(i,)) for i in range(n_threads)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        total = sum(v for _, _, v in c.samples())
        assert total == n_threads * n_incs
        assert c.labels("0").value == n_threads * n_incs / 2

    def test_counter_rejects_negative(self):
        c = metrics.Registry().counter("t_total")
        with pytest.raises(ValueError):
            c.inc(-1)

    def test_labelled_family_requires_labels(self):
        c = metrics.Registry().counter("t_total", "", ("peer",))
        with pytest.raises(ValueError):
            c.inc()

    def test_reregistration_is_idempotent_but_typed(self):
        reg = metrics.Registry()
        a = reg.counter("x_total")
        assert reg.counter("x_total") is a
        with pytest.raises(ValueError):
            reg.gauge("x_total")
        with pytest.raises(ValueError):
            reg.counter("x_total", labelnames=("p",))

    def test_gauge_set_inc_dec(self):
        g = metrics.Registry().gauge("g")
        g.set(5)
        g.inc(2)
        g.dec()
        assert g.value == 6


class TestHistogram:
    def test_quantiles(self):
        reg = metrics.Registry()
        h = reg.histogram("lat_seconds", buckets=(0.01, 0.1, 1.0, 10.0))
        for _ in range(100):
            h.observe(0.05)  # all in the (0.01, 0.1] bucket
        # interpolation inside the owning bucket
        assert 0.01 < h.quantile(0.5) <= 0.1
        assert h.quantile(0.0) <= h.quantile(0.5) <= h.quantile(1.0)
        assert h.count == 100
        assert h.sum == pytest.approx(5.0)

    def test_quantile_empty_is_nan(self):
        h = metrics.Registry().histogram("h", buckets=(1.0,))
        assert math.isnan(h.quantile(0.5))

    def test_quantile_spread(self):
        h = metrics.Registry().histogram(
            "h", buckets=(1.0, 2.0, 4.0, 8.0)
        )
        for v in (0.5, 1.5, 3.0, 6.0):
            h.observe(v)
        assert h.quantile(0.25) <= 1.0
        assert 4.0 <= h.quantile(1.0) <= 8.0

    def test_concurrent_observes(self):
        h = metrics.Registry().histogram("h", buckets=(0.5,))

        def run():
            for _ in range(1000):
                h.observe(0.1)

        ts = [threading.Thread(target=run) for _ in range(4)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        assert h.count == 4000


class TestExposition:
    def test_prometheus_text_format(self):
        reg = metrics.Registry()
        c = reg.counter("kf_bytes_total", "bytes", ("peer",))
        c.labels('ho"st:1').inc(3)
        g = reg.gauge("kf_gauge", "a gauge")
        g.set(1.5)
        h = reg.histogram("kf_lat_seconds", "lat", buckets=(0.1, 1.0))
        h.observe(0.05)
        h.observe(5.0)
        text = reg.render()
        assert "# TYPE kf_bytes_total counter" in text
        assert "# HELP kf_bytes_total bytes" in text
        # label escaping per the exposition spec
        assert 'kf_bytes_total{peer="ho\\"st:1"} 3' in text
        assert "# TYPE kf_gauge gauge" in text
        assert "kf_gauge 1.5" in text
        # cumulative buckets + +Inf + sum/count
        assert 'kf_lat_seconds_bucket{le="0.1"} 1' in text
        assert 'kf_lat_seconds_bucket{le="1"} 1' in text
        assert 'kf_lat_seconds_bucket{le="+Inf"} 2' in text
        assert "kf_lat_seconds_count 2" in text
        assert text.endswith("\n")

    def test_bad_metric_names_rejected(self):
        reg = metrics.Registry()
        for bad in ("", "1abc", "a-b", "a b"):
            with pytest.raises(ValueError):
                reg.counter(bad)

    def test_extra_renderer_appended(self):
        reg = metrics.Registry()
        reg.add_renderer(lambda: "# custom block\ncustom 1\n")
        assert "custom 1" in reg.render()


# ---------------------------------------------------------------------------
# tracing
# ---------------------------------------------------------------------------

class TestTracing:
    def test_span_nesting_depths_and_containment(self):
        tracing.clear()
        with tracing.span("t_outer", step=1):
            with tracing.span("t_inner"):
                pass
            with tracing.span("t_inner2"):
                pass
        evs = {e.name: e for e in tracing.full_events("t_")}
        assert evs["t_outer"].depth == 0
        assert evs["t_inner"].depth == 1
        assert evs["t_inner2"].depth == 1
        # children temporally contained in the parent
        out = evs["t_outer"]
        for name in ("t_inner", "t_inner2"):
            e = evs[name]
            assert out.start <= e.start
            assert e.start + e.duration <= out.start + out.duration + 1e-9
        assert evs["t_outer"].args == {"step": 1}

    def test_depth_resets_after_exception(self):
        tracing.clear()
        with pytest.raises(RuntimeError):
            with tracing.span("t_err"):
                raise RuntimeError("x")
        with tracing.span("t_after"):
            pass
        evs = {e.name: e for e in tracing.full_events("t_")}
        assert evs["t_err"].depth == 0
        assert evs["t_after"].depth == 0  # stack unwound despite the raise

    def test_chrome_trace_json_roundtrip(self):
        tracing.clear()
        with tracing.span("t_step", bytes=1024):
            with tracing.span("t_child"):
                pass
        tracing.instant("t_mark", reason="test")
        doc = json.loads(tracing.chrome_trace_json("t_"))
        evs = doc["traceEvents"]
        by_name = {e["name"]: e for e in evs}
        step = by_name["t_step"]
        assert step["ph"] == "X"
        assert step["dur"] >= by_name["t_child"]["dur"]
        assert step["args"]["bytes"] == 1024
        mark = by_name["t_mark"]
        assert mark["ph"] == "i"
        for e in evs:
            assert {"name", "ph", "ts", "pid", "tid"} <= set(e)
            assert isinstance(e["ts"], float)
            if e["ph"] == "X":
                assert "dur" in e

    def test_export_chrome_writes_loadable_file(self, tmp_path):
        tracing.clear()
        with tracing.span("t_io"):
            pass
        path = tracing.export_chrome(str(tmp_path / "trace.json"), "t_")
        with open(path) as f:
            doc = json.load(f)
        assert any(e["name"] == "t_io" for e in doc["traceEvents"])

    def test_legacy_shim_api(self):
        """utils.trace call sites keep working and feed the same buffer."""
        from kungfu_tpu.utils import trace as shim

        shim.clear()
        shim.record("t_legacy", 0.25)
        with shim.span("t_scoped"):
            pass
        names = [n for n, _, _ in shim.events("t_")]
        assert "t_legacy" in names and "t_scoped" in names
        assert shim.summary_ms("t_legacy")["t_legacy"] == pytest.approx(250.0)
        assert any(
            e["name"] == "t_legacy" for e in tracing.chrome_trace()["traceEvents"]
        )


# ---------------------------------------------------------------------------
# config: truthy parsing + feature selection
# ---------------------------------------------------------------------------

class TestConfig:
    def test_truthy_variants(self):
        for v in ("1", "true", "TRUE", "yes", "On", " on ", "y"):
            assert config.truthy(v), v
        for v in ("", "0", "false", "off", "no", "garbage", "None"):
            assert not config.truthy(v), v

    def test_feature_parsing(self, monkeypatch):
        cases = {
            "metrics,trace": {"metrics", "trace"},
            "all": set(config.KNOWN_FEATURES),
            "1": set(config.KNOWN_FEATURES),
            "trace": {"trace"},
            "": set(),
            "0": set(),
            "bogus": set(),
            "metrics, bogus": {"metrics"},
        }
        for raw, want in cases.items():
            monkeypatch.setenv(config.TELEMETRY_ENV, raw)
            config.refresh()
            assert set(config.features()) == want, raw
        config.refresh()

    def test_monitoring_env_variants_enable_metrics(self, monkeypatch):
        """Satellite: KF_CONFIG_ENABLE_MONITORING "yes"/"on" used to be
        silently rejected by monitor.net.enabled()."""
        from kungfu_tpu.monitor import net

        monkeypatch.delenv(config.TELEMETRY_ENV, raising=False)
        config.refresh()
        for v in ("1", "true", "yes", "on", "ON", "Yes"):
            monkeypatch.setenv("KF_CONFIG_ENABLE_MONITORING", v)
            assert net.enabled(), v
        monkeypatch.setenv("KF_CONFIG_ENABLE_MONITORING", "0")
        assert not net.enabled()
        monkeypatch.delenv("KF_CONFIG_ENABLE_MONITORING")
        assert not net.enabled()


# ---------------------------------------------------------------------------
# log
# ---------------------------------------------------------------------------

class TestLog:
    def test_structured_fields_and_levels(self, capsys):
        log.set_level("INFO")
        try:
            log.info("resize landed", old=4, new=3)
            log.debug("hidden")
            err = capsys.readouterr().err
            assert "resize landed old=4 new=3" in err
            assert "hidden" not in err
        finally:
            log.set_level("INFO")

    def test_percent_args_still_work(self, capsys):
        log.warn("workers exited %s; restarting", [1, 0])
        assert "workers exited [1, 0]; restarting" in capsys.readouterr().err

    def test_echo_goes_to_stdout_unfiltered(self, capsys):
        log.set_level("OFF")
        try:
            log.echo("RESULT: 1.0 GiB/s")
            out = capsys.readouterr().out
            assert out == "RESULT: 1.0 GiB/s\n"
        finally:
            log.set_level("INFO")


# ---------------------------------------------------------------------------
# http endpoint + dump
# ---------------------------------------------------------------------------

def test_telemetry_server_routes():
    from kungfu_tpu.telemetry.http import TelemetryServer

    metrics.counter("t_http_total", "x").inc(7)
    tracing.clear()
    with tracing.span("t_http_span"):
        pass
    srv = TelemetryServer(0, host="127.0.0.1")
    srv.start()
    try:
        base = f"http://127.0.0.1:{srv.port}"
        with urllib.request.urlopen(base + "/metrics", timeout=5) as r:
            body = r.read().decode()
        assert "t_http_total 7" in body
        # a scraper's cache-buster query must not 404 the route
        with urllib.request.urlopen(base + "/metrics?t=1", timeout=5) as r:
            assert "t_http_total 7" in r.read().decode()
        with urllib.request.urlopen(base + "/trace", timeout=5) as r:
            doc = json.loads(r.read().decode())
        assert any(e["name"] == "t_http_span" for e in doc["traceEvents"])
        with urllib.request.urlopen(base + "/audit", timeout=5) as r:
            assert isinstance(json.loads(r.read().decode()), list)
        with pytest.raises(urllib.error.HTTPError):
            urllib.request.urlopen(base + "/nope", timeout=5)
    finally:
        srv.stop()
    # clean shutdown released the port: a new server can bind it at once
    from kungfu_tpu.telemetry.http import TelemetryServer as TS2

    srv2 = TS2(srv.port, host="127.0.0.1")
    srv2.stop()


def test_dump_shape():
    from kungfu_tpu import telemetry

    d = telemetry.dump()
    assert set(d) >= {"features", "metrics", "trace", "audit", "spans"}
    assert isinstance(d["trace"]["traceEvents"], list)
    json.dumps(d["trace"])  # must be JSON-serializable


def test_audit_record_shape():
    audit.clear()
    try:
        rec = audit.record_resize(
            peer="h:1",
            cluster_version=3,
            trigger="config_server",
            old_peers=["h:1", "h:2"],
            new_peers=["h:1"],
            phases_ms={"consensus_ms": 1.0, "update_ms": 2.5},
            progress=128,
        )
        assert rec.old_size == 2 and rec.new_size == 1
        assert rec.duration_ms == pytest.approx(3.5)
        (got,) = audit.records(kind="resize")
        assert got.trigger == "config_server"
        assert audit.annotate_last(peer="h:1", checkpoint_version=9)
        assert audit.records()[0].checkpoint_version == 9
        line = audit.to_jsonl().strip()
        assert json.loads(line)["progress"] == 128
        # the config-server WAIT is recorded but excluded from duration
        # (it measures idling before agreement, not resize work)
        rec2 = audit.record_resize(
            peer="h:1",
            trigger="config_server",
            old_peers=["h:1", "h:2"],
            new_peers=["h:1"],
            phases_ms={"wait_config_ms": 15000.0, "update_ms": 2.0},
        )
        assert rec2.duration_ms == pytest.approx(2.0)
    finally:
        audit.clear()
