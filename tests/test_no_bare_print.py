"""Lint shim (ISSUE 7 satellite): the bare-print ban is now kfcheck
rule KF500 (kungfu_tpu/devtools/kfcheck/rules.py) so one driver owns
all project lint; this file keeps the lint in tier-1 under its
historical name and documents where the rule moved.

Policy unchanged since ISSUE 1: everything routes through
kungfu_tpu.telemetry.log (leveled, rank-prefixed, structured) or
log.echo() for CLI result lines; runner/cli.py and info/ are exempt —
user-facing CLIs whose stdout IS the product.
"""

from kungfu_tpu.devtools.kfcheck import core


def test_no_bare_print_outside_cli_surfaces():
    core._ensure_rules_loaded()
    findings = core.run_project(select=["KF500"])
    assert not findings, (
        "bare print() calls found (use kungfu_tpu.telemetry.log, or "
        "log.echo() for CLI result lines):\n  "
        + "\n  ".join(f.render() for f in findings)
    )
