"""Lint (ISSUE 1 satellite): no bare print() calls under kungfu_tpu/.

Everything routes through kungfu_tpu.telemetry.log (leveled, rank-
prefixed, structured) or log.echo() for CLI result lines. Exempt:
runner/cli.py and info/ — user-facing CLIs whose stdout IS the product.

AST-based (not grep) so docstrings and comments mentioning print() are
not false positives.
"""

import ast
import os

PKG = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "kungfu_tpu"
)

EXEMPT = {
    os.path.join("runner", "cli.py"),
}
EXEMPT_DIRS = {"info"}


def _exempt(rel: str) -> bool:
    if rel in EXEMPT:
        return True
    return rel.split(os.sep)[0] in EXEMPT_DIRS


def _print_calls(path):
    with open(path) as f:
        tree = ast.parse(f.read(), filename=path)
    out = []
    for node in ast.walk(tree):
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Name)
            and node.func.id == "print"
        ):
            out.append(node.lineno)
    return out


def test_no_bare_print_outside_cli_surfaces():
    offenders = []
    for root, _, files in os.walk(PKG):
        for fn in files:
            if not fn.endswith(".py"):
                continue
            path = os.path.join(root, fn)
            rel = os.path.relpath(path, PKG)
            if _exempt(rel):
                continue
            for lineno in _print_calls(path):
                offenders.append(f"kungfu_tpu/{rel}:{lineno}")
    assert not offenders, (
        "bare print() calls found (use kungfu_tpu.telemetry.log, or "
        "log.echo() for CLI result lines):\n  " + "\n  ".join(offenders)
    )
