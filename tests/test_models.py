"""Model zoo tests (tiny configs, CPU mesh)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from kungfu_tpu.models.fake import FAKE_MODELS, fake_gradients, total_size_bytes
from kungfu_tpu.models.mlp import init_mlp, mlp_apply, mlp_loss
from kungfu_tpu.models.transformer import (
    TransformerConfig,
    init_transformer,
    param_pspecs,
    transformer_apply,
    transformer_loss,
)


def test_mlp_forward_and_loss():
    params = init_mlp(jax.random.PRNGKey(0))
    x = jnp.ones((4, 784))
    y = jnp.zeros((4,), jnp.int32)
    logits = mlp_apply(params, x)
    assert logits.shape == (4, 10)
    loss = mlp_loss(params, (x, y))
    assert np.isfinite(float(loss))


def test_mlp_hidden():
    params = init_mlp(jax.random.PRNGKey(0), hidden=32)
    assert mlp_apply(params, jnp.ones((2, 784))).shape == (2, 10)


def test_transformer_forward():
    cfg = TransformerConfig.tiny()
    params = init_transformer(jax.random.PRNGKey(0), cfg)
    tokens = jnp.zeros((2, 16), jnp.int32)
    logits = jax.jit(lambda p, t: transformer_apply(p, t, cfg))(params, tokens)
    assert logits.shape == (2, 16, cfg.vocab_size)
    assert logits.dtype == jnp.float32
    assert np.isfinite(np.asarray(logits)).all()


def test_transformer_causality():
    """Changing a future token must not change past logits."""
    cfg = TransformerConfig.tiny()
    params = init_transformer(jax.random.PRNGKey(1), cfg)
    t1 = jnp.zeros((1, 8), jnp.int32)
    t2 = t1.at[0, 7].set(3)
    l1 = transformer_apply(params, t1, cfg)
    l2 = transformer_apply(params, t2, cfg)
    np.testing.assert_allclose(
        np.asarray(l1[0, :7]), np.asarray(l2[0, :7]), rtol=2e-2, atol=2e-3
    )


def test_transformer_trains():
    cfg = TransformerConfig.tiny()
    params = init_transformer(jax.random.PRNGKey(0), cfg)
    opt = optax.adam(1e-2)
    state = opt.init(params)
    batch = jax.random.randint(jax.random.PRNGKey(2), (4, 17), 0, cfg.vocab_size)

    @jax.jit
    def step(params, state, batch):
        loss, grads = jax.value_and_grad(lambda p: transformer_loss(p, batch, cfg))(params)
        updates, state = opt.update(grads, state, params)
        return optax.apply_updates(params, updates), state, loss

    losses = []
    for _ in range(10):
        params, state, loss = step(params, state, batch)
        losses.append(float(loss))
    assert losses[-1] < losses[0]


def test_param_pspecs_tree_matches():
    cfg = TransformerConfig.tiny()
    params = init_transformer(jax.random.PRNGKey(0), cfg)
    specs = param_pspecs(cfg)
    # same tree structure
    jax.tree.map(lambda p, s: None, params, specs,
                 is_leaf=lambda x: not isinstance(x, dict))


def test_fake_models():
    assert "resnet50-imagenet" in FAKE_MODELS
    grads = fake_gradients("tiny")
    assert [g.size for g in grads] == [1, 10, 100]
    assert total_size_bytes("slp-mnist") == (784 * 10 + 10) * 4
    # resnet50 full gradient set is ~25M params * 4B ≈ 100MB
    assert 20e6 < sum(FAKE_MODELS["resnet50-imagenet"]) < 40e6


def test_s2d_stem_equivalent_to_conv_stem():
    """SpaceToDepthStem is numerically exact vs the 7x7/s2 conv (same
    stored parameter, reassociated taps)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from kungfu_tpu.models.resnet import ResNet, init_resnet

    kw = dict(stage_sizes=[1, 1], num_classes=10, num_filters=8,
              dtype=jnp.float32)
    plain = ResNet(s2d_stem=False, **kw)
    s2d = ResNet(s2d_stem=True, **kw)
    key = jax.random.PRNGKey(0)
    params, stats = init_resnet(key, plain, image_size=32, batch=2)
    x = jax.random.normal(jax.random.PRNGKey(1), (2, 32, 32, 3), jnp.float32)
    out_a = plain.apply({"params": params, "batch_stats": stats}, x, train=False)
    out_b = s2d.apply({"params": params, "batch_stats": stats}, x, train=False)
    np.testing.assert_allclose(np.asarray(out_a), np.asarray(out_b),
                               rtol=2e-5, atol=2e-5)
